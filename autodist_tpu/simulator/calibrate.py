"""Measured mode: refine α-β constants from a short real run.

The analytic defaults in :class:`CostModelParams` come from topology
hints; a 3-step profiled run gives ground truth. The feed is
:func:`autodist_tpu.utils.profiling.collective_timeline` — one row per
distinct collective op (with bucketed sync, one per bucket) as
``(op text, total ns, count)``. The op text is the full HLO
instruction, so the RESULT shapes (between ``' = '`` and the op name)
give the wire bytes; a least-squares fit of per-occurrence time against
the KIND-AWARE cost shape (ring all-reduce ``2(n-1)α + 2(n-1)/n·B·β``,
reduce-scatter/all-gather ``(n-1)α + (n-1)/n·B·β``, permute ``α + B·β``)
yields α and β for the link class. Async ``-start`` halves are dropped
(operand-echoing result tuples, launch-only durations).

Degrades gracefully: no trace, no collective rows, or a degenerate fit
(all samples the same size) leaves the analytic constants in place with
a logged warning — CPU-fallback runs calibrate nothing and lose nothing.
"""
import re

from autodist_tpu.utils import logging

_DTYPE_BYTES = {'pred': 1, 's8': 1, 'u8': 1, 's16': 2, 'u16': 2,
                'bf16': 2, 'f16': 2, 's32': 4, 'u32': 4, 'f32': 4,
                's64': 8, 'u64': 8, 'f64': 8}
_SHAPE_RE = re.compile(r'(\w+)\[([\d,]*)\]')
_KIND_RE = re.compile(
    r'(all-reduce|all-gather|reduce-scatter|collective-permute|'
    r'all-to-all)(-start|-done)?\(')


def _result_bytes_and_kind(op_text):
    """(wire bytes, collective kind) of one HLO instruction, or None.

    Result shapes only — operands sit after the op name. ``-start``
    halves of async pairs are DROPPED: their result tuples include the
    input operand buffer (double-counted bytes) and their duration is
    the launch, not the transfer; the ``-done`` half carries the
    completion wait at the true output shape.
    """
    m = _KIND_RE.search(op_text)
    eq = op_text.find(' = ')
    if not m or eq < 0 or m.start() < eq:
        return None
    if m.group(2) == '-start':
        return None
    total = 0
    for dtype, dims in _SHAPE_RE.findall(op_text[eq + 3:m.start()]):
        size = _DTYPE_BYTES.get(dtype, 4)
        for d in filter(None, dims.split(',')):
            size *= int(d)
        total += size
    if not total:
        return None
    return total, m.group(1)


def samples_from_timeline(timeline):
    """``[(wire_bytes, kind, seconds_per_occurrence)]`` from timeline
    rows (``-start`` async halves dropped — see
    :func:`_result_bytes_and_kind`)."""
    samples = []
    for name, ns, cnt in timeline:
        bk = _result_bytes_and_kind(name)
        if bk is None or not cnt or ns <= 0:
            continue
        samples.append((bk[0], bk[1], ns / 1e9 / cnt))
    return samples


#: (hop multiplier, byte multiplier as a fraction of (n-1)/n·B) per
#: collective kind — the kind-specific cost shapes the fit inverts.
#: all-reduce is the ring (two phases); RS/AG are one phase each;
#: a permute is one hop moving the full buffer once.
def _kind_factors(kind, n):
    if kind == 'all-reduce':
        return 2.0 * (n - 1), 2.0 * (n - 1) / n
    if kind in ('reduce-scatter', 'all-gather', 'all-to-all'):
        return float(n - 1), float(n - 1) / n
    if kind == 'collective-permute':
        return 1.0, 1.0
    return None


def fit_alpha_beta(samples, num_replicas):
    """Least-squares (α, β) over kind-aware cost shapes.

    Each sample contributes ``t ≈ h(kind)·α + w(kind)·B·β`` with the
    hop/byte multipliers of ITS collective kind — so reduce-scatter/
    all-gather rows (a ZeRO run's whole timeline) are not mispriced
    through the ring-all-reduce formula. Returns ``(alpha_s,
    beta_s_per_byte)`` or None when the fit is degenerate (fewer than
    2 distinct byte sizes, or a non-positive β — measurement noise on
    tiny collectives).
    """
    import numpy as np

    n = max(2, int(num_replicas))
    rows = []
    for b, kind, t in samples:
        f = _kind_factors(kind, n)
        if f is None:
            continue
        rows.append((f[0], f[1] * b, t))
    if len({w for _, w, _ in rows}) < 2:
        return None
    design = np.asarray([(h, w) for h, w, _ in rows], dtype=np.float64)
    ts = np.asarray([t for _, _, t in rows], dtype=np.float64)
    (alpha, beta), *_ = np.linalg.lstsq(design, ts, rcond=None)
    if beta <= 0:
        return None
    return float(max(alpha, 0.0)), float(beta)


def calibrate_from_timeline(params, timeline, num_replicas,
                            cross_node=False):
    """Refined copy of ``params`` from collective timeline rows.

    Leaves ``params`` untouched (and returns it as-is, warned) when the
    timeline yields no usable fit.
    """
    samples = samples_from_timeline(timeline or [])
    fit = fit_alpha_beta(samples, num_replicas) if samples else None
    if fit is None:
        logging.warning(
            'calibrate: no usable collective samples (%d rows, %d '
            'parsed) — keeping analytic α-β constants', len(timeline or []),
            len(samples))
        return params
    alpha, beta = fit
    import dataclasses
    if cross_node:
        out = dataclasses.replace(params, alpha_dcn_s=alpha,
                                  beta_dcn_s_per_byte=beta,
                                  calibrated=True)
    else:
        out = dataclasses.replace(params, alpha_ici_s=alpha,
                                  beta_ici_s_per_byte=beta,
                                  calibrated=True)
    logging.info('calibrate: fitted alpha=%.3gs beta=%.3gs/B from %d '
                 'collective samples (%s link)', alpha, beta,
                 len(samples), 'DCN' if cross_node else 'ICI')
    return out


def calibrate_from_trace(params, trace_dir, num_replicas,
                         cross_node=False, line_name='XLA Ops'):
    """Refined copy of ``params`` from a captured profiler trace dir
    (``Trainer.profile`` / ``RunOptions`` output). Degrades to the
    analytic constants when the trace has no collective rows (e.g.
    CPU-fallback runs, where profiling.collective_timeline itself
    degrades to empty)."""
    from autodist_tpu.utils.profiling import collective_timeline
    timeline = collective_timeline(trace_dir, line_name=line_name)
    return calibrate_from_timeline(params, timeline, num_replicas,
                                   cross_node=cross_node)
