"""Measured mode: refine α-β constants from a short real run.

The analytic defaults in :class:`CostModelParams` come from topology
hints; a 3-step profiled run gives ground truth. The feed is
:func:`autodist_tpu.utils.profiling.collective_timeline` — one row per
distinct collective op (with bucketed sync, one per bucket) as
``(op text, total ns, count)``. The op text is the full HLO
instruction, so the RESULT shapes (between ``' = '`` and the op name)
give the wire bytes; a least-squares fit of per-occurrence time against
the KIND-AWARE cost shape (ring all-reduce ``2(n-1)α + 2(n-1)/n·B·β``,
reduce-scatter/all-gather ``(n-1)α + (n-1)/n·B·β``, permute ``α + B·β``)
yields α and β for the link class. Async ``-start`` halves are dropped
(operand-echoing result tuples, launch-only durations).

Degrades gracefully: no trace, no collective rows, or a degenerate fit
(all samples the same size) leaves the analytic constants in place with
a logged warning — CPU-fallback runs calibrate nothing and lose nothing.
"""
import re

from autodist_tpu.utils import logging

_DTYPE_BYTES = {'pred': 1, 's8': 1, 'u8': 1, 's16': 2, 'u16': 2,
                'bf16': 2, 'f16': 2, 's32': 4, 'u32': 4, 'f32': 4,
                's64': 8, 'u64': 8, 'f64': 8}
_SHAPE_RE = re.compile(r'(\w+)\[([\d,]*)\]')
_KIND_RE = re.compile(
    r'(all-reduce|all-gather|reduce-scatter|collective-permute|'
    r'all-to-all)(-start|-done)?\(')
_GROUPS_RE = re.compile(
    r'replica_groups=\{(\{[^{}]*\}(?:,\{[^{}]*\})*)\}')


def _result_bytes_and_kind(op_text):
    """(wire bytes, collective kind) of one HLO instruction, or None.

    Result shapes only — operands sit after the op name. ``-start``
    halves of async pairs are DROPPED: their result tuples include the
    input operand buffer (double-counted bytes) and their duration is
    the launch, not the transfer; the ``-done`` half carries the
    completion wait at the true output shape.
    """
    m = _KIND_RE.search(op_text)
    eq = op_text.find(' = ')
    if not m or eq < 0 or m.start() < eq:
        return None
    if m.group(2) == '-start':
        return None
    total = 0
    for dtype, dims in _SHAPE_RE.findall(op_text[eq + 3:m.start()]):
        size = _DTYPE_BYTES.get(dtype, 4)
        for d in filter(None, dims.split(',')):
            size *= int(d)
        total += size
    if not total:
        return None
    return total, m.group(1)


def _replica_groups(op_text):
    """Parsed ``replica_groups={{0,1},{2,3}}`` of one HLO instruction,
    or None for the global (empty / absent) group — flat collectives
    over the whole mesh carry ``replica_groups={}``."""
    m = _GROUPS_RE.search(op_text)
    if not m:
        return None
    groups = []
    for grp in re.findall(r'\{([^{}]*)\}', m.group(1)):
        ids = [int(x) for x in grp.split(',') if x.strip()]
        if ids:
            groups.append(ids)
    return groups or None


def samples_from_timeline(timeline):
    """``[(wire_bytes, kind, seconds_per_occurrence)]`` from timeline
    rows (``-start`` async halves dropped — see
    :func:`_result_bytes_and_kind`)."""
    samples = []
    for name, ns, cnt in timeline:
        bk = _result_bytes_and_kind(name)
        if bk is None or not cnt or ns <= 0:
            continue
        samples.append((bk[0], bk[1], ns / 1e9 / cnt))
    return samples


def tiered_samples_from_timeline(timeline, devices_per_node):
    """Split timeline rows by LINK CLASS for per-tier calibration.

    A hierarchical schedule's timeline mixes collectives on two
    physically different links: the intra-node phases run over groups
    that stay within one node, the inter-node phase over groups that
    span nodes. Fitting one α-β through both mispriced exactly the
    flat-vs-hierarchical ranking calibration exists to sharpen, so
    each row is classified by its HLO ``replica_groups``: every group
    within one node (``id // devices_per_node`` constant) -> ICI; any
    cross-node group — including the global ``{}`` group a flat
    collective carries, which spans nodes by construction on a
    multi-node run — -> DCN.

    Returns ``(ici, dcn)`` sample lists; each sample is
    ``(wire_bytes, kind, seconds, group_size)`` with the group size
    the fit's hop count must use (an intra-node ring has ``g-1`` hops,
    not ``n-1``).
    """
    g = max(1, int(devices_per_node))
    ici, dcn = [], []
    for name, ns, cnt in timeline:
        bk = _result_bytes_and_kind(name)
        if bk is None or not cnt or ns <= 0:
            continue
        t = ns / 1e9 / cnt
        groups = _replica_groups(name)
        if groups is None:
            dcn.append((bk[0], bk[1], t, 0))
            continue
        cross = any(len({i // g for i in grp}) > 1 for grp in groups)
        size = len(groups[0])
        (dcn if cross else ici).append((bk[0], bk[1], t, size))
    return ici, dcn


#: (hop multiplier, byte multiplier as a fraction of (n-1)/n·B) per
#: collective kind — the kind-specific cost shapes the fit inverts.
#: all-reduce is the ring (two phases); RS/AG are one phase each;
#: a permute is one hop moving the full buffer once.
def _kind_factors(kind, n):
    if kind == 'all-reduce':
        return 2.0 * (n - 1), 2.0 * (n - 1) / n
    if kind in ('reduce-scatter', 'all-gather', 'all-to-all'):
        return float(n - 1), float(n - 1) / n
    if kind == 'collective-permute':
        return 1.0, 1.0
    return None


def fit_alpha_beta(samples, num_replicas):
    """Least-squares (α, β) over kind-aware cost shapes.

    Each sample contributes ``t ≈ h(kind)·α + w(kind)·B·β`` with the
    hop/byte multipliers of ITS collective kind — so reduce-scatter/
    all-gather rows (a ZeRO run's whole timeline) are not mispriced
    through the ring-all-reduce formula. A sample may carry a fourth
    element, its own replica-GROUP size (hierarchical schedules run
    intra-node collectives over ``g`` devices, not ``n``); 0 or absent
    falls back to ``num_replicas``. Returns ``(alpha_s,
    beta_s_per_byte)`` or None when the fit is degenerate (fewer than
    2 distinct byte sizes, or a non-positive β — measurement noise on
    tiny collectives).
    """
    import numpy as np

    n = max(2, int(num_replicas))
    rows = []
    for s in samples:
        b, kind, t = s[0], s[1], s[2]
        n_s = int(s[3]) if len(s) > 3 and s[3] else n
        f = _kind_factors(kind, max(2, n_s))
        if f is None:
            continue
        rows.append((f[0], f[1] * b, t))
    if len({w for _, w, _ in rows}) < 2:
        return None
    design = np.asarray([(h, w) for h, w, _ in rows], dtype=np.float64)
    ts = np.asarray([t for _, _, t in rows], dtype=np.float64)
    (alpha, beta), *_ = np.linalg.lstsq(design, ts, rcond=None)
    if beta <= 0:
        return None
    return float(max(alpha, 0.0)), float(beta)


def tier_links(params, host_scale=None):
    """Per-tier ``{tier: (alpha, beta)}`` for schedule-IR pricing
    (:func:`cost_model.program_time`'s ``links`` argument). The ICI
    and DCN tiers come straight from ``params`` — calibrated constants
    when a fit ran, analytic otherwise. The intermediate ``host`` tier
    (cross-host but intra-slice; no legacy schedule runs collectives
    there, so nothing calibrates it directly) defaults to the
    geometric mean of the two measured tiers — the standard
    interpolation for an unmeasured middle link — or to
    ``host_scale`` × the ICI constants when the caller knows the
    ratio."""
    ai, bi = params.link(cross_node=False)
    ad, bd = params.link(cross_node=True)
    if host_scale:
        host = (ai * float(host_scale), bi * float(host_scale))
    else:
        host = ((ai * ad) ** 0.5, (bi * bd) ** 0.5)
    return {'local': (0.0, 0.0), 'ici': (ai, bi), 'host': host,
            'dcn': (ad, bd)}


def samples_from_drift(table):
    """Entry-labeled ``(ici, dcn)`` sample lists from a roofline
    drift table (:func:`autodist_tpu.telemetry.roofline.drift_table`).

    Each sample is ``(full_buffer_bytes, hlo kind, seconds,
    group_size)`` — tier-labeled BY THE SCHEDULE ENTRY, not by the
    replica-groups heuristic, and carrying the schedule's FULL buffer
    bytes rather than the HLO result shape. That second point is the
    correctness fix: a reduce-scatter's HLO result is the 1/n shard,
    so the unlabeled path (:func:`tiered_samples_from_timeline` /
    :func:`samples_from_timeline`) feeds ``B/n`` into a cost shape
    priced over ``B`` and fits a β inflated by ``n`` — a ZeRO or
    weight-update-sharded trace calibrated through it overprices
    every reduce-scatter/all-gather by the replica count
    (``tests/test_roofline.py`` pins the divergence).
    """
    ici, dcn = [], []
    for tier, full_b, hlo_kind, seconds, group in \
            (table or {}).get('samples', ()):
        row = (full_b, hlo_kind, seconds, group)
        (dcn if tier == 'dcn' else ici).append(row)
    return ici, dcn


def calibrate_from_drift(params, table, num_replicas,
                         devices_per_node=0):
    """Refined copy of ``params`` from an entry-labeled drift table —
    the roofline observatory's replacement for the unlabeled-row
    heuristic classification.

    The ICI and DCN tiers are fitted from the table's entry-labeled
    samples (:func:`samples_from_drift`) under the same
    fallback rules as :func:`calibrate_from_timeline`'s tiered path:
    a tier with a degenerate fit borrows the group-aware shared fit,
    a tier ABSENT from the table keeps its analytic constants, and an
    empty table returns ``params`` untouched (warned).
    """
    ici, dcn = samples_from_drift(table)
    if not (ici or dcn):
        logging.warning(
            'calibrate: drift table carries no joinable samples — '
            'keeping analytic α-β constants')
        return params
    shared = fit_alpha_beta(ici + dcn, num_replicas)
    return _apply_tier_fits(params, ici, dcn, shared, num_replicas,
                            devices_per_node or num_replicas)


def _apply_tier_fits(params, ici, dcn, shared, num_replicas,
                     devices_per_node):
    """Per-tier least-squares application with the shared-fit /
    analytic fallback rules (the one implementation behind
    :func:`calibrate_from_timeline`'s tiered path and
    :func:`calibrate_from_drift`)."""
    import dataclasses

    fit_i = fit_alpha_beta(ici, devices_per_node) if ici else None
    fit_d = fit_alpha_beta(dcn, num_replicas) if dcn else None
    out = params
    for tier, fit, nrows in (('ICI', fit_i, len(ici)),
                             ('DCN', fit_d, len(dcn))):
        if fit is None:
            # a tier with SOME rows but a degenerate fit borrows
            # the group-aware shared fit (its own rows are in it);
            # a tier ABSENT from the trace keeps its analytic
            # constants — assigning an all-DCN shared fit to an
            # unmeasured ICI tier would make the model reject
            # every two-level schedule, the opposite of what
            # calibration is for
            if nrows == 0 or shared is None:
                logging.info(
                    'calibrate: %s tier has no usable fit (%d '
                    'rows%s) — keeping its analytic constants',
                    tier, nrows,
                    '' if nrows else ', tier absent from trace')
                continue
            logging.info(
                'calibrate: %s tier has too few samples (%d '
                'rows); falling back to the shared fit', tier,
                nrows)
            fit = shared
        alpha, beta = fit
        if tier == 'DCN':
            out = dataclasses.replace(
                out, alpha_dcn_s=alpha, beta_dcn_s_per_byte=beta,
                calibrated=True)
        else:
            out = dataclasses.replace(
                out, alpha_ici_s=alpha, beta_ici_s_per_byte=beta,
                calibrated=True)
        logging.info(
            'calibrate: fitted %s tier alpha=%.3gs beta=%.3gs/B '
            '(%d rows)', tier, alpha, beta, nrows)
    return out


def calibrate_from_timeline(params, timeline, num_replicas,
                            cross_node=False, devices_per_node=0):
    """Refined copy of ``params`` from collective timeline rows.

    With ``devices_per_node > 1`` (a multi-node run whose node shape
    the caller knows), the ICI and DCN tiers are fitted SEPARATELY:
    rows are split by replica-group span
    (:func:`tiered_samples_from_timeline`) and each tier gets its own
    least-squares α-β, so the flat-vs-hierarchical ranking is
    calibrated per link class. A tier with too few samples for its own
    fit falls back to the SHARED fit over all rows (the pre-tier
    behavior); when that is degenerate too, the analytic constants for
    that tier stay in place.

    Without ``devices_per_node``, the single shared fit lands on the
    tier ``cross_node`` selects, exactly as before.

    Leaves ``params`` untouched (and returns it as-is, warned) when the
    timeline yields no usable fit at all.
    """
    import dataclasses

    samples = samples_from_timeline(timeline or [])
    shared = fit_alpha_beta(samples, num_replicas) if samples else None
    if devices_per_node and devices_per_node > 1:
        ici, dcn = tiered_samples_from_timeline(timeline or [],
                                                devices_per_node)
        # the tier fallback inverts through each row's OWN group size
        # (a group-aware shared fit), not the legacy flat-n assumption
        shared = fit_alpha_beta(ici + dcn, num_replicas) or shared \
            if (ici or dcn) else shared
        out = _apply_tier_fits(params, ici, dcn, shared, num_replicas,
                               devices_per_node)
        if not out.calibrated:
            logging.warning(
                'calibrate: no usable collective samples in either '
                'tier (%d rows) — keeping analytic α-β constants',
                len(timeline or []))
        return out
    if shared is None:
        logging.warning(
            'calibrate: no usable collective samples (%d rows, %d '
            'parsed) — keeping analytic α-β constants', len(timeline or []),
            len(samples))
        return params
    alpha, beta = shared
    if cross_node:
        out = dataclasses.replace(params, alpha_dcn_s=alpha,
                                  beta_dcn_s_per_byte=beta,
                                  calibrated=True)
    else:
        out = dataclasses.replace(params, alpha_ici_s=alpha,
                                  beta_ici_s_per_byte=beta,
                                  calibrated=True)
    logging.info('calibrate: fitted alpha=%.3gs beta=%.3gs/B from %d '
                 'collective samples (%s link)', alpha, beta,
                 len(samples), 'DCN' if cross_node else 'ICI')
    return out


def calibrate_from_trace(params, trace_dir, num_replicas,
                         cross_node=False, line_name='XLA Ops',
                         devices_per_node=0, expected_collectives=0):
    """Refined copy of ``params`` from a captured profiler trace dir
    (``Trainer.profile`` / ``RunOptions`` output). Degrades to the
    analytic constants when the trace has no collective rows (e.g.
    CPU-fallback runs, where profiling.collective_timeline itself
    degrades to empty). ``devices_per_node`` > 1 fits the ICI and DCN
    tiers separately (see :func:`calibrate_from_timeline`).
    ``expected_collectives`` (the plan's statically-known emission
    count, e.g. ``len(grad_bucket_layout(...))``) makes a
    zero-collective parse on a run that emitted buckets log loudly
    instead of silently keeping analytic constants."""
    from autodist_tpu.utils.profiling import collective_timeline
    timeline = collective_timeline(
        trace_dir, line_name=line_name,
        expected_collectives=expected_collectives)
    return calibrate_from_timeline(params, timeline, num_replicas,
                                   cross_node=cross_node,
                                   devices_per_node=devices_per_node)
