"""Analytic α-β cost model for per-variable synchronizer choices.

Grounded in the PCCL formulation (per-process-group collective cost as
α + β·bytes over link latency/bandwidth) and *Automatic Cross-Replica
Sharding of Weight Update in Data-Parallel Training* (when ZeRO-style
reduce-scatter + all-gather beats plain AllReduce):

- ring all-reduce of ``B`` bytes over ``n`` devices:
  ``2(n-1)·α + 2(n-1)/n · B·β``
- reduce-scatter or all-gather (the two ZeRO halves):
  ``(n-1)·α + (n-1)/n · B·β``

α comes from link latency (one hop per ring step), β = 1/bandwidth.
Which (α, β) pair applies — ICI or DCN — comes from the
:class:`~autodist_tpu.resource_spec.Topology` hints: multi-node specs
price collectives at the DCN link (DP reduction is the cross-boundary
traffic; mesh.py keeps everything else on ICI).

The schedule being priced is NOT re-derived here: it is the exact
bucket/chunk layout the execution plan would emit, computed statically
by :func:`autodist_tpu.parallel.plan.static_collective_schedule` — same
packing, same reverse-production ordering, same ZeRO chunking. Grad-sync
buckets other than the final one are assumed to overlap backward compute
(the XLA latency-hiding scheduler the bucketing exists for) and get an
``overlap_discount`` haircut; the last-emitted bucket (the FIRST layers'
gradients, produced when no backward compute is left to hide behind) is
always priced in full.
"""
from dataclasses import dataclass, field, asdict

import numpy as np

from autodist_tpu.parallel.plan import static_collective_schedule
from autodist_tpu.utils import logging

#: Wire bytes per element by compressor (None = tensor's own itemsize).
#: HorovodCompressor casts f32→bf16 for the wire; Int8Ring ships int8
#: blocks plus one f32 scale per AUTODIST_QUANT_BLOCK elements (the
#: scale overhead is added by :func:`wire_bytes`, not folded in here).
#: PowerSGD's wire is rank-dependent and it never fuses — priced at
#: full bytes (None) as a conservative bound. Keys MUST cover the
#: compressor registry in :mod:`autodist_tpu.parallel.compressor`
#: exactly — a compressor missing here would silently price as f32
#: (tools/check_wire_pricing.py is the tier-1 drift check).
_WIRE_ITEMSIZE = {
    'NoneCompressor': None,
    'HorovodCompressor': 2,
    'HorovodCompressorEF': 2,
    'Int8RingCompressor': 1,
    'PowerSGDCompressor': None,
}

#: Grad + optimizer-slot accounting assumptions: gradients match the
#: param dtype; optimizer slots are kept in f32 (optax default).
_OPT_SLOT_ITEMSIZE = 4


def wire_bytes(nbytes, dtype, compressor=None):
    """Bytes that actually cross the wire for a raw ``nbytes`` tensor.

    The block-quantized int8 tier additionally carries one f32 scale
    per ``AUTODIST_QUANT_BLOCK`` elements (the EQuARX blockscale
    header) — at the default block of 256 that is ~1.6% on top of the
    int8 payload, priced here so the 4x headline never overstates."""
    itemsize = np.dtype(dtype).itemsize if dtype is not None else 4
    wire = _WIRE_ITEMSIZE.get(compressor or 'NoneCompressor')
    if wire is None or wire >= itemsize:
        return int(nbytes)
    elems = int(nbytes) // itemsize
    out = elems * wire
    if compressor == 'Int8RingCompressor':
        from autodist_tpu.parallel.compressor import quant_block_size
        out += 4 * (-(-elems // quant_block_size()))
    return out


@dataclass
class CostModelParams:
    """α-β constants (per link class) + overlap/compute assumptions.

    ``alpha_*`` is seconds per ring hop, ``beta_*`` seconds per byte.
    Defaults come from a :class:`Topology`'s bandwidth/latency hints;
    :mod:`calibrate` refines them from measured collective timelines.
    ``compute_time_s`` is an optional calibrated per-step compute
    estimate — 0 means "rank by sync cost alone", which preserves
    ordering (compute is strategy-invariant for a fixed model).
    """
    alpha_ici_s: float = 1e-6
    beta_ici_s_per_byte: float = 1e-11        # 100 GB/s
    alpha_dcn_s: float = 30e-6
    beta_dcn_s_per_byte: float = 8e-9         # 0.125 GB/s
    overlap_discount: float = 0.5             # hidden fraction of
    # overlappable grad-bucket time (latency-hiding scheduler)
    # Async-PS pull-ahead haircut (AUTODIST_PS_PIPELINE_DEPTH >= 2):
    # the fraction of PS param-phase traffic (the post-update re-gather
    # / next-step pull) the background pipeline hides behind the host
    # tail. Default 0 — predictions for the serial depth-1 plane stay
    # unchanged unless the caller opts in (tools/simulate.py
    # --ps-overlap, or a calibrated ps_stats overlap_frac).
    ps_overlap_discount: float = 0.0
    compute_time_s: float = 0.0
    # compressors are not free: the wire cast reads+writes the full
    # tensor at HBM speed on both ends (~800 GB/s, two passes)
    compress_s_per_byte: float = 2.5e-12
    # block quantization costs MORE than a cast: the max-abs scan, the
    # scale divide and the per-hop requantization of the int8 ring are
    # extra HBM passes over the bucket (~2 additional round trips).
    # Added ON TOP of compress_s_per_byte for Int8RingCompressor
    # entries — this is what lets a bandwidth-rich ICI topology
    # correctly REJECT the int8 tier while a DCN-bound one picks it.
    quant_s_per_byte: float = 5.0e-12
    # Two-level (hierarchical) schedules pay a tier-boundary cost the
    # flat ring does not: the re-layout between the intra-node
    # reduce-scatter and the inter-node phase (and, under the int8
    # wire, the boundary requantization) is an extra HBM round trip
    # over the bucket. Priced per RAW byte, like compress_s_per_byte —
    # this is what keeps flat the winner on topologies whose "DCN"
    # is as fast as ICI (single fat switch), where the two extra
    # phases buy nothing.
    hier_boundary_s_per_byte: float = 2.5e-12
    # What one byte of freed per-device HBM is worth in step-time
    # seconds — the exchange rate choose_update_sharding prices the
    # weight-update-sharding trade with (arXiv:2112.01075's point:
    # price the extra all-gather against the freed memory instead of
    # hard-coding the choice). Sharding the update frees
    # ~(n-1)/n of the opt-slot bytes but exposes the param all-gather
    # (it cannot hide behind backward compute the way grad buckets
    # do). The default is calibrated so an ICI-rich mesh (where wire
    # time is cheap and HBM is the binding resource — the paper's TPU
    # pod setting) shards, while a DCN-bound link (where the exposed
    # gather is expensive) keeps the replicated update. Freed HBM
    # also feeds back mechanically: the memory estimate drops sharded
    # slots to 1/n, so AutoStrategy's budget pruning unlocks sharded
    # candidates (and thus bigger batches) on tight budgets.
    freed_hbm_s_per_byte: float = 4e-12
    # Local-SGD divergence haircut (docs/design/local-sgd.md): each
    # EXTRA local step in an H-step window lets worker copies drift
    # before the averaged merge, which costs statistical efficiency —
    # modeled as (H-1) x bytes x this rate added to the per-step cost
    # of every PS sync entry whose vars ride the window. Calibrated so
    # the H enumeration flips where it should: on a weak-DCN link the
    # H-fold wire amortization (~nbytes x beta_dcn x (1-1/H)) dwarfs
    # the penalty and H in {8,16} wins, while on pure ICI the saved
    # wire (~nbytes x beta_ici) is SMALLER than one extra step's
    # penalty and H=1 stays the winner. Divergence is a per-window
    # statistical cost, not a wall-clock one — pricing it as pseudo-
    # seconds keeps the ranking one-dimensional, exactly like
    # freed_hbm_s_per_byte's exchange rate above.
    local_sgd_divergence_s_per_byte: float = 5e-11
    calibrated: bool = False

    @classmethod
    def from_topology(cls, topology):
        ici_bw, ici_lat = topology.link(cross_node=False)
        dcn_bw, dcn_lat = topology.link(cross_node=True)
        return cls(alpha_ici_s=ici_lat,
                   beta_ici_s_per_byte=1.0 / ici_bw,
                   alpha_dcn_s=dcn_lat,
                   beta_dcn_s_per_byte=1.0 / dcn_bw)

    def link(self, cross_node=False):
        """(α seconds/hop, β seconds/byte) for one link class."""
        if cross_node:
            return self.alpha_dcn_s, self.beta_dcn_s_per_byte
        return self.alpha_ici_s, self.beta_ici_s_per_byte

    def to_dict(self):
        return asdict(self)

    @classmethod
    def from_dict(cls, d):
        import dataclasses
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in known})


def collective_time(kind, nbytes, n, alpha, beta):
    """Predicted seconds for ONE collective of ``nbytes`` wire bytes
    over an ``n``-way group with link constants (α, β).

    Kinds follow the schedule schema: ``all_reduce`` (ring: reduce-
    scatter phase + all-gather phase), ``psum_scatter`` /
    ``sparse_scatter`` (reduce-scatter half), ``all_gather`` /
    ``sparse_all_gather`` (all-gather half).
    """
    n = int(n)
    if n <= 1:
        return 0.0
    nbytes = float(nbytes)
    if kind == 'all_reduce':
        return 2 * (n - 1) * alpha + 2 * (n - 1) / n * nbytes * beta
    if kind in ('psum_scatter', 'all_gather', 'sparse_scatter',
                'sparse_all_gather'):
        return (n - 1) * alpha + (n - 1) / n * nbytes * beta
    raise ValueError('Unknown collective kind %r' % (kind,))


def hierarchical_time(nbytes, n, nodes, params, ici_bytes=None):
    """Predicted seconds for a TWO-LEVEL all-reduce of ``nbytes`` wire
    bytes over ``n`` devices grouped into ``nodes`` node groups of
    ``g = n/nodes`` devices each (PCCL-style process-group synthesis):

    - intra-node reduce-scatter + all-gather: ``2(g-1)`` ICI hops
      moving ``(g-1)/g·B_ici`` each phase,
    - inter-node all-reduce of the owned ``B/g`` chunk over one
      representative per node: ``2(k-1)`` DCN hops at ``2(k-1)/k·B/g``
      bytes,
    - plus the tier-boundary re-layout/requantize HBM pass
      (``hier_boundary_s_per_byte``, charged on the intra-tier bytes).

    ``ici_bytes`` is the byte count the INTRA phases actually move
    when it differs from the cross-node wire: the int8 schedule
    quantizes only at the tier boundary, so its ICI phases ride the
    full f32 payload while the DCN phase rides the int8 wire
    (default: same as ``nbytes``).

    The degenerate shapes collapse to the flat formulas: ``nodes=1``
    is a pure-ICI ring, ``nodes=n`` a pure-DCN ring (plus the
    boundary term, which is why flat stays preferred there).
    """
    n = int(n)
    k = max(1, int(nodes))
    if n <= 1:
        return 0.0
    nbytes = float(nbytes)
    ici = nbytes if ici_bytes is None else float(ici_bytes)
    a_i, b_i = params.link(cross_node=False)
    a_d, b_d = params.link(cross_node=True)
    g = max(1, n // k)
    t = 2.0 * (g - 1) * a_i + 2.0 * (g - 1) / g * ici * b_i
    if k > 1:
        t += 2.0 * (k - 1) * a_d + \
            2.0 * (k - 1) / k * (nbytes / g) * b_d
        t += ici * params.hier_boundary_s_per_byte
    return t


def hierarchical_half_time(nbytes, n, nodes, params, ici_bytes=None):
    """Predicted seconds for ONE two-level HALF (a reduce-scatter or an
    all-gather) over ``n`` devices in ``nodes`` node groups.

    :func:`hierarchical_time` is phase-symmetric (each tier's
    reduce-scatter and all-gather phases move the same bytes, and the
    boundary HBM pass splits evenly between the two halves), so a half
    is exactly half of the full two-level all-reduce — which keeps
    RS + AG == AR, the same identity the flat formulas satisfy, and
    means :func:`choose_hierarchical` is THE decision for halves too:
    flat-half beats hier-half exactly when flat AR beats hier AR.
    Used for the hierarchical ZeRO scatter/gather halves and the
    weight-update-sharding schedule's bucket halves.
    """
    return 0.5 * hierarchical_time(nbytes, n, nodes, params,
                                   ici_bytes=ici_bytes)


#: f32 optimizer-slot tensors per parameter by captured optimizer name
#: (autodist_tpu.frontend.optimizers capture tuples). Used to size the
#: freed-memory credit choose_update_sharding prices; unknown names
#: fall back to the Adam-shaped default (2) — over-estimating the
#: credit merely shards a low-state optimizer's update early, which
#: costs one exposed all-gather, never correctness.
_SLOTS_BY_OPTIMIZER = {
    'SGD': 1, 'GradientDescent': 1, 'Momentum': 1, 'LazyMomentum': 1,
    'Adagrad': 1, 'RMSProp': 2, 'Adadelta': 2,
    'Adam': 2, 'AdamW': 2, 'LazyAdam': 2, 'Nadam': 2, 'Adamax': 2,
    'LAMB': 2, 'Ftrl': 2,
}


def optimizer_slot_count(graph_item, default=2):
    """f32 slot tensors per param for the graph's captured optimizers
    (the max across them — one shared placement serves every var).

    Reads the frontend graph's optimizer capture when present
    (``graph_item.graph.optimizers``); pytree graph items (no captured
    optimizer) and unknown names use ``default``. A plain SGD capture
    with momentum 0 counts 0 (optax.sgd keeps no slot state then).
    """
    g = getattr(graph_item, 'graph', None)
    caps = list(getattr(g, 'optimizers', None) or ()) if g is not None \
        else []
    if not caps:
        return default
    out = 0
    for cap in caps:
        name, _, kwargs = (tuple(cap) + ((), {}))[:3]
        slots = _SLOTS_BY_OPTIMIZER.get(name, default)
        if name in ('SGD', 'GradientDescent') and \
                not (kwargs or {}).get('momentum'):
            slots = 0
        out = max(out, slots)
    return out


def choose_update_sharding(nbytes, dtype, compressor, n, params,
                           knob='never', opt_slots=2, cross_node=False,
                           spec='AUTO'):
    """THE per-bucket replicated-vs-sharded weight-update decision,
    shared by ``plan.sync_gradients`` (trace-time emission and slot
    placement) and ``plan.static_collective_schedule`` (what predict()
    prices) so the predicted and traced schedules can never drift.

    Returns True when the bucket's post-sync optimizer update should
    shard across replicas (reduce-scatter + shard-local fused update +
    bucketed param all-gather, arXiv:2004.13336) instead of running
    replicated after a plain all-reduce. Replicated stays the emission
    (False) on single-replica meshes, compressed wires (the RS/AG
    halves would need the compressor's reduction semantics on both
    phases — only the uncompressed f32/native wire shards), forced
    RING specs (an explicit flat-ring request — RS/AG would drop the
    forced ppermute emission), ``knob='ineligible'`` (sparse-read /
    row-lazy variables: the flat 1/n shard layout cannot preserve
    row-lazy update semantics, so VarPlan marks them ineligible and
    not even the env override shards them), and ``knob='never'`` (the
    legacy default). 'always' forces it; 'auto' shards when the freed
    opt-slot HBM (``opt_slots`` f32 slots x (n-1)/n of the params),
    valued at ``params.freed_hbm_s_per_byte``, outweighs the newly
    *exposed* wire time — the param all-gather runs after the update
    and cannot hide behind backward compute, so the exposure is the
    overlap haircut the replaced all-reduce would have enjoyed (the
    reduce-scatter half stays in the backward and keeps it, which is
    how predict() prices every non-LAST grad bucket). The last-emitted
    grad bucket gets no haircut in either schedule, so for it the true
    exposure delta is zero and this per-bucket decision (which cannot
    know emission position — the same call marks slot placement before
    any trace) overstates the cost: a deliberate conservatism that
    only errs toward the legacy replicated update, and only matters
    for models whose gradients pack into a single bucket ('always'
    overrides it).

    The ``AUTODIST_WEIGHT_UPDATE_SHARDING`` env knob overrides the
    strategy knob globally (it is forwarded to workers: the schedule
    is part of the traced program, and divergent HLO across SPMD
    hosts deadlocks).
    """
    from autodist_tpu.const import ENV
    if (knob or 'never') == 'ineligible':
        return False
    forced = ENV.AUTODIST_WEIGHT_UPDATE_SHARDING.val
    knob = forced or knob or 'never'
    n = int(n)
    if n <= 1 or (compressor or 'NoneCompressor') != 'NoneCompressor':
        return False
    if spec == 'RING' or knob == 'never':
        return False
    if knob == 'always':
        return True
    wb = wire_bytes(nbytes, dtype, compressor)
    alpha, beta = params.link(cross_node=cross_node)
    exposed_extra = params.overlap_discount * 0.5 * collective_time(
        'all_reduce', wb, n, alpha, beta)
    itemsize = np.dtype(dtype).itemsize if dtype is not None else 4
    elems = int(nbytes) // itemsize
    freed = opt_slots * _OPT_SLOT_ITEMSIZE * elems * (n - 1) / n
    return freed * params.freed_hbm_s_per_byte >= exposed_extra


def choose_hierarchical(nbytes, dtype, compressor, n, nodes, params,
                        knob='auto', spec='AUTO'):
    """THE per-bucket flat-vs-two-level decision, shared by
    ``plan.sync_gradients`` (trace-time emission) and
    ``plan.static_collective_schedule`` (what predict() prices) so the
    predicted and traced schedules can never drift.

    Returns True when the bucket should ride the hierarchical
    schedule. Flat stays the emission (False) on single-node meshes
    (``nodes <= 1``), non-dividing group layouts, one-device groups
    (``g == 1`` degenerates to the flat DCN ring), forced RING specs
    (an explicit flat-ring request), and whenever the two-tier α-β
    prediction does not beat the flat ring priced at the DCN link —
    so existing single-node behavior is the degenerate case.
    """
    n = int(n)
    nodes = int(nodes or 0)
    if n <= 1 or nodes <= 1 or n % nodes or n // nodes <= 1:
        return False
    if spec == 'RING' or knob == 'never':
        return False
    if knob == 'always':
        return True
    wb = wire_bytes(nbytes, dtype, compressor)
    # the int8 schedule requantizes ONLY at the tier boundary: its
    # intra-node phases move the full (raw f32) payload on ICI while
    # the DCN phase rides the int8 wire
    ici_b = nbytes if compressor == 'Int8RingCompressor' else wb
    a_d, b_d = params.link(cross_node=True)
    flat = collective_time('all_reduce', wb, n, a_d, b_d)
    return hierarchical_time(wb, n, nodes, params,
                             ici_bytes=ici_b) < flat


#: fallback reasons already warned about this process — the decision
#: is re-made per bucket, and one line per node SHAPE (not per call)
#: is what an operator can read.
_UNEQUAL_WARNED = set()


def _warn_hier_fallback(reason):
    if reason and reason not in _UNEQUAL_WARNED:
        _UNEQUAL_WARNED.add(reason)
        logging.warning('hierarchical schedule falls back to flat: %s',
                        reason)


def num_node_groups_with_reason(strategy=None, resource_spec=None,
                                num_replicas=None):
    """``(k, reason)``: the node-group count plus, when the host layout
    forced the flat fallback, a one-line machine-readable reason naming
    the node shape (e.g. ``unequal-hosts:hostA=4,hostB=2``). ``reason``
    is None whenever the returned count is a genuine hierarchy (or the
    mesh is single-host, where flat is not a degradation). The reason
    rides the static schedule entries (``hier_fallback``) so a priced
    flat win stays distinguishable from a layout that could not go
    two-level — and :mod:`simulator.search` can still synthesize an
    unequal-group IR schedule for exactly these shapes."""
    from autodist_tpu.const import ENV
    forced = ENV.AUTODIST_HIERARCHY_NODES.val
    if forced and forced >= 2:
        n = int(num_replicas or 0)
        if n and n % forced == 0 and n // forced >= 2:
            return forced, None
        return 1, 'forced-nodes:%d does not split n=%d' % (forced, n)
    hosts = []
    replicas = list(strategy.graph_config.replicas) if strategy and \
        strategy.graph_config.replicas else []
    if replicas:
        hosts = [d.rsplit(':', 2)[0] for d in replicas]
    elif resource_spec is not None:
        per_node = resource_spec.node_accelerator_devices or \
            {a: [a] for a in resource_spec.nodes}
        hosts = [h for h, devs in per_node.items() for _ in devs]
    if not hosts:
        return 1, None
    counts = {}
    for h in hosts:
        counts[h] = counts.get(h, 0) + 1
    k = len(counts)
    n = int(num_replicas or len(hosts))
    if k <= 1:
        return 1, None
    shape = ','.join('%s=%d' % (h, c) for h, c in counts.items())
    if len(set(counts.values())) != 1:
        return 1, 'unequal-hosts:%s' % shape
    if n % k:
        return 1, 'replicas:%d not divisible by hosts:%d (%s)' \
            % (n, k, shape)
    return k, None


def num_node_groups(strategy=None, resource_spec=None, num_replicas=None):
    """Node-group count for hierarchical pricing: distinct hosts among
    the strategy's replica devices (the same host-major order the mesh
    builder lays devices out in), falling back to the spec's
    accelerator-bearing node count. Returns 1 (flat) when the layout
    is not an EQUAL split — every host must contribute the same number
    of replica devices and that size must divide the replica count,
    mirroring ``mesh.data_axis_node_groups``'s equal-group requirement
    so pricing never assumes a two-level schedule the trace would
    refuse to emit. The ``AUTODIST_HIERARCHY_NODES`` override takes
    the same precedence it does at trace time — under the override the
    emission groups by it regardless of the spec's host layout, and
    pricing must describe the program that actually runs. A silent
    degrade is indistinguishable from a priced flat win, so the flat
    fallback logs a one-line warning naming the node shape (once per
    shape; :func:`num_node_groups_with_reason` exposes the reason)."""
    k, reason = num_node_groups_with_reason(strategy, resource_spec,
                                            num_replicas)
    _warn_hier_fallback(reason)
    return k


def entry_time(e, n, params, cross_node=False):
    """Predicted seconds (pre-overlap) + wire bytes for ONE schedule
    entry — the per-entry pricing :func:`predict` sums and the
    roofline observatory's drift table compares achieved timings
    against (:mod:`autodist_tpu.telemetry.roofline`), factored out so
    the two can never price the same entry differently.

    Returns ``(seconds, wire_bytes)``. Two-level (``hier``) entries
    ride :func:`hierarchical_time`/:func:`hierarchical_half_time`
    (int8 buckets' intra phases at raw f32 bytes); compressed wires
    pay the cast/quantize HBM passes on top.
    """
    wb = wire_bytes(e['bytes'], e['dtype'], e.get('compressor'))
    hier = int(e.get('hier', 0))
    alpha, beta = params.link(cross_node=cross_node)
    if hier > 1 and e['kind'] == 'all_reduce':
        # two-level schedule: ICI phases + DCN phase + boundary.
        # int8 buckets quantize only at the tier boundary, so
        # their intra phases move the raw f32 bytes on ICI.
        ici_b = e['bytes'] \
            if e.get('compressor') == 'Int8RingCompressor' else wb
        t = hierarchical_time(wb, n, hier, params, ici_bytes=ici_b)
    elif hier > 1 and e['kind'] in ('psum_scatter', 'all_gather'):
        # a two-level ZeRO / update-sharding HALF: exactly half of
        # the two-level all-reduce (phase symmetry), so the same
        # choose_hierarchical decision applies
        t = hierarchical_half_time(wb, n, hier, params)
    else:
        t = collective_time(e['kind'], wb, n, alpha, beta)
    if wb < e['bytes']:   # compressor cast: two HBM passes per end
        t += e['bytes'] * params.compress_s_per_byte
    if e.get('compressor') == 'Int8RingCompressor':
        # block quantization: max-abs scan + scale divide + the
        # ring's per-hop requantization — extra HBM passes
        t += e['bytes'] * params.quant_s_per_byte
    return t, wb


#: schedule-IR tier ladder, fastest link first (mirrors
#: parallel.schedule_ir.TIER_ORDER — kept local to avoid importing the
#: IR module at pricing time).
_IR_TIER_ORDER = {'local': 0, 'ici': 1, 'host': 2, 'dcn': 3}


def program_links(params, links=None):
    """Per-tier ``(α, β)`` link constants for :func:`program_time`.

    Two-link topologies map the IR's four tiers onto the calibrated
    pair: ``ici`` rides the fast link, ``host`` and ``dcn`` the slow
    one, ``local`` is free. A 3-level topology (distinct host- and
    slice-crossing links) passes ``links`` overrides per tier —
    :class:`simulator.search.ScheduleTopo` carries them."""
    out = {'local': (0.0, 0.0),
           'ici': params.link(cross_node=False),
           'host': params.link(cross_node=True),
           'dcn': params.link(cross_node=True)}
    if links:
        out.update(links)
    return out


def program_time(program, params, links=None, per_step=False):
    """Predicted seconds for a schedule-IR :class:`Program`, priced
    per step from the SAME α-β constants :func:`entry_time` uses —
    for the hand-written shapes (flat ring, equal two-level, the
    ZeRO/WUS halves) this reproduces :func:`collective_time` /
    :func:`hierarchical_time` / :func:`hierarchical_half_time`
    exactly, which is what lets synthesized programs rank against
    legacy entries on one scale.

    Per comm step the time is the MAX over its device groups (groups
    run concurrently; the straggler group of an unequal split sets the
    step's pace — waves are separate steps and sum sequentially).
    Each adjacent pair of comm steps on DIFFERENT tiers charges half a
    tier-boundary re-layout pass (``hier_boundary_s_per_byte`` on the
    faster-tier step's bytes — two transitions recover the full
    boundary term of :func:`hierarchical_time`). Requantize steps
    charge the cast HBM passes (plus the quantization passes when an
    int8 wire is involved) at half the per-entry rate each, so a
    down+up pair prices exactly like the compressor charges in
    :func:`entry_time`.

    ``per_step=True`` returns ``(total, [seconds per comm step])`` —
    the list excludes the boundary/requantize overheads (they are
    between-step costs), so ``total >= sum(list)``.
    """
    link = program_links(params, links)
    times = []
    total = 0.0
    prev_tier = None
    prev_nbytes = 0.0
    cur_wire = None
    raw = float(program.meta.get('raw_bytes') or
                program.elems * np.dtype(program.dtype).itemsize)
    for s in program.steps:
        if s.op == 'requantize':
            extra = 0.5 * raw * params.compress_s_per_byte
            if 'i8' in (s.wire, cur_wire):
                extra += 0.5 * raw * params.quant_s_per_byte
            total += extra
            cur_wire = s.wire
            continue
        if s.op not in ('reduce_scatter', 'all_reduce', 'all_gather'):
            continue
        alpha, beta = link[s.tier]
        factor = 2.0 if s.op == 'all_reduce' else 1.0
        t = 0.0
        for g in s.groups:
            gs = len(g)
            if gs <= 1:
                continue
            t = max(t, factor * (gs - 1) * alpha +
                    factor * (gs - 1) / gs * float(s.nbytes) * beta)
        if prev_tier is not None and s.tier != prev_tier:
            # tier boundary: half a re-layout HBM pass per crossing,
            # charged on the faster tier's payload (the buffer that
            # gets re-laid-out lives at the fast tier's width)
            fast = s.nbytes if _IR_TIER_ORDER.get(s.tier, 1) < \
                _IR_TIER_ORDER.get(prev_tier, 1) else prev_nbytes
            total += 0.5 * float(fast) * params.hier_boundary_s_per_byte
        prev_tier, prev_nbytes = s.tier, float(s.nbytes)
        times.append(t)
        total += t
    return (total, times) if per_step else total


def program_tier_bytes(program):
    """Wire bytes a schedule-IR program moves per tier — the
    worst-case single device's traffic (max over each step's groups,
    the figure a link is actually sized against), summed over steps.
    Ring accounting matches :func:`collective_time`: an all-reduce
    moves ``2(g-1)/g`` of its payload, a half moves ``(g-1)/g``."""
    out = {}
    for s in program.steps:
        if s.op not in ('reduce_scatter', 'all_reduce', 'all_gather'):
            continue
        factor = 2.0 if s.op == 'all_reduce' else 1.0
        b = 0.0
        for g in s.groups:
            gs = len(g)
            if gs <= 1:
                continue
            b = max(b, factor * (gs - 1) / gs * float(s.nbytes))
        if b:
            out[s.tier] = out.get(s.tier, 0.0) + b
    return out


def strategy_local_steps(strategy):
    """The program-wide local-SGD window length H a strategy requests:
    the min over its PS synchronizers' ``local_steps`` (mirroring
    ``ExecutionPlan``'s mixed->min collapse — the step is one program,
    so the tightest window applies), 1 when the strategy has no PS
    vars. Legacy strategies (no ``local_steps`` attribute) read 1."""
    hs = []
    for node in strategy.node_config:
        syncs = node.part_config if node.part_config \
            else [node.synchronizer]
        for s in syncs:
            if getattr(s, 'kind', '') == 'PS':
                hs.append(max(1, int(getattr(s, 'local_steps', 1)
                                     or 1)))
    return min(hs) if hs else 1


def _ps_var_names(strategy):
    """Names of variables synced through the PS plane (any shard)."""
    out = set()
    for node in strategy.node_config:
        syncs = node.part_config if node.part_config \
            else [node.synchronizer]
        if any(getattr(s, 'kind', '') == 'PS' for s in syncs):
            out.add(node.var_name)
    return out


def serve_wire_cost(dense_bytes, params=None, replicas=1, poll_hz=2.0,
                    qps=0.0, rows_per_query=0, row_bytes=0,
                    row_cache_hit_rate=0.0, compressor=None,
                    dtype=np.float32):
    """Serve-side wire model of the read-only replica fleet.

    A serving replica costs the training plane exactly its wire
    traffic (it holds no fence, votes in no gate): each replica pulls
    the whole dense model once per accepted poll (``poll_hz``, the
    ``AUTODIST_SERVE_POLL_S`` cadence upper bound — rejected polls
    move counters, not tensors) and the fleet's row-cache MISSES
    (``qps × rows_per_query × (1 − hit_rate)``) fetch embedding rows
    on demand. Both ride the DCN link class — replicas live outside
    the pod.

    Returns a dict: ``snapshot_wire_bytes`` (one pull, after the
    optional wire cast — the bf16/int8 tier halves/quarters the bulk
    pull exactly like a push), ``snapshot_pull_s`` (α-β time of one
    pull), ``snapshot_bytes_per_s`` / ``row_bytes_per_s`` /
    ``serve_bytes_per_s`` (fleet aggregates), and ``dcn_link_frac`` —
    the fraction of ONE DCN link's bandwidth the fleet consumes, the
    number an operator sizes ``replicas × poll_hz`` against so serving
    never eats the training cohort's sync budget.
    """
    params = params or CostModelParams()
    snap_wire = wire_bytes(int(dense_bytes), dtype, compressor)
    pull_s = params.alpha_dcn_s + snap_wire * params.beta_dcn_s_per_byte
    snap_rate = float(replicas) * float(poll_hz) * snap_wire
    miss_rows = float(qps) * float(rows_per_query) \
        * max(0.0, 1.0 - float(row_cache_hit_rate))
    row_rate = miss_rows * wire_bytes(int(row_bytes), dtype, compressor)
    total = snap_rate + row_rate
    return {
        'replicas': int(replicas),
        'snapshot_wire_bytes': snap_wire,
        'snapshot_pull_s': pull_s,
        'snapshot_bytes_per_s': snap_rate,
        'row_bytes_per_s': row_rate,
        'serve_bytes_per_s': total,
        'dcn_link_frac': total * params.beta_dcn_s_per_byte,
    }


@dataclass
class CostReport:
    """Per-strategy prediction: step time, sync decomposition, memory."""
    predicted_step_time_s: float = 0.0
    sync_time_s: float = 0.0           # raw (no-overlap) collective sum
    exposed_sync_time_s: float = 0.0   # after the overlap haircut
    predicted_peak_bytes: int = 0
    num_collectives: int = 0
    num_replicas: int = 1
    cross_node: bool = False
    # local-SGD window length the priced strategy syncs at (H): PS wire
    # terms above are per-STEP averages (the per-round cost / H)
    local_steps: int = 1
    # every priced schedule entry's IR program passed the shape
    # algebra (schedule_ir.verify) — a False here means the prediction
    # priced a schedule that loses or double-counts elements
    schedule_verified: bool = False
    memory: dict = field(default_factory=dict)
    breakdown: list = field(default_factory=list)

    def to_dict(self):
        return asdict(self)

    def summary(self):
        """Compact dict for Strategy.cost / bench records."""
        return {
            'predicted_step_time_s': self.predicted_step_time_s,
            'predicted_peak_bytes': self.predicted_peak_bytes,
            'sync_time_s': self.sync_time_s,
            'num_collectives': self.num_collectives,
            'num_replicas': self.num_replicas,
            'local_steps': self.local_steps,
            'schedule_verified': self.schedule_verified,
        }


def memory_footprint(strategy, graph_item, num_replicas,
                     optimizer_slots=2, schedule=None):
    """Per-device peak-bytes estimate for a strategy.

    Components: params + grads (param dtype), optimizer slots (f32,
    ``optimizer_slots`` per param — 2 for Adam's mu/nu, 1 for momentum
    SGD, 0 for plain SGD), and bucket staging (the largest grad bucket's
    concat input + reduced output live simultaneously). Opt-slot bytes
    are LAYOUT-aware: any variable whose schedule reduce-scatters its
    gradient to a shard owner — ZeRO-sharded (partitioned PS) variables
    AND weight-update-sharded AR buckets — keeps only 1/n of its slot
    (and resident-grad) bytes per device, so budget pruning stops
    rejecting sharded-update configs that actually fit. Every replica
    still materializes the FULL gathered param for compute, which
    params counts at full size.
    """
    n = max(1, int(num_replicas))
    if schedule is None:
        schedule = static_collective_schedule(strategy, graph_item, n)
    sharded = set()
    for e in schedule:
        if e['kind'] in ('psum_scatter', 'sparse_scatter'):
            sharded.update(e['members'])
    params_b = grads_b = opt_b = 0
    for var in graph_item.trainable_var_op_to_var.values():
        itemsize = np.dtype(var.dtype).itemsize
        size = int(np.prod(var.shape or (1,)))
        nbytes = size * itemsize
        frac = 1.0 / n if var.name in sharded and n > 1 else 1.0
        # the gathered full param is live during compute regardless
        params_b += nbytes
        grads_b += int(nbytes * frac)
        opt_b += int(size * _OPT_SLOT_ITEMSIZE * optimizer_slots * frac)
    # staging: a multi-var bucket's concat input + collective output
    # coexist — for the all-reduce buckets AND the update-sharding
    # reduce-scatter buckets (same concat, scattered output)
    max_bucket = max(
        [e['bytes'] for e in schedule
         if e['kind'] in ('all_reduce', 'psum_scatter')
         and e['vars'] > 1] or [0])
    staging_b = 2 * max_bucket
    total = params_b + grads_b + opt_b + staging_b
    return {'params_bytes': params_b, 'grads_bytes': grads_b,
            'optimizer_bytes': opt_b, 'bucket_staging_bytes': staging_b,
            'total_bytes': total}


def predict(strategy, graph_item, resource_spec=None, params=None,
            num_replicas=None, optimizer_slots=2,
            sparse_lookups_per_replica=4096, nodes=None):
    """Price a built strategy: predicted step time + per-device memory.

    Args:
        strategy: a built :class:`Strategy`.
        graph_item: the GraphItem it was built against (only shapes and
            sparsity are read — nothing runs).
        resource_spec: supplies the topology (α-β defaults) and, when
            ``num_replicas`` is not given, the replica count. Optional
            when both ``params`` and ``num_replicas`` are passed.
        params: :class:`CostModelParams` override (e.g. calibrated).
        optimizer_slots: f32 slot tensors per param for the memory
            estimate (2 = Adam, 1 = momentum, 0 = SGD).
        nodes: node-group count for hierarchical (two-level) schedule
            decisions; None derives it from the strategy's replica
            hosts / the spec (``num_node_groups``). 1 forces flat-only
            pricing.

    Returns a :class:`CostReport`.
    """
    if num_replicas is None:
        num_replicas = len(strategy.graph_config.replicas)
        if not num_replicas and resource_spec is not None:
            num_replicas = max(1, resource_spec.num_accelerators)
    n = max(1, int(num_replicas))
    cross_node = False
    if params is None:
        if resource_spec is None:
            raise ValueError('predict() needs resource_spec or params')
        params = CostModelParams.from_topology(resource_spec.topology)
    if resource_spec is not None:
        cross_node = resource_spec.topology.multi_node
    hier_fallback = None
    if nodes is None:
        nodes, hier_fallback = num_node_groups_with_reason(
            strategy, resource_spec, n)
        _warn_hier_fallback(hier_fallback)

    schedule = static_collective_schedule(
        strategy, graph_item, n,
        sparse_lookups_per_replica=sparse_lookups_per_replica,
        nodes=nodes, params=params, hier_fallback=hier_fallback)
    breakdown = []
    sync = 0.0
    # grad-phase buckets that ride the backward: all-reduce buckets
    # AND the update-sharding reduce-scatter halves (the RS replaces
    # an AR bucket in the same backward position, so it keeps the same
    # overlap haircut — the exposure choose_update_sharding assumes:
    # only the param all-gather is newly exposed)
    grad_ar = [i for i, e in enumerate(schedule)
               if e['phase'] == 'grad' and
               (e['kind'] == 'all_reduce' or
                (e.get('wus') and e['kind'] == 'psum_scatter'))]
    last_grad_ar = grad_ar[-1] if grad_ar else -1
    # local-SGD amortization (docs/design/local-sgd.md): PS-synced vars
    # under an H-step window ship once per H steps, so their per-step
    # wire price is the per-round cost / H plus the window-averaging
    # HBM pass (amortized) plus the (H-1)-step divergence haircut.
    # Only entries wholly made of PS vars amortize — AR buckets in a
    # mixed (Parallax-style) strategy still sync every step.
    local_h = strategy_local_steps(strategy)
    ps_vars = _ps_var_names(strategy) if local_h > 1 else set()
    exposed = 0.0
    for i, e in enumerate(schedule):
        t, wb = entry_time(e, n, params, cross_node=cross_node)
        hier = int(e.get('hier', 0))
        # grad buckets before the last-emitted one overlap backward
        # compute; ZeRO scatters are conservatively priced in full.
        # Param-phase traffic (the post-update re-gather — the static
        # analog of the loose-mode next-step pull) takes the optional
        # async-PS haircut so AutoStrategy predictions stay honest for
        # PS strategies once the pipelined data plane hides that wire
        # time (ps_overlap_discount defaults to 0 = serial plane).
        overlappable = (i in grad_ar and i != last_grad_ar)
        if overlappable:
            t_exposed = t * (1.0 - params.overlap_discount)
        elif e['phase'] == 'param' and params.ps_overlap_discount \
                and not e.get('wus'):
            # the weight-update-sharding param all-gather is an
            # in-step SPMD collective after the optimizer update — the
            # async-PS pipeline cannot hide it, so it is priced fully
            # exposed (exactly the exposure choose_update_sharding
            # weighs against the freed memory)
            t_exposed = t * (1.0 - params.ps_overlap_discount)
        else:
            t_exposed = t
        if local_h > 1 and e['members'] and \
                all(m in ps_vars for m in e['members']):
            # per-round wire / H, plus one averaging pass over the
            # window delta (two HBM touches, amortized over the
            # window) and the per-extra-step divergence haircut
            win = e['bytes'] * params.compress_s_per_byte / local_h \
                + (local_h - 1) * e['bytes'] \
                * params.local_sgd_divergence_s_per_byte
            t = t / local_h + win
            t_exposed = t_exposed / local_h + win
        sync += t
        exposed += t_exposed
        breakdown.append({
            'kind': e['kind'], 'phase': e['phase'], 'vars': e['vars'],
            'bytes': e['bytes'], 'wire_bytes': wb,
            'hier': hier, 'wus': bool(e.get('wus')),
            'time_s': t, 'exposed_time_s': t_exposed,
            'members': e['members'][:4] + (
                ['... %d more' % (len(e['members']) - 4)]
                if len(e['members']) > 4 else []),
        })
    mem = memory_footprint(strategy, graph_item, n,
                           optimizer_slots=optimizer_slots,
                           schedule=schedule)
    # re-derive each priced entry's IR program and run the shape
    # algebra on it, so the prediction a strategy is selected by also
    # certifies the schedule moves every element exactly once
    from autodist_tpu.parallel import schedule_ir as _sir
    verified = True
    for e in schedule:
        try:
            if _sir.verify(_sir.entry_program(e, n)):
                verified = False
                break
        except ValueError:
            verified = False
            break
    report = CostReport(
        predicted_step_time_s=params.compute_time_s + exposed,
        sync_time_s=sync,
        exposed_sync_time_s=exposed,
        predicted_peak_bytes=mem['total_bytes'],
        num_collectives=len(schedule),
        num_replicas=n,
        cross_node=cross_node,
        local_steps=local_h,
        schedule_verified=verified,
        memory=mem,
        breakdown=breakdown)
    logging.debug('cost_model.predict: %d collectives, sync=%.3gs '
                  'exposed=%.3gs peak=%dB over n=%d',
                  len(schedule), sync, exposed,
                  mem['total_bytes'], n)
    return report
