"""Analytic α-β cost model for per-variable synchronizer choices.

Grounded in the PCCL formulation (per-process-group collective cost as
α + β·bytes over link latency/bandwidth) and *Automatic Cross-Replica
Sharding of Weight Update in Data-Parallel Training* (when ZeRO-style
reduce-scatter + all-gather beats plain AllReduce):

- ring all-reduce of ``B`` bytes over ``n`` devices:
  ``2(n-1)·α + 2(n-1)/n · B·β``
- reduce-scatter or all-gather (the two ZeRO halves):
  ``(n-1)·α + (n-1)/n · B·β``

α comes from link latency (one hop per ring step), β = 1/bandwidth.
Which (α, β) pair applies — ICI or DCN — comes from the
:class:`~autodist_tpu.resource_spec.Topology` hints: multi-node specs
price collectives at the DCN link (DP reduction is the cross-boundary
traffic; mesh.py keeps everything else on ICI).

The schedule being priced is NOT re-derived here: it is the exact
bucket/chunk layout the execution plan would emit, computed statically
by :func:`autodist_tpu.parallel.plan.static_collective_schedule` — same
packing, same reverse-production ordering, same ZeRO chunking. Grad-sync
buckets other than the final one are assumed to overlap backward compute
(the XLA latency-hiding scheduler the bucketing exists for) and get an
``overlap_discount`` haircut; the last-emitted bucket (the FIRST layers'
gradients, produced when no backward compute is left to hide behind) is
always priced in full.
"""
from dataclasses import dataclass, field, asdict

import numpy as np

from autodist_tpu.parallel.plan import static_collective_schedule
from autodist_tpu.utils import logging

#: Wire bytes per element by compressor (None = tensor's own itemsize).
#: HorovodCompressor casts f32→bf16 for the wire; Int8Ring ships int8
#: blocks plus one f32 scale per AUTODIST_QUANT_BLOCK elements (the
#: scale overhead is added by :func:`wire_bytes`, not folded in here).
#: PowerSGD's wire is rank-dependent and it never fuses — priced at
#: full bytes (None) as a conservative bound. Keys MUST cover the
#: compressor registry in :mod:`autodist_tpu.parallel.compressor`
#: exactly — a compressor missing here would silently price as f32
#: (tools/check_wire_pricing.py is the tier-1 drift check).
_WIRE_ITEMSIZE = {
    'NoneCompressor': None,
    'HorovodCompressor': 2,
    'HorovodCompressorEF': 2,
    'Int8RingCompressor': 1,
    'PowerSGDCompressor': None,
}

#: Grad + optimizer-slot accounting assumptions: gradients match the
#: param dtype; optimizer slots are kept in f32 (optax default).
_OPT_SLOT_ITEMSIZE = 4


def wire_bytes(nbytes, dtype, compressor=None):
    """Bytes that actually cross the wire for a raw ``nbytes`` tensor.

    The block-quantized int8 tier additionally carries one f32 scale
    per ``AUTODIST_QUANT_BLOCK`` elements (the EQuARX blockscale
    header) — at the default block of 256 that is ~1.6% on top of the
    int8 payload, priced here so the 4x headline never overstates."""
    itemsize = np.dtype(dtype).itemsize if dtype is not None else 4
    wire = _WIRE_ITEMSIZE.get(compressor or 'NoneCompressor')
    if wire is None or wire >= itemsize:
        return int(nbytes)
    elems = int(nbytes) // itemsize
    out = elems * wire
    if compressor == 'Int8RingCompressor':
        from autodist_tpu.parallel.compressor import quant_block_size
        out += 4 * (-(-elems // quant_block_size()))
    return out


@dataclass
class CostModelParams:
    """α-β constants (per link class) + overlap/compute assumptions.

    ``alpha_*`` is seconds per ring hop, ``beta_*`` seconds per byte.
    Defaults come from a :class:`Topology`'s bandwidth/latency hints;
    :mod:`calibrate` refines them from measured collective timelines.
    ``compute_time_s`` is an optional calibrated per-step compute
    estimate — 0 means "rank by sync cost alone", which preserves
    ordering (compute is strategy-invariant for a fixed model).
    """
    alpha_ici_s: float = 1e-6
    beta_ici_s_per_byte: float = 1e-11        # 100 GB/s
    alpha_dcn_s: float = 30e-6
    beta_dcn_s_per_byte: float = 8e-9         # 0.125 GB/s
    overlap_discount: float = 0.5             # hidden fraction of
    # overlappable grad-bucket time (latency-hiding scheduler)
    # Async-PS pull-ahead haircut (AUTODIST_PS_PIPELINE_DEPTH >= 2):
    # the fraction of PS param-phase traffic (the post-update re-gather
    # / next-step pull) the background pipeline hides behind the host
    # tail. Default 0 — predictions for the serial depth-1 plane stay
    # unchanged unless the caller opts in (tools/simulate.py
    # --ps-overlap, or a calibrated ps_stats overlap_frac).
    ps_overlap_discount: float = 0.0
    compute_time_s: float = 0.0
    # compressors are not free: the wire cast reads+writes the full
    # tensor at HBM speed on both ends (~800 GB/s, two passes)
    compress_s_per_byte: float = 2.5e-12
    # block quantization costs MORE than a cast: the max-abs scan, the
    # scale divide and the per-hop requantization of the int8 ring are
    # extra HBM passes over the bucket (~2 additional round trips).
    # Added ON TOP of compress_s_per_byte for Int8RingCompressor
    # entries — this is what lets a bandwidth-rich ICI topology
    # correctly REJECT the int8 tier while a DCN-bound one picks it.
    quant_s_per_byte: float = 5.0e-12
    calibrated: bool = False

    @classmethod
    def from_topology(cls, topology):
        ici_bw, ici_lat = topology.link(cross_node=False)
        dcn_bw, dcn_lat = topology.link(cross_node=True)
        return cls(alpha_ici_s=ici_lat,
                   beta_ici_s_per_byte=1.0 / ici_bw,
                   alpha_dcn_s=dcn_lat,
                   beta_dcn_s_per_byte=1.0 / dcn_bw)

    def link(self, cross_node=False):
        """(α seconds/hop, β seconds/byte) for one link class."""
        if cross_node:
            return self.alpha_dcn_s, self.beta_dcn_s_per_byte
        return self.alpha_ici_s, self.beta_ici_s_per_byte

    def to_dict(self):
        return asdict(self)

    @classmethod
    def from_dict(cls, d):
        import dataclasses
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in known})


def collective_time(kind, nbytes, n, alpha, beta):
    """Predicted seconds for ONE collective of ``nbytes`` wire bytes
    over an ``n``-way group with link constants (α, β).

    Kinds follow the schedule schema: ``all_reduce`` (ring: reduce-
    scatter phase + all-gather phase), ``psum_scatter`` /
    ``sparse_scatter`` (reduce-scatter half), ``all_gather`` /
    ``sparse_all_gather`` (all-gather half).
    """
    n = int(n)
    if n <= 1:
        return 0.0
    nbytes = float(nbytes)
    if kind == 'all_reduce':
        return 2 * (n - 1) * alpha + 2 * (n - 1) / n * nbytes * beta
    if kind in ('psum_scatter', 'all_gather', 'sparse_scatter',
                'sparse_all_gather'):
        return (n - 1) * alpha + (n - 1) / n * nbytes * beta
    raise ValueError('Unknown collective kind %r' % (kind,))


@dataclass
class CostReport:
    """Per-strategy prediction: step time, sync decomposition, memory."""
    predicted_step_time_s: float = 0.0
    sync_time_s: float = 0.0           # raw (no-overlap) collective sum
    exposed_sync_time_s: float = 0.0   # after the overlap haircut
    predicted_peak_bytes: int = 0
    num_collectives: int = 0
    num_replicas: int = 1
    cross_node: bool = False
    memory: dict = field(default_factory=dict)
    breakdown: list = field(default_factory=list)

    def to_dict(self):
        return asdict(self)

    def summary(self):
        """Compact dict for Strategy.cost / bench records."""
        return {
            'predicted_step_time_s': self.predicted_step_time_s,
            'predicted_peak_bytes': self.predicted_peak_bytes,
            'sync_time_s': self.sync_time_s,
            'num_collectives': self.num_collectives,
            'num_replicas': self.num_replicas,
        }


def memory_footprint(strategy, graph_item, num_replicas,
                     optimizer_slots=2, schedule=None):
    """Per-device peak-bytes estimate for a strategy.

    Components: params + grads (param dtype), optimizer slots (f32,
    ``optimizer_slots`` per param — 2 for Adam's mu/nu, 1 for momentum
    SGD, 0 for plain SGD), and bucket staging (the largest grad bucket's
    concat input + reduced output live simultaneously). ZeRO-sharded
    (partitioned PS) variables count 1/n of their padded size for state
    components; every replica still materializes the FULL gathered param
    for compute, which params counts at full size.
    """
    n = max(1, int(num_replicas))
    if schedule is None:
        schedule = static_collective_schedule(strategy, graph_item, n)
    sharded = set()
    for e in schedule:
        if e['kind'] in ('psum_scatter', 'sparse_scatter'):
            sharded.update(e['members'])
    params_b = grads_b = opt_b = 0
    for var in graph_item.trainable_var_op_to_var.values():
        itemsize = np.dtype(var.dtype).itemsize
        size = int(np.prod(var.shape or (1,)))
        nbytes = size * itemsize
        frac = 1.0 / n if var.name in sharded and n > 1 else 1.0
        # the gathered full param is live during compute regardless
        params_b += nbytes
        grads_b += int(nbytes * frac)
        opt_b += int(size * _OPT_SLOT_ITEMSIZE * optimizer_slots * frac)
    max_bucket = max(
        [e['bytes'] for e in schedule
         if e['kind'] == 'all_reduce' and e['vars'] > 1] or [0])
    staging_b = 2 * max_bucket
    total = params_b + grads_b + opt_b + staging_b
    return {'params_bytes': params_b, 'grads_bytes': grads_b,
            'optimizer_bytes': opt_b, 'bucket_staging_bytes': staging_b,
            'total_bytes': total}


def predict(strategy, graph_item, resource_spec=None, params=None,
            num_replicas=None, optimizer_slots=2,
            sparse_lookups_per_replica=4096):
    """Price a built strategy: predicted step time + per-device memory.

    Args:
        strategy: a built :class:`Strategy`.
        graph_item: the GraphItem it was built against (only shapes and
            sparsity are read — nothing runs).
        resource_spec: supplies the topology (α-β defaults) and, when
            ``num_replicas`` is not given, the replica count. Optional
            when both ``params`` and ``num_replicas`` are passed.
        params: :class:`CostModelParams` override (e.g. calibrated).
        optimizer_slots: f32 slot tensors per param for the memory
            estimate (2 = Adam, 1 = momentum, 0 = SGD).

    Returns a :class:`CostReport`.
    """
    if num_replicas is None:
        num_replicas = len(strategy.graph_config.replicas)
        if not num_replicas and resource_spec is not None:
            num_replicas = max(1, resource_spec.num_accelerators)
    n = max(1, int(num_replicas))
    cross_node = False
    if params is None:
        if resource_spec is None:
            raise ValueError('predict() needs resource_spec or params')
        params = CostModelParams.from_topology(resource_spec.topology)
    if resource_spec is not None:
        cross_node = resource_spec.topology.multi_node
    alpha, beta = params.link(cross_node=cross_node)

    schedule = static_collective_schedule(
        strategy, graph_item, n,
        sparse_lookups_per_replica=sparse_lookups_per_replica)
    breakdown = []
    sync = 0.0
    grad_ar = [i for i, e in enumerate(schedule)
               if e['kind'] == 'all_reduce' and e['phase'] == 'grad']
    last_grad_ar = grad_ar[-1] if grad_ar else -1
    exposed = 0.0
    for i, e in enumerate(schedule):
        wb = wire_bytes(e['bytes'], e['dtype'], e.get('compressor'))
        t = collective_time(e['kind'], wb, n, alpha, beta)
        if wb < e['bytes']:   # compressor cast: two HBM passes per end
            t += e['bytes'] * params.compress_s_per_byte
        if e.get('compressor') == 'Int8RingCompressor':
            # block quantization: max-abs scan + scale divide + the
            # ring's per-hop requantization — extra HBM passes
            t += e['bytes'] * params.quant_s_per_byte
        # grad buckets before the last-emitted one overlap backward
        # compute; ZeRO scatters are conservatively priced in full.
        # Param-phase traffic (the post-update re-gather — the static
        # analog of the loose-mode next-step pull) takes the optional
        # async-PS haircut so AutoStrategy predictions stay honest for
        # PS strategies once the pipelined data plane hides that wire
        # time (ps_overlap_discount defaults to 0 = serial plane).
        overlappable = (i in grad_ar and i != last_grad_ar)
        if overlappable:
            t_exposed = t * (1.0 - params.overlap_discount)
        elif e['phase'] == 'param' and params.ps_overlap_discount:
            t_exposed = t * (1.0 - params.ps_overlap_discount)
        else:
            t_exposed = t
        sync += t
        exposed += t_exposed
        breakdown.append({
            'kind': e['kind'], 'phase': e['phase'], 'vars': e['vars'],
            'bytes': e['bytes'], 'wire_bytes': wb,
            'time_s': t, 'exposed_time_s': t_exposed,
            'members': e['members'][:4] + (
                ['... %d more' % (len(e['members']) - 4)]
                if len(e['members']) > 4 else []),
        })
    mem = memory_footprint(strategy, graph_item, n,
                           optimizer_slots=optimizer_slots,
                           schedule=schedule)
    report = CostReport(
        predicted_step_time_s=params.compute_time_s + exposed,
        sync_time_s=sync,
        exposed_sync_time_s=exposed,
        predicted_peak_bytes=mem['total_bytes'],
        num_collectives=len(schedule),
        num_replicas=n,
        cross_node=cross_node,
        memory=mem,
        breakdown=breakdown)
    logging.debug('cost_model.predict: %d collectives, sync=%.3gs '
                  'exposed=%.3gs peak=%dB over n=%d',
                  len(schedule), sync, exposed,
                  mem['total_bytes'], n)
    return report
