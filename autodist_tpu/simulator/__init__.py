"""Strategy simulator: analytic cost model + candidate search + calibration.

The reference paper's value proposition is *automatic* strategy
synthesis; upstream AutoDist ships a ``simulator/`` package that prices
candidate strategies before running any of them. This package is the
TPU-native equivalent:

- :mod:`cost_model` — α-β collective pricing per variable (ring
  AllReduce, ZeRO reduce-scatter+all-gather, partitioned AR) from tensor
  bytes, compressor wire dtype, the bucket layout the execution plan
  would emit (``parallel.plan.static_collective_schedule``), and the
  ICI/DCN bandwidth+latency hints in :class:`ResourceSpec`'s topology;
  plus a per-device memory footprint estimate (params, grads, optimizer
  state, bucket staging).
- :mod:`search` — candidate enumeration over the strategy builders (and
  their chunk_size / partition knobs) with memory-budget pruning,
  returning ranked ``(Strategy, predicted_step_time, peak_bytes)``.
- :mod:`calibrate` — optional measured mode refining the α-β constants
  from a ``profiling.collective_timeline`` of a short real run.

The user-facing entry points are ``strategy.builders.AutoStrategy`` (the
tenth builder — calls the simulator inside ``build()``) and
``tools/simulate.py`` (prints the ranked table without running anything).
"""
from autodist_tpu.simulator.cost_model import (  # noqa: F401
    CostModelParams, CostReport, collective_time, memory_footprint,
    predict, wire_bytes)
from autodist_tpu.simulator.search import (  # noqa: F401
    Candidate, default_candidates, rank)
from autodist_tpu.simulator.calibrate import (  # noqa: F401
    calibrate_from_timeline, calibrate_from_trace, fit_alpha_beta,
    samples_from_timeline)
