"""Collective group/instance keys (reference collective_key.py:43-70).

XLA assigns channel ids automatically, so keys are not needed for
correctness on TPU; the registry is kept because (a) strategy protos
carry ``group`` ids that must be stable and content-addressed across
independently-lowering workers (every worker re-derives the same fused
buckets, SURVEY.md §1 "every worker independently re-runs the full
transformation"), and (b) the DSL plan uses group keys to order fused
flat-bucket collectives deterministically.
"""
import hashlib
import threading

from autodist_tpu.const import MAX_INT32


class CollectiveKey:
    """Thread-safe singleton: group keys per device-set, instance keys
    content-addressed by variable name (md5 mod int32)."""

    _instance = None
    _lock = threading.Lock()

    def __new__(cls):
        if cls._instance is None:
            with cls._lock:
                if cls._instance is None:
                    inst = super().__new__(cls)
                    inst._group_keys = {}
                    inst._next_group = 1
                    inst._mu = threading.Lock()
                    cls._instance = inst
        return cls._instance

    def group_key(self, devices):
        """Stable int key for a device set (incrementing per new set)."""
        canon = tuple(sorted(str(d) for d in devices))
        with self._mu:
            if canon not in self._group_keys:
                self._group_keys[canon] = self._next_group
                self._next_group += 1
            return self._group_keys[canon]

    @staticmethod
    def instance_key(var_name):
        """Content-addressed per-variable key: md5(name) mod int32."""
        digest = hashlib.md5(var_name.encode()).hexdigest()
        return int(digest, 16) % MAX_INT32

    @classmethod
    def _reset_for_testing(cls):
        with cls._lock:
            cls._instance = None
