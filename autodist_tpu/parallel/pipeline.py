"""Pipeline parallelism: GPipe and 1F1B microbatch schedules over the
``pipe`` axis.

Absent from the reference (SURVEY.md §2.3: PP = No). TPU-native design:
the repeated transformer blocks are parameter-stacked along a leading
``stage`` axis which shards over the ``pipe`` mesh axis; inside a manual
shard_map region each pipe rank scans its local layer shard, and
activations hop stage-to-stage with ``ppermute``.

Two schedules:

- :func:`gpipe` — fill/drain schedule, differentiated by autodiff's
  reverse scan. Simple and composes with anything, but the backward
  starts only after every microbatch's forward: all ``M`` microbatches'
  residuals are live at the fwd/bwd boundary (the GPipe memory profile).
- :func:`one_f_one_b` with ``tail_params`` — a REAL 1F1B: a
  ``jax.custom_vjp`` with a hand-written interleaved backward. The
  head/loss folds into the last stage (``tail_fn``) and the embedding
  into the first (``head_fn``). Two variants of the backward
  (``variant=``, default ``'auto'``):

  * ``'remat'`` — the forward saves NO activations; the backward
    re-runs the forward chain and interleaves one recompute-vjp per
    step. A rank's live working set is a circular stash of at most
    ``2(pp-1)+1`` microbatch activations — bounded by the pipe depth,
    independent of ``M``; no full-batch ``[B, s, d]`` activation,
    logits slab, or input cotangent ever materializes. Cost: a step is
    ~3 forward + 1 backward block passes.
  * ``'stash'`` — the forward stashes each microbatch's stack INPUT
    (one boundary activation per microbatch: a single ``[B, ...]``
    hidden slab per rank, still far below GPipe's per-layer
    residuals), and the backward skips the chain re-forward — one
    vjp-internal recompute only, ~2 forward + 1 backward passes.
  * ``'auto'`` — ``'stash'`` while the stash fits
    ``AUTODIST_PP_STASH_LIMIT_MB`` (default 2048) per rank, else
    ``'remat'``: trade the memory bound for the faster step whenever
    memory allows.

Delivery is collective-clean: microbatch inputs ride a backward-rotating
ppermute relay register (owner ``j % pp`` sits that many backward hops
from stage 0; every rank injects its next owned microbatch each ``pp``
steps) — one mb-sized hop per link per step, replacing the round-3
masked-``psum`` delivery that moved ~pp× the bytes. ``M % pp`` may be
ragged: residency slots are padded and masked.

Fill/drain efficiency: rank r holds a *valid* microbatch only for
schedule steps t in [r, r+M); outside that window block compute is
skipped via ``lax.cond`` (a real XLA conditional — ``rank``/``t`` are
runtime values inside the manual region), so the inherent bubble
(fraction (pp-1)/(M+pp-1)) idles instead of burning FLOPs.
"""
import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from autodist_tpu.parallel.axes import axis_size


def _ceil_div(a, b):
    return -(-a // b)


def _local_stack_fn(block_fn):
    """(params_stack, h) -> (h, summed aux) over this rank's layers."""
    def local_stack(stacked_params, h):
        def body(c, p):
            h, aux = c
            h, a = block_fn(p, h)
            return (h, aux + a.astype(jnp.float32)), None
        (h, aux), _ = lax.scan(body, (h, jnp.zeros((), jnp.float32)),
                               stacked_params)
        return h, aux
    return local_stack


def _own_slices(arr_mb, rank, pp, share, M):
    """Round-robin residency: this rank's owned microbatches (padded to
    ``share`` slots; slots past M alias the last valid one and are
    masked by schedule validity)."""
    idx = jnp.clip(jnp.arange(share) * pp + rank, 0, M - 1)
    return jnp.take(arr_mb, idx, axis=0)


def _inject(own, reg, t, share, pp):
    """Relay injection: at steps t % pp == 0 every rank loads its next
    owned microbatch into the rotating register."""
    slot = jnp.clip(t // pp, 0, share - 1)
    fresh = lax.dynamic_index_in_dim(own, slot, 0, keepdims=False)
    return jnp.where(jnp.equal(jnp.mod(t, pp), 0), fresh, reg)


def _back_rotation(pp):
    """Full backward rotation (toward stage 0): one relay hop/step."""
    return [(i, (i - 1) % pp) for i in range(pp)]


def _reassemble(own_out, axis_name, pp, share, mb, M, B):
    """all_gather each rank's owned outputs and restore microbatch
    order j = slot*pp + rank; slice off residency padding."""
    gathered = lax.all_gather(own_out, axis_name)   # [pp, share, mb,...]
    out = jnp.moveaxis(gathered, 0, 1)              # [share, pp, mb,...]
    out = out.reshape((share * pp * mb,) + out.shape[3:])
    return out[:B]


def _scatter_own(own_out, rank, pp, share, mb, B):
    """Per-rank [B, ...] layout of this rank's owned outputs (zeros on
    other ranks' rows): ``psum`` of this across the pipe axis is the
    reassembled batch. Used so the cross-rank collection happens
    OUTSIDE the fused schedule's custom_vjp — the trailing psum's own
    transpose then delivers the full output cotangent to every rank's
    hand-written backward regardless of the boundary's
    replicated-output cotangent convention (a custom_vjp that
    all_gathers internally silently received 1/pp-scaled cotangents
    under shard_map check_vma=False)."""
    buf = jnp.zeros((share, pp) + own_out.shape[1:], own_out.dtype)
    buf = lax.dynamic_update_index_in_dim(
        buf, own_out, rank, 1)
    out = buf.reshape((share * pp * mb,) + own_out.shape[2:])
    return out[:B]


def gpipe(block_fn, stacked_params, x, axis_name, microbatches):
    """Run a stage-sharded layer stack as a GPipe pipeline.

    Must be called inside a shard_map region manual over ``axis_name``.

    Args:
        block_fn: ``block_fn(layer_params, h) -> (h, aux)`` single-block
            apply; ``aux`` is a scalar auxiliary loss contribution (e.g.
            MoE router balance) summed over layers.
        stacked_params: pytree with local leading dim = layers_per_stage.
        x: [batch, ...] full activation batch (replicated over the pipe
            axis — every rank holds it; only rank 0's copy is consumed).
        axis_name: the pipe mesh axis.
        microbatches: M, the microbatch count (batch must divide by M).

    Returns:
        ``(out, aux)``: [batch, ...] final activations and the scalar aux
        loss (mean over microbatches, summed over all stages' layers),
        both replicated over the pipe axis.

    MoE note: under pipelining the router's balance statistics are
    computed per MICROBATCH (each microbatch is a routing group, the
    GShard grouping — same principle as per-seq-shard groups under SP),
    so for microbatches > 1 the aux term is the mean of per-group losses
    rather than one full-batch statistic. The two coincide at
    microbatches=1 (pinned by test_moe_aux_loss_kept_under_pipelining);
    beyond that the objective is the grouped one, by design.
    """
    pp = axis_size(axis_name)
    rank = lax.axis_index(axis_name)
    B = x.shape[0]
    M = int(microbatches)
    assert B % M == 0, 'batch %d not divisible by microbatches %d' % (B, M)
    mb = B // M
    xs = x.reshape(M, mb, *x.shape[1:])
    stack = _local_stack_fn(block_fn)

    if pp == 1:
        return stack(stacked_params, x)

    fwd_perm = [(i, i + 1) for i in range(pp - 1)]

    def step(carry, t):
        state, buf, aux_acc = carry
        # stage 0 consumes microbatch t (clamped in the drain phase);
        # other stages consume what the previous stage sent
        mb_idx = jnp.clip(t, 0, M - 1)
        first_in = lax.dynamic_index_in_dim(xs, mb_idx, 0, keepdims=False)
        inp = jnp.where(rank == 0, first_in, state)
        # rank r holds valid work only for t in [r, r+M): skip the block
        # compute in the fill/drain bubble instead of processing garbage
        valid = jnp.logical_and(t >= rank, t < rank + M)
        out, aux = lax.cond(
            valid, lambda h: stack(stacked_params, h),
            lambda h: (h, jnp.zeros((), jnp.float32)), inp)
        aux_acc = aux_acc + aux
        # last stage records microbatch t-(pp-1) once the pipe is full
        out_idx = jnp.clip(t - (pp - 1), 0, M - 1)
        ready = jnp.logical_and(rank == pp - 1, t >= pp - 1)
        prev = lax.dynamic_index_in_dim(buf, out_idx, 0, keepdims=False)
        buf = lax.dynamic_update_index_in_dim(
            buf, jnp.where(ready, out, prev), out_idx, 0)
        nxt = lax.ppermute(out, axis_name, fwd_perm)
        return (nxt, buf, aux_acc), None

    state = jnp.zeros((mb,) + x.shape[1:], x.dtype)
    buf = jnp.zeros((M, mb) + x.shape[1:], x.dtype)
    (_, buf, aux_acc), _ = lax.scan(
        step, (state, buf, jnp.zeros((), jnp.float32)),
        jnp.arange(M + pp - 1))
    out = buf.reshape(B, *x.shape[1:])
    # broadcast the last stage's result to every rank (the head/loss run
    # replicated over pipe): mask + psum
    out = lax.psum(
        jnp.where(rank == pp - 1, out, jnp.zeros_like(out)), axis_name)
    # aux: every stage accumulated its local layers' contribution for the
    # M valid microbatches; sum stages, average microbatches
    aux = lax.psum(aux_acc, axis_name) / M
    return out, aux


def one_f_one_b(block_fn, stacked_params, x, axis_name, microbatches,
                tail_fn=None, extra=None, tail_params=None,
                head_fn=None, head_params=None, variant='auto'):
    """1F1B schedule with per-rank microbatch residency.

    Same fill/steady/drain forward timing as :func:`gpipe` (the forward
    bubble is inherent); the memory contract differs — full-batch
    activations never live across the schedule. Two modes:

    - **fused (pass ``tail_params``)** — the real 1F1B: a custom-vjp
      with a hand-written interleaved backward (see the module
      docstring for the ``variant`` trade: ``'remat'`` bounds each
      rank's live activations at a ``2(pp-1)+1``-slot circular stash,
      ``'stash'`` saves one boundary activation per microbatch and
      skips the chain re-forward, ``'auto'`` picks ``'stash'`` while
      it fits ``AUTODIST_PP_STASH_LIMIT_MB``). Fold
      the head + loss into ``tail_fn(tail_params, h, extra_mb)`` (runs
      on the last stage per microbatch) and the embedding into
      ``head_fn(head_params, x_mb)`` (first stage) so the region's
      inputs/outputs are token-sized, not activation-sized. Gradients
      flow to ``stacked_params`` (local stage shard), ``tail_params``
      and ``head_params`` (replicated via psum), and to a floating
      ``x``. ``M % pp`` may be ragged.
    - **legacy (no ``tail_params``)** — forward schedule differentiated
      by autodiff's reverse scan; per-step residuals are
      microbatch-sized but all ``M + pp - 1`` of them are live at the
      fwd/bwd boundary. ``tail_fn(h, extra_mb)`` here CLOSES OVER its
      params (autodiff sees through the closure).

    Inputs ride a backward-rotating ppermute relay (one mb hop per link
    per step); only the small per-microbatch tail outputs use masked
    psum delivery to their owner rank.
    """
    pp = axis_size(axis_name)
    M = int(microbatches)
    stack = _local_stack_fn(block_fn)

    if pp == 1:
        if head_fn is not None:
            x = head_fn(head_params, x)
        h, aux = stack(stacked_params, x)
        if tail_fn is not None:
            h = tail_fn(tail_params, h, extra) if tail_params is not None \
                else tail_fn(h, extra)
        return h, aux

    if tail_params is not None or head_params is not None:
        if tail_fn is not None and tail_params is None:
            raise ValueError(
                'fused 1F1B (head_params given) needs the param-explicit '
                'tail convention: pass tail_params with '
                'tail_fn(tail_params, h, extra_mb) — a closure-style '
                'tail_fn(h, extra) would silently lose its parameter '
                'gradients')
        return _fused_1f1b(block_fn, stacked_params, x, axis_name, M,
                           tail_fn, extra, tail_params, head_fn,
                           head_params, variant)
    if head_fn is not None:
        # the legacy schedule has no head slot; silently skipping it
        # would diverge from the pp==1 branch above
        raise ValueError(
            'head_fn requires the fused 1F1B mode: pass head_params '
            '(and tail_params if a tail_fn is used)')
    return _legacy_1f1b(block_fn, stacked_params, x, axis_name, M,
                        tail_fn, extra)


def _legacy_1f1b(block_fn, stacked_params, x, axis_name, M, tail_fn,
                 extra):
    """Autodiff-through-the-scan 1F1B memory profile (see
    :func:`one_f_one_b`)."""
    pp = axis_size(axis_name)
    rank = lax.axis_index(axis_name)
    B = x.shape[0]
    assert B % M == 0, 'batch %d not divisible by microbatches %d' % (B, M)
    mb = B // M
    share = _ceil_div(M, pp)
    stack = _local_stack_fn(block_fn)

    def to_mb(a):
        return a.reshape(M, mb, *a.shape[1:])

    own_in = _own_slices(to_mb(x), rank, pp, share, M)
    own_extra = None if extra is None else \
        _own_slices(to_mb(extra), rank, pp, share, M)
    fwd_perm = [(i, i + 1) for i in range(pp - 1)]
    back_rot = _back_rotation(pp)
    zero_h = jnp.zeros((mb,) + x.shape[1:], x.dtype)
    zero_e = None if extra is None else \
        jnp.zeros((mb,) + extra.shape[1:], extra.dtype)

    def tail(h, e):
        return h if tail_fn is None else tail_fn(h, e)

    out_shape = jax.eval_shape(tail, zero_h, zero_e)
    zero_out = jnp.zeros(out_shape.shape, out_shape.dtype)

    def step(carry, t):
        reg_x, reg_e, state_h, state_e, own_out, aux_acc = carry
        # input relay: every pp steps each rank injects its next owned
        # microbatch; one backward hop per step delivers one microbatch
        # per step to stage 0
        reg_x = _inject(own_in, reg_x, t, share, pp)
        if extra is not None:
            reg_e = _inject(own_extra, reg_e, t, share, pp)
        inp_h = jnp.where(rank == 0, reg_x, state_h)
        inp_e = None if extra is None else \
            jnp.where(rank == 0, reg_e, state_e)
        valid = jnp.logical_and(t >= rank, t - rank < M)
        h, aux = lax.cond(
            valid, lambda v: stack(stacked_params, v),
            lambda v: (v, jnp.zeros((), jnp.float32)), inp_h)
        aux_acc = aux_acc + aux
        # the last stage's per-microbatch tail (head/loss when folded)
        # runs UNCONDITIONALLY and is masked after: rank-divergent conds
        # around code with sharding constraints deadlock when the
        # partitioner inserts resharding collectives in one branch only
        # (the full-batch head this replaces also ran on every rank)
        j = t - (pp - 1)
        is_out = jnp.logical_and(rank == pp - 1,
                                 jnp.logical_and(j >= 0, j < M))
        out_val = tail(h, inp_e)
        # output delivery: microbatch j leaves the last stage this step
        # (masked psum of the SMALL tail output)
        done = lax.psum(jnp.where(is_out, out_val, zero_out), axis_name)
        take = jnp.logical_and(jnp.logical_and(j >= 0, j < M),
                               jnp.mod(j, pp) == rank)
        slot_out = jnp.clip(j // pp, 0, share - 1)
        prev = lax.dynamic_index_in_dim(own_out, slot_out, 0,
                                        keepdims=False)
        own_out = lax.dynamic_update_index_in_dim(
            own_out, jnp.where(take, done, prev), slot_out, 0)
        nxt_h = lax.ppermute(h, axis_name, fwd_perm)
        nxt_e = None if extra is None else \
            lax.ppermute(inp_e, axis_name, fwd_perm)
        reg_x = lax.ppermute(reg_x, axis_name, back_rot)
        if extra is not None:
            reg_e = lax.ppermute(reg_e, axis_name, back_rot)
        return (reg_x, reg_e, nxt_h, nxt_e, own_out, aux_acc), None

    own_out = jnp.zeros((share,) + zero_out.shape, zero_out.dtype)
    carry0 = (zero_h, zero_e, zero_h, zero_e, own_out,
              jnp.zeros((), jnp.float32))
    (_, _, _, _, own_out, aux_acc), _ = lax.scan(
        step, carry0, jnp.arange(M + pp - 1))
    out = _reassemble(own_out, axis_name, pp, share, mb, M, B)
    aux = lax.psum(aux_acc, axis_name) / M
    return out, aux


def _fused_1f1b(block_fn, stacked_params, x, axis_name, M, tail_fn,
                extra, tail_params, head_fn, head_params,
                variant='auto'):
    """Custom-vjp 1F1B (see :func:`one_f_one_b`).

    ``variant='remat'``: forward saves NO activations; the backward
    re-runs the forward chain and interleaves one recompute-vjp per
    step, stash bounded at ``2(pp-1)+1`` microbatches per rank.
    ``variant='stash'``: forward saves each microbatch's stack-input
    boundary activation ([M, mb, ...] per rank — one full-batch hidden
    slab); the backward indexes the stash directly (no chain
    re-forward, no relay), paying only the vjp-internal recompute.
    ``'auto'`` resolves to 'stash' while the stash fits
    ``AUTODIST_PP_STASH_LIMIT_MB`` per rank."""
    pp = axis_size(axis_name)
    B = x.shape[0]
    assert B % M == 0, 'batch %d not divisible by microbatches %d' % (B, M)
    mb = B // M
    share = _ceil_div(M, pp)
    stack = _local_stack_fn(block_fn)
    if tail_params is None:
        tail_params = {}
    if head_params is None:
        head_params = {}
    if tail_fn is None:
        tail_fn = lambda tp, h, e: h           # noqa: E731
    have_head = head_fn is not None
    if head_fn is None:
        head_fn = lambda hp, v: v              # noqa: E731
    # extra always present internally (dummy keeps the schedule uniform)
    have_extra = extra is not None
    if not have_extra:
        extra = jnp.zeros((B, 1), jnp.int32)
    elif jnp.issubdtype(jnp.asarray(extra).dtype, jnp.inexact):
        # the hand-written backward does not propagate d(extra) (the
        # tail cotangent for it is discarded); int targets — the lm/
        # classification case — have no cotangent, but a float extra
        # (soft labels, distillation targets) would silently train with
        # d(extra)=0. Refuse rather than diverge from the legacy path.
        raise ValueError(
            'fused 1F1B does not backpropagate into a floating-point '
            "`extra` stream; use integer targets or the legacy "
            'schedule (no tail_params)')
    x_differentiable = jnp.issubdtype(jnp.asarray(x).dtype, jnp.inexact)

    if variant not in ('auto', 'remat', 'stash'):
        raise ValueError('unknown 1F1B variant %r' % (variant,))
    if variant == 'auto':
        from autodist_tpu.const import ENV
        probe = jax.eval_shape(
            lambda v: head_fn(head_params, v),
            jax.ShapeDtypeStruct((mb,) + x.shape[1:],
                                 jnp.asarray(x).dtype))
        stash_bytes = M * int(np.prod(probe.shape)) * probe.dtype.itemsize
        limit = ENV.AUTODIST_PP_STASH_LIMIT_MB.val * (1 << 20)
        variant = 'stash' if stash_bytes <= limit else 'remat'

    fwd_perm = [(i, i + 1) for i in range(pp - 1)]
    rev_perm = [(i, i - 1) for i in range(1, pp)]
    back_rot = _back_rotation(pp)

    def zero_ct(v):
        """Cotangent for a possibly-integer primal leaf."""
        v = jnp.asarray(v)
        if jnp.issubdtype(v.dtype, jnp.inexact):
            return jnp.zeros_like(v)
        return np.zeros(v.shape, jax.dtypes.float0)

    def run_forward(sp, tp, hp, x_, e_, with_stash=False):
        rank = lax.axis_index(axis_name)
        xs = x_.reshape(M, mb, *x_.shape[1:])
        es = e_.reshape(M, mb, *e_.shape[1:])
        own_x = _own_slices(xs, rank, pp, share, M)
        own_e = _own_slices(es, rank, pp, share, M)
        zero_x = jnp.zeros_like(own_x[0])
        zero_e = jnp.zeros_like(own_e[0])
        h_shape = jax.eval_shape(lambda v: head_fn(hp, v), zero_x)
        zero_h = jnp.zeros(h_shape.shape, h_shape.dtype)
        out_shape = jax.eval_shape(lambda h, e: tail_fn(tp, h, e),
                                   zero_h, zero_e)
        zero_out = jnp.zeros(out_shape.shape, out_shape.dtype)

        def step(carry, t):
            reg_x, reg_e, state_h, state_e, own_out, aux_acc, stash = \
                carry
            reg_x = _inject(own_x, reg_x, t, share, pp)
            reg_e = _inject(own_e, reg_e, t, share, pp)
            # first stage embeds its incoming microbatch (head folded
            # in). head/tail run UNCONDITIONALLY and mask after: a
            # rank-divergent cond around code with sharding constraints
            # deadlocks when the partitioner inserts resharding
            # collectives in one branch only (found by the 8-device
            # dp4xpp2 dryrun); only the bare block stack may sit under
            # the validity cond.
            inp_h = jnp.where(rank == 0, head_fn(hp, reg_x), state_h)
            inp_e = jnp.where(rank == 0, reg_e, state_e)
            valid = jnp.logical_and(t >= rank, t - rank < M)
            if with_stash:
                # stash-variant: keep this microbatch's stack INPUT for
                # the backward (j = t - rank is the microbatch this
                # rank processes at step t)
                j_w = jnp.clip(t - rank, 0, M - 1)
                prev_s = lax.dynamic_index_in_dim(stash, j_w, 0,
                                                  keepdims=False)
                stash = lax.dynamic_update_index_in_dim(
                    stash, jnp.where(valid, inp_h, prev_s), j_w, 0)
            h, aux = lax.cond(
                valid, lambda v: stack(sp, v),
                lambda v: (v, jnp.zeros((), jnp.float32)), inp_h)
            aux_acc = aux_acc + aux
            j = t - (pp - 1)
            is_out = jnp.logical_and(rank == pp - 1,
                                     jnp.logical_and(j >= 0, j < M))
            out_val = tail_fn(tp, h, inp_e)
            done = lax.psum(jnp.where(is_out, out_val, zero_out),
                            axis_name)
            take = jnp.logical_and(jnp.logical_and(j >= 0, j < M),
                                   jnp.mod(j, pp) == rank)
            slot_out = jnp.clip(j // pp, 0, share - 1)
            prev = lax.dynamic_index_in_dim(own_out, slot_out, 0,
                                            keepdims=False)
            own_out = lax.dynamic_update_index_in_dim(
                own_out, jnp.where(take, done, prev), slot_out, 0)
            nxt_h = lax.ppermute(h, axis_name, fwd_perm)
            nxt_e = lax.ppermute(inp_e, axis_name, fwd_perm)
            reg_x = lax.ppermute(reg_x, axis_name, back_rot)
            reg_e = lax.ppermute(reg_e, axis_name, back_rot)
            return (reg_x, reg_e, nxt_h, nxt_e, own_out, aux_acc,
                    stash), None

        own_out = jnp.zeros((share,) + zero_out.shape, zero_out.dtype)
        stash0 = jnp.zeros((M,) + zero_h.shape, zero_h.dtype) \
            if with_stash else jnp.zeros((1, 1))
        carry0 = (zero_x, zero_e, zero_h, zero_e, own_out,
                  jnp.zeros((), jnp.float32), stash0)
        (_, _, _, _, own_out, aux_acc, stash), _ = lax.scan(
            step, carry0, jnp.arange(M + pp - 1))
        # PER-RANK partials: the cross-rank psum happens OUTSIDE the
        # custom_vjp (see _scatter_own)
        out_part = _scatter_own(own_out, rank, pp, share, mb, B)
        if with_stash:
            return out_part, aux_acc, stash
        return out_part, aux_acc

    def run_backward(sp, tp, hp, x_, e_, ct_out, ct_aux):
        """Interleaved recompute-forward + backward schedule.

        Timing (step u): chain-fwd of microbatch j=u-r at rank r
        (received inputs stashed, circular, 2(pp-1)+1 slots);
        tail-vjp of j=u-(pp-1) at the last rank the step its chain
        output appears; stack-vjp of j=u-2(pp-1)+r at rank r, with the
        activation cotangent hopping one rank backward per step. The
        stash entry written at chain-fwd step j+r is consumed at
        stack-vjp step j+2(pp-1)-r — retention <= 2(pp-1), so the
        circular buffer never overwrites a live slot.
        """
        rank = lax.axis_index(axis_name)
        S = 2 * (pp - 1) + 1
        T = M + 2 * (pp - 1)
        xs = x_.reshape(M, mb, *x_.shape[1:])
        es = e_.reshape(M, mb, *e_.shape[1:])
        own_x = _own_slices(xs, rank, pp, share, M)
        own_e = _own_slices(es, rank, pp, share, M)
        cts = ct_out.reshape(M, mb, *ct_out.shape[1:])
        zero_x = jnp.zeros_like(own_x[0])
        zero_e = jnp.zeros_like(own_e[0])
        h_shape = jax.eval_shape(lambda v: head_fn(hp, v), zero_x)
        zero_h = jnp.zeros(h_shape.shape, h_shape.dtype)
        # the caller-side `psum(aux_part)/M` transpose already applied
        # the 1/M: the incoming ct IS the per-(microbatch, rank) aux
        # cotangent
        ct_aux_mb = ct_aux.astype(jnp.float32)

        def stack_fwd(v):
            return stack(sp, v)[0]

        g_sp0 = jax.tree.map(jnp.zeros_like, sp)
        g_tp0 = jax.tree.map(jnp.zeros_like, tp)
        g_hp0 = jax.tree.map(jnp.zeros_like, hp)
        dx0 = jnp.zeros((M,) + zero_x.shape, zero_x.dtype) \
            if x_differentiable else None

        def step(carry, u):
            (reg_x, reg_e, state_h, state_e, stash_x, stash_h,
             ct_reg, g_sp, g_tp, g_hp, dx_buf) = carry
            # ---- recompute-forward chain (identical to run_forward) --
            reg_x = _inject(own_x, reg_x, u, share, pp)
            reg_e = _inject(own_e, reg_e, u, share, pp)
            inp_h = jnp.where(rank == 0, head_fn(hp, reg_x), state_h)
            inp_e = jnp.where(rank == 0, reg_e, state_e)
            valid_f = jnp.logical_and(u >= rank, u - rank < M)
            h = lax.cond(valid_f, stack_fwd, lambda v: v, inp_h)
            # stash this step's received input (rank 0: the raw/token
            # microbatch; others: the incoming activation). The slot
            # being overwritten was consumed at step u-1 (see docstring)
            slot_w = jnp.mod(u, S)
            if have_head:
                # pre-head inputs stashed only when a head exists (for
                # its re-vjp); without one, stash_h already holds rank
                # 0's raw input — a second activation-sized stash would
                # double the advertised pipe-depth bound
                stash_x = lax.dynamic_update_index_in_dim(
                    stash_x, reg_x, slot_w, 0)
            stash_h = lax.dynamic_update_index_in_dim(
                stash_h, inp_h, slot_w, 0)
            # ---- tail vjp at the last rank, same step as chain out ---
            # run UNCONDITIONALLY with a masked cotangent (J^T*0 = 0 on
            # off ranks/steps): a rank-divergent cond around the tail's
            # sharding constraints deadlocks (see run_forward note)
            j_t = u - (pp - 1)
            valid_t = jnp.logical_and(rank == pp - 1,
                                      jnp.logical_and(j_t >= 0, j_t < M))
            ct_mb = lax.dynamic_index_in_dim(
                cts, jnp.clip(j_t, 0, M - 1), 0, keepdims=False)
            ct_mb = jnp.where(valid_t, ct_mb, jnp.zeros_like(ct_mb))
            _, tail_vjp_fn = jax.vjp(
                lambda tp_, h_, e_in: tail_fn(tp_, h_, e_in),
                tp, h, inp_e)
            d_tp, ct_h_tail = tail_vjp_fn(ct_mb)[:2]
            g_tp = jax.tree.map(jnp.add, g_tp, d_tp)
            # ---- stack vjp (the 1F1B backward of microbatch j_b) -----
            j_b = u - 2 * (pp - 1) + rank
            valid_b = jnp.logical_and(j_b >= 0, j_b < M)
            ct_in = jnp.where(rank == pp - 1, ct_h_tail, ct_reg)
            slot_r = jnp.mod(u - 2 * (pp - 1) + 2 * rank, S)
            h_in_b = lax.dynamic_index_in_dim(stash_h, slot_r, 0,
                                              keepdims=False)

            if have_head:
                # Rank 0's stashed input is pre-head (tokens);
                # recompute the head UNCONDITIONALLY on every rank
                # (uniform program — the head's sharding constraints
                # must not sit in rank-divergent control flow) and
                # select the effective stack input.
                x_in_b = lax.dynamic_index_in_dim(stash_x, slot_r, 0,
                                                  keepdims=False)
                head_out_b, head_vjp_fn = jax.vjp(
                    lambda hp_, xv: head_fn(hp_, xv), hp, x_in_b)
                h_eff = jnp.where(rank == 0, head_out_b, h_in_b)
            else:
                h_eff = h_in_b   # rank 0 stashed the raw input itself

            def stack_vjp(args):
                hv, ct = args
                _, vjp_fn = jax.vjp(
                    lambda sp_, h_: stack(sp_, h_), sp, hv)
                return vjp_fn((ct, ct_aux_mb))

            d_sp, d_h = lax.cond(
                valid_b, stack_vjp,
                lambda args: (g_sp0, jnp.zeros_like(args[0])),
                (h_eff, ct_in))
            # head backward with a rank/validity-masked cotangent
            # (J^T*0 = 0 elsewhere) — uniform across ranks
            ct_head = jnp.where(
                jnp.logical_and(valid_b, rank == 0), d_h,
                jnp.zeros_like(d_h))
            if have_head:
                d_hp, d_x = head_vjp_fn(ct_head)
            else:
                d_hp, d_x = g_hp0, ct_head
            ct_prev = d_h
            if x_differentiable:
                take_dx = jnp.logical_and(valid_b, rank == 0)
                slot_dx = jnp.clip(j_b, 0, M - 1)
                prev_dx = lax.dynamic_index_in_dim(dx_buf, slot_dx, 0,
                                                   keepdims=False)
                dx_buf = lax.dynamic_update_index_in_dim(
                    dx_buf, jnp.where(take_dx, d_x, prev_dx),
                    slot_dx, 0)
            g_sp = jax.tree.map(jnp.add, g_sp, d_sp)
            g_hp = jax.tree.map(jnp.add, g_hp, d_hp)
            # ---- rotations -------------------------------------------
            ct_reg = lax.ppermute(ct_prev, axis_name, rev_perm)
            state_h = lax.ppermute(h, axis_name, fwd_perm)
            state_e = lax.ppermute(inp_e, axis_name, fwd_perm)
            reg_x = lax.ppermute(reg_x, axis_name, back_rot)
            reg_e = lax.ppermute(reg_e, axis_name, back_rot)
            return (reg_x, reg_e, state_h, state_e, stash_x, stash_h,
                    ct_reg, g_sp, g_tp, g_hp, dx_buf), None

        stash_x = jnp.zeros((S,) + zero_x.shape, zero_x.dtype) \
            if have_head else jnp.zeros((1, 1))
        stash_h = jnp.zeros((S,) + zero_h.shape, zero_h.dtype)
        carry0 = (zero_x, zero_e, zero_h, zero_e, stash_x, stash_h,
                  jnp.zeros_like(zero_h), g_sp0, g_tp0, g_hp0, dx0)
        carry, _ = lax.scan(step, carry0, jnp.arange(T))
        (_, _, _, _, _, _, _, g_sp, g_tp, g_hp, dx_buf) = carry
        # Cotangents are returned as PER-RANK PARTIALS — tail/head
        # params and x are replicated primals, and the transpose of
        # replication is a sum: the shard_map boundary psums the
        # per-rank returns itself. (Psumming here too double-counted;
        # the direct no-head test pins the 1x scaling.)
        if x_differentiable:
            dx = jnp.where(rank == 0, dx_buf, jnp.zeros_like(dx_buf))
            dx = dx.reshape(x_.shape).astype(x_.dtype)
        else:
            dx = zero_ct(x_)
        return g_sp, g_tp, g_hp, dx, zero_ct(e_)

    def run_backward_stash(sp, tp, hp, x_, e_, stash, ct_out, ct_aux):
        """Stash-variant backward: no chain re-forward, no relay of
        inputs — every rank indexes its saved stack-input stash and the
        primal streams directly.  Rank r runs microbatch j's stack-vjp
        at step ``u = j + (pp-1-r)``; the input cotangent it produces
        is exactly what rank r-1 needs one step later (one rev-ppermute
        hop per step).  Tail/head/stack vjps run UNCONDITIONALLY with
        masked cotangents (J^T·0 = 0): rank-divergent conds around
        sharding-constrained code deadlock (see run_forward note), so
        the (pp-1)/(M+pp-1) bubble burns compute on zeros instead."""
        rank = lax.axis_index(axis_name)
        xs = x_.reshape(M, mb, *x_.shape[1:])
        es = e_.reshape(M, mb, *e_.shape[1:])
        cts = ct_out.reshape(M, mb, *ct_out.shape[1:])
        ct_aux_mb = ct_aux.astype(jnp.float32)

        g_sp0 = jax.tree.map(jnp.zeros_like, sp)
        g_tp0 = jax.tree.map(jnp.zeros_like, tp)
        g_hp0 = jax.tree.map(jnp.zeros_like, hp)
        zero_x = jnp.zeros((mb,) + x_.shape[1:], x_.dtype)
        dx0 = jnp.zeros((M,) + zero_x.shape, zero_x.dtype) \
            if x_differentiable else None

        def step(carry, u):
            ct_reg, g_sp, g_tp, g_hp, dx_buf = carry
            j = u - (pp - 1 - rank)
            valid = jnp.logical_and(j >= 0, j < M)
            jc = jnp.clip(j, 0, M - 1)
            h_in = lax.dynamic_index_in_dim(stash, jc, 0,
                                            keepdims=False)
            inp_e = lax.dynamic_index_in_dim(es, jc, 0, keepdims=False)
            # ONE stack recompute, inside the vjp (the stash variant's
            # whole point: no second, chain-level recompute)
            (h_out, _), stack_vjp_fn = jax.vjp(
                lambda sp_, h_: stack(sp_, h_), sp, h_in)
            # tail vjp at the last rank, cotangent masked elsewhere
            ct_mb = lax.dynamic_index_in_dim(cts, jc, 0, keepdims=False)
            ct_mb = jnp.where(
                jnp.logical_and(valid, rank == pp - 1), ct_mb,
                jnp.zeros_like(ct_mb))
            _, tail_vjp_fn = jax.vjp(
                lambda tp_, h_, e_in: tail_fn(tp_, h_, e_in),
                tp, h_out, inp_e)
            d_tp, ct_h_tail = tail_vjp_fn(ct_mb)[:2]
            g_tp = jax.tree.map(jnp.add, g_tp, d_tp)
            ct_h = jnp.where(rank == pp - 1, ct_h_tail, ct_reg)
            ct_h = jnp.where(valid, ct_h, jnp.zeros_like(ct_h))
            d_sp, d_h_in = stack_vjp_fn(
                (ct_h, jnp.where(valid, ct_aux_mb, 0.0)))
            g_sp = jax.tree.map(jnp.add, g_sp, d_sp)
            # head vjp at rank 0 (embed recompute from the token primal)
            x_in = lax.dynamic_index_in_dim(xs, jc, 0, keepdims=False)
            _, head_vjp_fn = jax.vjp(
                lambda hp_, xv: head_fn(hp_, xv), hp, x_in)
            ct_head = jnp.where(
                jnp.logical_and(valid, rank == 0), d_h_in,
                jnp.zeros_like(d_h_in))
            d_hp, d_x = head_vjp_fn(ct_head)
            g_hp = jax.tree.map(jnp.add, g_hp, d_hp)
            if x_differentiable:
                take_dx = jnp.logical_and(valid, rank == 0)
                prev_dx = lax.dynamic_index_in_dim(dx_buf, jc, 0,
                                                   keepdims=False)
                dx_buf = lax.dynamic_update_index_in_dim(
                    dx_buf, jnp.where(take_dx, d_x, prev_dx), jc, 0)
            ct_reg = lax.ppermute(d_h_in, axis_name, rev_perm)
            return (ct_reg, g_sp, g_tp, g_hp, dx_buf), None

        h_probe = stash[0]
        carry0 = (jnp.zeros_like(h_probe), g_sp0, g_tp0, g_hp0, dx0)
        carry, _ = lax.scan(step, carry0, jnp.arange(M + pp - 1))
        _, g_sp, g_tp, g_hp, dx_buf = carry
        # PER-RANK PARTIALS, same convention as the remat backward: the
        # shard_map boundary psums replicated primals' cotangents
        if x_differentiable:
            dx = jnp.where(rank == 0, dx_buf, jnp.zeros_like(dx_buf))
            dx = dx.reshape(x_.shape).astype(x_.dtype)
        else:
            dx = zero_ct(x_)
        return g_sp, g_tp, g_hp, dx, zero_ct(e_)

    if variant == 'stash':
        @jax.custom_vjp
        def fused(sp, tp, hp, x_, e_):
            # primal (non-differentiated) path: no stash — eval steps
            # must not pay the [M, mb, ...] hidden slab
            return run_forward(sp, tp, hp, x_, e_)

        def fused_fwd(sp, tp, hp, x_, e_):
            out, aux, stash = run_forward(sp, tp, hp, x_, e_,
                                          with_stash=True)
            return (out, aux), (sp, tp, hp, x_, e_, stash)

        def fused_bwd(res, cts):
            sp, tp, hp, x_, e_, stash = res
            ct_out, ct_aux = cts
            return run_backward_stash(sp, tp, hp, x_, e_, stash,
                                      ct_out, ct_aux)
    else:
        @jax.custom_vjp
        def fused(sp, tp, hp, x_, e_):
            return run_forward(sp, tp, hp, x_, e_)

        def fused_fwd(sp, tp, hp, x_, e_):
            out = run_forward(sp, tp, hp, x_, e_)
            return out, (sp, tp, hp, x_, e_)

        def fused_bwd(res, cts):
            sp, tp, hp, x_, e_ = res
            ct_out, ct_aux = cts
            return run_backward(sp, tp, hp, x_, e_, ct_out, ct_aux)

    fused.defvjp(fused_fwd, fused_bwd)
    out_part, aux_part = fused(stacked_params, tail_params, head_params,
                               x, extra)
    # collection outside the custom_vjp: the psum's transpose hands the
    # backward the FULL output cotangent on every rank
    out = lax.psum(out_part, axis_name)
    aux = lax.psum(aux_part, axis_name) / M
    return out, aux
