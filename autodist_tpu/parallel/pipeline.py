"""Pipeline parallelism: GPipe microbatch schedule over the ``pipe`` axis.

Absent from the reference (SURVEY.md §2.3: PP = No). TPU-native design:
the repeated transformer blocks are parameter-stacked along a leading
``stage`` axis which shards over the ``pipe`` mesh axis; inside a manual
shard_map region each pipe rank scans its local layer shard, and
activations hop stage-to-stage with ``ppermute`` following the GPipe
schedule (microbatches fill/drain the pipe; bubble fraction
(pp-1)/(M+pp-1)). Autodiff through ppermute gives the backward schedule
for free; XLA overlaps the hop DMA with the next microbatch's compute.

Fill/drain efficiency: each rank r only holds a *valid* microbatch for
schedule steps t in [r, r+M); outside that window the block compute is
skipped via ``lax.cond`` (a real XLA conditional — ``rank``/``t`` are
runtime values inside the manual region), so the inherent bubble idles
instead of burning FLOPs on garbage activations. Wall-clock per step is
still one block time (some rank is always busy, and the per-step
``ppermute`` aligns ranks), so the schedule's latency overhead remains
the textbook (pp-1)/(M+pp-1) bubble — measured in
tests/test_functional_api.py's pipeline parity tests.
"""
import jax
import jax.numpy as jnp
from jax import lax


def gpipe(block_fn, stacked_params, x, axis_name, microbatches):
    """Run a stage-sharded layer stack as a GPipe pipeline.

    Must be called inside a shard_map region manual over ``axis_name``.

    Args:
        block_fn: ``block_fn(layer_params, h) -> (h, aux)`` single-block
            apply; ``aux`` is a scalar auxiliary loss contribution (e.g.
            MoE router balance) summed over layers.
        stacked_params: pytree with local leading dim = layers_per_stage.
        x: [batch, ...] full activation batch (replicated over the pipe
            axis — every rank holds it; only rank 0's copy is consumed).
        axis_name: the pipe mesh axis.
        microbatches: M, the microbatch count (batch must divide by M).

    Returns:
        ``(out, aux)``: [batch, ...] final activations and the scalar aux
        loss (mean over microbatches, summed over all stages' layers),
        both replicated over the pipe axis.

    MoE note: under pipelining the router's balance statistics are
    computed per MICROBATCH (each microbatch is a routing group, the
    GShard grouping — same principle as per-seq-shard groups under SP),
    so for microbatches > 1 the aux term is the mean of per-group losses
    rather than one full-batch statistic. The two coincide at
    microbatches=1 (pinned by test_moe_aux_loss_kept_under_pipelining);
    beyond that the objective is the grouped one, by design.
    """
    pp = lax.axis_size(axis_name)
    rank = lax.axis_index(axis_name)
    B = x.shape[0]
    M = int(microbatches)
    assert B % M == 0, 'batch %d not divisible by microbatches %d' % (B, M)
    mb = B // M
    xs = x.reshape(M, mb, *x.shape[1:])

    def local_stack(h):
        def body(c, p):
            h, aux = c
            h, a = block_fn(p, h)
            return (h, aux + a.astype(jnp.float32)), None
        (h, aux), _ = lax.scan(body, (h, jnp.zeros((), jnp.float32)),
                               stacked_params)
        return h, aux

    if pp == 1:
        return local_stack(x)

    fwd_perm = [(i, i + 1) for i in range(pp - 1)]

    def step(carry, t):
        state, buf, aux_acc = carry
        # stage 0 consumes microbatch t (clamped in the drain phase);
        # other stages consume what the previous stage sent
        mb_idx = jnp.clip(t, 0, M - 1)
        first_in = lax.dynamic_index_in_dim(xs, mb_idx, 0, keepdims=False)
        inp = jnp.where(rank == 0, first_in, state)
        # rank r holds valid work only for t in [r, r+M): skip the block
        # compute in the fill/drain bubble instead of processing garbage
        valid = jnp.logical_and(t >= rank, t < rank + M)
        out, aux = lax.cond(
            valid, local_stack,
            lambda h: (h, jnp.zeros((), jnp.float32)), inp)
        aux_acc = aux_acc + aux
        # last stage records microbatch t-(pp-1) once the pipe is full
        out_idx = jnp.clip(t - (pp - 1), 0, M - 1)
        ready = jnp.logical_and(rank == pp - 1, t >= pp - 1)
        prev = lax.dynamic_index_in_dim(buf, out_idx, 0, keepdims=False)
        buf = lax.dynamic_update_index_in_dim(
            buf, jnp.where(ready, out, prev), out_idx, 0)
        nxt = lax.ppermute(out, axis_name, fwd_perm)
        return (nxt, buf, aux_acc), None

    state = jnp.zeros((mb,) + x.shape[1:], x.dtype)
    buf = jnp.zeros((M, mb) + x.shape[1:], x.dtype)
    (_, buf, aux_acc), _ = lax.scan(
        step, (state, buf, jnp.zeros((), jnp.float32)),
        jnp.arange(M + pp - 1))
    out = buf.reshape(B, *x.shape[1:])
    # broadcast the last stage's result to every rank (the head/loss run
    # replicated over pipe): mask + psum
    out = lax.psum(
        jnp.where(rank == pp - 1, out, jnp.zeros_like(out)), axis_name)
    # aux: every stage accumulated its local layers' contribution for the
    # M valid microbatches; sum stages, average microbatches
    aux = lax.psum(aux_acc, axis_name) / M
    return out, aux
