"""Pipeline parallelism: GPipe microbatch schedule over the ``pipe`` axis.

Absent from the reference (SURVEY.md §2.3: PP = No). TPU-native design:
the repeated transformer blocks are parameter-stacked along a leading
``stage`` axis which shards over the ``pipe`` mesh axis; inside a manual
shard_map region each pipe rank scans its local layer shard, and
activations hop stage-to-stage with ``ppermute`` following the GPipe
schedule (microbatches fill/drain the pipe; bubble fraction
(pp-1)/(M+pp-1)). Autodiff through ppermute gives the backward schedule
for free; XLA overlaps the hop DMA with the next microbatch's compute.

Fill/drain efficiency: each rank r only holds a *valid* microbatch for
schedule steps t in [r, r+M); outside that window the block compute is
skipped via ``lax.cond`` (a real XLA conditional — ``rank``/``t`` are
runtime values inside the manual region), so the inherent bubble idles
instead of burning FLOPs on garbage activations. Wall-clock per step is
still one block time (some rank is always busy, and the per-step
``ppermute`` aligns ranks), so the schedule's latency overhead remains
the textbook (pp-1)/(M+pp-1) bubble — measured in
tests/test_functional_api.py's pipeline parity tests.
"""
import jax
import jax.numpy as jnp
from jax import lax


def gpipe(block_fn, stacked_params, x, axis_name, microbatches):
    """Run a stage-sharded layer stack as a GPipe pipeline.

    Must be called inside a shard_map region manual over ``axis_name``.

    Args:
        block_fn: ``block_fn(layer_params, h) -> (h, aux)`` single-block
            apply; ``aux`` is a scalar auxiliary loss contribution (e.g.
            MoE router balance) summed over layers.
        stacked_params: pytree with local leading dim = layers_per_stage.
        x: [batch, ...] full activation batch (replicated over the pipe
            axis — every rank holds it; only rank 0's copy is consumed).
        axis_name: the pipe mesh axis.
        microbatches: M, the microbatch count (batch must divide by M).

    Returns:
        ``(out, aux)``: [batch, ...] final activations and the scalar aux
        loss (mean over microbatches, summed over all stages' layers),
        both replicated over the pipe axis.

    MoE note: under pipelining the router's balance statistics are
    computed per MICROBATCH (each microbatch is a routing group, the
    GShard grouping — same principle as per-seq-shard groups under SP),
    so for microbatches > 1 the aux term is the mean of per-group losses
    rather than one full-batch statistic. The two coincide at
    microbatches=1 (pinned by test_moe_aux_loss_kept_under_pipelining);
    beyond that the objective is the grouped one, by design.
    """
    pp = lax.axis_size(axis_name)
    rank = lax.axis_index(axis_name)
    B = x.shape[0]
    M = int(microbatches)
    assert B % M == 0, 'batch %d not divisible by microbatches %d' % (B, M)
    mb = B // M
    xs = x.reshape(M, mb, *x.shape[1:])

    def local_stack(h):
        def body(c, p):
            h, aux = c
            h, a = block_fn(p, h)
            return (h, aux + a.astype(jnp.float32)), None
        (h, aux), _ = lax.scan(body, (h, jnp.zeros((), jnp.float32)),
                               stacked_params)
        return h, aux

    if pp == 1:
        return local_stack(x)

    fwd_perm = [(i, i + 1) for i in range(pp - 1)]

    def step(carry, t):
        state, buf, aux_acc = carry
        # stage 0 consumes microbatch t (clamped in the drain phase);
        # other stages consume what the previous stage sent
        mb_idx = jnp.clip(t, 0, M - 1)
        first_in = lax.dynamic_index_in_dim(xs, mb_idx, 0, keepdims=False)
        inp = jnp.where(rank == 0, first_in, state)
        # rank r holds valid work only for t in [r, r+M): skip the block
        # compute in the fill/drain bubble instead of processing garbage
        valid = jnp.logical_and(t >= rank, t < rank + M)
        out, aux = lax.cond(
            valid, local_stack,
            lambda h: (h, jnp.zeros((), jnp.float32)), inp)
        aux_acc = aux_acc + aux
        # last stage records microbatch t-(pp-1) once the pipe is full
        out_idx = jnp.clip(t - (pp - 1), 0, M - 1)
        ready = jnp.logical_and(rank == pp - 1, t >= pp - 1)
        prev = lax.dynamic_index_in_dim(buf, out_idx, 0, keepdims=False)
        buf = lax.dynamic_update_index_in_dim(
            buf, jnp.where(ready, out, prev), out_idx, 0)
        nxt = lax.ppermute(out, axis_name, fwd_perm)
        return (nxt, buf, aux_acc), None

    state = jnp.zeros((mb,) + x.shape[1:], x.dtype)
    buf = jnp.zeros((M, mb) + x.shape[1:], x.dtype)
    (_, buf, aux_acc), _ = lax.scan(
        step, (state, buf, jnp.zeros((), jnp.float32)),
        jnp.arange(M + pp - 1))
    out = buf.reshape(B, *x.shape[1:])
    # broadcast the last stage's result to every rank (the head/loss run
    # replicated over pipe): mask + psum
    out = lax.psum(
        jnp.where(rank == pp - 1, out, jnp.zeros_like(out)), axis_name)
    # aux: every stage accumulated its local layers' contribution for the
    # M valid microbatches; sum stages, average microbatches
    aux = lax.psum(aux_acc, axis_name) / M
    return out, aux


def one_f_one_b(block_fn, stacked_params, x, axis_name, microbatches,
                tail_fn=None, extra=None):
    """1F1B-memory-profile schedule with per-rank microbatch residency.

    Same fill/steady/drain timing as :func:`gpipe` (the forward bubble
    is inherent), but the memory contract differs — full-batch
    activations never live across the schedule:

    - inputs: rank ``r`` owns microbatches ``r, r+pp, ...`` (``M/pp`` of
      them) and puts each on the wire (a masked ``psum`` delivery to
      stage 0) exactly when the schedule consumes it — instead of every
      rank closing over the full ``[M, mb]`` input stack;
    - ``tail_fn(h, extra_mb)``: applied after the last stage's blocks,
      PER MICROBATCH — fold the head + loss in here so the pipeline
      emits ``[mb, seq]`` per-token losses instead of ``[mb, seq, dim]``
      activations (and per-microbatch logits instead of a full-batch
      ``[B, seq, vocab]`` slab). ``extra`` ([B, ...], e.g. targets)
      streams through the pipe alongside the activations;
    - outputs: the last stage's (tail) result for microbatch ``j`` is
      delivered to its owner ``j % pp`` the step it is produced; each
      rank holds only its ``[M/pp, mb, ...]`` share, and the (small)
      full result is reassembled once at region exit.

    The fwd/bwd *interleave* itself is autodiff's reverse scan, not a
    hand-written schedule; what is delivered (and asserted by
    ``compiled.memory_analysis()`` in the tests) is the 1F1B working-set
    property — live full-batch buffers are eliminated and per-step
    residuals are microbatch-sized.

    Requires ``M % pp == 0`` (round-robin residency); use ``gpipe`` for
    ragged microbatch counts.
    """
    pp = lax.axis_size(axis_name)
    rank = lax.axis_index(axis_name)
    B = x.shape[0]
    M = int(microbatches)
    assert B % M == 0, 'batch %d not divisible by microbatches %d' % (B, M)
    mb = B // M

    def local_stack(h):
        def body(c, p):
            h, aux = c
            h, a = block_fn(p, h)
            return (h, aux + a.astype(jnp.float32)), None
        (h, aux), _ = lax.scan(body, (h, jnp.zeros((), jnp.float32)),
                               stacked_params)
        return h, aux

    if pp == 1:
        h, aux = local_stack(x)
        if tail_fn is not None:
            h = tail_fn(h, extra)
        return h, aux
    if M % pp:
        raise ValueError(
            "pp_schedule='1f1b' needs microbatches %% pp == 0 "
            '(got M=%d, pp=%d); use gpipe for ragged counts' % (M, pp))

    share = M // pp
    own_idx = jnp.arange(share) * pp + rank   # round-robin residency

    def to_mb(a):
        return a.reshape(M, mb, *a.shape[1:])

    xs = to_mb(x)
    own_in = jnp.take(xs, own_idx, axis=0)
    extra_s = None if extra is None else to_mb(extra)
    own_extra = None if extra is None else jnp.take(extra_s, own_idx,
                                                    axis=0)
    fwd_perm = [(i, i + 1) for i in range(pp - 1)]
    zero_h = jnp.zeros((mb,) + x.shape[1:], x.dtype)
    zero_e = None if extra is None else \
        jnp.zeros((mb,) + extra.shape[1:], extra.dtype)

    def tail(h, e):
        return h if tail_fn is None else tail_fn(h, e)

    out_shape = jax.eval_shape(tail, zero_h, zero_e)
    zero_out = jnp.zeros(out_shape.shape, out_shape.dtype)

    def deliver(mine, zero, cond_):
        """Masked-psum delivery of one microbatch-sized tensor."""
        return lax.psum(jnp.where(cond_, mine, zero), axis_name)

    def step(carry, t):
        state_h, state_e, own_out, aux_acc = carry
        # input delivery: the owner of microbatch t puts it on the wire
        owner = jnp.mod(t, pp)
        slot = jnp.clip(t // pp, 0, share - 1)
        emit = jnp.logical_and(rank == owner, t < M)
        feed_h = deliver(lax.dynamic_index_in_dim(own_in, slot, 0,
                                                  keepdims=False),
                         zero_h, emit)
        inp_h = jnp.where(rank == 0, feed_h, state_h)
        if extra is None:
            inp_e = None
        else:
            feed_e = deliver(lax.dynamic_index_in_dim(own_extra, slot, 0,
                                                      keepdims=False),
                             zero_e, emit)
            inp_e = jnp.where(rank == 0, feed_e, state_e)
        valid = jnp.logical_and(t >= rank, t < rank + M)
        h, aux = lax.cond(
            valid, local_stack,
            lambda v: (v, jnp.zeros((), jnp.float32)), inp_h)
        aux_acc = aux_acc + aux
        # the last stage's per-microbatch tail (head/loss when folded);
        # other ranks compute it on pipeline-register values and the
        # result is masked out — the bubble idles either way, and the
        # full-batch head this replaces also ran on every rank
        out_val = tail(h, inp_e)
        # output delivery: microbatch j leaves the last stage this step
        j = t - (pp - 1)
        done = deliver(out_val, zero_out,
                       jnp.logical_and(rank == pp - 1, j >= 0))
        take = jnp.logical_and(j >= 0, jnp.mod(j, pp) == rank)
        slot_out = jnp.clip(j // pp, 0, share - 1)
        prev = lax.dynamic_index_in_dim(own_out, slot_out, 0,
                                        keepdims=False)
        own_out = lax.dynamic_update_index_in_dim(
            own_out, jnp.where(take, done, prev), slot_out, 0)
        nxt_h = lax.ppermute(h, axis_name, fwd_perm)
        nxt_e = None if extra is None else \
            lax.ppermute(inp_e, axis_name, fwd_perm)
        return (nxt_h, nxt_e, own_out, aux_acc), None

    own_out = jnp.zeros((share,) + zero_out.shape, zero_out.dtype)
    (_, _, own_out, aux_acc), _ = lax.scan(
        step, (zero_h, zero_e, own_out, jnp.zeros((), jnp.float32)),
        jnp.arange(M + pp - 1))
    # reassemble once, at exit: gathered[r, s] is microbatch s*pp + r
    gathered = lax.all_gather(own_out, axis_name)  # [pp, share, mb, ...]
    out = jnp.moveaxis(gathered, 0, 1).reshape(
        (B,) + zero_out.shape[1:])
    aux = lax.psum(aux_acc, axis_name) / M
    return out, aux
