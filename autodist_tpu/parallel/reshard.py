"""Device-side resharding: strategy-A layout -> strategy-B layout.

The portable-redistribution idea (PAPERS.md) applied to this runtime's
per-variable state layouts: an :class:`~autodist_tpu.parallel.plan.
ExecutionPlan` places every variable either REPLICATED or ZeRO-sharded
along one axis of the ``data`` mesh axis (padded for uneven partitions).
Migrating live state between two plans — an elastic re-plan picking a
new strategy, checkpoint-free strategy switching generally — is then a
per-variable layout map, executed ON DEVICE with collectives chosen by
the redistribution cost model, never a host round trip:

==================  ==================  ===========================
source layout       target layout       collective
==================  ==================  ===========================
replicated          replicated          none (``noop``)
replicated          sharded(b)          local slice (``shard``, 0 wire)
sharded(a)          replicated          ``all_gather``
sharded(a)          sharded(b), a != b  ``all_to_all`` OR
                                        ``gather_scatter`` — cheaper
                                        one per the cost model
sharded(a)          sharded(a), pad'    ``gather_scatter`` (repad)
==================  ==================  ===========================

``all_to_all`` moves the same ``(n-1)/n`` wire fraction as a gather
but never materializes the full tensor per device; ``gather_scatter``
(all-gather + local re-slice in ONE program) handles the padded /
non-dividing shapes ``all_to_all``'s tiled split cannot, at an extra
full-size HBM pass the model prices. The chosen op per variable rides
the :class:`ReshardOp` record so migrations are auditable
(``session.health_stats`` replan entries embed the summary).

Numerics: every path is a pure data movement — no arithmetic touches
the values — so a round trip A -> B -> A is bit-identical (the
property ``tests/test_reshard.py`` pins).
"""
from dataclasses import dataclass, field, asdict

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from autodist_tpu.const import AXIS_DATA
from autodist_tpu.parallel.axes import shard_map_compat as _shard_map
from autodist_tpu.utils import logging


def var_layout(plan, name):
    """One variable's physical layout under ``plan``:
    ``{'sharded', 'axis', 'padded_dim', 'pad'}`` (axis fields are None
    for replicated state)."""
    p = plan.var_plans[name]
    if not p.state_sharded:
        return {'sharded': False, 'axis': None, 'padded_dim': None,
                'pad': 0}
    return {'sharded': True, 'axis': int(p.shard_axis),
            'padded_dim': int(p.padded_dim or
                              p.var.shape[p.shard_axis]),
            'pad': int(p.pad)}


@dataclass
class ReshardOp:
    """One variable's planned layout move."""
    var_name: str
    kind: str                      # noop|shard|all_gather|all_to_all|
    #                                gather_scatter
    src: dict = field(default_factory=dict)
    dst: dict = field(default_factory=dict)
    wire_bytes: int = 0            # per-device bytes on the wire
    est_time_s: float = 0.0        # redistribution cost-model estimate

    def to_dict(self):
        return asdict(self)

    def ir_program(self, n, elems, dtype='float32'):
        """This move as a :mod:`~autodist_tpu.parallel.schedule_ir`
        program — the same IR gradient syncs lower through, so the
        shape algebra verifies reshards too (``tools/analyze.py
        --schedule`` runs it). Element space is the flattened padded
        physical array in the DESTINATION coordinate frame; every path
        is pure data movement, so holdings carry full-value (ALL-
        contrib) fragments and the algebra checks coverage, never
        reduction completeness. ``ReshardOp`` stores layouts only, so
        the caller supplies the mesh size ``n`` and the physical
        element count ``elems``. Chaining ``run_algebra`` holdings
        through consecutive programs proves A -> B -> A identity
        (``tests/test_schedule_ir.py`` pins it)."""
        from autodist_tpu.parallel import schedule_ir as sir
        n = int(n)
        wire = sir.wire_of_dtype(dtype)
        meta = {'reshard': self.kind, 'var': self.var_name}
        name = 'reshard_%s_%s' % (self.kind, self.var_name)
        full = (tuple(range(n)),)
        if self.kind == 'noop':
            state = 'value_sharded' if self.src.get('sharded') \
                else 'value_replicated'
            E = sir._pad_to(elems, n) if state == 'value_sharded' \
                else int(elems)
            return sir.Program(name, n, E, str(dtype), (), state,
                               state, meta)
        E = sir._pad_to(elems, n)
        m = E // n
        chunks = (tuple((d * m, (d + 1) * m) for d in range(n)),)
        if self.kind == 'shard':
            # replicated -> sharded: zero-wire local projection; the
            # algebra checks each device already covers its chunk.
            steps = (sir.Step('scatter', tier='local', wire=wire,
                              groups=full, chunks=chunks),)
            return sir.Program(name, n, E, str(dtype), steps,
                               'value_replicated', 'value_sharded',
                               meta)
        if self.kind == 'all_gather':
            steps = (sir.Step('all_gather', tier='dcn', wire=wire,
                              groups=full, span=((0, E),),
                              nbytes=sir.wire_nbytes(E, wire)),)
            return sir.Program(name, n, E, str(dtype), steps,
                               'value_sharded', 'value_replicated',
                               meta)
        if self.kind == 'all_to_all':
            # sharded(a) -> sharded(b): in the destination frame each
            # source shard is the block transpose — device d holds one
            # mm-slice of every destination chunk — and one wired
            # scatter redistributes them into contiguous chunks.
            E = sir._pad_to(elems, n * n)
            m = E // n
            mm = m // n
            ALL = frozenset(range(n))
            init = [[(j * m + d * mm, j * m + (d + 1) * mm, ALL)
                     for j in range(n)] for d in range(n)]
            chunks = (tuple((d * m, (d + 1) * m) for d in range(n)),)
            nb = (n - 1) / float(max(1, n)) * \
                sir.wire_nbytes(E, wire) or 1.0
            steps = (sir.Step('scatter', tier='dcn', wire=wire,
                              groups=full, chunks=chunks, nbytes=nb),)
            return sir.Program(name, n, E, str(dtype), steps, init,
                               'value_sharded', meta)
        if self.kind == 'gather_scatter':
            steps = (sir.Step('all_gather', tier='dcn', wire=wire,
                              groups=full, span=((0, E),),
                              nbytes=sir.wire_nbytes(E, wire)),
                     sir.Step('scatter', tier='local', wire=wire,
                              groups=full, chunks=chunks))
            return sir.Program(name, n, E, str(dtype), steps,
                               'value_sharded', 'value_sharded', meta)
        raise ValueError('Unknown reshard kind %r' % (self.kind,))


def _move_cost(kind, nbytes, n, params):
    """Redistribution cost-model estimate for one move of ``nbytes``
    physical bytes over the ``n``-way data axis. Collectives price at
    the DCN tier when the plan spans nodes is unknowable here, so the
    conservative cross-node constants apply; ``gather_scatter``
    additionally pays a full-tensor HBM pass (the per-device
    materialize + re-slice ``all_to_all`` avoids)."""
    if n <= 1 or kind in ('noop', 'shard'):
        return 0.0
    alpha, beta = params.link(cross_node=True)
    t = (n - 1) * alpha + (n - 1) / n * float(nbytes) * beta
    if kind == 'gather_scatter':
        t += float(nbytes) * params.compress_s_per_byte
    return t


def plan_reshard(old_plan, new_plan, params=None):
    """Plan the per-variable moves from ``old_plan``'s layouts to
    ``new_plan``'s. Pure (no device work); returns ``[ReshardOp]``
    covering every variable both plans know, cheapest collective per
    the redistribution cost model."""
    if params is None:
        params = getattr(new_plan, 'cost_params', None) or \
            getattr(old_plan, 'cost_params', None)
    n = old_plan.num_replicas
    ops = []
    for name in old_plan.var_plans:
        if name not in new_plan.var_plans:
            continue
        src = var_layout(old_plan, name)
        dst = var_layout(new_plan, name)
        var = old_plan.var_plans[name].var
        itemsize = np.dtype(var.dtype).itemsize
        phys = list(var.shape)
        if src['sharded']:
            phys[src['axis']] = src['padded_dim']
        nbytes = int(np.prod(phys or [1])) * itemsize
        if src == dst:
            kind = 'noop'
        elif not src['sharded'] and dst['sharded']:
            kind = 'shard'
        elif src['sharded'] and not dst['sharded']:
            kind = 'all_gather'
        else:
            # sharded -> sharded: all_to_all only lowers when neither
            # side is padded (its tiled split needs exact division);
            # otherwise the single-program gather+re-slice handles any
            # geometry. Where both apply, the cost model picks.
            clean = (src['pad'] == 0 and dst['pad'] == 0 and
                     src['axis'] != dst['axis'])
            if clean and _move_cost('all_to_all', nbytes, n, params) <= \
                    _move_cost('gather_scatter', nbytes, n, params):
                kind = 'all_to_all'
            else:
                kind = 'gather_scatter'
        wire = 0 if kind in ('noop', 'shard') else \
            int((n - 1) / max(1, n) * nbytes)
        ops.append(ReshardOp(
            var_name=name, kind=kind, src=src, dst=dst,
            wire_bytes=wire,
            est_time_s=_move_cost(kind, nbytes, n, params)))
    return ops


def _spec_for(layout, ndim):
    if not layout['sharded']:
        return P()
    spec = [None] * ndim
    spec[layout['axis']] = AXIS_DATA
    return P(*spec)


def reshard_fn(op, old_plan, new_plan):
    """Compile-ready callable moving ONE variable's physical array from
    ``op.src`` to ``op.dst`` layout — a single device-side program
    (shard_map over the data axis; XLA lowers the collective), reusable
    for any array of the variable's physical shape (optimizer slots
    shaped like their variable ride the same fn)."""
    mesh = new_plan.mesh
    n = new_plan.num_replicas
    var = new_plan.var_plans[op.var_name].var
    logical = tuple(int(d) for d in var.shape)
    ndim = len(logical)
    src, dst = op.src, op.dst

    def unpad_src(x):
        if src['sharded'] and src['pad']:
            x = jax.lax.slice_in_dim(x, 0, logical[src['axis']],
                                     axis=src['axis'])
        return x

    def pad_dst(x):
        if dst['sharded'] and dst['pad']:
            cfg = [(0, 0)] * x.ndim
            cfg[dst['axis']] = (0, dst['pad'])
            x = jnp.pad(x, cfg)
        return x

    if op.kind == 'noop':
        return lambda x: x

    if op.kind == 'shard':
        def shard(x):
            x = pad_dst(x)
            size = x.shape[dst['axis']] // n
            me = jax.lax.axis_index(AXIS_DATA)
            return jax.lax.dynamic_slice_in_dim(
                x, me * size, size, axis=dst['axis'])
        return jax.jit(_shard_map(shard, mesh, P(),
                                  _spec_for(dst, ndim)))

    if op.kind == 'all_gather':
        def gather(x):
            full = jax.lax.all_gather(x, AXIS_DATA, axis=src['axis'],
                                      tiled=True)
            return unpad_src(full)
        return jax.jit(_shard_map(gather, mesh,
                                  _spec_for(src, ndim), P()))

    if op.kind == 'all_to_all':
        def a2a(x):
            return jax.lax.all_to_all(x, AXIS_DATA,
                                      split_axis=dst['axis'],
                                      concat_axis=src['axis'],
                                      tiled=True)
        return jax.jit(_shard_map(a2a, mesh, _spec_for(src, ndim),
                                  _spec_for(dst, ndim)))

    if op.kind == 'gather_scatter':
        def gs(x):
            full = unpad_src(
                jax.lax.all_gather(x, AXIS_DATA, axis=src['axis'],
                                   tiled=True))
            full = pad_dst(full)
            size = full.shape[dst['axis']] // n
            me = jax.lax.axis_index(AXIS_DATA)
            return jax.lax.dynamic_slice_in_dim(
                full, me * size, size, axis=dst['axis'])
        return jax.jit(_shard_map(gs, mesh, _spec_for(src, ndim),
                                  _spec_for(dst, ndim)))

    raise ValueError('Unknown reshard kind %r' % (op.kind,))


def apply_reshard(old_plan, new_plan, arrays, ops=None, extra=None):
    """Execute a reshard plan on device.

    Args:
        old_plan / new_plan: the two :class:`ExecutionPlan`\\ s. They
            must share one mesh (a reshard moves layouts, not devices —
            growing the mesh itself is a different operation).
        arrays: ``{var name: physical jax.Array}`` under ``old_plan``'s
            layouts (the session's ``_var_state``).
        ops: a ``plan_reshard`` result to execute (default: planned
            fresh).
        extra: optional ``{var name: [more arrays]}`` that share their
            variable's physical layout (optimizer slot tensors); moved
            through the SAME compiled fn.

    Returns ``(new_arrays, new_extra, ops)`` with every array placed
    per ``new_plan``. Values are moved, never recomputed — bit-exact.
    """
    if list(old_plan.mesh.devices.flat) != \
            list(new_plan.mesh.devices.flat):
        raise ValueError('reshard requires both plans on one mesh; '
                         'got %s vs %s' % (old_plan.mesh, new_plan.mesh))
    if ops is None:
        ops = plan_reshard(old_plan, new_plan)
    extra = extra or {}
    out, out_extra = {}, {}
    moved = 0
    for op in ops:
        arr = arrays.get(op.var_name)
        if arr is None:
            continue
        fn = reshard_fn(op, old_plan, new_plan)
        out[op.var_name] = fn(arr)
        if op.var_name in extra:
            out_extra[op.var_name] = [fn(a)
                                      for a in extra[op.var_name]]
        if op.kind != 'noop':
            moved += 1
    logging.info('reshard: %d vars moved (%d layout changes), '
                 'est %.3g s, %.1f KiB wire per device', len(out),
                 moved, sum(o.est_time_s for o in ops),
                 sum(o.wire_bytes for o in ops) / 1024.0)
    return out, out_extra, ops


def summarize(ops):
    """Compact audit record of a reshard plan (rides health_stats)."""
    kinds = {}
    for op in ops:
        kinds[op.kind] = kinds.get(op.kind, 0) + 1
    return {'vars': len(ops), 'kinds': kinds,
            'wire_bytes': sum(o.wire_bytes for o in ops),
            'est_time_s': sum(o.est_time_s for o in ops)}
