"""Ulysses sequence parallelism: all-to-all head/sequence transposition.

The second of the two public long-context recipes (DeepSpeed-Ulysses,
arXiv:2309.14509; the reference has neither — SURVEY.md §5 long-context:
absent). Where ring attention (parallel/ring_attention.py) keeps Q local
and rotates K/V around the ``seq`` mesh axis, Ulysses transposes the
sharding instead: one ``all_to_all`` re-shards activations from
sequence-sharded/full-heads to head-sharded/full-sequence, runs ordinary
*local* attention per head group (which composes with the Pallas flash
kernel, since the whole sequence is device-local), and a second
``all_to_all`` transposes back.

Trade-off vs ring: 2 all-to-alls of activation size per layer (cheap on
ICI) instead of n-1 K/V hops, but heads must divide the ``seq`` axis so
it caps at n <= n_heads; ring has no such cap. Select per-step with
``ParallelSpec(sp_mode='ulysses')``.
"""
import jax

from autodist_tpu.kernels import flash_attention as fa
from autodist_tpu.parallel.axes import axis_size, unsharded_execution
from autodist_tpu.parallel.ring_attention import local_flash_attention


def _local_attn(q, k, v, causal, sm_scale):
    if unsharded_execution() and fa.preferred(q.shape):
        return fa.flash_attention(q, k, v, causal=causal,
                                  sm_scale=sm_scale)
    return local_flash_attention(q, k, v, causal=causal,
                                 sm_scale=sm_scale)


def ulysses_attention(q, k, v, axis_name, causal=True, sm_scale=None):
    """Exact attention over a sequence-sharded axis via all-to-all.

    Args:
        q, k, v: [batch, heads, seq_shard, head_dim] local shards with
            the FULL head dimension (sequence sharded over ``axis_name``).
        axis_name: mesh axis carrying the sequence shards.
        causal: standard causal mask (positions are global after the
            transposition — no offset bookkeeping needed).
        sm_scale: softmax scale (default 1/sqrt(head_dim)).

    Returns:
        [batch, heads, seq_shard, head_dim] local output shard.
    """
    n = axis_size(axis_name)
    heads = q.shape[1]
    if heads % n != 0:
        raise ValueError(
            'ulysses sp_mode needs heads %% sp == 0 (heads=%d, sp=%d); '
            'use sp_mode="ring" for this config' % (heads, n))
    if n == 1:
        return _local_attn(q, k, v, causal, sm_scale)

    def to_heads(x):   # [b, h, s/n, d] -> [b, h/n, s, d]
        return jax.lax.all_to_all(x, axis_name, split_axis=1,
                                  concat_axis=2, tiled=True)

    q, k, v = to_heads(q), to_heads(k), to_heads(v)
    o = _local_attn(q, k, v, causal, sm_scale)
    # [b, h/n, s, d] -> [b, h, s/n, d]
    return jax.lax.all_to_all(o, axis_name, split_axis=2,
                              concat_axis=1, tiled=True)
