"""Ring attention: sequence/context-parallel exact attention.

The reference has no long-context support at all (SURVEY.md §5: absent);
this is the greenfield TPU-native subsystem. Design follows the public
ring-attention recipe (Liu et al., arXiv:2310.01889): the sequence axis is
sharded over the ``seq`` mesh axis; each device keeps its Q shard resident
and rotates K/V shards around the ring with ``ppermute`` while
accumulating the attention output with a numerically-stable online
softmax (flash-attention accumulation). Communication overlaps compute on
TPU because XLA's latency-hiding scheduler overlaps the ppermute DMA with
the per-block matmuls.

Runs inside ``shard_map``; the inner block math is pure jnp (XLA fuses
it into the ring schedule) so the same code executes on the CPU test
mesh. The single-device long-sequence path uses the Pallas flash kernel
instead (kernels/flash_attention.py via models/attention.py).
"""
import jax
import jax.numpy as jnp

from autodist_tpu.parallel.axes import axis_size


def _block_attn(q, k, v, mask, sm_scale):
    """One (Q-shard x KV-block) flash-style partial: returns
    (unnormalized out, running max, running sum) contributions."""
    # q: [B, H, Sq, D], k/v: [B, H, Sk, D], mask: [Sq, Sk] additive
    s = jnp.einsum('bhqd,bhkd->bhqk', q, k,
                   preferred_element_type=jnp.float32) * sm_scale
    if mask is not None:
        s = s + mask
    m = jnp.max(s, axis=-1)                       # [B,H,Sq]
    p = jnp.exp(s - m[..., None])
    l = jnp.sum(p, axis=-1)                       # [B,H,Sq]
    o = jnp.einsum('bhqk,bhkd->bhqd', p.astype(v.dtype), v)
    return o.astype(jnp.float32), m, l


def ring_attention(q, k, v, axis_name, causal=True, sm_scale=None):
    """Exact attention over a ring-sharded sequence axis.

    Args:
        q, k, v: [batch, heads, seq_shard, head_dim] local shards.
        axis_name: mesh axis carrying the sequence shards.
        causal: apply a causal mask using *global* positions.
        sm_scale: softmax scale (default 1/sqrt(head_dim)).

    Returns:
        [batch, heads, seq_shard, head_dim] local output shard.
    """
    n = axis_size(axis_name)
    my = jax.lax.axis_index(axis_name)
    s_shard = q.shape[2]
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5

    q_pos = my * s_shard + jnp.arange(s_shard)

    def mask_for(kv_owner):
        if not causal:
            return None
        k_pos = kv_owner * s_shard + jnp.arange(s_shard)
        allowed = q_pos[:, None] >= k_pos[None, :]
        return jnp.where(allowed, 0.0, -1e30).astype(jnp.float32)

    # Online-softmax accumulators.
    acc = jnp.zeros(q.shape[:3] + (v.shape[-1],), jnp.float32)
    m_run = jnp.full(q.shape[:3], -jnp.inf, jnp.float32)
    l_run = jnp.zeros(q.shape[:3], jnp.float32)

    perm = [(i, (i + 1) % n) for i in range(n)]

    def body(step, carry, rotate):
        acc, m_run, l_run, k_cur, v_cur = carry
        owner = (my - step) % n  # whose KV block we hold after `step` hops
        o, m, l = _block_attn(q, k_cur, v_cur, mask_for(owner), sm_scale)
        m_new = jnp.maximum(m_run, m)
        alpha = jnp.exp(m_run - m_new)       # rescale old accumulator
        beta = jnp.exp(m - m_new)            # rescale new block
        acc = acc * alpha[..., None] + o * beta[..., None]
        l_run = l_run * alpha + l * beta
        m_run = m_new
        if rotate:  # the final hop would be idle; skip it
            k_cur = jax.lax.ppermute(k_cur, axis_name, perm)
            v_cur = jax.lax.ppermute(v_cur, axis_name, perm)
        return acc, m_run, l_run, k_cur, v_cur

    carry = (acc, m_run, l_run, k, v)
    # python loop: n is static and small; lets XLA pipeline the ring
    for step in range(n):
        carry = body(step, carry, rotate=step < n - 1)
    acc, m_run, l_run, _, _ = carry

    # Fully-masked rows (can't happen with causal self-attention because
    # position attends to itself) would produce l_run == 0; guard anyway.
    out = acc / jnp.maximum(l_run, 1e-30)[..., None]
    return out.astype(q.dtype)


def local_flash_attention(q, k, v, causal=True, sm_scale=None):
    """Single-device exact attention with the same accumulation; used as
    the non-SP fallback so numerics match ring_attention bit-for-bit-ish."""
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    s = jnp.einsum('bhqd,bhkd->bhqk', q, k,
                   preferred_element_type=jnp.float32) * sm_scale
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        mask = jnp.arange(sq)[:, None] >= jnp.arange(sk)[None, :]
        s = jnp.where(mask, s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum('bhqk,bhkd->bhqd', p.astype(v.dtype), v)
