"""Execution plan: lower a compiled Strategy onto the device mesh.

This is the TPU-native replacement for the reference's graph-transformer
backend (``autodist/kernel/graph_transformer.py:55-92`` and the
synchronizer kernels). Where the reference rewrites a TF graph op-by-op —
replicating subgraphs, splicing collective ops, placing variables — the
rebuild expresses the same per-variable decisions *functionally*:

- **Replication** (replicator.py:73-156) is SPMD: the captured step is
  interpreted once inside ``shard_map`` over the ``data`` mesh axis.
- **AllReduceSynchronizer** (all_reduce_synchronizer.py:102-130) becomes a
  ``jax.lax.pmean`` over ``data``, optionally compressor-wrapped, with
  same-``group`` variables fused into one flat-bucket collective (the
  scoped-allocator equivalent, runner.py:33-46).
- **PSSynchronizer** (ps_synchronizer.py) in synchronous mode is
  numerically an average; its *placement* semantics (variables and
  optimizer slots living on reduction destinations) lower to ZeRO-style
  sharded state over the mesh with gather-on-read / scatter-on-update.
  Partitioned vars shard along the strategy's partition axis.
- Collective "spec" NCCL/RING collapses into XLA's ICI algorithm choice;
  ``RING`` forces an explicit ppermute ring (useful over DCN).
- Collective group/instance keys (reference collective_key.py:43-70, which
  disambiguate concurrent TF collectives) are subsumed: within one XLA
  program channel ids are compiler-assigned, and the cross-process data
  plane namespaces its keys by strategy id + variable name
  (runtime/session.py ``_key``).
"""
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from autodist_tpu.const import (AXIS_DATA, BUCKET_BYTES_PER_CHUNK,
                                DEFAULT_CHUNK_SIZE, ENV)
from autodist_tpu.kernels.partitioner import PartitionerConfig
from autodist_tpu.telemetry import core as _telemetry
from autodist_tpu.parallel import compressor as comp
from autodist_tpu.parallel import schedule_ir as sir
from autodist_tpu.strategy.base import (AllReduceSynchronizer,
                                        PSSynchronizer)
from autodist_tpu.utils import logging


def ring_all_reduce(x, axis_name):
    """Explicit ring all-reduce (sum) via ppermute (reference RING spec).

    Bandwidth-optimal form: ring reduce-scatter (n-1 hops, each moving a
    1/n-size chunk) then a tiled all-gather of the reduced chunks — per
    device the wire is 2·(n-1)/n·|T| ≈ 2·|T|, vs (n-1)·|T| for a naive
    whole-tensor ring. That bound is why a strategy forces ``spec='RING'``
    on DCN-dominated meshes; on ICI, XLA's own algorithm choice usually
    does better, so this only runs when forced. Wire volume is pinned by
    ``tests/test_hlo_collectives.py`` against the compiled HLO.
    """
    from autodist_tpu.parallel.axes import axis_size
    n = axis_size(axis_name)
    if n == 1:
        return x
    shape = x.shape
    flat = jnp.ravel(x)
    m = -(-flat.size // n)
    flat = jnp.pad(flat, (0, m * n - flat.size))
    chunks = flat.reshape(n, m)
    me = jax.lax.axis_index(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]

    # reduce-scatter: after n-1 hops device i owns the full sum of
    # chunk (i+1) % n
    cur = jax.lax.dynamic_index_in_dim(chunks, me, 0, keepdims=False)
    for step in range(n - 1):
        cur = jax.lax.ppermute(cur, axis_name, perm)
        idx = (me - step - 1) % n
        cur = cur + jax.lax.dynamic_index_in_dim(chunks, idx, 0,
                                                 keepdims=False)

    full = jax.lax.all_gather(cur, axis_name)   # [n, m]
    # device row j holds chunk (j+1)%n -> chunk c sits at row (c-1)%n
    full = full[jnp.asarray([(c - 1) % n for c in range(n)])]
    return full.reshape(-1)[:x.size].reshape(shape)


def hierarchical_all_reduce(x, axis_name, node_groups):
    """Two-level all-reduce (sum) over ``node_groups`` of axis
    positions: intra-node reduce-scatter, inter-node all-reduce over
    one chunk-owner per node, intra-node all-gather.

    This is the PCCL-style process-group synthesis for a two-tier
    (ICI within a node, DCN across nodes) topology: the only traffic
    that crosses the node boundary is each node's ``1/g`` chunk of the
    already-reduced bucket, so the DCN wire carries ``2(k-1)/k·B/g``
    bytes instead of the flat ring's ``2(n-1)/n·B`` — the gap
    :func:`~autodist_tpu.simulator.cost_model.hierarchical_time`
    prices. Addition is associative over the regrouping, so the result
    is the same sum the flat ring computes (bit-identical whenever the
    per-element sums are exactly representable). Degenerate group
    shapes (one node, or one device per node) collapse to a plain
    ``psum``.
    """
    k = len(node_groups)
    g = len(node_groups[0]) if node_groups else 0
    if k <= 1 or g <= 1:
        return jax.lax.psum(x, axis_name)
    shape = x.shape
    flat = jnp.ravel(x)
    m = -(-flat.size // g) * g
    flat = jnp.pad(flat, (0, m - flat.size))
    cur = jax.lax.psum_scatter(flat, axis_name, scatter_dimension=0,
                               tiled=True,
                               axis_index_groups=node_groups)
    inter = [[grp[r] for grp in node_groups] for r in range(g)]
    cur = jax.lax.psum(cur, axis_name, axis_index_groups=inter)
    out = jax.lax.all_gather(cur, axis_name, tiled=True,
                             axis_index_groups=node_groups)
    return out[:x.size].reshape(shape)


def hierarchical_psum_scatter(x, axis_name, node_groups, axis=0):
    """Two-level reduce-scatter (sum) along ``axis``: intra-node
    reduce-scatter, then inter-node reduce-scatter of the owned chunk
    over one representative per node — the scatter HALF of
    :func:`hierarchical_all_reduce`, so the only cross-node traffic is
    ``(k-1)/k`` of each node's ``1/g`` chunk. A chunk pre-permutation
    makes the final ownership IDENTICAL to the flat ``psum_scatter``
    (the device at data-axis position ``d`` owns chunk ``d``), so ZeRO
    shard layouts and update-sharding buckets can swap schedules
    without any relayout; the result is a pure re-association of the
    flat sum (bit-identical whenever the per-element sums are exactly
    representable). ``axis`` length must divide by the axis size.
    Degenerate group shapes collapse to the flat collective.
    """
    k = len(node_groups) if node_groups else 0
    g = len(node_groups[0]) if node_groups else 0
    if k <= 1 or g <= 1:
        return jax.lax.psum_scatter(x, axis_name, scatter_dimension=axis,
                                    tiled=True)
    n = k * g
    moved = jnp.moveaxis(x, axis, 0)
    m = moved.shape[0] // n
    rest = moved.shape[1:]
    # the two scatters deliver block (p, j) of a (g, k, m)-blocked
    # layout to the device at intra position p in node j (= data-axis
    # position j*g+p); pre-permuting (k, g) -> (g, j) block order makes
    # that block the flat layout's chunk j*g+p
    arranged = jnp.moveaxis(moved.reshape((k, g, m) + rest), 1, 0)
    arranged = arranged.reshape((n * m,) + rest)
    cur = jax.lax.psum_scatter(arranged, axis_name, scatter_dimension=0,
                               tiled=True, axis_index_groups=node_groups)
    inter = [[grp[r] for grp in node_groups] for r in range(g)]
    cur = jax.lax.psum_scatter(cur, axis_name, scatter_dimension=0,
                               tiled=True, axis_index_groups=inter)
    return jnp.moveaxis(cur, 0, axis)


def hierarchical_all_gather(x, axis_name, node_groups, axis=0):
    """Two-level all-gather along ``axis``: inter-node all-gather of
    this device's chunk (the DCN phase moves ``(k-1)/k`` of ``1/g`` of
    the payload per device), then intra-node all-gather, then the
    inverse of :func:`hierarchical_psum_scatter`'s chunk permutation —
    the result is IDENTICAL to the flat tiled ``all_gather`` (chunk
    ``d`` comes from data-axis position ``d``). The gather HALF of the
    two-level schedule: ZeRO param re-gathers and the weight-update-
    sharding bucket gather ride it when the shared cost-model decision
    picks the hierarchical schedule.
    """
    k = len(node_groups) if node_groups else 0
    g = len(node_groups[0]) if node_groups else 0
    if k <= 1 or g <= 1:
        return jax.lax.all_gather(x, axis_name, axis=axis, tiled=True)
    moved = jnp.moveaxis(x, axis, 0)
    m = moved.shape[0]
    rest = moved.shape[1:]
    inter = [[grp[r] for grp in node_groups] for r in range(g)]
    cur = jax.lax.all_gather(moved, axis_name, axis=0, tiled=True,
                             axis_index_groups=inter)
    out = jax.lax.all_gather(cur, axis_name, axis=0, tiled=True,
                             axis_index_groups=node_groups)
    # out block (p, j) holds the shard of data-axis position j*g+p;
    # permute back to flat chunk order
    out = jnp.moveaxis(out.reshape((g, k, m) + rest), 1, 0)
    out = out.reshape((k * g * m,) + rest)
    return jnp.moveaxis(out, 0, axis)


def _numel(shape):
    n = 1
    for d in (shape or (1,)):
        n *= int(d)
    return n


def bucket_bytes_cap(chunk_size=0):
    """Per-bucket byte cap for fused gradient collectives.

    ``AUTODIST_BUCKET_BYTES`` overrides directly; otherwise the cap
    derives from the strategy's ``chunk_size`` (tensors per merged
    group) at ``BUCKET_BYTES_PER_CHUNK`` each, so the reference knob
    keeps meaning something at modern model sizes: a group is never
    fused into one model-sized concat, it is packed into byte-capped
    buckets whose collectives can overlap the backward pass.
    """
    cap = ENV.AUTODIST_BUCKET_BYTES.val
    if cap:
        return max(1, cap)
    return (chunk_size or DEFAULT_CHUNK_SIZE) * BUCKET_BYTES_PER_CHUNK


def pack_buckets(items, cap_bytes, max_vars=0):
    """Greedy contiguous packing of ``[(key, nbytes)]`` into buckets.

    Pure and deterministic (the same inputs produce the same buckets on
    every process — divergent bucket layouts across SPMD hosts would
    deadlock the collective). A bucket closes when adding the next item
    would exceed ``cap_bytes`` (an item larger than the cap still gets
    a bucket of its own) or when it already holds ``max_vars`` items
    (0 = unbounded). Returns ``[[key, ...], ...]`` in input order.
    """
    buckets = []
    cur, cur_bytes = [], 0
    for key, nbytes in items:
        if cur and (cur_bytes + nbytes > cap_bytes or
                    (max_vars and len(cur) >= max_vars)):
            buckets.append(cur)
            cur, cur_bytes = [], 0
        cur.append(key)
        cur_bytes += nbytes
    if cur:
        buckets.append(cur)
    return buckets


def bucket_fusable(plan, dtype, size):
    """THE per-variable admission predicate for fused AR buckets,
    shared verbatim by the traced emitter (``sync_gradients``) and the
    static mirror (``static_collective_schedule``): same-group
    AllReduce vars whose compressor is stateless on the bucket wire
    (none / bf16 cast) or whose int8 error-feedback state admits
    bucket-level residuals (``compressor.int8_bucket_fusable``)."""
    return bool(plan.is_ar and plan.group is not None and
                (type(plan.compressor) in (comp.NoneCompressor,
                                           comp.HorovodCompressor) or
                 comp.int8_bucket_fusable(plan.compressor, dtype,
                                          size)))


def bucket_fusion_key(plan, dtype):
    """THE bucket-fusion identity: variables may share a bucket only
    when every field that changes the emitted collective agrees —
    group, compressor, dtype, spec, and the two per-bucket schedule
    knobs (hierarchical, weight-update sharding). Both emitters key
    their packing off this tuple, so the traced and static bucket
    layouts cannot drift."""
    return (plan.group, type(plan.compressor).__name__,
            str(jnp.dtype(dtype)), plan.spec, plan.hierarchical,
            plan.weight_update_sharding)


def _emit_bucket_tag(entry):
    """Telemetry tag for one emitted sync bucket (trace-time, so this
    fires once per compiled step, not per executed step): schedule
    shape (flat vs two-level), wire dtype, byte count and the
    schedule entry id — the per-bucket emission evidence the cohort
    timeline (and the roofline drift table) pairs with the measured
    step spans. No-op when telemetry is disabled."""
    tel = _telemetry.get()
    if not tel.enabled:
        return
    wire = {'Int8RingCompressor': 'i8',
            'HorovodCompressor': 'bf16',
            'HorovodCompressorEF': 'bf16'}.get(entry['compressor'],
                                               entry['dtype'])
    schedule = 'hier' if entry.get('hier') else 'flat'
    tel.event('bucket_emit', kind=entry['kind'], group=entry['group'],
              schedule=schedule, wire=wire, vars=entry['vars'],
              bytes=entry['bytes'],
              entry_id=entry.get('entry_id', ''))
    tel.count('plan/buckets_emitted')
    tel.count('plan/bucket_%s' % schedule)


def schedule_entry_key(entry):
    """Content key of one collective-schedule entry — THE join key
    between the static schedule (``static_collective_schedule``), the
    traced emission records (``ExecutionPlan.last_bucket_stats``) and
    the roofline observatory's per-entry drift table
    (:mod:`autodist_tpu.telemetry.roofline`). Built only from fields
    both sides carry identically (kind, dtype, compressor, byte count,
    leading member + member count); ``phase`` is deliberately excluded
    — the traced records do not know it, and kind already separates
    the grad/param halves of every pair the schedule emits."""
    members = entry.get('members') or []
    return '%s:%s:%s:%dB:%s+%d' % (
        entry['kind'], entry.get('dtype'),
        entry.get('compressor') or '-', int(entry.get('bytes', 0)),
        members[0] if members else '?', len(members))


def assign_entry_ids(entries, counts=None):
    """Stamp each entry with a stable ``entry_id``: its content key,
    suffixed ``#k`` for the k-th repeat of an identical key (equal-size
    ZeRO chunks of one variable). Deterministic given emission order,
    which both emission paths pin — so an id minted by the traced
    emission round-trips to exactly one static-schedule entry.
    ``counts`` threads the occurrence map across multiple calls within
    ONE trace (the param-gather records land after sync_gradients
    returns). Returns ``entries`` (mutated in place)."""
    counts = {} if counts is None else counts
    for e in entries:
        key = schedule_entry_key(e)
        k = counts.get(key, 0)
        counts[key] = k + 1
        e['entry_id'] = key if k == 0 else '%s#%d' % (key, k)
    return entries


def static_collective_schedule(strategy, graph_item, num_replicas,
                               sparse_lookups_per_replica=4096,
                               nodes=1, params=None,
                               hier_fallback=None):
    """Static mirror of :meth:`ExecutionPlan.sync_gradients`'s emission.

    Computes, WITHOUT tracing a step, the per-step collective schedule a
    strategy lowers to on an ``num_replicas``-way data mesh: the same
    bucket packing (``pack_buckets`` under the chunk_size-derived byte
    cap, reverse production order), the same ZeRO ``psum_scatter``
    chunking, the same per-bucket flat-vs-hierarchical decision
    (``cost_model.choose_hierarchical`` over ``nodes`` node groups and
    ``params``), and the param re-gather each sharded variable pays on
    the next step. This is what the simulator's cost model prices.

    Entries match the ``last_bucket_stats`` schema plus a ``phase``
    field: ``{'kind', 'group', 'compressor', 'dtype', 'spec', 'vars',
    'bytes', 'members', 'phase', 'hier', 'wus'}`` where ``phase`` is
    ``'grad'`` (gradient sync) or ``'param'`` (the post-update param
    re-gather — ZeRO all-gather or the weight-update-sharding bucket
    gather), ``hier`` is the node-group count of a two-level schedule
    (0 = flat; ZeRO scatter/gather halves and update-sharding buckets
    route through the same ``choose_hierarchical`` decision as AR
    buckets) and ``wus`` marks the reduce-scatter + all-gather pair a
    weight-update-sharded bucket lowers to
    (``choose_update_sharding``, the shared decision — padded bytes,
    sharded opt slots). Every entry additionally carries a stable
    ``entry_id`` (:func:`assign_entry_ids` over
    :func:`schedule_entry_key`) that the traced emission records and
    the roofline drift table join on.
    ``bytes``
    are RAW tensor bytes; anything REPORTING traffic must route them
    through ``simulator.cost_model.wire_bytes`` (as the cost model,
    ``profiling.bucket_report`` and ``bench.py`` do) — under a
    compressed wire the raw figure overstates by 2-4x. Sparse
    (embedding) vars
    assume ``sparse_lookups_per_replica`` looked-up rows per step, the
    runtime's data-dependent quantity.

    Every entry is DERIVED from the schedule IR: the same
    ``schedule_ir.bucket_program`` lowering the traced emission
    executes produces the entry via ``schedule_ir.schedule_entry``, so
    predicted==traced is structural rather than test-pinned. When the
    caller's host layout forced the hierarchical fallback
    (``cost_model.num_node_groups_with_reason``), ``hier_fallback``
    carries the reason and rides every flat comm entry, so a priced
    flat win stays distinguishable from a layout degrade.
    """
    import numpy as np

    n = int(num_replicas)
    entries = []
    if n <= 1:
        return entries
    nodes = int(nodes or 1)
    from autodist_tpu.simulator.cost_model import (
        choose_hierarchical, choose_update_sharding,
        optimizer_slot_count)
    if params is None:
        from autodist_tpu.simulator.cost_model import CostModelParams
        params = CostModelParams()
    opt_slots = optimizer_slot_count(graph_item)

    def half_hier(nbytes, dtype, knob, spec):
        """Two-level decision for ONE scatter/gather half — the same
        shared choose_hierarchical call as the AR buckets (half time
        is exactly half of AR time, so the comparison is identical)."""
        if nodes <= 1:
            return 0
        return nodes if choose_hierarchical(
            nbytes, dtype, 'NoneCompressor', n, nodes, params,
            knob=knob, spec=spec) else 0

    node_cfg = {nd.var_name: nd for nd in strategy.node_config}
    sources = list(graph_item.trainable_var_op_to_var.values())
    plans = []
    for var in sources:
        node = node_cfg.get(var.name)
        if node is None:
            from autodist_tpu.strategy.base import StrategyNode
            node = StrategyNode(var_name=var.name,
                                synchronizer=AllReduceSynchronizer())
        plan = VarPlan(var, node)
        # mirror ExecutionPlan.__init__'s state-sharding rule
        if plan.is_ps and len(var.shape) > 0:
            ax = plan.shard_axis
            if var.shape[ax] >= n and plan.num_shards > 1:
                plan.state_sharded = True
                dim = int(var.shape[ax])
                plan.padded_dim = -(-dim // n) * n
                plan.pad = plan.padded_dim - dim
        plans.append(plan)

    def entry(kind, plan, nbytes, members, phase='grad', vars_=1,
              group=None, compressor=None, hier=0):
        prog = sir.bucket_program(kind, nbytes,
                                  str(np.dtype(plan.var.dtype)),
                                  compressor, plan.spec, n, hier=hier)
        e = sir.schedule_entry(prog, group=group, members=list(members),
                               vars_=vars_, phase=phase)
        # the legacy schema keeps the caller's literal compressor field
        # (None for the un-grouped kinds) — the IR meta normalizes to
        # registry names, which would change pinned entry ids
        e['compressor'] = compressor
        return e

    fusable = {}   # (group, compressor, dtype, spec, hier, wus) -> [idx]
    for i, (var, plan) in enumerate(zip(sources, plans)):
        itemsize = np.dtype(var.dtype).itemsize
        size = int(np.prod(var.shape or (1,)))
        nbytes = size * itemsize
        sparse = bool(graph_item.is_sparse(var)) and len(var.shape) == 2
        b = min(sparse_lookups_per_replica, int(var.shape[0])) \
            if sparse else 0
        sparse_bytes = n * b * (int(var.shape[1]) + 1) * itemsize \
            if sparse else None
        cname = type(plan.compressor).__name__
        if plan.state_sharded:
            padded_shape = list(var.shape)
            padded_shape[plan.shard_axis] = plan.padded_dim or \
                var.shape[plan.shard_axis]
            padded = int(np.prod(padded_shape)) * itemsize
            if sparse and plan.shard_axis == 0 and \
                    sparse_bytes < nbytes // n:
                entries.append(entry('sparse_scatter', plan, sparse_bytes,
                                     [var.name]))
            else:
                # mirror _capped_psum_scatter's chunking exactly
                # (incl. its per-chunk two-level decision)
                cap = bucket_bytes_cap(plan.chunk_size)
                ndim = len(var.shape)
                dstr = str(np.dtype(var.dtype))
                if padded <= cap or ndim < 2:
                    entries.append(entry(
                        'psum_scatter', plan, padded, [var.name],
                        hier=half_hier(padded, dstr,
                                       plan.hierarchical, plan.spec)))
                else:
                    split_axis = 0 if plan.shard_axis != 0 else 1
                    dim = int(padded_shape[split_axis])
                    row = padded // dim
                    k = min(dim, -(-padded // cap))
                    for j in range(k):
                        rows = dim * (j + 1) // k - dim * j // k
                        entries.append(entry(
                            'psum_scatter', plan, rows * row,
                            [var.name],
                            hier=half_hier(rows * row, dstr,
                                           plan.hierarchical,
                                           plan.spec)))
            # the updated shard is re-gathered for the next step. A
            # sparse (embedding) table only needs its looked-up rows
            # fresh — the loose-mode row-sparse plane refreshes them
            # point-to-point (BGETROWS), and the SPMD lowering gathers
            # rows, not the table — so the param phase is priced by
            # expected touched rows, not O(vocab x dim): full-size
            # pricing made AutoStrategy reject PS for exactly the
            # variables PS exists for.
            if sparse and plan.shard_axis == 0 and \
                    sparse_bytes < padded:
                entries.append(entry('sparse_all_gather', plan,
                                     sparse_bytes, [var.name],
                                     phase='param'))
            else:
                entries.append(entry(
                    'all_gather', plan, padded, [var.name],
                    phase='param',
                    hier=half_hier(padded, str(np.dtype(var.dtype)),
                                   plan.hierarchical, plan.spec)))
        elif sparse and type(plan.compressor) is comp.NoneCompressor \
                and sparse_bytes < nbytes:
            entries.append(entry('sparse_all_gather', plan, sparse_bytes,
                                 [var.name]))
        elif bucket_fusable(plan, var.dtype, size):
            fusable.setdefault(bucket_fusion_key(plan, var.dtype),
                               []).append(i)
        else:
            entries.append(entry('all_reduce', plan, nbytes, [var.name],
                                 group=plan.group, compressor=cname))
    # pack fusable groups exactly like sync_gradients: byte-capped
    # buckets in reverse production order, emitted tail-first
    pending = []
    for (group, cname, dtype, spec, hknob, wknob), idxs in \
            fusable.items():
        chunk = max(plans[i].chunk_size for i in idxs)
        cap = bucket_bytes_cap(chunk)
        items = [(i, int(np.prod(sources[i].shape or (1,))) *
                  np.dtype(sources[i].dtype).itemsize)
                 for i in reversed(idxs)]
        sizes = dict(items)
        for bucket in pack_buckets(items, cap,
                                   chunk or DEFAULT_CHUNK_SIZE):
            pending.append((bucket, sizes, group, cname, dtype, spec,
                            hknob, wknob))
    pending.sort(key=lambda b: -max(b[0]))
    for bucket, sizes, group, cname, dtype, spec, hknob, wknob in \
            pending:
        nbytes = sum(sizes[i] for i in bucket)
        if choose_update_sharding(nbytes, dtype, cname, n, params,
                                  knob=wknob, opt_slots=opt_slots,
                                  cross_node=nodes > 1, spec=spec):
            # weight-update-sharded bucket: reduce-scatter (grad
            # phase) + bucketed param all-gather (param phase), each
            # member zero-padded to a multiple of n — exactly what
            # _wus_scatter_bucket / gather_updated_params emit. The
            # psum_scatter kind is what makes memory_footprint drop
            # the members' opt-slot (and resident-grad) bytes to 1/n.
            itemsize = np.dtype(dtype).itemsize
            wbytes = sum((-(-(sizes[i] // itemsize) // n)) * n * itemsize
                         for i in bucket)
            hier = 0
            if nodes > 1 and choose_hierarchical(
                    wbytes, dtype, cname, n, nodes, params,
                    knob=hknob, spec=spec):
                hier = nodes
            members = [sources[i].name for i in bucket]
            for kind, phase in (('psum_scatter', 'grad'),
                                ('all_gather', 'param')):
                prog = sir.bucket_program(kind, wbytes, dtype, cname,
                                          spec, n, hier=hier, wus=True)
                entries.append(sir.schedule_entry(
                    prog, group=group, members=list(members),
                    vars_=len(bucket), phase=phase))
            continue
        hier = 0
        if nodes > 1 and choose_hierarchical(
                nbytes, dtype, cname, n, nodes, params,
                knob=hknob, spec=spec):
            hier = nodes
        prog = sir.bucket_program('all_reduce', nbytes, dtype, cname,
                                  spec, n, hier=hier)
        entries.append(sir.schedule_entry(
            prog, group=group,
            members=[sources[i].name for i in bucket],
            vars_=len(bucket), phase='grad'))
    if hier_fallback:
        # satellite of the unequal-host warning: the reason a flat
        # schedule was forced (vs merely priced cheaper) rides every
        # flat comm entry, joinable downstream by entry id
        for e in entries:
            if e['kind'] in ('all_reduce', 'psum_scatter',
                             'all_gather') and not e.get('hier'):
                e['hier_fallback'] = hier_fallback
    return assign_entry_ids(entries)


class ShardedGrad:
    """A reduce-scattered gradient shard (ZeRO-sharded PS variables).

    Produced by :meth:`ExecutionPlan.sync_gradients` for variables whose
    optimizer state is sharded; consumed by ``Optimizer._apply`` (updates
    the local shard only) or gathered to full on direct fetch.

    ``logical_dim`` records the unpadded size of the shard axis for
    uneven partitions (UnevenPartitionedPS): physical shards are padded
    to equal size, and :meth:`gather` slices the padding back off.

    ``hier_groups`` carries the node groups of a two-level param
    re-gather (the gather half of the hierarchical ZeRO schedule) when
    the shared cost-model decision picked it
    (:meth:`ExecutionPlan.gather_hier_groups`); None = flat.
    """

    def __init__(self, value, axis, logical_dim=None, hier_groups=None):
        self.value = value
        self.axis = axis
        self.logical_dim = logical_dim
        self.hier_groups = hier_groups

    def gather(self):
        if self.hier_groups:
            full = hierarchical_all_gather(self.value, AXIS_DATA,
                                           self.hier_groups,
                                           axis=self.axis)
        else:
            full = jax.lax.all_gather(self.value, AXIS_DATA,
                                      axis=self.axis, tiled=True)
        if self.logical_dim is not None and \
                full.shape[self.axis] != self.logical_dim:
            full = jax.lax.slice_in_dim(full, 0, self.logical_dim,
                                        axis=self.axis)
        return full


class UpdateShard:
    """One variable's 1/n flat shard inside a weight-update-sharded
    bucket (cross-replica weight-update sharding, arXiv:2004.13336).

    Produced by :meth:`ExecutionPlan.sync_gradients` carrying the
    MEAN-gradient shard of an update-sharded AR bucket member;
    consumed by ``Optimizer._apply``, which slices the matching param
    shard (:meth:`slice_param`), runs the fused shard-local update
    against shard-resident slots (``Optimizer.shard_update``) and
    hands back an UpdateShard of the UPDATED param via
    :meth:`with_value`; the frontend's ApplyGradients evaluation then
    re-gathers whole buckets at once through
    :meth:`ExecutionPlan.gather_updated_params`.

    The flat layout is row-major over the variable, zero-padded to a
    multiple of n; the device at data-axis position d owns elements
    ``[d*m, (d+1)*m)`` — the same ownership the flat and hierarchical
    reduce-scatters deliver. ``meta`` is the bucket record shared by
    every member (names, shard sizes, hier groups), which is how the
    gather side reassembles the exact scatter buckets.
    """

    is_update_shard = True
    axis_name = AXIS_DATA

    def __init__(self, value, plan, var, meta, index):
        self.value = value
        self.plan = plan
        self.var = var
        self.meta = meta
        self.index = index

    @property
    def shard_size(self):
        return self.meta['shard_sizes'][self.index]

    def slice_param(self, full_value):
        """This replica's flat param shard of the (replicated) full
        value — a local dynamic-slice, no communication."""
        m = self.shard_size
        flat = jnp.ravel(full_value)
        padded = m * self.plan.num_replicas
        if padded > flat.shape[0]:
            flat = jnp.pad(flat, (0, padded - flat.shape[0]))
        start = jax.lax.axis_index(AXIS_DATA) * m
        return jax.lax.dynamic_slice(flat, (start,), (m,))

    def with_value(self, new_value):
        return UpdateShard(new_value, self.plan, self.var, self.meta,
                           self.index)

    def gather(self):
        """Full var-shaped value from the shards (single-member gather
        — used by direct fetches / user arithmetic via ``_degrade``;
        the ApplyGradients fast path gathers whole buckets instead)."""
        if self.meta.get('hier_groups'):
            full = hierarchical_all_gather(self.value, AXIS_DATA,
                                           self.meta['hier_groups'])
        else:
            full = jax.lax.all_gather(self.value, AXIS_DATA, tiled=True)
        return full[:_numel(self.var.shape)].reshape(self.var.shape)


class VarPlan:
    """Resolved per-variable execution decisions."""

    def __init__(self, var, node):
        self.var = var
        self.node = node
        syncs = node.part_config if node.part_config else [node.synchronizer]
        self.sync = syncs[0]
        self.all_syncs = syncs
        self.is_ps = isinstance(self.sync, PSSynchronizer)
        self.is_ar = isinstance(self.sync, AllReduceSynchronizer)
        # shard geometry via the partitioner math module (reference
        # PartitionerConfig, kernel/partitioner.py:38-150)
        self.part_config = PartitionerConfig(node.partitioner)
        self.num_shards = self.part_config.num_shards
        self.partition_axis = self.part_config.axis
        self.sparse_synced = False   # set at trace time by sync_gradients
        self.staleness = getattr(self.sync, 'staleness', 0)
        self.sync_mode = getattr(self.sync, 'sync', True)
        # local-SGD window length H (PSSynchronizer.local_steps);
        # legacy strategies and AR synchronizers carry 1 (every-step)
        self.local_steps = max(
            1, int(getattr(self.sync, 'local_steps', 1) or 1))
        if self.is_ar:
            self.compressor = comp.create(self.sync.compressor, var.name)
            self.group = self.sync.group
            self.spec = self.sync.spec
            self.chunk_size = getattr(self.sync, 'chunk_size', 0)
            self.hierarchical = getattr(self.sync, 'hierarchical',
                                        'auto') or 'auto'
            self.weight_update_sharding = getattr(
                self.sync, 'weight_update_sharding', 'never') or 'never'
            if getattr(var, 'sparse_read', False):
                # row-lazy semantics (LazyAdam/LazyMomentum keep
                # zero-grad rows bit-identical) are defined over whole
                # rows; the flat 1/n shard layout cannot compute the
                # row mask shard-locally, so sparse-read variables keep
                # the replicated update — 'ineligible' is stronger than
                # 'never': the env override does not shard it either
                self.weight_update_sharding = 'ineligible'
        else:
            self.compressor = comp.create('NoneCompressor', var.name)
            self.group = None
            self.spec = 'AUTO'
            self.chunk_size = 0
            # the ZeRO scatter/gather halves route through the same
            # choose_hierarchical decision as the AR buckets; the
            # PSSynchronizer's knob governs it ('auto' default)
            self.hierarchical = getattr(self.sync, 'hierarchical',
                                        'auto') or 'auto'
            self.weight_update_sharding = 'never'
        # Cross-replica weight-update sharding (set by ExecutionPlan
        # from the per-bucket choose_update_sharding decision): the
        # gradient bucket is reduce-scattered, the optimizer updates
        # this replica's 1/n flat shard against shard-resident slots,
        # and the updated params ride a bucketed all-gather. The flat
        # layout is row-major, zero-padded to wus_padded = n * wus_shard.
        self.update_sharded = False
        self.wus_shard = 0       # per-replica flat shard elements
        self.wus_padded = 0      # padded flat size (n * wus_shard)
        self.wus_pad = 0         # zero-pad elements at the flat tail
        # ZeRO-style state sharding applies to partitioned vars; when the
        # partition axis does not divide the mesh data axis (the uneven
        # case, UnevenPartitionedPS) the physical state is zero-padded to
        # the next multiple and the padding sliced off on every read.
        self.state_sharded = False
        self.shard_axis = self.partition_axis if \
            self.partition_axis is not None else 0
        self.pad = 0             # physical padding rows on shard_axis
        self.padded_dim = None   # physical (padded) size of shard_axis


class ExecutionPlan:
    """Binds (strategy, graph_item, mesh) into callable sync/sharding hooks."""

    def __init__(self, strategy, graph_item, mesh, shard_ps_state=True,
                 loose=False, topology=None):
        self.strategy = strategy
        self.graph_item = graph_item
        self.mesh = mesh
        self.num_replicas = mesh.shape[AXIS_DATA]
        # two-level collective context: the data axis's node groups
        # (None = single-node mesh, flat emission — the degenerate
        # case) and the α-β constants the per-bucket flat-vs-
        # hierarchical decision prices with. ``topology`` is the
        # resource spec's validated Topology when the caller has one;
        # without it the analytic defaults apply.
        from autodist_tpu.parallel.mesh import data_axis_node_groups
        self.topology = topology
        self.hier_groups = data_axis_node_groups(
            mesh, forced_nodes=ENV.AUTODIST_HIERARCHY_NODES.val)
        from autodist_tpu.simulator.cost_model import CostModelParams
        self.cost_params = CostModelParams.from_topology(topology) \
            if topology is not None else CostModelParams()
        # loose mode: independent per-process programs + coord-service PS
        # (relaxed-consistency strategies); mesh is process-local.
        self.loose = loose
        # how many jax processes share this mesh (global SPMD mode); the
        # feed/fetch contract is process-local (between-graph semantics)
        self.num_processes = 1 if loose else \
            max(1, len({d.process_index for d in mesh.devices.flat}))
        self.local_replicas = max(1, self.num_replicas //
                                  self.num_processes)
        self.var_plans = {}
        nodes = {n.var_name: n for n in strategy.node_config}
        for name, var in graph_item.trainable_var_op_to_var.items():
            node = nodes.get(name)
            if node is None:
                from autodist_tpu.strategy.base import StrategyNode
                node = StrategyNode(
                    var_name=name, synchronizer=AllReduceSynchronizer())
                logging.debug('Variable %s missing from strategy; '
                              'defaulting to AllReduce', name)
            plan = VarPlan(var, node)
            if shard_ps_state and plan.is_ps and len(var.shape) > 0:
                ax = plan.shard_axis
                n = self.num_replicas
                if var.shape[ax] >= n and plan.num_shards > 1:
                    plan.state_sharded = True
                    dim = int(var.shape[ax])
                    plan.padded_dim = -(-dim // n) * n
                    plan.pad = plan.padded_dim - dim
            self.var_plans[name] = plan
        # Weight-update-sharding marking: the per-BUCKET decision
        # (cost_model.choose_update_sharding over the exact packed
        # buckets) is precomputed here because the optimizer-slot
        # PLACEMENT must be known before any trace — the session
        # places each marked variable's slots as flat 1/n shards.
        # static_collective_schedule runs the SAME packing and the
        # SAME shared decision the traced emission re-derives
        # (_wus_for), so marking, trace and pricing can never drift.
        env_wus = ENV.AUTODIST_WEIGHT_UPDATE_SHARDING.val
        may_shard = env_wus in ('auto', 'always') or (
            env_wus != 'never' and any(
                p.is_ar and p.weight_update_sharding != 'never'
                for p in self.var_plans.values()))
        if may_shard and self.num_replicas > 1:
            nodes_n = len(self.hier_groups) if self.hier_groups else 1
            for e in static_collective_schedule(
                    strategy, graph_item, self.num_replicas,
                    nodes=nodes_n, params=self.cost_params):
                if not (e.get('wus') and e['kind'] == 'psum_scatter'):
                    continue
                for name in e['members']:
                    p = self.var_plans.get(name)
                    if p is None:
                        continue
                    size = _numel(p.var.shape)
                    p.update_sharded = True
                    p.wus_shard = -(-size // self.num_replicas)
                    p.wus_padded = p.wus_shard * self.num_replicas
                    p.wus_pad = p.wus_padded - size
        self.max_staleness = max(
            [p.staleness for p in self.var_plans.values()] + [0])
        self._pure_sparse_cache = {}
        # per-bucket accounting from the most recent sync_gradients
        # trace: [{'kind', 'group', 'compressor', 'dtype', 'spec',
        # 'vars', 'bytes'}] — 'bytes' are RAW tensor bytes; bench.py
        # and utils/profiling.bucket_report attach the wire figure via
        # simulator.cost_model.wire_bytes so the bucket layout (and the
        # overlap + compression it enables) is auditable without
        # reading HLO. Each record carries the schedule 'entry_id'
        # (assign_entry_ids over the shared content key), which
        # round-trips to static_collective_schedule — the join the
        # roofline drift table runs on.
        self.last_bucket_stats = []
        self._entry_id_counts = {}
        # loose-mode gate: any sync=True var demands its staleness bound;
        # the program-wide gate enforces the tightest one (per-variable
        # windows collapse to one window since the step is one program).
        sync_stale = [p.staleness for p in self.var_plans.values()
                      if p.sync_mode]
        self.gate_enabled = bool(sync_stale)
        self.gate_staleness = min(sync_stale) if sync_stale else 0
        relaxed = [p for p in self.var_plans.values()
                   if p.staleness > 0 or not p.sync_mode]
        if relaxed and not loose:
            # Within one SPMD program all replicas are lock-step, which
            # trivially satisfies any staleness bound; the relaxed-
            # consistency fast path (multi-process async PS over the
            # coordination service) only engages in multi-process runs
            # with an all-relaxed-PS strategy.
            logging.warning(
                'Strategy requests relaxed consistency (async/stale) for '
                '%d vars; single-program execution is synchronous, which '
                'is a valid (staleness=0) schedule of the requested bound.',
                len(relaxed))
        # local-SGD window length H (docs/design/local-sgd.md): one
        # step is one program, so per-variable windows collapse to one
        # program-wide H — mixed requests take the tightest (min),
        # mirroring the gate's min-staleness collapse above.
        ps_h = [p.local_steps for p in self.var_plans.values()
                if p.is_ps]
        h = min(ps_h) if ps_h else 1
        if ps_h and len(set(ps_h)) > 1:
            logging.warning(
                'Strategy requests mixed local_steps %s across PS vars; '
                'the step is one program, so the tightest window (%d) '
                'applies to all of them.', sorted(set(ps_h)), h)
        env_h = ENV.AUTODIST_LOCAL_STEPS.val
        if env_h > 0:
            h = env_h
        if h > 1 and any(
                p.is_ps and getattr(p.sync, 'shared_optimizer', False)
                for p in self.var_plans.values()):
            logging.warning(
                'local_steps=%d is incompatible with shared_optimizer '
                '(the PS-resident update consumes per-step deltas, not '
                'window-averaged parameter deltas); clamping to 1.', h)
            h = 1
        if h > 1 and not loose:
            # within one SPMD program replicas are lock-step and sync
            # every step by construction — H>1 only means anything on
            # the multi-process loose PS data plane
            logging.warning(
                'local_steps=%d requested but execution is not loose-'
                'mode; single-program execution syncs every step '
                '(H=1 is the only schedule of this program).', h)
            h = 1
        self.local_steps = h

    def plan_for(self, var):
        name = var if isinstance(var, str) else var.name
        return self.var_plans[name]

    def _record_entry(self, entry):
        """Append one traced emission record, stamped with its
        schedule entry id (the occurrence map persists across the
        whole trace — sync_gradients resets it, the param-gather
        records reuse it), and emit its telemetry tag."""
        assign_entry_ids([entry], self._entry_id_counts)
        self.last_bucket_stats.append(entry)
        _emit_bucket_tag(entry)

    # -- gradient synchronization (runs inside shard_map) -----------------
    def _reduce_fn(self, spec, hier_groups=None):
        """Mean-reduce callable for ONE collective, routed through the
        schedule IR: the value's flat/two-level AR program lowers via
        ``schedule_ir.execute`` to the exact legacy emission (pmean,
        the forced ppermute ring, or the two-level composition) — one
        invocation per emitted collective, which the bucketing tests'
        reduce spy counts."""
        n = self.num_replicas
        k = len(hier_groups) if hier_groups else 0

        def fn(g):
            prog = sir.bucket_program(
                'all_reduce', g.size * jnp.dtype(g.dtype).itemsize,
                str(g.dtype), None, spec, n, hier=k,
                node_groups=hier_groups)
            return sir.execute(prog, g, AXIS_DATA)
        return fn

    def _hier_groups_for(self, nbytes, dtype, compressor_name, spec,
                         knob):
        """Node groups for ONE bucket's collective, or None for flat —
        the trace-time side of the SHARED cost-model decision
        (``cost_model.choose_hierarchical``), so the traced emission
        and ``static_collective_schedule`` can never drift."""
        groups = self.hier_groups
        if not groups:
            return None
        from autodist_tpu.simulator.cost_model import choose_hierarchical
        ok = choose_hierarchical(nbytes, dtype, compressor_name,
                                 self.num_replicas, len(groups),
                                 self.cost_params, knob=knob, spec=spec)
        return groups if ok else None

    def _wus_for(self, nbytes, dtype, compressor_name, spec, knob):
        """Replicated-vs-sharded weight-update decision for ONE bucket
        — the trace-time side of the SHARED cost-model decision
        (``cost_model.choose_update_sharding``), the same call the
        init-time slot-placement marking and
        ``static_collective_schedule`` make, so the traced emission,
        the slot layout and the priced schedule can never drift."""
        from autodist_tpu.simulator.cost_model import (
            choose_update_sharding, optimizer_slot_count)
        return choose_update_sharding(
            nbytes, dtype, compressor_name, self.num_replicas,
            self.cost_params, knob=knob,
            opt_slots=optimizer_slot_count(self.graph_item),
            cross_node=bool(self.hier_groups), spec=spec)

    def gather_hier_groups(self, plan):
        """Node groups for a ZeRO-sharded variable's param re-gather
        (``ShardedGrad.gather``), or None for flat — the gather half
        routes through the same shared ``choose_hierarchical``
        decision as its reduce-scatter half (half-vs-half compares
        exactly like AR-vs-AR; ``cost_model.hierarchical_half_time``)."""
        if not plan.state_sharded:
            return None
        import numpy as np
        shape = self.padded_shape(plan.var.name) or plan.var.shape
        nbytes = _numel(shape) * np.dtype(plan.var.dtype).itemsize
        return self._hier_groups_for(nbytes,
                                     str(np.dtype(plan.var.dtype)),
                                     'NoneCompressor', plan.spec,
                                     plan.hierarchical)

    # -- sparse (IndexedSlices-equivalent) gradient sync ------------------
    def _purely_sparse(self, var):
        """True iff every consumer of ``var`` is a recorded lookup: a
        dense use (tied embeddings, weight decay on the table, ...) puts
        gradient mass on rows outside the looked-up set, which the sparse
        wire would silently drop."""
        cached = self._pure_sparse_cache.get(var.name)
        if cached is not None:
            return cached
        from autodist_tpu.frontend import graph as fe
        lookup_ops = set(map(id, var.lookup_ops))
        read = var._read
        pure = True
        for node in self.graph_item.graph.nodes:
            if not isinstance(node, fe.Op) or id(node) in lookup_ops:
                continue
            operands = list(node.inputs) + list(node.kwargs.values())
            if any(x is var or (read is not None and x is read)
                   for x in operands):
                pure = False
                break
        self._pure_sparse_cache[var.name] = pure
        return pure

    def _sparse_ids(self, var, env):
        """Traced, flattened lookup-id vector for a sparse-read var, or
        None when the sparse path does not apply."""
        if not getattr(var, 'sparse_read', False) or \
                not getattr(var, 'lookup_ids', None) or \
                len(var.shape) != 2 or not self._purely_sparse(var):
            return None
        from autodist_tpu.frontend import graph as fe
        try:
            parts = [jnp.ravel(fe.evaluate(n, env)).astype(jnp.int32)
                     for n in var.lookup_ids]
        except KeyError:        # ids node depends on an un-fed placeholder
            return None
        return parts[0] if len(parts) == 1 else jnp.concatenate(parts)

    def _gather_slices(self, grad, ids):
        """All-gather each replica's (ids, rows) — the wire format of the
        reference's sparse sync (all_reduce_synchronizer.py:132-173
        all_gathers IndexedSlices indices+values)."""
        rows = jnp.take(grad, ids, axis=0)
        all_ids = jax.lax.all_gather(ids, AXIS_DATA)       # (n, B)
        all_rows = jax.lax.all_gather(rows, AXIS_DATA)     # (n, B, dim)
        return all_ids, all_rows

    def _sparse_allreduce(self, grad, ids):
        """Dense-equivalent mean of per-replica sparse grads: per replica,
        scatter-SET dedups repeated ids (rows already carry the summed
        contribution), then summing over replicas adds distinct workers."""
        all_ids, all_rows = self._gather_slices(grad, ids)

        def body(acc, xs):
            ids_r, rows_r = xs
            upd = jnp.zeros_like(grad).at[ids_r].set(rows_r, mode='drop')
            return acc + upd, None

        acc, _ = jax.lax.scan(body, jnp.zeros_like(grad),
                              (all_ids, all_rows))
        return acc / self.num_replicas

    def _pad_grad(self, plan, grad):
        """Zero-pad a gradient on the shard axis for uneven partitions."""
        if not plan.pad:
            return grad
        cfg = [(0, 0)] * grad.ndim
        cfg[plan.shard_axis] = (0, plan.pad)
        return jnp.pad(grad, cfg)

    def _sparse_scatter_to_shard(self, plan, grad, ids):
        """ZeRO variant: each shard owner keeps only its index range
        (reference splits IndexedSlices by index range,
        partitioner.py:660-684); out-of-range rows drop. Uneven
        partitions use the padded per-shard row count — real ids never
        land in the pad range, so padded rows stay zero."""
        n = self.num_replicas
        shard_rows = (grad.shape[0] + plan.pad) // n
        dim = grad.shape[1]
        all_ids, all_rows = self._gather_slices(grad, ids)
        offset = jax.lax.axis_index(AXIS_DATA) * shard_rows

        def body(acc, xs):
            ids_r, rows_r = xs
            local = ids_r - offset
            # negative indices would wrap (numpy semantics); send them
            # out of bounds high so mode='drop' discards them
            local = jnp.where(local >= 0, local, shard_rows)
            upd = jnp.zeros((shard_rows, dim), grad.dtype) \
                .at[local].set(rows_r, mode='drop')
            return acc + upd, None

        acc, _ = jax.lax.scan(
            body, jnp.zeros((shard_rows, dim), grad.dtype),
            (all_ids, all_rows))
        return ShardedGrad(acc / n, 0, logical_dim=grad.shape[0])

    def _capped_psum_scatter(self, plan, grad):
        """ZeRO reduce-scatter under the same byte cap as the AR buckets.

        A whole-tensor ``psum_scatter`` of a huge gradient serializes
        exactly like a mega all-reduce bucket would, so gradients above
        the cap are split along a NON-scatter axis and reduce-scattered
        chunk by chunk: ownership along the scatter axis is unchanged
        (each chunk scatters the same row ranges to the same owners),
        so concatenating the chunk results is elementwise-identical to
        the single collective. 1-D gradients have no other axis to
        split and go out whole (they are small in practice).
        Returns the local shard value (pre-divided mean).
        """
        n = self.num_replicas
        axis = plan.shard_axis
        g = self._pad_grad(plan, grad)
        cap = bucket_bytes_cap(plan.chunk_size)
        nbytes = g.size * jnp.dtype(g.dtype).itemsize

        def scatter(x, nb):
            # each chunk's scatter independently takes the two-level
            # schedule when the shared cost-model decision prices it
            # cheaper (the hierarchical treatment of the ZeRO scatter
            # half; the gather half decides in gather_hier_groups)
            groups = self._hier_groups_for(int(nb), str(x.dtype),
                                           'NoneCompressor', plan.spec,
                                           plan.hierarchical)
            prog = sir.bucket_program(
                'psum_scatter', int(nb), str(x.dtype), None, plan.spec,
                n, hier=len(groups) if groups else 0,
                node_groups=groups)
            self._record_entry(sir.schedule_entry(
                prog, members=[plan.var.name]))
            return sir.execute(prog, x, AXIS_DATA, axis=axis)

        if nbytes <= cap or g.ndim < 2:
            return scatter(g, nbytes)
        split_axis = 0 if axis != 0 else 1
        dim = g.shape[split_axis]
        k = min(dim, -(-int(nbytes) // cap))
        bounds = [dim * i // k for i in range(1, k)]
        parts = jnp.split(g, bounds, axis=split_axis)
        return jnp.concatenate(
            [scatter(p, p.size * jnp.dtype(p.dtype).itemsize)
             for p in parts], axis=split_axis)

    def sync_gradients(self, sources, grads, env):
        """Average gradients across the data axis per each var's strategy.

        Same-group AllReduce vars with a stateless compressor are packed
        into byte-capped buckets (``pack_buckets``; cap from the
        strategy's ``chunk_size`` / ``AUTODIST_BUCKET_BYTES``) and one
        collective is issued per bucket, in REVERSE gradient-production
        order: the backward pass produces the LAST layer's gradients
        first, so the tail bucket's collective launches while earlier
        layers' backward compute is still in flight (with the XLA
        latency-hiding scheduler, runtime/session.py) instead of one
        model-sized concat serializing behind the whole backward and
        doubling peak gradient memory. Stateful compressors (EF /
        PowerSGD) and PS vars are reduced individually; sparse-read
        (embedding) vars ship (indices, rows) instead of the dense
        vocab-sized gradient whenever that moves fewer bytes; ZeRO
        reduce-scatters are chunked under the same cap.
        """
        self.last_bucket_stats = []
        self._entry_id_counts = {}
        if self.num_replicas == 1:
            return grads
        n = self.num_replicas
        out = list(grads)
        fusable = {}   # (group, compressor cls, dtype, spec) -> [idx]
        for i, (var, grad) in enumerate(zip(sources, grads)):
            plan = self.plan_for(var)
            ids = self._sparse_ids(plan.var, env)
            sparse_bytes = None if ids is None else \
                n * ids.size * (grad.shape[1] + 1)
            if plan.state_sharded:
                if ids is not None and plan.shard_axis == 0 and \
                        sparse_bytes < grad.size // n:
                    out[i] = self._sparse_scatter_to_shard(plan, grad, ids)
                    plan.sparse_synced = True
                    continue
                # ZeRO path: reduce-scatter straight to the shard owner;
                # uneven partitions pad to the next multiple of the mesh.
                out[i] = ShardedGrad(
                    self._capped_psum_scatter(plan, grad),
                    plan.shard_axis,
                    logical_dim=grad.shape[plan.shard_axis],
                    hier_groups=self.gather_hier_groups(plan))
            elif (ids is not None and
                    type(plan.compressor) is comp.NoneCompressor and
                    sparse_bytes < grad.size):
                out[i] = self._sparse_allreduce(grad, ids)
                plan.sparse_synced = True
            elif bucket_fusable(plan, grad.dtype, grad.size):
                fusable.setdefault(bucket_fusion_key(plan, grad.dtype),
                                   []).append(i)
            else:
                out[i] = plan.compressor.reduce(
                    grad, env, self._reduce_fn(plan.spec))
        # Pack every fusable group into byte-capped buckets, then emit
        # ALL buckets (across groups) ordered by reverse production:
        # the bucket holding the highest variable indices first. Each
        # bucket independently picks flat vs two-level: on a multi-node
        # mesh the shared cost-model decision can send a large
        # DCN-bound bucket down the hierarchical schedule while small
        # buckets keep the flat ring.
        pending = []   # (bucket idxs, group, cname, dtype, spec,
        #                 hknob, wknob)
        for (group, cname, dtype, spec, hknob, wknob), idxs in \
                fusable.items():
            chunk = max(self.plan_for(sources[i]).chunk_size
                        for i in idxs)
            cap = bucket_bytes_cap(chunk)
            items = [(i, int(grads[i].size *
                             jnp.dtype(grads[i].dtype).itemsize))
                     for i in reversed(idxs)]
            for bucket in pack_buckets(items, cap,
                                       chunk or DEFAULT_CHUNK_SIZE):
                pending.append((bucket, group, cname, dtype, spec,
                                hknob, wknob))
        pending.sort(key=lambda b: -max(b[0]))
        for bucket, group, cname, dtype, spec, hknob, wknob in pending:
            nbytes = sum(int(grads[i].size *
                             jnp.dtype(grads[i].dtype).itemsize)
                         for i in bucket)
            if self._wus_for(nbytes, dtype, cname, spec, wknob):
                # cross-replica weight-update sharding: the bucket is
                # reduce-SCATTERED instead of all-reduced — each
                # replica receives its contiguous 1/n of every member,
                # updates it shard-locally (Optimizer.shard_update
                # against shard-resident slots) and the updated params
                # ride one bucketed all-gather (gather_updated_params)
                for i, sh in self._wus_scatter_bucket(
                        bucket, sources, grads, group, cname, dtype,
                        spec, hknob):
                    out[i] = sh
                continue
            groups = self._hier_groups_for(nbytes, dtype, cname, spec,
                                           hknob)
            prog = sir.bucket_program(
                'all_reduce', nbytes, dtype, cname, spec,
                self.num_replicas, hier=len(groups) if groups else 0,
                node_groups=groups)
            self._record_entry(sir.schedule_entry(
                prog, group=group,
                members=[sources[i].name for i in bucket],
                vars_=len(bucket)))
            if len(bucket) == 1 and groups is None:
                i = bucket[0]
                plan = self.plan_for(sources[i])
                out[i] = plan.compressor.reduce(
                    grads[i], env, self._reduce_fn(spec))
                continue
            flats = [grads[i].reshape(-1) for i in bucket]
            sizes = [f.shape[0] for f in flats]
            if cname == 'Int8RingCompressor':
                buf = self._int8_bucket_reduce(bucket, sources, flats,
                                               env, hier_groups=groups,
                                               program=prog)
            else:
                reduce_fn = self._reduce_fn(spec, hier_groups=groups) \
                    if groups else self._reduce_fn(spec)
                buf = jnp.concatenate(flats)
                if cname == 'HorovodCompressor' and \
                        buf.dtype == jnp.float32:
                    buf = reduce_fn(
                        buf.astype(jnp.bfloat16)).astype(jnp.float32)
                else:
                    buf = reduce_fn(buf)
            offset = 0
            for i, size in zip(bucket, sizes):
                out[i] = buf[offset:offset + size].reshape(
                    grads[i].shape)
                offset += size
        return out

    def _int8_bucket_reduce(self, bucket, sources, flats, env,
                            hier_groups=None, program=None):
        """Quantized-collective reduction of ONE packed bucket.

        The whole bucket is quantized as a single vector with per-block
        scales (``AUTODIST_QUANT_BLOCK`` elements per f32 scale — an
        outlier gradient poisons only its own block, not every member of
        the bucket) and rides one block-quantized int8 ring all-reduce
        with per-hop requantization. Error feedback stays PER MEMBER:
        each variable's residual from aux-state is added to its slice
        before quantization, and the slice of what the wire dropped is
        written back as that member's next-step residual. The fusion
        predicate (``compressor.int8_bucket_fusable``) only admits
        members with a residual (f32, >= ``MIN_SIZE``) — the
        missing-residual branch below is a safety net for callers with
        uninitialized aux-state (bench harnesses), not a sanctioned
        uncompensated mode. Returns the reduced (mean) flat bucket
        buffer, ready to slice back into member shapes.
        """
        aux = getattr(env, 'aux_state', None) or {}
        comp_flats, res_keys = [], []
        for i, flat in zip(bucket, flats):
            key = 'compressor/%s' % sources[i].name
            res = (aux.get(key) or {}).get('residual')
            if res is not None:
                flat = flat + res.reshape(-1)
                res_keys.append(key)
            else:
                res_keys.append(None)
            comp_flats.append(flat)
        buf = jnp.concatenate(comp_flats)
        transmitted = comp.block_roundtrip(buf)
        offset = 0
        for i, key, flat in zip(bucket, res_keys, comp_flats):
            size = flat.shape[0]
            if key is not None:
                env.aux_updates[key] = {'residual': (
                    flat - transmitted[offset:offset + size]
                ).reshape(self.plan_for(sources[i]).var.shape)}
            offset += size
        n = self.num_replicas
        if program is None:
            program = sir.bucket_program(
                'all_reduce',
                int(buf.size * jnp.dtype(buf.dtype).itemsize),
                str(buf.dtype), 'Int8RingCompressor', 'AUTO', n,
                hier=len(hier_groups) if hier_groups else 0,
                node_groups=hier_groups)
        # quantize once (the roundtrip above), requantize at the tier
        # boundary: the IR lowering dispatches the int8 ring (flat) or
        # the f32-ICI / int8-DCN two-level composition
        return sir.execute(program, transmitted, AXIS_DATA)

    def _wus_scatter_bucket(self, bucket, sources, grads, group, cname,
                            dtype, spec, hknob):
        """Scatter half of ONE weight-update-sharded bucket.

        Pads each member's flat gradient to a multiple of n, interleaves
        the members' per-replica rows so a SINGLE reduce-scatter hands
        every replica the contiguous concat of its member shards (no
        second relayout collective), and wraps each member's
        mean-gradient shard in an :class:`UpdateShard`. The scatter
        independently takes the two-level schedule under the same
        shared ``choose_hierarchical`` decision as an equal-bytes AR
        bucket (half-vs-half prices exactly like AR-vs-AR). Returns
        ``[(source index, UpdateShard)]``.
        """
        n = self.num_replicas
        rows, shard_sizes = [], []
        for i in bucket:
            f = grads[i].reshape(-1)
            padded = -(-f.shape[0] // n) * n
            if padded > f.shape[0]:
                f = jnp.pad(f, (0, padded - f.shape[0]))
            rows.append(f.reshape(n, -1))
            shard_sizes.append(padded // n)
        buf = jnp.concatenate(rows, axis=1).reshape(-1)
        padded_bytes = int(buf.size * jnp.dtype(buf.dtype).itemsize)
        groups = self._hier_groups_for(padded_bytes, dtype, cname, spec,
                                       hknob)
        prog = sir.bucket_program(
            'psum_scatter', padded_bytes, dtype, cname, spec, n,
            hier=len(groups) if groups else 0, wus=True,
            node_groups=groups)
        shard = sir.execute(prog, buf, AXIS_DATA)
        meta = {'members': [sources[i].name for i in bucket],
                'shard_sizes': shard_sizes,
                'hier_groups': groups,
                'group': group, 'compressor': cname, 'dtype': dtype,
                'spec': spec, 'bytes': padded_bytes}
        self._record_entry(sir.schedule_entry(
            prog, group=group, members=list(meta['members']),
            vars_=len(bucket)))
        out, off = [], 0
        for pos, (i, m) in enumerate(zip(bucket, shard_sizes)):
            out.append((i, UpdateShard(shard[off:off + m], self,
                                       sources[i], meta, pos)))
            off += m
        return out

    def gather_updated_params(self, shards):
        """Gather half of the weight-update-sharding schedule: one
        bucketed all-gather per scatter bucket, reassembling every
        member's full updated value from the shard-local optimizer
        results.

        ``shards`` maps var name -> :class:`UpdateShard` carrying the
        UPDATED param shard (``Optimizer._apply``'s output); called by
        the frontend's ApplyGradients evaluation. Buckets mirror the
        scatter buckets exactly (the shared ``meta`` record), which is
        what ``static_collective_schedule``'s param-phase
        ``all_gather`` entries price; a PARTIALLY applied bucket (the
        user updated only some members — rare) degrades to per-member
        gathers. Returns ``{var name: full var-shaped value}``.
        """
        out = {}
        buckets = {}
        for name, sh in shards.items():
            buckets.setdefault(id(sh.meta), (sh.meta, {}))[1][name] = sh
        for meta, members in buckets.values():
            names = meta['members']
            hier = len(meta['hier_groups']) if meta['hier_groups'] \
                else 0
            if set(names) != set(members):
                for name, sh in members.items():
                    out[name] = sh.gather()
                    mprog = sir.bucket_program(
                        'all_gather',
                        sh.shard_size * self.num_replicas *
                        jnp.dtype(sh.value.dtype).itemsize,
                        meta['dtype'], meta['compressor'],
                        meta['spec'], self.num_replicas, hier=hier,
                        wus=True, node_groups=meta['hier_groups'])
                    self._record_entry(sir.schedule_entry(
                        mprog, group=meta['group'], members=[name]))
                continue
            cat = jnp.concatenate([members[nm].value for nm in names])
            groups = meta['hier_groups']
            prog = sir.bucket_program(
                'all_gather', meta['bytes'], meta['dtype'],
                meta['compressor'], meta['spec'], self.num_replicas,
                hier=hier, wus=True, node_groups=groups)
            full = sir.execute(prog, cat, AXIS_DATA)
            self._record_entry(sir.schedule_entry(
                prog, group=meta['group'], members=list(names),
                vars_=len(names)))
            mat = full.reshape(self.num_replicas, -1)
            off = 0
            for nm, m in zip(names, meta['shard_sizes']):
                var = members[nm].var
                flat = mat[:, off:off + m].reshape(-1)
                out[nm] = flat[:_numel(var.shape)].reshape(var.shape)
                off += m
        return out

    # -- padded physical layout (uneven partitions) ------------------------
    def padded_shape(self, var_name):
        """Physical (device) shape of a variable's state array."""
        plan = self.var_plans.get(var_name)
        if plan is None:
            return None
        shape = list(plan.var.shape)
        if plan.state_sharded and plan.pad:
            shape[plan.shard_axis] = plan.padded_dim
        return tuple(shape)

    def pad_host(self, var_name, value):
        """Logical host value -> physical (padded) layout."""
        plan = self.var_plans.get(var_name)
        if plan is None or not (plan.state_sharded and plan.pad):
            return value
        return self._pad_grad(plan, jnp.asarray(value))

    def unpad_host(self, var_name, value):
        """Physical layout -> logical host value."""
        plan = self.var_plans.get(var_name)
        if plan is None or not (plan.state_sharded and plan.pad):
            return value
        dim = plan.var.shape[plan.shard_axis]
        slicer = [slice(None)] * value.ndim
        slicer[plan.shard_axis] = slice(0, dim)
        return value[tuple(slicer)]

    # -- state shardings (used by the Session when placing arrays) --------
    def var_sharding(self, var_name):
        plan = self.var_plans.get(var_name)
        if plan is not None and plan.state_sharded:
            spec = [None] * len(plan.var.shape)
            spec[plan.shard_axis] = AXIS_DATA
            return NamedSharding(self.mesh, P(*spec))
        return NamedSharding(self.mesh, P())

    def var_spec(self, var_name):
        """PartitionSpec form (for shard_map in_specs)."""
        plan = self.var_plans.get(var_name)
        if plan is not None and plan.state_sharded:
            spec = [None] * len(plan.var.shape)
            spec[plan.shard_axis] = AXIS_DATA
            return P(*spec)
        return P()

    def replicated_sharding(self):
        return NamedSharding(self.mesh, P())

    def feed_splittable(self, value, placeholder=None):
        """Reference remapper rule (remapper.py:109-123): split feeds with a
        *polymorphic* (declared-None) batch dim across replicas, duplicate
        the rest. Fixed-shape placeholders are never split, matching the
        reference's shape-compatibility check.

        Unlike the reference's ``np.array_split`` (ragged per-replica
        batches under TF's dynamic shapes), XLA needs static equal
        shards, so a batch that does not divide the replica count is
        REPLICATED — numerically exact for mean losses but n× the
        FLOPs; warned once per placeholder so the cost is never silent.
        """
        if placeholder is not None:
            shape = getattr(placeholder, 'shape', None)
            if shape is not None and (len(shape) == 0 or
                                      shape[0] is not None):
                return False
        # Feeds are process-local (between-graph semantics): the value only
        # has to split across this process's local replicas.
        ok = (getattr(value, 'ndim', 0) >= 1 and
              value.shape[0] % self.local_replicas == 0 and
              value.shape[0] > 0)
        if (not ok and self.local_replicas > 1 and
                getattr(value, 'ndim', 0) >= 1 and value.shape[0] > 0):
            key = id(placeholder) if placeholder is not None else None
            if not hasattr(self, '_split_warned'):
                self._split_warned = set()
            if key not in self._split_warned:
                self._split_warned.add(key)
                logging.warning(
                    'Feed %s batch dim %d does not divide the %d local '
                    'replicas; the feed is REPLICATED on every replica '
                    '(exact numerics, %dx the FLOPs). Pad the batch to '
                    'a multiple of %d to split it.',
                    getattr(placeholder, 'name', '<tensor>'),
                    value.shape[0], self.local_replicas,
                    self.local_replicas, self.local_replicas)
        return ok

    def describe(self):
        """Human-readable lowering summary (logged like the reference logs
        its compiled strategy, autodist.py:117)."""
        lines = ['ExecutionPlan over mesh %s:' % dict(self.mesh.shape)]
        if any(p.is_ps and getattr(p.sync, 'reduction_destination', '')
               for p in self.var_plans.values()):
            lines.append(
                '  (PS reduction destinations are advisory under SPMD: '
                'state shards over the mesh, collectives replace '
                'push/pull. In loose mode they are load-bearing: each '
                'variable lives on the PS endpoint its destination maps '
                'to — session._init_ps_endpoints)')
        for name, p in self.var_plans.items():
            kind = 'AllReduce' if p.is_ar else 'PS'
            extra = ''
            if p.is_ps and getattr(p.sync, 'reduction_destination', ''):
                extra += ' dest=%s' % p.sync.reduction_destination
            if p.num_shards > 1:
                extra += ' shards=%d axis=%s' % (p.num_shards,
                                                 p.partition_axis)
            if p.state_sharded:
                extra += ' [ZeRO-sharded%s]' % (
                    ' pad=%d' % p.pad if p.pad else '')
            if p.is_ar:
                extra += ' group=%s compressor=%s' % (
                    p.group, type(p.compressor).__name__)
            if p.update_sharded:
                extra += ' [update-sharded%s]' % (
                    ' pad=%d' % p.wus_pad if p.wus_pad else '')
            if p.staleness:
                extra += ' staleness=%d' % p.staleness
            lines.append('  %s: %s%s' % (name, kind, extra))
        return '\n'.join(lines)
