"""Logical-axis sharding system + ParallelSpec.

The reference expresses distribution as per-variable protobuf nodes
(strategy.proto:30-69) because its substrate is graph surgery. The
TPU-native functional path expresses it as *logical axis rules*: every
parameter (and key activations) carries a tuple of logical axis names
(``('embed', 'mlp')``); a rule table maps logical axes to mesh axes; the
compiler binds params to ``NamedSharding``s and lets GSPMD insert the
collectives. This is the sharding recipe of the public scaling-book /
GSPMD lineage, replacing the reference's kernel layer for compute
parallelism (which the reference never had — SURVEY.md §2.3).

``ParallelSpec`` is the user-facing knob: sizes for the five mesh axes
(dp/tp/pp/sp/ep) plus rematerialization and ZeRO options. It serializes
like a reference Strategy so chief-built specs ship to workers unchanged.
"""
import threading
from dataclasses import dataclass, field, asdict

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from autodist_tpu.const import (AXIS_DATA, AXIS_EXPERT, AXIS_MODEL,
                                AXIS_PIPELINE, AXIS_SEQUENCE)

# Default logical-axis -> mesh-axis rules. First match wins; a logical
# axis absent from the table is unsharded. ``batch`` rides the data axis,
# sequence rides the context-parallel axis, and the two classic Megatron
# families (hidden-expanding vs hidden-contracting matmul dims) ride the
# tensor axis.
DEFAULT_RULES = (
    ('batch', AXIS_DATA),
    ('seq', AXIS_SEQUENCE),
    ('embed', None),
    ('mlp', AXIS_MODEL),
    ('heads', AXIS_MODEL),
    ('kv', None),
    ('vocab', AXIS_MODEL),
    ('expert', AXIS_EXPERT),
    ('stage', AXIS_PIPELINE),
    ('classes', None),
)


@dataclass
class ParallelSpec:
    """Mesh-axis sizes + execution options for the functional path.

    dp/tp/pp/sp/ep: data / tensor / pipeline / sequence(context) / expert
    parallel degrees. ``dp=0`` means "use all remaining devices".
    ``zero``: optimizer-state sharding stage (1 = replicated state,
    2 = shard opt state over dp, 3 = also shard params over dp).
    ``remat``: 'none' | 'full' — jax.checkpoint policy on the step.
    """
    dp: int = 0
    tp: int = 1
    pp: int = 1
    sp: int = 1
    ep: int = 1
    # multi-slice factor: the data axis is split this many ways across
    # slice (DCN) boundaries, so DP gradient reduction is the only
    # cross-DCN traffic while tp/pp/sp/ep stay on ICI within a slice.
    # Must divide the resolved dp.
    dcn_dp: int = 1
    zero: int = 1
    remat: str = 'none'
    microbatches: int = 1          # pipeline microbatches (pp>1)
    # 'gpipe' | '1f1b': 1f1b keeps only each rank's microbatch share
    # resident (+ per-microbatch remat); gpipe holds full input/output
    # stacks on every rank but accepts ragged microbatch counts
    pp_schedule: str = 'gpipe'
    # fused-1F1B backward variant: 'remat' (pp-bounded activation
    # stash, ~3 fwd passes), 'stash' (one boundary activation per
    # microbatch, ~2 fwd passes), 'auto' (stash while it fits
    # AUTODIST_PP_STASH_LIMIT_MB per rank), 'legacy'
    # (autodiff-through-the-schedule: zero recompute but GPipe-class
    # memory — full-batch head/tail + all M+pp-1 step residuals live at
    # the boundary; measured SLOWEST wall in the BASELINE.md round-5
    # table, kept for A/B comparison)
    pp_variant: str = 'auto'
    sp_mode: str = 'ring'          # 'ring' | 'ulysses' (sp>1 attention)
    grad_accum: int = 1            # gradient-accumulation chunks
    rules: list = field(default_factory=lambda: [list(r)
                                                 for r in DEFAULT_RULES])

    # -- serialization (parity with Strategy JSON round-trip) -------------
    def to_dict(self):
        return asdict(self)

    @classmethod
    def from_dict(cls, d):
        """Tolerates version skew in BOTH directions: missing fields
        take their defaults (old dict, new code) and unknown fields are
        dropped with a warning (new dict, old code) — a chief and its
        workers need not run identical builds to exchange specs."""
        import dataclasses
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(d) - known
        if unknown:
            from autodist_tpu.utils import logging
            logging.warning('ParallelSpec.from_dict: dropping unknown '
                            'fields %s (newer peer?)', sorted(unknown))
        return cls(**{k: v for k, v in d.items() if k in known})

    def resolve_dp(self, n_devices):
        fixed = self.tp * self.pp * self.sp * self.ep
        if self.dp:
            return self.dp
        if n_devices % fixed:
            raise ValueError(
                'tp*pp*sp*ep=%d does not divide device count %d'
                % (fixed, n_devices))
        return n_devices // fixed

    def build_mesh(self, devices=None):
        """Mesh with axes (data, pipe, seq, expert, model); size-1 axes kept.

        Axis order puts ``model`` (highest-traffic collectives) innermost so
        tensor-parallel groups land on adjacent ICI neighbors, then expert,
        seq, pipe, with data outermost — the standard hierarchy-matching
        layout.
        """
        devices = list(devices if devices is not None else jax.devices())
        dp = self.resolve_dp(len(devices))
        names = (AXIS_DATA, AXIS_PIPELINE, AXIS_SEQUENCE, AXIS_EXPERT,
                 AXIS_MODEL)
        sizes = (dp, self.pp, self.sp, self.ep, self.tp)
        total = int(np.prod(sizes))
        if total > len(devices):
            raise ValueError('ParallelSpec wants %d devices, have %d'
                             % (total, len(devices)))
        from autodist_tpu.parallel.mesh import device_mesh_array
        arr = device_mesh_array(sizes, devices, dcn_dp=self.dcn_dp)
        return Mesh(arr, names)


def mesh_axis_for(logical, rules, mesh):
    """Resolve one logical axis to a live mesh axis name (or None)."""
    for name, target in rules:
        if name == logical:
            if target is None or target not in mesh.shape:
                return None
            if mesh.shape[target] == 1:
                return None  # size-1 axis: sharding is a no-op; keep specs tidy
            return target
    return None


def spec_for_axes(axes, rules, mesh):
    """PartitionSpec for a tuple of logical axis names."""
    if axes is None:
        return P()
    used = set()
    out = []
    for logical in axes:
        target = mesh_axis_for(logical, rules, mesh)
        if target in used:
            target = None  # a mesh axis may shard only one tensor dim
        if target is not None:
            used.add(target)
        out.append(target)
    while out and out[-1] is None:
        out.pop()
    return P(*out)


def shardings_for_tree(axes_tree, rules, mesh):
    """Map an axes-metadata pytree to NamedShardings.

    ``axes_tree`` mirrors the param tree but holds tuples of logical axis
    names (or None) at the leaves.
    """
    return jax.tree.map(
        lambda axes: NamedSharding(mesh, spec_for_axes(axes, rules, mesh)),
        axes_tree,
        is_leaf=lambda x: x is None or (isinstance(x, tuple) and
                                        all(isinstance(a, (str, type(None)))
                                            for a in x)))


class _ShardingCtx(threading.local):
    def __init__(self):
        self.mesh = None
        self.rules = None
        self.manual_axes = ()   # mesh axes under shard_map (explicit mode)
        self.options = {}       # execution options (e.g. microbatches)


_CTX = _ShardingCtx()


def ctx_option(key, default=None):
    """Read an execution option installed by the active sharding_ctx."""
    return _CTX.options.get(key, default)


class sharding_ctx:
    """Context manager installing (mesh, rules) for :func:`constrain`.

    The Trainer enters this around tracing so model code can annotate
    activations by logical axes without threading the mesh through every
    call signature. ``manual_axes`` marks mesh axes the step runs manually
    (inside shard_map) — model code uses explicit collectives over those
    (e.g. ring attention over ``seq``) instead of sharding constraints.
    """

    def __init__(self, mesh, rules, manual_axes=(), options=None):
        self._new = (mesh, rules, tuple(manual_axes), options or {})
        self._old = None

    def __enter__(self):
        self._old = (_CTX.mesh, _CTX.rules, _CTX.manual_axes,
                     _CTX.options)
        (_CTX.mesh, _CTX.rules, _CTX.manual_axes,
         _CTX.options) = self._new
        return self

    def __exit__(self, *exc):
        (_CTX.mesh, _CTX.rules, _CTX.manual_axes,
         _CTX.options) = self._old


def axis_size(axis_name):
    """``lax.axis_size`` across jax versions: older jax has no such
    helper, but ``psum(1, axis)`` constant-folds to the axis size."""
    try:
        return jax.lax.axis_size(axis_name)
    except AttributeError:
        return int(jax.lax.psum(1, axis_name))


def supports_partial_manual():
    """True when this jax can lower partial-manual shard_map regions
    (jax>=0.6 ``jax.shard_map`` with ``axis_names=``). Old jax's
    partial-auto spelling crashes in lowering, so
    :func:`shard_map_compat` refuses it up front — tests gate the
    nested-manual kernel-dispatch paths on this probe."""
    return hasattr(jax, 'shard_map')


def shard_map_compat(f, mesh, in_specs, out_specs, axis_names=None):
    """Partial-manual shard_map across jax spellings.

    jax>=0.6 exposes ``jax.shard_map`` with ``axis_names=`` (the manual
    set) and ``check_vma``; older jax spells the manual set as its
    complement ``auto=`` on ``jax.experimental.shard_map.shard_map``
    and the flag ``check_rep``. Replication checking is off either way
    (these regions mix manual collectives with replicated outputs).
    """
    import jax as _jax
    if hasattr(_jax, 'shard_map'):
        kw = {}
        if axis_names is not None:
            kw['axis_names'] = set(axis_names)
        try:
            return _jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                                  out_specs=out_specs, check_vma=False,
                                  **kw)
        except TypeError:   # pragma: no cover - intermediate jax
            return _jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                                  out_specs=out_specs, check_rep=False,
                                  **kw)
    from jax.experimental.shard_map import shard_map as _sm
    if axis_names is not None:
        # old jax's partial-auto shard_map (auto=) lowers these regions
        # to PartitionId crashes — often after a multi-minute doomed
        # compile. Refuse up front with an actionable error instead:
        # the functional partial-manual paths need jax>=0.6.
        raise NotImplementedError(
            'partial-manual shard_map over %s needs jax>=0.6 '
            '(jax.shard_map axis_names=); this jax has only the '
            'experimental fully-manual shard_map'
            % sorted(axis_names))
    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=False)


def manual_axis(mesh_axis):
    """The live manual (shard_map) axis name, or None.

    Returns ``mesh_axis`` only when the current step executes that mesh
    axis manually AND its size exceeds 1."""
    return mesh_axis if mesh_axis in _CTX.manual_axes else None


def current_mesh():
    """The mesh installed by the active sharding_ctx (or None)."""
    return _CTX.mesh


def active_manual_axes():
    """Mesh axes the current trace runs manually (shard_map), if any."""
    return _CTX.manual_axes


def unsharded_execution():
    """True when the current trace computes on purely device-local data:
    no mesh, a single-device mesh, or every size>1 mesh axis manual
    (shard_map). This is the safety condition for invoking an opaque
    kernel (``pallas_call``) that GSPMD cannot partition — under
    automatic sharding XLA would all-gather its operands instead."""
    if _CTX.mesh is None:
        return True
    for name, size in _CTX.mesh.shape.items():
        if size > 1 and name not in _CTX.manual_axes:
            return False
    return True


def live_mesh_axis(logical):
    """Mesh axis a logical axis is currently bound to (size>1), or None.

    Lets modules pick sharding-aware algorithms (e.g. one-hot-matmul
    embedding lookup when the vocab dim is tensor-sharded)."""
    if _CTX.mesh is None:
        return None
    rules = _CTX.rules
    if rules is None:
        rules = [list(r) for r in DEFAULT_RULES]
    return mesh_axis_for(logical, rules, _CTX.mesh)


def constrain(x, axes, rules=None, mesh=None):
    """with_sharding_constraint by logical axes; no-op outside a ctx.

    Inside a partial-manual shard_map region, manual axes are stripped
    from the spec (they are positional there, not sharding annotations).
    """
    mesh = mesh if mesh is not None else _CTX.mesh
    if mesh is None:
        return x
    rules = rules if rules is not None else _CTX.rules
    if rules is None:
        rules = [list(r) for r in DEFAULT_RULES]
    spec = spec_for_axes(axes, rules, mesh)
    if _CTX.manual_axes:
        spec = P(*[None if a in _CTX.manual_axes else a for a in spec])
        while len(spec) and spec[-1] is None:
            spec = P(*spec[:-1])
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))
