"""Collective-schedule IR: communication schedules as verifiable data.

``plan.sync_gradients`` composes five orthogonal schedule dimensions
(flat vs two-level, the int8 tier boundary, ZeRO chunking, sparse rows,
weight-update sharding) and ``static_collective_schedule`` mirrors each
case by hand. This module is the PCCL-style fix (PAPERS.md,
arXiv:2606.07019; array redistribution, arXiv:2112.01075): ONE small IR
of composable steps that

- both emission paths lower through (``bucket_program`` builds the
  program, ``schedule_entry`` derives the static entry dict from it,
  ``execute`` drives the traced emission), so predicted == traced is
  structural rather than test-pinned;
- a shape algebra verifies (``verify``): device groups are disjoint,
  reduce-scatter chunks tile their span exactly, byte flow conserves
  across requantize boundaries, and the final per-device element
  partition matches the program's declared goal;
- a search synthesizes over (``simulator/search.py``): 3-level
  hierarchies, per-link wire dtypes, unequal node groups — shapes no
  hand-written emitter covers — priced per step by
  ``cost_model.program_time`` from the same calibrated α-β constants
  ``entry_time`` uses.

The element model: a program runs over ``elems`` padded elements
``[0, E)``. Each device holds a set of fragments ``(lo, hi, contribs)``
where ``contribs`` is the set of devices whose local addends are summed
into that range. A gradient sync starts ``replicated`` (every device
holds ``[0, E)`` with contribs ``{itself}``) and must end with contribs
= ALL devices everywhere it claims reduced data. Permute steps change
the coordinate frame (the hierarchical schedules' block pre-permutation)
and the goal check maps holdings back to original coordinates, so "the
two-level scatter lands the flat layout" is a theorem the verifier
checks, not a comment.

jax is imported lazily (only by ``execute``/``execute_generic``) so the
algebra, the builders and the pricing stay importable on device-less
hosts and inside the static analyzers.
"""
from dataclasses import dataclass, field

#: wire-name -> bytes per element. The i8 wire additionally carries one
#: f32 scale per AUTODIST_QUANT_BLOCK elements (wire_nbytes adds it).
WIRE_ITEMSIZE = {'f32': 4, 'bf16': 2, 'i8': 1, 'f64': 8}

COMM_OPS = ('reduce_scatter', 'all_reduce', 'all_gather')
LOCAL_OPS = ('requantize', 'permute', 'gather', 'scatter')

#: tier ladder, fastest first — program_time charges the hierarchical
#: boundary cost on each adjacent comm-step pair that changes tier.
TIER_ORDER = {'local': 0, 'ici': 1, 'host': 2, 'dcn': 3}


def wire_of_dtype(dtype):
    """Wire name a raw (uncompressed) tensor dtype rides."""
    import numpy as np
    return {1: 'i8', 2: 'bf16', 4: 'f32',
            8: 'f64'}.get(np.dtype(dtype).itemsize, 'f32')


def _quant_block():
    from autodist_tpu.parallel.compressor import quant_block_size
    return quant_block_size()


def wire_nbytes(elems, wire, block=None):
    """Wire bytes of ``elems`` payload elements at ``wire``, including
    the blockscale header of the int8 tier (one f32 scale per
    ``AUTODIST_QUANT_BLOCK`` elements — same accounting as
    ``cost_model.wire_bytes``)."""
    elems = int(elems)
    out = elems * WIRE_ITEMSIZE[wire]
    if wire == 'i8' and elems:
        out += 4 * (-(-elems // (block or _quant_block())))
    return out


@dataclass(frozen=True)
class Step:
    """One IR step.

    ``groups`` are tuples of device positions on the data axis
    (explicit, never implied by a mesh). ``chunks`` (reduce_scatter /
    scatter) give each group's per-member ABSOLUTE element interval
    ``(lo, hi)``; ``span`` (all_reduce / all_gather) the per-group
    interval the collective covers. ``perm`` (permute) maps new block
    index -> old block index at ``block`` elements per block.
    ``nbytes`` declares the per-group wire payload in bytes — the
    byte-flow conservation check bounds it against the algebra, and
    ``program_time`` prices from it.
    """
    op: str
    tier: str = 'ici'
    wire: str = 'f32'
    groups: tuple = ()
    chunks: tuple = ()
    span: tuple = ()
    perm: tuple = ()
    block: int = 0
    nbytes: float = 0.0


@dataclass
class Program:
    """One schedule: ``steps`` over ``n`` devices and ``elems`` padded
    elements of ``dtype``. ``init``/``goal`` declare the boundary
    layouts the algebra checks; ``meta`` carries everything the legacy
    entry schema needs (kind, compressor, spec, raw_bytes, hier, wus,
    node_groups) plus anything synthesis wants to remember."""
    name: str
    n: int
    elems: int
    dtype: str = 'float32'
    steps: tuple = ()
    init: str = 'replicated'
    goal: str = 'reduced_replicated'
    meta: dict = field(default_factory=dict)

    def to_dict(self):
        return {
            'name': self.name, 'n': self.n, 'elems': self.elems,
            'dtype': self.dtype,
            'init': self.init if isinstance(self.init, str)
            else 'custom', 'goal': self.goal,
            'meta': {k: v for k, v in self.meta.items()
                     if k != 'node_groups'},
            'steps': [{'op': s.op, 'tier': s.tier, 'wire': s.wire,
                       'groups': [list(g) for g in s.groups],
                       'nbytes': s.nbytes} for s in self.steps],
        }


# -- fragment algebra --------------------------------------------------

def _merge(frags):
    """Normalize a fragment list: sort, merge adjacent equal-contrib
    ranges, drop empties."""
    frags = sorted((lo, hi, c) for lo, hi, c in frags if hi > lo)
    out = []
    for lo, hi, c in frags:
        if out and out[-1][1] == lo and out[-1][2] == c:
            out[-1] = (out[-1][0], hi, c)
        else:
            out.append((lo, hi, c))
    return out


def _covers(frags, lo, hi):
    """True iff the fragments cover every element of [lo, hi)."""
    pos = lo
    for flo, fhi, _ in frags:
        if flo > pos:
            break
        if fhi > pos:
            pos = fhi
        if pos >= hi:
            return True
    return pos >= hi or lo >= hi

def _restrict(frags, lo, hi):
    return [(max(flo, lo), min(fhi, hi), c) for flo, fhi, c in frags
            if fhi > lo and flo < hi]


def _subtract(frags, lo, hi):
    out = []
    for flo, fhi, c in frags:
        if fhi <= lo or flo >= hi:
            out.append((flo, fhi, c))
            continue
        if flo < lo:
            out.append((flo, lo, c))
        if fhi > hi:
            out.append((hi, fhi, c))
    return out


def _overlay(frag_lists, lo, hi):
    """Pointwise union of contribs over [lo, hi) across several
    fragment lists. Returns (fragments, holes) where holes are the
    sub-ranges no list covers."""
    cuts = {lo, hi}
    for frags in frag_lists:
        for flo, fhi, _ in frags:
            if fhi > lo and flo < hi:
                cuts.add(max(flo, lo))
                cuts.add(min(fhi, hi))
    cuts = sorted(cuts)
    out, holes = [], []
    for a, b in zip(cuts, cuts[1:]):
        union = frozenset()
        seen = False
        for frags in frag_lists:
            for flo, fhi, c in frags:
                if flo <= a and fhi >= b:
                    union = union | c
                    seen = True
                    break
        if seen:
            out.append((a, b, union))
        else:
            holes.append((a, b))
    return _merge(out), holes


def _apply_perm(frags, perm, block):
    """Map a fragment list through a block permutation (new block b
    holds old block perm[b])."""
    inv = {old: new for new, old in enumerate(perm)}
    out = []
    for lo, hi, c in frags:
        b0, b1 = lo // block, -(-hi // block)
        for ob in range(b0, b1):
            slo, shi = max(lo, ob * block), min(hi, (ob + 1) * block)
            nb = inv[ob]
            off = nb * block - ob * block
            out.append((slo + off, shi + off, c))
    return _merge(out)


def _init_holdings(program):
    E, n = program.elems, program.n
    init = program.init
    if isinstance(init, (list, tuple)):
        return [_merge(list(h)) for h in init]
    ALL = frozenset(range(n))
    if init in ('replicated', 'value_replicated'):
        c = ALL if init == 'value_replicated' else None
        return [[(0, E, c if c is not None else frozenset([d]))]
                for d in range(n)]
    if init in ('sharded', 'rows', 'value_sharded'):
        m = E // n
        c = ALL if init == 'value_sharded' else None
        return [[(d * m, (d + 1) * m,
                  c if c is not None else frozenset([d]))]
                for d in range(n)]
    raise ValueError('unknown init %r' % (init,))


def _byte_slack(elems, wire):
    """Tolerance of the declared-vs-derived wire-byte check: exact for
    fixed-width wires, blockscale rounding for i8 (builders may declare
    the inter-phase payload as total/g, which rounds the scale header
    differently than a per-chunk recount)."""
    if wire != 'i8':
        return 0.5
    return 4.0 * (elems / float(_quant_block()) + 2.0)


def run_algebra(program, init_holdings=None):
    """Run the shape algebra over ``program``; returns
    ``(findings, holdings)`` where holdings are the final per-device
    fragment lists in ORIGINAL coordinates. Empty findings = the
    schedule verifies."""
    findings = []
    E, n = int(program.elems), int(program.n)
    ALL = frozenset(range(n))
    try:
        hold = [list(h) for h in (init_holdings or
                                  _init_holdings(program))]
    except ValueError as err:
        return ['schedule-ir %s: %s' % (program.name, err)], []
    cur_wire = wire_of_dtype(program.dtype)
    to_orig = None          # current block -> original block
    perm_block = 0

    def ctx(i, step):
        return 'schedule-ir %s step %d (%s/%s)' % (
            program.name, i, step.op, step.tier)

    for i, step in enumerate(program.steps):
        where = ctx(i, step)
        if step.op == 'requantize':
            if step.wire not in WIRE_ITEMSIZE:
                findings.append('%s: unknown wire %r' % (where,
                                                         step.wire))
            cur_wire = step.wire
            continue
        if step.op == 'permute':
            B = len(step.perm)
            if not B or step.block <= 0 or B * step.block != E:
                findings.append('%s: permute must cover the %d '
                                'padded elements exactly' % (where, E))
                continue
            if sorted(step.perm) != list(range(B)):
                findings.append('%s: perm is not a bijection' % where)
                continue
            if step.nbytes:
                findings.append('%s: permute is local relabeling; '
                                'declared %.0f wire bytes'
                                % (where, step.nbytes))
            hold = [_apply_perm(h, step.perm, step.block)
                    for h in hold]
            if to_orig is None:
                to_orig = tuple(step.perm)
                perm_block = step.block
            elif perm_block != step.block:
                findings.append('%s: mixed permute block sizes'
                                % where)
            else:
                to_orig = tuple(to_orig[old] for old in step.perm)
            continue
        if step.op == 'gather':
            if step.nbytes:
                findings.append('%s: gather is local row '
                                'materialization; declared %.0f wire '
                                'bytes' % (where, step.nbytes))
            continue
        if step.op == 'scatter' and not step.groups:
            # bare marker: local dense materialization (sparse wire)
            continue

        # -- comm ops (and grouped scatter) ---------------------------
        if step.op not in COMM_OPS + ('scatter',):
            findings.append('%s: unknown op' % where)
            continue
        if not step.groups:
            findings.append('%s: comm step with no groups' % where)
            continue
        seen = set()
        bad = False
        for grp in step.groups:
            for d in grp:
                if not 0 <= d < n:
                    findings.append('%s: device %d outside mesh [0,%d)'
                                    % (where, d, n))
                    bad = True
                if d in seen:
                    findings.append('%s: device %d appears in two '
                                    'groups — groups must partition '
                                    'disjointly' % (where, d))
                    bad = True
                seen.add(d)
        if bad:
            continue
        if step.op in COMM_OPS and step.wire != cur_wire:
            findings.append(
                '%s: declared wire %r but the live buffer is %r — a '
                'requantize is missing or misplaced at this tier '
                'boundary' % (where, step.wire, cur_wire))
        payload = 0          # max per-group payload elements

        if step.op in ('reduce_scatter', 'scatter'):
            if len(step.chunks) != len(step.groups):
                findings.append('%s: %d chunk lists for %d groups'
                                % (where, len(step.chunks),
                                   len(step.groups)))
                continue
            for grp, chs in zip(step.groups, step.chunks):
                if len(chs) != len(grp):
                    findings.append('%s: %d chunks for %d members'
                                    % (where, len(chs), len(grp)))
                    continue
                nonempty = sorted((lo, hi) for lo, hi in chs
                                  if hi > lo)
                if not nonempty:
                    continue
                ulo, uhi = nonempty[0][0], nonempty[-1][1]
                pos = ulo
                tiled = True
                for lo, hi in nonempty:
                    if lo != pos:
                        tiled = False
                    pos = hi
                if not tiled or pos != uhi:
                    findings.append(
                        '%s: chunks %s do not tile [%d,%d) exactly '
                        '(gap or overlap)' % (where, nonempty, ulo,
                                              uhi))
                    continue
                if ulo < 0 or uhi > E:
                    findings.append('%s: span [%d,%d) outside the %d '
                                    'padded elements'
                                    % (where, ulo, uhi, E))
                    continue
                payload = max(payload, uhi - ulo)
                member_frags = [hold[d] for d in grp]
                if step.op == 'reduce_scatter':
                    for d in grp:
                        if not _covers(hold[d], ulo, uhi):
                            findings.append(
                                '%s: device %d does not hold the full '
                                'span [%d,%d) it must reduce'
                                % (where, d, ulo, uhi))
                    merged, holes = _overlay(member_frags, ulo, uhi)
                    for d, (lo, hi) in zip(grp, chs):
                        kept = _restrict(merged, lo, hi)
                        hold[d] = _merge(
                            _subtract(hold[d], ulo, uhi) + kept)
                else:   # scatter: redistribution / local projection
                    if step.nbytes == 0:
                        for d, (lo, hi) in zip(grp, chs):
                            if hi > lo and not _covers(hold[d], lo,
                                                       hi):
                                findings.append(
                                    '%s: zero-wire scatter but device '
                                    '%d lacks its chunk [%d,%d)'
                                    % (where, d, lo, hi))
                            hold[d] = _merge(_restrict(hold[d], lo,
                                                       hi))
                    else:
                        merged, holes = _overlay(member_frags, ulo,
                                                 uhi)
                        if holes:
                            findings.append(
                                '%s: span holes %s held by no member'
                                % (where, holes))
                        for d, (lo, hi) in zip(grp, chs):
                            hold[d] = _merge(_restrict(merged, lo,
                                                       hi))
        else:   # all_reduce / all_gather
            if len(step.span) != len(step.groups):
                findings.append('%s: %d spans for %d groups'
                                % (where, len(step.span),
                                   len(step.groups)))
                continue
            for grp, (slo, shi) in zip(step.groups, step.span):
                if slo < 0 or shi > E or shi < slo:
                    findings.append('%s: span [%d,%d) outside the %d '
                                    'padded elements'
                                    % (where, slo, shi, E))
                    continue
                payload = max(payload, shi - slo)
                member_frags = [hold[d] for d in grp]
                merged, holes = _overlay(member_frags, slo, shi)
                if step.op == 'all_reduce':
                    for d in grp:
                        if not _covers(hold[d], slo, shi):
                            findings.append(
                                '%s: device %d does not hold the full '
                                'span [%d,%d) it must reduce'
                                % (where, d, slo, shi))
                elif holes:
                    findings.append('%s: span holes %s held by no '
                                    'member' % (where, holes))
                for d in grp:
                    hold[d] = _merge(
                        _subtract(hold[d], slo, shi) + merged)

        if step.op in COMM_OPS:
            expect = wire_nbytes(payload, step.wire)
            slack = _byte_slack(payload, step.wire)
            if abs(float(step.nbytes) - expect) > slack:
                findings.append(
                    '%s: declares %.0f wire bytes but the algebra '
                    'moves %d payload elements = %d bytes at %s '
                    '(byte flow must conserve across requantize '
                    'boundaries)' % (where, step.nbytes, payload,
                                     expect, step.wire))

    # -- goal ---------------------------------------------------------
    if to_orig is not None:
        hold = [_apply_perm(h,
                            tuple(to_orig.index(b)
                                  for b in range(len(to_orig))),
                            perm_block) for h in hold]
    goal = program.goal
    m = E // n if n and E % n == 0 else 0

    def _contribs_all(h, lo, hi, d):
        for flo, fhi, c in _restrict(h, lo, hi):
            if c != ALL:
                findings.append(
                    'schedule-ir %s: device %d range [%d,%d) ends '
                    'with contributions from %d of %d devices — the '
                    'reduction is incomplete' % (program.name, d, flo,
                                                 fhi, len(c), n))
                return

    if goal == 'none':
        pass
    elif goal in ('reduced_replicated', 'value_replicated',
                  'gathered'):
        ref = None
        for d in range(n):
            if not _covers(hold[d], 0, E):
                findings.append('schedule-ir %s: device %d does not '
                                'hold the full result'
                                % (program.name, d))
            elif goal == 'reduced_replicated':
                _contribs_all(hold[d], 0, E, d)
            elif goal == 'gathered':
                if ref is None:
                    ref = hold[d]
                elif _merge(list(hold[d])) != _merge(list(ref)):
                    findings.append(
                        'schedule-ir %s: device %d gathered a '
                        'different contribution map than device 0'
                        % (program.name, d))
    elif goal in ('reduced_scattered', 'value_sharded'):
        if not m:
            findings.append('schedule-ir %s: %d elements do not '
                            'shard over %d devices'
                            % (program.name, E, n))
        else:
            for d in range(n):
                lo, hi = d * m, (d + 1) * m
                if not _covers(hold[d], lo, hi):
                    findings.append(
                        'schedule-ir %s: device %d does not hold its '
                        'shard [%d,%d)' % (program.name, d, lo, hi))
                elif goal == 'reduced_scattered':
                    _contribs_all(hold[d], lo, hi, d)
                extra = _subtract(hold[d], lo, hi)
                if extra:
                    findings.append(
                        'schedule-ir %s: device %d holds %s outside '
                        'its shard — the scatter leaked'
                        % (program.name, d, extra))
    else:
        findings.append('schedule-ir %s: unknown goal %r'
                        % (program.name, goal))
    return findings, hold


def verify(program, init_holdings=None):
    """Shape-algebra verification; returns findings ([] = clean)."""
    return run_algebra(program, init_holdings=init_holdings)[0]


def staging_bytes(program):
    """Peak staging-buffer estimate of a program's local steps — the
    memory axis synthesis prunes on: a requantize materializes the
    re-encoded buffer next to the live one, a permute its re-blocked
    copy. Wire-only accounting (the live f32 buffer itself is the
    plan's peak-bytes business, not the schedule's)."""
    E = int(program.elems)
    peak = 0
    for s in program.steps:
        if s.op == 'requantize':
            peak = max(peak, wire_nbytes(E, s.wire))
        elif s.op == 'permute':
            peak = max(peak, len(s.perm) * int(s.block) *
                       WIRE_ITEMSIZE.get(s.wire, 4))
    return int(peak)


# -- builders ----------------------------------------------------------

def contiguous_groups(n, k):
    """``k`` equal contiguous groups over ``n`` positions — the
    canonical host-major layout ``mesh.data_axis_node_groups`` lays
    devices out in, and what a static entry's ``hier`` count
    reconstructs to."""
    n, k = int(n), int(k)
    if k <= 1 or n % k:
        return None
    g = n // k
    return tuple(tuple(range(j * g, (j + 1) * g)) for j in range(k))


def _pad_to(elems, mult):
    mult = max(1, int(mult))
    return -(-int(elems) // mult) * mult


def _full_group(n):
    return (tuple(range(n)),)


def _flat_chunks(E, n):
    m = E // n
    return (tuple((d * m, (d + 1) * m) for d in range(n)),)


def flat_program(elems, dtype, *, kind='all_reduce', tier='dcn',
                 wire=None, name='', meta=None, n=None):
    """Flat single-group program: one AR / RS / AG over the whole mesh
    at ``tier``. ``wire`` defaults to the dtype's own width; a narrower
    wire gets requantize steps around the collective (the flat int8 /
    bf16 schedules)."""
    n = int(n)
    raw_wire = wire_of_dtype(dtype)
    wire = wire or raw_wire
    E = _pad_to(elems, n) if kind != 'all_reduce' else int(elems)
    steps = []
    if wire != raw_wire:
        steps.append(Step('requantize', tier='local', wire=wire))
    nb = wire_nbytes(E, wire)
    if kind == 'all_reduce':
        steps.append(Step('all_reduce', tier=tier, wire=wire,
                          groups=_full_group(n), span=((0, E),),
                          nbytes=nb))
        init, goal = 'replicated', 'reduced_replicated'
    elif kind == 'psum_scatter':
        steps.append(Step('reduce_scatter', tier=tier, wire=wire,
                          groups=_full_group(n),
                          chunks=_flat_chunks(E, n), nbytes=nb))
        init, goal = 'replicated', 'reduced_scattered'
    elif kind == 'all_gather':
        steps.append(Step('all_gather', tier=tier, wire=wire,
                          groups=_full_group(n), span=((0, E),),
                          nbytes=nb))
        init, goal = 'sharded', 'gathered'
    else:
        raise ValueError('flat_program: unknown kind %r' % (kind,))
    if wire != raw_wire and kind != 'all_gather':
        steps.append(Step('requantize', tier='local', wire=raw_wire))
    return Program(name or 'flat_%s' % kind, n, E, str(dtype),
                   tuple(steps), init, goal, dict(meta or {}))


def _wave_groups(host_sizes, c):
    """Inter-phase wave schedule for (possibly unequal) ``host_sizes``:
    the span splits into ``c = max(host_sizes)`` chunks; device ``i``
    of host ``h`` owns chunks ``[i*c//g_h, (i+1)*c//g_h)``. Rounds
    (one AR per chunk across its per-host owners) pack into
    ``W = max chunks/device`` sequential waves of device-disjoint
    groups — the straggler host pays extra waves, which is exactly how
    the cost model prices it. Equal hosts degenerate to one wave of
    the classic representative groups. Returns (waves, W) where waves
    is a list of lists of (chunk_index, group_tuple)."""
    owners = []          # per chunk: tuple of owning device positions
    base = 0
    per_dev_chunks = []
    for g in host_sizes:
        for i in range(g):
            per_dev_chunks.append((i * c // g, (i + 1) * c // g))
        base += g
    W = max((hi - lo) for lo, hi in per_dev_chunks) if per_dev_chunks \
        else 1
    for q in range(c):
        grp = []
        base = 0
        di = 0
        for g in host_sizes:
            for i in range(g):
                lo, hi = per_dev_chunks[di]
                if lo <= q < hi:
                    grp.append(base + i)
                di += 1
            base += g
        owners.append(tuple(grp))
    waves = [[] for _ in range(W)]
    for q, grp in enumerate(owners):
        waves[q % W].append((q, grp))
    return waves, W


def two_level_program(elems, dtype, host_sizes, *, kind='all_reduce',
                      tiers=('ici', 'dcn'), wires=None, name='',
                      meta=None, node_groups=None):
    """Two-level program over ``host_sizes`` devices per node (host-
    major positions). Equal sizes reproduce the legacy hierarchical
    schedules step for step; unequal sizes lift ``num_node_groups``'s
    equal-split requirement via the wave construction (the synthesis
    path — the traced emitter cannot run these yet, but the algebra
    verifies them and the cost model prices the straggler).

    ``wires`` is (intra_wire, inter_wire); an inter wire narrower than
    intra inserts the boundary requantize pair (the int8 tier-boundary
    schedule). ``kind`` 'all_reduce' | 'psum_scatter' | 'all_gather'
    (the ZeRO / weight-update-sharding halves).
    """
    host_sizes = tuple(int(g) for g in host_sizes)
    n = sum(host_sizes)
    k = len(host_sizes)
    c = max(host_sizes)
    raw_wire = wire_of_dtype(dtype)
    w_in, w_out = wires or (raw_wire, raw_wire)
    equal = len(set(host_sizes)) == 1
    if node_groups is None:
        node_groups = []
        base = 0
        for g in host_sizes:
            node_groups.append(tuple(range(base, base + g)))
            base += g
        node_groups = tuple(node_groups)
    else:
        node_groups = tuple(tuple(g) for g in node_groups)
    E = _pad_to(elems, c * (n if kind != 'all_reduce' else 1))
    if kind != 'all_reduce':
        # the flat-identity permute needs chunk granularity E/n AND
        # the intra phase needs E/c; pad to both
        E = _pad_to(elems, c * n)
    m = E // c                      # elements per inter chunk
    meta = dict(meta or {})
    meta.setdefault('node_groups', node_groups)
    meta.setdefault('hier', k)

    # intra chunks: device i of host h owns chunks [i*c//g, (i+1)*c//g)
    intra_chunks = []
    for grp, g in zip(node_groups, host_sizes):
        intra_chunks.append(tuple(
            (i * c // g * m, (i + 1) * c // g * m)
            for i in range(g)))
    intra_chunks = tuple(intra_chunks)
    waves, W = _wave_groups(host_sizes, c)
    inter_nb = wire_nbytes(E, w_out) / float(c)

    def rq(w):
        return Step('requantize', tier='local', wire=w)

    steps = []
    if w_in != raw_wire:
        steps.append(rq(w_in))
    if kind == 'all_reduce':
        steps.append(Step('reduce_scatter', tier=tiers[0], wire=w_in,
                          groups=node_groups, chunks=intra_chunks,
                          nbytes=wire_nbytes(E, w_in)))
        if w_out != w_in:
            steps.append(rq(w_out))
        for wave in waves:
            steps.append(Step(
                'all_reduce', tier=tiers[1], wire=w_out,
                groups=tuple(grp for _, grp in wave),
                span=tuple((q * m, (q + 1) * m) for q, _ in wave),
                nbytes=inter_nb))
        if w_out != w_in:
            steps.append(rq(w_in))
        steps.append(Step('all_gather', tier=tiers[0], wire=w_in,
                          groups=node_groups,
                          span=((0, E),) * k,
                          nbytes=wire_nbytes(E, w_in)))
        if w_in != raw_wire:
            steps.append(rq(raw_wire))
        init, goal = 'replicated', 'reduced_replicated'
    elif kind == 'psum_scatter':
        if not equal:
            raise ValueError('two_level_program: the scatter half '
                             'requires equal host sizes (flat-'
                             'identity layout)')
        g = host_sizes[0]
        mm = E // n                 # flat chunk size
        # arranged (permuted) coordinates: block (p, j) of a
        # (g, k, mm) layout is flat block j*g+p — the pre-permutation
        # that makes hierarchical ownership identical to flat
        perm = [0] * n
        for p in range(g):
            for j in range(k):
                perm[p * k + j] = j * g + p
        steps.append(Step('permute', tier='local', wire=w_in,
                          perm=tuple(perm), block=mm))
        intra = tuple(tuple((p * k * mm, (p + 1) * k * mm)
                            for p in range(g)) for _ in range(k))
        steps.append(Step('reduce_scatter', tier=tiers[0], wire=w_in,
                          groups=node_groups, chunks=intra,
                          nbytes=wire_nbytes(E, w_in)))
        inter_groups = tuple(
            tuple(grp[p] for grp in node_groups) for p in range(g))
        inter_chunks = tuple(
            tuple((p * k * mm + j * mm, p * k * mm + (j + 1) * mm)
                  for j in range(k)) for p in range(g))
        if w_out != w_in:
            steps.append(rq(w_out))
        steps.append(Step('reduce_scatter', tier=tiers[1],
                          wire=w_out, groups=inter_groups,
                          chunks=inter_chunks,
                          nbytes=wire_nbytes(E, w_out) / float(g)))
        if w_out != w_in:
            steps.append(rq(w_in))
        init, goal = 'replicated', 'reduced_scattered'
    elif kind == 'all_gather':
        if not equal:
            raise ValueError('two_level_program: the gather half '
                             'requires equal host sizes (flat-'
                             'identity layout)')
        g = host_sizes[0]
        mm = E // n
        perm = [0] * n
        for p in range(g):
            for j in range(k):
                perm[p * k + j] = j * g + p
        # the leading permute reinterprets each device's flat chunk d
        # as arranged block (p, j) — zero wire, pure coordinates
        steps.append(Step('permute', tier='local', wire=w_in,
                          perm=tuple(perm), block=mm))
        inter_groups = tuple(
            tuple(grp[p] for grp in node_groups) for p in range(g))
        if w_out != w_in:
            steps.append(rq(w_out))
        steps.append(Step('all_gather', tier=tiers[1], wire=w_out,
                          groups=inter_groups,
                          span=tuple((p * k * mm, (p + 1) * k * mm)
                                     for p in range(g)),
                          nbytes=wire_nbytes(E, w_out) / float(g)))
        if w_out != w_in:
            steps.append(rq(w_in))
        steps.append(Step('all_gather', tier=tiers[0], wire=w_in,
                          groups=node_groups,
                          span=((0, E),) * k,
                          nbytes=wire_nbytes(E, w_in)))
        inv = [0] * n
        for b, old in enumerate(perm):
            inv[old] = b
        steps.append(Step('permute', tier='local', wire=w_in,
                          perm=tuple(inv), block=mm))
        init, goal = 'sharded', 'gathered'
    else:
        raise ValueError('two_level_program: unknown kind %r'
                         % (kind,))
    meta.setdefault('waves', W)
    return Program(name or 'two_level_%s' % kind, n, E, str(dtype),
                   tuple(steps), init, goal, meta)


def three_level_program(elems, dtype, slices, hosts_per_slice,
                        devs_per_host, *,
                        tiers=('ici', 'host', 'dcn'), wires=None,
                        name='', meta=None):
    """Three-level all-reduce: RS(device tier within host), RS(host
    tier within slice), AR(slice tier), AG(host), AG(ici) — the AG
    phases invert the RS phases exactly, so no permute is needed and
    the goal is full replication. Only the synthesis path emits these
    (a hand-written emitter covers at most two tiers)."""
    s, h, g = int(slices), int(hosts_per_slice), int(devs_per_host)
    n = s * h * g
    raw_wire = wire_of_dtype(dtype)
    w0, w1, w2 = wires or (raw_wire, raw_wire, raw_wire)
    E = _pad_to(elems, g * h)
    mg = E // g                     # per-device chunk after RS(ici)
    mh = mg // h                    # ... after RS(host)

    def pos(si, hi, di):
        return (si * h + hi) * g + di

    host_groups = tuple(
        tuple(pos(si, hi, di) for di in range(g))
        for si in range(s) for hi in range(h))
    host_chunks = tuple(
        tuple((di * mg, (di + 1) * mg) for di in range(g))
        for _ in range(s * h))
    slice_groups = tuple(
        tuple(pos(si, hi, di) for hi in range(h))
        for si in range(s) for di in range(g))
    slice_chunks = tuple(
        tuple((di * mg + hi * mh, di * mg + (hi + 1) * mh)
              for hi in range(h))
        for si in range(s) for di in range(g))
    top_groups = tuple(
        tuple(pos(si, hi, di) for si in range(s))
        for hi in range(h) for di in range(g))
    top_spans = tuple(
        (di * mg + hi * mh, di * mg + (hi + 1) * mh)
        for hi in range(h) for di in range(g))

    steps = []

    def rq(w):
        return Step('requantize', tier='local', wire=w)

    if w0 != raw_wire:
        steps.append(rq(w0))
    steps.append(Step('reduce_scatter', tier=tiers[0], wire=w0,
                      groups=host_groups, chunks=host_chunks,
                      nbytes=wire_nbytes(E, w0)))
    if w1 != w0:
        steps.append(rq(w1))
    steps.append(Step('reduce_scatter', tier=tiers[1], wire=w1,
                      groups=slice_groups, chunks=slice_chunks,
                      nbytes=wire_nbytes(E, w1) / float(g)))
    if w2 != w1:
        steps.append(rq(w2))
    steps.append(Step('all_reduce', tier=tiers[2], wire=w2,
                      groups=top_groups, span=top_spans,
                      nbytes=wire_nbytes(E, w2) / float(g * h)))
    if w2 != w1:
        steps.append(rq(w1))
    steps.append(Step('all_gather', tier=tiers[1], wire=w1,
                      groups=slice_groups,
                      span=tuple((di * mg, (di + 1) * mg)
                                 for si in range(s)
                                 for di in range(g)),
                      nbytes=wire_nbytes(E, w1) / float(g)))
    if w1 != w0:
        steps.append(rq(w0))
    steps.append(Step('all_gather', tier=tiers[0], wire=w0,
                      groups=host_groups,
                      span=((0, E),) * (s * h),
                      nbytes=wire_nbytes(E, w0)))
    if w0 != raw_wire:
        steps.append(rq(raw_wire))
    m = dict(meta or {})
    m.setdefault('levels', 3)
    m.setdefault('uniform', True)
    return Program(name or 'three_level_all_reduce', n, E,
                   str(dtype), tuple(steps), 'replicated',
                   'reduced_replicated', m)


def sparse_program(elems, dtype, *, kind='sparse_all_gather',
                   tier='dcn', name='', meta=None, n=None):
    """Sparse (ids, rows) wire program over wire-buffer element space:
    device d materializes its segment locally (``gather``, zero wire),
    one all-gather ships every segment, and ``sparse_scatter``
    additionally marks the local dense materialization of the shard
    (outside the wire algebra — pure compute)."""
    n = int(n)
    E = _pad_to(elems, n)
    wire = wire_of_dtype(dtype)
    steps = [Step('gather', tier='local', wire=wire),
             Step('all_gather', tier=tier, wire=wire,
                  groups=_full_group(n), span=((0, E),),
                  nbytes=wire_nbytes(E, wire))]
    if kind == 'sparse_scatter':
        steps.append(Step('scatter', tier='local', wire=wire))
    return Program(name or kind, n, E, str(dtype), tuple(steps),
                   'rows', 'gathered', dict(meta or {}))


#: compressor name -> the wire its collective phases ride (None = the
#: tensor's own width). Mirrors cost_model._WIRE_ITEMSIZE.
_COMPRESSOR_WIRE = {
    'NoneCompressor': None,
    'HorovodCompressor': 'bf16',
    'HorovodCompressorEF': 'bf16',
    'Int8RingCompressor': 'i8',
    'PowerSGDCompressor': None,
}


def bucket_program(kind, nbytes, dtype, compressor, spec, n, *,
                   hier=0, wus=False, node_groups=None,
                   flat_tier='dcn', name=''):
    """THE shared lowering: the IR program for one legacy schedule
    entry, built identically by ``plan.sync_gradients`` (which then
    ``execute``\\ s it) and ``plan.static_collective_schedule`` (which
    derives its entry dict via ``schedule_entry``). ``nbytes`` are RAW
    tensor bytes (the entry schema's figure); ``hier`` the node-group
    count (0/1 = flat); ``node_groups`` the real mesh groups when the
    caller has them (defaults to the canonical contiguous layout —
    entry ids only carry the count, so both reconstruct identically).
    """
    import numpy as np
    n = int(n)
    itemsize = np.dtype(dtype).itemsize
    elems = max(1, int(nbytes) // itemsize)
    cname = compressor or 'NoneCompressor'
    raw_wire = wire_of_dtype(dtype)
    cwire = _COMPRESSOR_WIRE.get(cname) or raw_wire
    if WIRE_ITEMSIZE[cwire] >= itemsize:
        cwire = raw_wire
    k = int(hier or 0)
    meta = {'kind': kind, 'compressor': cname, 'spec': spec,
            'raw_bytes': int(nbytes), 'dtype': str(dtype),
            'hier': k if k > 1 else 0, 'wus': bool(wus)}
    if kind in ('sparse_all_gather', 'sparse_scatter'):
        return sparse_program(elems, dtype, kind=kind, tier=flat_tier,
                              name=name, meta=meta, n=n)
    if kind not in ('all_reduce', 'psum_scatter', 'all_gather'):
        raise ValueError('bucket_program: unknown kind %r' % (kind,))
    if k > 1:
        groups = node_groups or contiguous_groups(n, k)
        if groups is None:
            raise ValueError('bucket_program: %d devices do not '
                             'split into %d node groups' % (n, k))
        host_sizes = tuple(len(g) for g in groups)
        if cname == 'Int8RingCompressor' and kind == 'all_reduce':
            # the int8 tier boundary: f32 intra phases, i8 only
            # across the slow tier (requantize at the boundary)
            wires = (raw_wire, 'i8')
        else:
            wires = (cwire, cwire)
        return two_level_program(elems, dtype, host_sizes, kind=kind,
                                 wires=wires, name=name, meta=meta,
                                 node_groups=groups)
    return flat_program(elems, dtype, kind=kind, tier=flat_tier,
                        wire=cwire, name=name, meta=meta, n=n)


def schedule_entry(program, *, group=None, members=(), vars_=1,
                   phase=None):
    """The legacy entry dict DERIVED from an IR program — the static
    schedule and the traced emission records both route through this,
    so the entry schema (and the PR 14 entry ids that join the drift
    table) is a projection of the IR rather than a parallel encoding.
    ``phase`` is only stamped when given (traced records carry none).
    """
    meta = program.meta
    cname = meta.get('compressor')
    e = {'kind': meta.get('kind'), 'group': group,
         'compressor': None if cname == 'NoneCompressor' and
         group is None else cname,
         'dtype': meta.get('dtype', program.dtype),
         'spec': meta.get('spec', 'AUTO'), 'vars': int(vars_),
         'bytes': int(meta.get('raw_bytes', 0)),
         'members': list(members),
         'hier': int(meta.get('hier', 0)),
         'wus': bool(meta.get('wus', False))}
    if phase is not None:
        e['phase'] = phase
    if meta.get('hier_fallback'):
        e['hier_fallback'] = meta['hier_fallback']
    return e


def entry_program(entry, n, *, node_groups=None, flat_tier='dcn'):
    """Rebuild the IR program a static-schedule entry lowers to — the
    inverse of ``schedule_entry`` up to padding, used by the schedule
    lint and ``tools/simulate.py --schedule-dump``."""
    prog = bucket_program(
        entry['kind'], entry.get('bytes', 0), entry.get('dtype') or
        'float32', entry.get('compressor'), entry.get('spec', 'AUTO'),
        n, hier=entry.get('hier', 0), wus=entry.get('wus', False),
        node_groups=node_groups, flat_tier=flat_tier,
        name=entry.get('entry_id', ''))
    if entry.get('entry_id'):
        prog.meta['entry_id'] = entry['entry_id']
    return prog


# -- lowering / execution ----------------------------------------------

def _comm_steps(program):
    return [s for s in program.steps if s.op in COMM_OPS]


def node_groups_of(program):
    """The intra-tier device groups of a hierarchical program (list of
    lists, the ``axis_index_groups`` the legacy collectives take)."""
    groups = program.meta.get('node_groups')
    if groups:
        return [list(g) for g in groups]
    for s in _comm_steps(program):
        if len(s.groups) > 1 and len(s.groups[0]) > 1:
            return [list(g) for g in s.groups]
    return None


def lowering_of(program):
    """Structural pattern-match of the step sequence onto a traced-
    emission tag. The tags name the EXACT legacy collective
    compositions ``execute`` dispatches to, so bit-identity with the
    hand-written emitter is by construction; anything else is
    ``generic`` (synthesized — executable via ``execute_generic`` when
    uniform, otherwise priced/verified only)."""
    kind = program.meta.get('kind', '')
    if kind.startswith('sparse'):
        return kind
    comm = _comm_steps(program)
    ops = tuple(s.op for s in comm)
    n = program.n

    def full(s):
        return len(s.groups) == 1 and len(s.groups[0]) == n

    if ops == ('all_reduce',) and full(comm[0]):
        if comm[0].wire == 'i8':
            return 'int8_ring'
        if program.meta.get('spec') == 'RING':
            return 'ring'
        return 'psum'
    if ops == ('reduce_scatter', 'all_reduce', 'all_gather') and \
            not any(s.tier == 'host' for s in comm):
        return 'int8_hier' if comm[1].wire == 'i8' else 'hier'
    if ops == ('reduce_scatter',) and full(comm[0]):
        return 'psum_scatter'
    if ops == ('reduce_scatter', 'reduce_scatter'):
        return 'hier_scatter'
    if ops == ('all_gather',) and full(comm[0]):
        return 'all_gather'
    if ops == ('all_gather', 'all_gather'):
        return 'hier_gather'
    return 'generic'


def execute(program, x, axis_name, *, axis=0):
    """Traced emission of ``program`` on ``x`` inside shard_map — the
    IR -> collective lowering ``plan.sync_gradients`` routes through.
    Reductions return the MEAN (what the legacy ``/ n`` sites
    produced); gathers return the gathered value. Dispatches to the
    exact legacy collective compositions per ``lowering_of``, which is
    what makes the IR lowering bit-identical to the hand-written
    emitter on every existing dimension combination."""
    import jax
    from autodist_tpu.parallel import compressor as comp
    from autodist_tpu.parallel import plan as _plan
    n = program.n
    tag = lowering_of(program)
    groups = node_groups_of(program)
    if tag == 'psum':
        return jax.lax.pmean(x, axis_name)
    if tag == 'ring':
        return _plan.ring_all_reduce(x, axis_name) / n
    if tag == 'hier':
        return _plan.hierarchical_all_reduce(x, axis_name, groups) / n
    if tag == 'int8_ring':
        return comp.int8_ring_all_reduce(x, axis_name) / n
    if tag == 'int8_hier':
        return comp.int8_hierarchical_all_reduce(x, axis_name,
                                                 groups) / n
    if tag == 'psum_scatter':
        return jax.lax.psum_scatter(x, axis_name,
                                    scatter_dimension=axis,
                                    tiled=True) / n
    if tag == 'hier_scatter':
        return _plan.hierarchical_psum_scatter(x, axis_name, groups,
                                               axis=axis) / n
    if tag == 'all_gather':
        return jax.lax.all_gather(x, axis_name, axis=axis, tiled=True)
    if tag == 'hier_gather':
        return _plan.hierarchical_all_gather(x, axis_name, groups,
                                             axis=axis)
    return execute_generic(program, x, axis_name)


def executable_generic(program):
    """True when ``execute_generic`` can trace this program on a real
    mesh: every comm step's groups are uniform-size (SPMD shapes must
    agree) and no step needs the int8 wire (the generic interpreter
    has no residual/blockscale state)."""
    for s in program.steps:
        if s.op == 'requantize' and s.wire == 'i8':
            return False
        if s.op in COMM_OPS:
            sizes = {len(g) for g in s.groups}
            if len(sizes) != 1:
                return False
            if s.op == 'reduce_scatter':
                widths = {hi - lo for chs in s.chunks
                          for lo, hi in chs}
                if len(widths) != 1:
                    return False
    return True


def execute_generic(program, x, axis_name):
    """Step-by-step interpreter for synthesized (uniform) programs —
    psum / psum_scatter / all_gather with explicit axis_index_groups
    per IR step, permutes as block relabeling. Reductions return the
    mean. Raises on programs ``executable_generic`` rejects."""
    import jax
    import jax.numpy as jnp
    n, E = program.n, program.elems
    if not executable_generic(program):
        raise ValueError('program %s is not generically executable '
                         '(non-uniform groups or int8 wire)'
                         % program.name)
    shape, size = x.shape, x.size
    buf = jnp.ravel(x)
    if E > size:
        buf = jnp.pad(buf, (0, E - size))
    reduced = program.goal in ('reduced_replicated',
                               'reduced_scattered')
    orig_dtype = buf.dtype
    for s in program.steps:
        if s.op == 'requantize':
            buf = buf.astype(jnp.bfloat16 if s.wire == 'bf16'
                             else orig_dtype)
            continue
        if s.op == 'permute':
            blocks = buf.reshape(len(s.perm), s.block)
            buf = blocks[jnp.asarray(list(s.perm))].reshape(-1)
            continue
        if s.op in ('gather', 'scatter'):
            continue
        groups = [list(g) for g in s.groups]
        covered = {d for g in groups for d in g}
        if s.op == 'all_reduce':
            # idle devices ride singleton groups (psum identity) so
            # the axis_index_groups partition the axis as XLA requires
            groups = groups + [[d] for d in range(n)
                               if d not in covered]
            buf = jax.lax.psum(buf, axis_name,
                               axis_index_groups=groups)
        elif s.op == 'reduce_scatter':
            buf = jax.lax.psum_scatter(buf, axis_name,
                                       scatter_dimension=0,
                                       tiled=True,
                                       axis_index_groups=groups)
        elif s.op == 'all_gather':
            buf = jax.lax.all_gather(buf, axis_name, axis=0,
                                     tiled=True,
                                     axis_index_groups=groups)
    buf = buf.astype(orig_dtype)
    if reduced:
        buf = buf / n
    if program.goal in ('reduced_replicated', 'gathered'):
        return buf[:size].reshape(shape)
    return buf


def format_program(program, params=None, links=None):
    """Human-readable step listing with per-step predicted times (when
    ``params`` given) — what ``tools/simulate.py --schedule-dump``
    prints so operators can see WHY a schedule won."""
    lines = ['%s: n=%d elems=%d dtype=%s goal=%s'
             % (program.name, program.n, program.elems,
                program.dtype, program.goal)]
    times = None
    if params is not None:
        from autodist_tpu.simulator.cost_model import program_time
        _, times = program_time(program, params, links=links,
                                per_step=True)
    ci = 0
    for s in program.steps:
        desc = '  %-14s %-5s %-4s' % (s.op, s.tier, s.wire)
        if s.op in COMM_OPS:
            gsz = sorted({len(g) for g in s.groups})
            desc += ' groups=%dx%s bytes=%.0f' % (
                len(s.groups),
                gsz[0] if len(gsz) == 1 else tuple(gsz), s.nbytes)
            if times is not None:
                desc += '  %.3fus' % (1e6 * times[ci])
            ci += 1
        elif s.op == 'permute':
            desc += ' blocks=%d' % len(s.perm)
        lines.append(desc)
    return '\n'.join(lines)
