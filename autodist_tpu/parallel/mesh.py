"""Device mesh construction.

The reference resolves abstract device strings to TF device strings and
lets TF's placer handle the rest (``autodist/kernel/device/resolver.py:
47-67``). The TPU-native equivalent builds a ``jax.sharding.Mesh`` whose
axes the strategy compiler binds shardings onto; XLA then handles placement
and collective lowering over ICI/DCN.

Axes follow :data:`autodist_tpu.const.ALL_AXES`:

``data``  — replica axis (the only axis the reference has),
``model`` — tensor parallelism, ``pipe`` — pipeline stages,
``seq``   — sequence/context parallelism (ring attention / Ulysses),
``expert``— MoE expert parallelism.
"""
import numpy as np

import jax
from jax.sharding import Mesh

from autodist_tpu.const import (ALL_AXES, AXIS_DATA)
from autodist_tpu.utils import logging


def build_mesh(num_replicas=None, axis_sizes=None, devices=None):
    """Build the framework mesh.

    Args:
        num_replicas: size of the ``data`` axis when no explicit
            ``axis_sizes`` is given. Defaults to all visible devices.
        axis_sizes: ordered dict-like {axis_name: size}; their product must
            divide the available device count. Axes of size 1 are kept so
            strategies can always reference the full axis set.
        devices: explicit device list (defaults to ``jax.devices()``).

    Returns:
        jax.sharding.Mesh
    """
    devices = list(devices if devices is not None else jax.devices())
    if axis_sizes:
        names = [a for a in ALL_AXES if a in axis_sizes]
        # preserve any user-defined extra axes in given order
        names += [a for a in axis_sizes if a not in names]
        sizes = [int(axis_sizes[a]) for a in names]
    else:
        n = num_replicas if num_replicas else len(devices)
        names, sizes = [AXIS_DATA], [int(n)]
    total = int(np.prod(sizes))
    if total > len(devices):
        raise ValueError(
            'Mesh wants %d devices (%s) but only %d are visible' %
            (total, dict(zip(names, sizes)), len(devices)))
    if total < len(devices):
        logging.debug('Using %d of %d visible devices for the mesh',
                      total, len(devices))
    arr = np.array(devices[:total]).reshape(sizes)
    return Mesh(arr, tuple(names))


def mesh_from_strategy(strategy, resource_spec=None, devices=None):
    """Mesh for a compiled reference-style strategy: 1-D ``data`` axis sized
    by the replica list, optionally extended by resource-spec mesh hints."""
    hints = dict(resource_spec.mesh_hint) if resource_spec is not None \
        else {}
    devices = list(devices if devices is not None else jax.devices())
    n_replicas = len(strategy.graph_config.replicas) or len(devices)
    n_replicas = min(n_replicas, len(devices))
    if hints:
        hints.setdefault(AXIS_DATA, n_replicas)
        return build_mesh(axis_sizes=hints, devices=devices)
    return build_mesh(num_replicas=n_replicas, devices=devices)
