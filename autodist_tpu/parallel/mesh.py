"""Device mesh construction.

The reference resolves abstract device strings to TF device strings and
lets TF's placer handle the rest (``autodist/kernel/device/resolver.py:
47-67``). The TPU-native equivalent builds a ``jax.sharding.Mesh`` whose
axes the strategy compiler binds shardings onto; XLA then handles placement
and collective lowering over ICI/DCN.

Axes follow :data:`autodist_tpu.const.ALL_AXES`:

``data``  — replica axis (the only axis the reference has),
``model`` — tensor parallelism, ``pipe`` — pipeline stages,
``seq``   — sequence/context parallelism (ring attention / Ulysses),
``expert``— MoE expert parallelism.
"""
import numpy as np

import jax
from jax.sharding import Mesh

from autodist_tpu.const import (ALL_AXES, AXIS_DATA)
from autodist_tpu.utils import logging


def device_mesh_array(sizes, devices, dcn_dp=1):
    """Topology-aware device placement for a mesh of shape ``sizes``.

    - ``dcn_dp > 1`` (multi-slice): the leading (data) axis is split
      ``dcn_dp``-ways across slices so data-parallel gradient reduction
      is the only traffic that crosses DCN; all other axes stay inside
      a slice on ICI (the scaling-book hierarchy rule). On real
      multi-slice TPU (devices carry ``slice_index``) this uses
      ``mesh_utils.create_hybrid_device_mesh``; elsewhere contiguous
      device groups emulate slices so the layout is testable on a
      virtual CPU mesh.
    - single-slice TPU: ``mesh_utils.create_device_mesh`` picks an
      ICI-neighbor-aware ordering (e.g. ring orders on a torus).
    - anything else (CPU/virtual): plain row-major reshape, keeping the
      deterministic device order the numeric-parity tests rely on.
    """
    sizes = [int(s) for s in sizes]
    n = int(np.prod(sizes))
    devices = list(devices)[:n]
    if dcn_dp > 1:
        if sizes[0] % dcn_dp:
            raise ValueError(
                'dcn_dp=%d must divide the data axis (%d)'
                % (dcn_dp, sizes[0]))
        ici_shape = [sizes[0] // dcn_dp] + sizes[1:]
        dcn_shape = [dcn_dp] + [1] * (len(sizes) - 1)
        slice_ids = {getattr(d, 'slice_index', None) for d in devices}
        if None not in slice_ids:
            # real multi-slice hardware: the slice structure must match,
            # else the emulation below would silently straddle physical
            # DCN boundaries with ICI axes — the exact layout this knob
            # exists to prevent
            if len(slice_ids) != dcn_dp:
                raise ValueError(
                    'dcn_dp=%d but the %d devices span %d slices'
                    % (dcn_dp, len(devices), len(slice_ids)))
            from jax.experimental import mesh_utils
            return mesh_utils.create_hybrid_device_mesh(
                ici_shape, dcn_shape, devices)
        groups = np.array(devices).reshape(dcn_dp, n // dcn_dp)
        subs = [device_mesh_array(ici_shape, list(g)) for g in groups]
        return np.stack(subs).reshape(sizes)
    if len(devices) > 1 and all(d.platform == 'tpu' for d in devices):
        from jax.experimental import mesh_utils
        try:
            return mesh_utils.create_device_mesh(sizes, devices)
        except Exception as e:   # noqa: BLE001 - topology probe only
            logging.warning('topology-aware mesh failed (%s); '
                            'falling back to row-major order', e)
    return np.array(devices).reshape(sizes)


def data_axis_node_groups(mesh, forced_nodes=0):
    """Node groups over the DATA axis for two-level collective
    schedules: ``[[positions of node 0], [positions of node 1], ...]``
    or None when the mesh is effectively single-node (flat stays the
    emission — the degenerate case).

    Grouping keys, in order of authority:

    - ``forced_nodes >= 2`` (the ``AUTODIST_HIERARCHY_NODES``
      override): that many CONTIGUOUS equal groups — how a virtual CPU
      mesh or a dcn_dp layout (slice-major data axis) expresses its
      node structure for tests and benches;
    - real multi-slice TPU: the device's ``slice_index``;
    - multi-host SPMD: the device's ``process_index``.

    Groups must partition the axis into equal sizes >= 2 (the
    two-level schedule needs a real intra phase and a real inter
    phase); anything else returns None. Deterministic for a fixed
    mesh, so every SPMD process traces the same group layout.
    """
    if AXIS_DATA not in mesh.axis_names:
        return None
    n = mesh.shape[AXIS_DATA]
    if n <= 1:
        return None
    ax = list(mesh.axis_names).index(AXIS_DATA)
    # one representative device per data-axis position (index 0 on
    # every other axis)
    arr = np.moveaxis(mesh.devices, ax, 0)
    lane = arr.reshape(n, -1)[:, 0]
    if forced_nodes and forced_nodes >= 2:
        if n % forced_nodes or n // forced_nodes < 2:
            logging.warning(
                'AUTODIST_HIERARCHY_NODES=%d does not split the %d-way '
                'data axis into equal groups of >= 2; hierarchical '
                'emission stays flat', forced_nodes, n)
            return None
        g = n // forced_nodes
        return [list(range(i * g, (i + 1) * g))
                for i in range(forced_nodes)]
    keys = [getattr(d, 'slice_index', None) for d in lane]
    if any(k is None for k in keys):
        keys = [getattr(d, 'process_index', 0) for d in lane]
    groups = {}
    for pos, key in enumerate(keys):
        groups.setdefault(key, []).append(pos)
    out = [groups[k] for k in sorted(groups)]
    sizes = {len(g) for g in out}
    if len(out) < 2 or len(sizes) != 1 or sizes == {1}:
        return None
    return out


def build_mesh(num_replicas=None, axis_sizes=None, devices=None,
               dcn_dp=1):
    """Build the framework mesh.

    Args:
        num_replicas: size of the ``data`` axis when no explicit
            ``axis_sizes`` is given. Defaults to all visible devices.
        axis_sizes: ordered dict-like {axis_name: size}; their product must
            divide the available device count. Axes of size 1 are kept so
            strategies can always reference the full axis set.
        devices: explicit device list (defaults to ``jax.devices()``).
        dcn_dp: multi-slice factor — split the data axis this many ways
            across slice (DCN) boundaries; see :func:`device_mesh_array`.

    Returns:
        jax.sharding.Mesh
    """
    devices = list(devices if devices is not None else jax.devices())
    if axis_sizes:
        names = [a for a in ALL_AXES if a in axis_sizes]
        # preserve any user-defined extra axes in given order
        names += [a for a in axis_sizes if a not in names]
        sizes = [int(axis_sizes[a]) for a in names]
        if dcn_dp > 1 and (not names or names[0] != AXIS_DATA):
            raise ValueError(
                'dcn_dp requires a leading data axis (got %s) — only the '
                'data axis may cross slice boundaries' % (names,))
    else:
        n = num_replicas if num_replicas else len(devices)
        names, sizes = [AXIS_DATA], [int(n)]
    total = int(np.prod(sizes))
    if total > len(devices):
        raise ValueError(
            'Mesh wants %d devices (%s) but only %d are visible' %
            (total, dict(zip(names, sizes)), len(devices)))
    if total < len(devices):
        logging.debug('Using %d of %d visible devices for the mesh',
                      total, len(devices))
    arr = device_mesh_array(sizes, devices, dcn_dp=dcn_dp)
    return Mesh(arr, tuple(names))


def mesh_from_strategy(strategy, resource_spec=None, devices=None):
    """Mesh for a compiled reference-style strategy: 1-D ``data`` axis sized
    by the replica list, optionally extended by resource-spec mesh hints.
    A ``dcn`` hint is the multi-slice factor (data axis split over DCN),
    not a mesh axis of its own."""
    hints = dict(resource_spec.mesh_hint) if resource_spec is not None \
        else {}
    dcn_dp = int(hints.pop('dcn', 1) or 1)
    devices = list(devices if devices is not None else jax.devices())
    n_replicas = len(strategy.graph_config.replicas) or len(devices)
    n_replicas = min(n_replicas, len(devices))
    if hints:
        hints.setdefault(AXIS_DATA, n_replicas)
        return build_mesh(axis_sizes=hints, devices=devices,
                          dcn_dp=dcn_dp)
    return build_mesh(num_replicas=n_replicas, devices=devices,
                      dcn_dp=dcn_dp)
