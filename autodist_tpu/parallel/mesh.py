"""Device mesh construction.

The reference resolves abstract device strings to TF device strings and
lets TF's placer handle the rest (``autodist/kernel/device/resolver.py:
47-67``). The TPU-native equivalent builds a ``jax.sharding.Mesh`` whose
axes the strategy compiler binds shardings onto; XLA then handles placement
and collective lowering over ICI/DCN.

Axes follow :data:`autodist_tpu.const.ALL_AXES`:

``data``  — replica axis (the only axis the reference has),
``model`` — tensor parallelism, ``pipe`` — pipeline stages,
``seq``   — sequence/context parallelism (ring attention / Ulysses),
``expert``— MoE expert parallelism.
"""
import numpy as np

import jax
from jax.sharding import Mesh

from autodist_tpu.const import (ALL_AXES, AXIS_DATA)
from autodist_tpu.utils import logging


def device_mesh_array(sizes, devices, dcn_dp=1):
    """Topology-aware device placement for a mesh of shape ``sizes``.

    - ``dcn_dp > 1`` (multi-slice): the leading (data) axis is split
      ``dcn_dp``-ways across slices so data-parallel gradient reduction
      is the only traffic that crosses DCN; all other axes stay inside
      a slice on ICI (the scaling-book hierarchy rule). On real
      multi-slice TPU (devices carry ``slice_index``) this uses
      ``mesh_utils.create_hybrid_device_mesh``; elsewhere contiguous
      device groups emulate slices so the layout is testable on a
      virtual CPU mesh.
    - single-slice TPU: ``mesh_utils.create_device_mesh`` picks an
      ICI-neighbor-aware ordering (e.g. ring orders on a torus).
    - anything else (CPU/virtual): plain row-major reshape, keeping the
      deterministic device order the numeric-parity tests rely on.
    """
    sizes = [int(s) for s in sizes]
    n = int(np.prod(sizes))
    devices = list(devices)[:n]
    if dcn_dp > 1:
        if sizes[0] % dcn_dp:
            raise ValueError(
                'dcn_dp=%d must divide the data axis (%d)'
                % (dcn_dp, sizes[0]))
        ici_shape = [sizes[0] // dcn_dp] + sizes[1:]
        dcn_shape = [dcn_dp] + [1] * (len(sizes) - 1)
        slice_ids = {getattr(d, 'slice_index', None) for d in devices}
        if None not in slice_ids:
            # real multi-slice hardware: the slice structure must match,
            # else the emulation below would silently straddle physical
            # DCN boundaries with ICI axes — the exact layout this knob
            # exists to prevent
            if len(slice_ids) != dcn_dp:
                raise ValueError(
                    'dcn_dp=%d but the %d devices span %d slices'
                    % (dcn_dp, len(devices), len(slice_ids)))
            from jax.experimental import mesh_utils
            return mesh_utils.create_hybrid_device_mesh(
                ici_shape, dcn_shape, devices)
        groups = np.array(devices).reshape(dcn_dp, n // dcn_dp)
        subs = [device_mesh_array(ici_shape, list(g)) for g in groups]
        return np.stack(subs).reshape(sizes)
    if len(devices) > 1 and all(d.platform == 'tpu' for d in devices):
        from jax.experimental import mesh_utils
        try:
            return mesh_utils.create_device_mesh(sizes, devices)
        except Exception as e:   # noqa: BLE001 - topology probe only
            logging.warning('topology-aware mesh failed (%s); '
                            'falling back to row-major order', e)
    return np.array(devices).reshape(sizes)


def build_mesh(num_replicas=None, axis_sizes=None, devices=None,
               dcn_dp=1):
    """Build the framework mesh.

    Args:
        num_replicas: size of the ``data`` axis when no explicit
            ``axis_sizes`` is given. Defaults to all visible devices.
        axis_sizes: ordered dict-like {axis_name: size}; their product must
            divide the available device count. Axes of size 1 are kept so
            strategies can always reference the full axis set.
        devices: explicit device list (defaults to ``jax.devices()``).
        dcn_dp: multi-slice factor — split the data axis this many ways
            across slice (DCN) boundaries; see :func:`device_mesh_array`.

    Returns:
        jax.sharding.Mesh
    """
    devices = list(devices if devices is not None else jax.devices())
    if axis_sizes:
        names = [a for a in ALL_AXES if a in axis_sizes]
        # preserve any user-defined extra axes in given order
        names += [a for a in axis_sizes if a not in names]
        sizes = [int(axis_sizes[a]) for a in names]
        if dcn_dp > 1 and (not names or names[0] != AXIS_DATA):
            raise ValueError(
                'dcn_dp requires a leading data axis (got %s) — only the '
                'data axis may cross slice boundaries' % (names,))
    else:
        n = num_replicas if num_replicas else len(devices)
        names, sizes = [AXIS_DATA], [int(n)]
    total = int(np.prod(sizes))
    if total > len(devices):
        raise ValueError(
            'Mesh wants %d devices (%s) but only %d are visible' %
            (total, dict(zip(names, sizes)), len(devices)))
    if total < len(devices):
        logging.debug('Using %d of %d visible devices for the mesh',
                      total, len(devices))
    arr = device_mesh_array(sizes, devices, dcn_dp=dcn_dp)
    return Mesh(arr, tuple(names))


def mesh_from_strategy(strategy, resource_spec=None, devices=None):
    """Mesh for a compiled reference-style strategy: 1-D ``data`` axis sized
    by the replica list, optionally extended by resource-spec mesh hints.
    A ``dcn`` hint is the multi-slice factor (data axis split over DCN),
    not a mesh axis of its own."""
    hints = dict(resource_spec.mesh_hint) if resource_spec is not None \
        else {}
    dcn_dp = int(hints.pop('dcn', 1) or 1)
    devices = list(devices if devices is not None else jax.devices())
    n_replicas = len(strategy.graph_config.replicas) or len(devices)
    n_replicas = min(n_replicas, len(devices))
    if hints:
        hints.setdefault(AXIS_DATA, n_replicas)
        return build_mesh(axis_sizes=hints, devices=devices,
                          dcn_dp=dcn_dp)
    return build_mesh(num_replicas=n_replicas, devices=devices,
                      dcn_dp=dcn_dp)
