"""Gradient compressors wrapping the all-reduce collective.

Parity with reference ``autodist/kernel/synchronization/compressor.py``:
``NoneCompressor`` (:146-166), ``HorovodCompressor`` (fp16 cast, :169-201),
``HorovodCompressorEF`` (error feedback, :120-143 + :204-205). PowerSGD is
commented out in the reference (:208-284); here it is implemented for real
as a low-rank compressor (round-robin power iteration), and
``Int8RingCompressor`` adds a quantized-collective tier the reference
never had (int8 wire with per-block f32 scales, EQuARX-style —
``AUTODIST_QUANT_BLOCK`` elements per scale), since low-precision +
low-rank collectives are where TPU ICI bandwidth wins come from.

A compressor transforms the *local* gradient before the collective and
inverse-transforms after; persistent state (error-feedback residual,
PowerSGD ``q`` matrix) lives in the session's aux-state pytree, threaded
through the jitted step.
"""
import jax
import jax.numpy as jnp

from autodist_tpu.const import AXIS_DATA
from autodist_tpu.parallel.axes import axis_size

_REGISTRY = {}


def register(cls):
    _REGISTRY[cls.__name__] = cls
    return cls


def create(name, var_name):
    """Factory by proto enum name (reference Compressor.create)."""
    if name not in _REGISTRY:
        raise ValueError('Unknown compressor %r (have %s)' %
                         (name, sorted(_REGISTRY)))
    return _REGISTRY[name](var_name)


class Compressor:
    """Base: ``reduce(grad, env, reduce_fn) -> averaged gradient``."""

    def __init__(self, var_name):
        self.var_name = var_name

    def init_state(self, var_value):
        """Aux-state pytree for this compressor ({} if stateless)."""
        return {}

    def reduce(self, grad, env, reduce_fn):
        raise NotImplementedError


@register
class NoneCompressor(Compressor):
    """Straight all-reduce."""

    def reduce(self, grad, env, reduce_fn):
        return reduce_fn(grad)


@register
class HorovodCompressor(Compressor):
    """Cast to bfloat16 for the wire, cast back after.

    The reference casts fp32→fp16 (compressor.py:169-201); bfloat16 is the
    TPU-native low-precision wire format (no loss-scaling needed).
    """

    def reduce(self, grad, env, reduce_fn):
        orig = grad.dtype
        if orig == jnp.float32:
            return reduce_fn(grad.astype(jnp.bfloat16)).astype(orig)
        return reduce_fn(grad)


@register
class HorovodCompressorEF(Compressor):
    """Low-precision all-reduce with error feedback.

    The quantization residual is carried to the next step and added back
    before compression (compressor.py:120-143), making the compression
    unbiased over time.
    """

    def init_state(self, var_value):
        import numpy as np
        if var_value.dtype != np.float32:
            # reduce() falls through to the plain collective for
            # non-f32 grads: a residual would be dead HBM per var (and
            # the simulator's memory estimate would count it)
            return {}
        return {'residual': jnp.zeros(var_value.shape, jnp.float32)}

    def reduce(self, grad, env, reduce_fn):
        key = 'compressor/%s' % self.var_name
        if grad.dtype != jnp.float32:
            return reduce_fn(grad)
        residual = env.aux_state[key]['residual']
        compensated = grad + residual
        compressed = compensated.astype(jnp.bfloat16)
        env.aux_updates[key] = {
            'residual': compensated - compressed.astype(jnp.float32)}
        return reduce_fn(compressed).astype(jnp.float32)


def quant_block_size():
    """Elements per int8 quantization block (``AUTODIST_QUANT_BLOCK``).

    One f32 scale per block: EQuARX-style block quantization bounds an
    outlier's damage to its own block instead of the whole tensor (or,
    on the bucketed sync path, the whole multi-variable bucket)."""
    from autodist_tpu.const import ENV
    return ENV.AUTODIST_QUANT_BLOCK.val


def _quantize_int8_blocks(x, block):
    """Symmetric per-BLOCK int8 quantization of a flat f32 vector.

    Pads to a block multiple and returns ``(q [nb, block] int8,
    scales [nb] f32)``; the pad region quantizes to zeros and is
    sliced off by :func:`_dequantize_int8_blocks`."""
    flat = jnp.ravel(x).astype(jnp.float32)
    nb = -(-flat.size // block)
    flat = jnp.pad(flat, (0, nb * block - flat.size))
    blocks = flat.reshape(nb, block)
    scales = jnp.max(jnp.abs(blocks), axis=1) / 127.0 + 1e-30
    q = jnp.clip(jnp.round(blocks / scales[:, None]),
                 -127, 127).astype(jnp.int8)
    return q, scales


def _dequantize_int8_blocks(q, scales, size):
    """Inverse of :func:`_quantize_int8_blocks` (flat f32, pad removed)."""
    return (q.astype(jnp.float32) *
            scales[:, None]).reshape(-1)[:size]


def block_roundtrip(x, block=None):
    """What a block-quantized int8 wire actually carries for ``x``:
    dequantize(quantize(x)), same shape. The error-feedback residual is
    ``x - block_roundtrip(x)`` — exactly the mass the wire dropped."""
    block = block or quant_block_size()
    q, scales = _quantize_int8_blocks(x, block)
    return _dequantize_int8_blocks(q, scales, jnp.ravel(x).size) \
        .reshape(x.shape)


def int8_ring_all_reduce(x, axis_name, block=None):
    """Bandwidth-optimal int8-wire all-reduce (sum), block-quantized.

    Ring reduce-scatter with per-hop requantization — each hop ships one
    int8 chunk (+ one f32 scale per ``block`` elements) instead of f32
    data, a ~4x wire saving — followed by an int8 all-gather of the
    fully-reduced chunks. Per-hop requantization keeps the growing
    partial sums in range (the EQuARX recipe), and per-BLOCK scales
    bound an outlier's quantization damage to its own block; callers
    carry an error-feedback residual for unbiasedness.
    """
    n = axis_size(axis_name)
    if n == 1:
        return x
    block = block or quant_block_size()
    shape = x.shape
    flat = jnp.ravel(x).astype(jnp.float32)
    m = -(-flat.size // n)
    flat = jnp.pad(flat, (0, m * n - flat.size))
    chunks = flat.reshape(n, m)
    me = jax.lax.axis_index(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]

    # reduce-scatter: after n-1 hops device i owns the full sum of
    # chunk (i+1) % n
    cur = jax.lax.dynamic_index_in_dim(chunks, me, 0, keepdims=False)
    for step in range(n - 1):
        q, scales = _quantize_int8_blocks(cur, block)
        q = jax.lax.ppermute(q, axis_name, perm)
        scales = jax.lax.ppermute(scales, axis_name, perm)
        idx = (me - step - 1) % n
        cur = _dequantize_int8_blocks(q, scales, m) + \
            jax.lax.dynamic_index_in_dim(chunks, idx, 0, keepdims=False)

    q, scales = _quantize_int8_blocks(cur, block)
    all_q = jax.lax.all_gather(q, axis_name)        # [n, nb, block] int8
    all_s = jax.lax.all_gather(scales, axis_name)   # [n, nb]
    full = (all_q.astype(jnp.float32) *
            all_s[:, :, None]).reshape(n, -1)[:, :m]
    # device row j holds chunk (j+1)%n -> chunk c sits at row (c-1)%n
    full = full[jnp.asarray([(c - 1) % n for c in range(n)])]
    return full.reshape(-1)[:x.size].reshape(shape)


def int8_grouped_ring_all_reduce(x, axis_name, groups, block=None):
    """Block-quantized int8 ring all-reduce (sum) over INDEPENDENT
    equal-size groups of axis positions.

    Same wire recipe as :func:`int8_ring_all_reduce` (per-hop
    requantization, per-block f32 scales), but the ring cycles run
    within each group concurrently — the union of the per-group cycles
    is one valid ppermute permutation, so all groups reduce in the
    same ``k-1`` hops. This is the inter-node (DCN) phase of the
    hierarchical schedule: ``groups`` then holds one same-chunk-rank
    representative per node.
    """
    k = len(groups[0])
    if k == 1:
        return x
    block = block or quant_block_size()
    shape = x.shape
    flat = jnp.ravel(x).astype(jnp.float32)
    m = -(-flat.size // k)
    flat = jnp.pad(flat, (0, m * k - flat.size))
    chunks = flat.reshape(k, m)
    n_axis = sum(len(g) for g in groups)
    ranks = [0] * n_axis
    for grp in groups:
        for i, pos in enumerate(grp):
            ranks[pos] = i
    me = jnp.asarray(ranks)[jax.lax.axis_index(axis_name)]
    perm = [(grp[i], grp[(i + 1) % k])
            for grp in groups for i in range(k)]

    cur = jax.lax.dynamic_index_in_dim(chunks, me, 0, keepdims=False)
    for step in range(k - 1):
        q, scales = _quantize_int8_blocks(cur, block)
        q = jax.lax.ppermute(q, axis_name, perm)
        scales = jax.lax.ppermute(scales, axis_name, perm)
        idx = (me - step - 1) % k
        cur = _dequantize_int8_blocks(q, scales, m) + \
            jax.lax.dynamic_index_in_dim(chunks, idx, 0, keepdims=False)

    q, scales = _quantize_int8_blocks(cur, block)
    all_q = jax.lax.all_gather(q, axis_name,
                               axis_index_groups=groups)
    all_s = jax.lax.all_gather(scales, axis_name,
                               axis_index_groups=groups)
    full = (all_q.astype(jnp.float32) *
            all_s[:, :, None]).reshape(k, -1)[:, :m]
    # group row j holds chunk (j+1)%k -> chunk c sits at row (c-1)%k
    full = full[jnp.asarray([(c - 1) % k for c in range(k)])]
    return full.reshape(-1)[:x.size].reshape(shape)


def int8_hierarchical_all_reduce(x, axis_name, node_groups, block=None):
    """Two-level int8-wire all-reduce (sum): quantize once, requantize
    at the tier boundary.

    The caller has already block-roundtripped the bucket once (the
    "quantize once" of the error-feedback contract); the intra-node
    phases then ride plain f32 grouped collectives on the cheap ICI
    tier, and only the tier BOUNDARY requantizes: each node's partial
    chunk sum rides the int8 ring across nodes (per-hop requant, the
    DCN tier the quantization exists to relieve), and the reduced
    chunks all-gather back within each node at f32.
    """
    k = len(node_groups)
    g = len(node_groups[0])
    if k <= 1 or g <= 1:
        return int8_ring_all_reduce(x, axis_name, block=block)
    shape = x.shape
    flat = jnp.ravel(x).astype(jnp.float32)
    m = -(-flat.size // g) * g
    flat = jnp.pad(flat, (0, m - flat.size))
    cur = jax.lax.psum_scatter(flat, axis_name, scatter_dimension=0,
                               tiled=True,
                               axis_index_groups=node_groups)
    inter = [[grp[r] for grp in node_groups] for r in range(g)]
    cur = int8_grouped_ring_all_reduce(cur, axis_name, inter,
                                       block=block)
    out = jax.lax.all_gather(cur, axis_name, tiled=True,
                             axis_index_groups=node_groups)
    return out[:x.size].reshape(shape)


def int8_bucket_fusable(compressor, dtype, size):
    """THE bucket-fusion predicate for the int8 tier, shared by
    ``plan.sync_gradients`` (runtime emission) and
    ``plan.static_collective_schedule`` (what the simulator prices) so
    the two can never drift. True only for f32 tensors at or above
    ``MIN_SIZE``: smaller tensors have no error-feedback residual
    (``init_state``) and must keep the plain lossless collective —
    riding a quantized bucket uncompensated would put a systematic,
    never-corrected bias on exactly the small, sensitive parameters
    (biases, norm scales)."""
    import numpy as np
    return (type(compressor) is Int8RingCompressor and
            np.dtype(dtype) == np.float32 and
            size >= Int8RingCompressor.MIN_SIZE)


@register
class Int8RingCompressor(Compressor):
    """Int8-wire quantized all-reduce with error feedback.

    The reference's compressor tier stops at fp16 casts; this is the
    quantized-collective extension (SURVEY.md §7 stage 4): gradients ride
    the ring as int8 + per-block f32 scales (``AUTODIST_QUANT_BLOCK``
    elements each — ~4x fewer wire bytes than f32, an outlier only
    poisons its own block), and the quantization error is carried to the
    next step, keeping training unbiased over time. Tensors below
    MIN_SIZE (or non-f32) fall through to the plain collective — no wire
    saving to be had there.

    Same-group f32 variables under this compressor are additionally
    BUCKET-fusable (``plan.sync_gradients``): the packed bucket is
    quantized as one vector with per-block scales and ONE collective,
    with each member's error-feedback residual carried separately in
    aux-state — see :meth:`~autodist_tpu.parallel.plan.ExecutionPlan.
    sync_gradients`.
    """

    MIN_SIZE = 128

    def init_state(self, var_value):
        import numpy as np
        if var_value.dtype != np.float32 or \
                np.prod(var_value.shape, dtype=int) < self.MIN_SIZE:
            return {}
        return {'residual': jnp.zeros(var_value.shape, jnp.float32)}

    def reduce(self, grad, env, reduce_fn):
        if grad.dtype != jnp.float32 or grad.size < self.MIN_SIZE:
            return reduce_fn(grad)
        key = 'compressor/%s' % self.var_name
        residual = env.aux_state[key]['residual']
        compensated = grad + residual
        transmitted = block_roundtrip(compensated)
        env.aux_updates[key] = {'residual': compensated - transmitted}
        n = axis_size(AXIS_DATA)
        return int8_ring_all_reduce(transmitted, AXIS_DATA) / n


@register
class PowerSGDCompressor(Compressor):
    """Rank-``r`` PowerSGD (arXiv:1905.13727) with error feedback.

    The gradient matrix ``M (n×m)`` is approximated as ``P Qᵀ`` where
    ``P = M Q`` is all-reduced (and orthogonalized) and ``Q = Mᵀ P`` is
    all-reduced; only ``P``/``Q`` cross the wire. Falls back to plain
    all-reduce for rank<2 tensors.
    """

    RANK = 2

    def init_state(self, var_value):
        if var_value.ndim < 2:
            return {}
        n = int(var_value.shape[0])
        m = 1
        for d in var_value.shape[1:]:
            m *= int(d)
        # Deterministic init (stable across processes — crc32, not the
        # salted builtin hash); orthogonalized on first use.
        import zlib
        import numpy as np
        rng = np.random.RandomState(
            zlib.crc32(self.var_name.encode()) % (2 ** 31))
        q = rng.standard_normal((m, self.RANK)).astype('float32')
        return {'q': jnp.asarray(q),
                'residual': jnp.zeros((n, m), jnp.float32)}

    @staticmethod
    def _orthogonalize(m):
        q, _ = jnp.linalg.qr(m)
        return q

    def reduce(self, grad, env, reduce_fn):
        if grad.ndim < 2:
            return reduce_fn(grad)
        key = 'compressor/%s' % self.var_name
        state = env.aux_state[key]
        shape = grad.shape
        mat = grad.reshape(shape[0], -1) + state['residual']
        q = state['q']
        p = reduce_fn(mat @ q)
        p = self._orthogonalize(p)
        new_q = reduce_fn(mat.T @ p)
        approx = p @ new_q.T
        env.aux_updates[key] = {'q': new_q, 'residual': mat - approx}
        return approx.reshape(shape)
