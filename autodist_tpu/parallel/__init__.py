"""parallel subpackage."""
