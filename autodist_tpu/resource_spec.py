"""Cluster resource specification.

TPU-native re-design of reference ``autodist/resource_spec.py:45-331``.
Parses the same YAML format (nodes with address / cpus / gpus / chief /
ssh_config / network_bandwidth, plus an ``ssh:`` config map) and extends it
with a first-class ``tpus`` device type and ICI/DCN topology hints used by
the mesh builder.

Device strings keep the reference's ``<address>:<TYPE>:<index>`` format
(resolver.py:47-67) so strategy protos remain human-readable.
"""
import os
from enum import Enum

import yaml

from autodist_tpu.utils import logging

DEFAULT_NETWORK_BANDWIDTH = 1  # GBE, reference resource_spec.py:210-215


class DeviceType(Enum):
    """Device categories; the rebuild adds TPU as a first-class type."""
    CPU = 0
    GPU = 1
    TPU = 2


class DeviceSpec:
    """One addressable device: ``<host>:<TYPE>:<index>``."""

    def __init__(self, host_address, device_index=0,
                 device_type=DeviceType.CPU):
        self.host_address = host_address
        self.device_index = int(device_index)
        self.device_type = device_type

    @property
    def name_string(self):
        return '%s:%s:%d' % (self.host_address, self.device_type.name,
                             self.device_index)

    def __repr__(self):
        return '<DeviceSpec %s>' % self.name_string

    def __eq__(self, other):
        return isinstance(other, DeviceSpec) and \
            self.name_string == other.name_string

    def __hash__(self):
        return hash(self.name_string)

    @classmethod
    def from_string(cls, name_string):
        """Parse ``host:TYPE:index`` back into a DeviceSpec."""
        host, type_name, index = name_string.rsplit(':', 2)
        return cls(host, int(index), DeviceType[type_name])


class SSHConfig:
    """SSH connection info for one config-map entry.

    Parity with reference resource_spec.py:280-318 (username, port,
    key_file, python_venv, shared environment variables).
    """

    def __init__(self, info):
        self.username = info.get('username', '')
        self.port = info.get('port', 22)
        self.key_file = info.get('key_file')
        self.python_venv = info.get('python_venv', '')
        self.env = dict(info.get('shared_envs', {}))


class SSHConfigMap(dict):
    """Named SSH configs: ``{conf_name: SSHConfig}``."""

    def __init__(self, info):
        super().__init__({name: SSHConfig(conf)
                          for name, conf in (info or {}).items()})


class ResourceSpec:
    """Parsed cluster description.

    Accepts the reference YAML schema plus:

    - ``tpus: [i, ...]`` per node (TPU chips on that host), or
      ``tpus: auto`` to discover via ``jax.local_devices()`` at runtime;
    - top-level ``mesh:`` hints (``{data: 4, model: 2, ...}``) consumed by
      the strategy compiler when building the jax.sharding.Mesh;
    - ``coordinator:`` address override for jax.distributed.
    """

    def __init__(self, resource_file=None, resource_info=None):
        self.__devices = {}          # name_string -> DeviceSpec
        self.__nodes = {}            # address -> node dict
        self.__chief_address = None
        self.__ssh_config_map = SSHConfigMap({})
        self.__network_bandwidth = {}
        self.mesh_hint = {}
        self.coordinator_address = None

        if resource_file is not None:
            if not os.path.isfile(resource_file):
                raise FileNotFoundError(
                    'Resource spec file not found: %s' % resource_file)
            with open(resource_file, 'r') as f:
                resource_info = yaml.safe_load(f)
        if resource_info is None:
            raise ValueError('Must provide resource_file or resource_info')
        self._parse(resource_info)

    # -- parsing ----------------------------------------------------------
    def _parse(self, info):
        nodes = info.get('nodes')
        if not nodes:
            raise ValueError("Resource spec needs at least one node "
                             "under 'nodes:'")
        self.mesh_hint = dict(info.get('mesh', {}))
        self.coordinator_address = info.get('coordinator')
        self.__ssh_config_map = SSHConfigMap(info.get('ssh'))

        for node in nodes:
            address = str(node['address'])
            if address in self.__nodes:
                raise ValueError('Duplicate node address %s' % address)
            self.__nodes[address] = node
            if node.get('chief', False):
                if self.__chief_address is not None:
                    raise ValueError('Only one node may be chief')
                self.__chief_address = address
            host_cpu = DeviceSpec(address, 0, DeviceType.CPU)
            self.__devices[host_cpu.name_string] = host_cpu
            for i in node.get('cpus', []):
                if int(i) == 0:
                    continue
                d = DeviceSpec(address, i, DeviceType.CPU)
                self.__devices[d.name_string] = d
            for i in node.get('gpus', []):
                d = DeviceSpec(address, i, DeviceType.GPU)
                self.__devices[d.name_string] = d
            tpus = node.get('tpus', [])
            if tpus == 'auto':
                tpus = self._discover_local_tpus()
            for i in tpus:
                d = DeviceSpec(address, i, DeviceType.TPU)
                self.__devices[d.name_string] = d
            bw = node.get('network_bandwidth')
            if bw is None:
                logging.warning(
                    'Network bandwidth missing for node %s; defaulting to '
                    '%d GBE', address, DEFAULT_NETWORK_BANDWIDTH)
                bw = DEFAULT_NETWORK_BANDWIDTH
            self.__network_bandwidth[address] = bw

        if len(self.__nodes) == 1:
            self.__chief_address = next(iter(self.__nodes))
        if self.__chief_address is None:
            raise ValueError('Must specify one chief node in a '
                             'multi-node spec')

    @staticmethod
    def _discover_local_tpus():
        import jax
        return [d.id for d in jax.local_devices()
                if d.platform in ('tpu', 'axon')]

    # -- accessors (parity with resource_spec.py:80-158) ------------------
    @property
    def chief(self):
        """Chief node address."""
        return self.__chief_address

    @property
    def nodes(self):
        """Iterable of node addresses."""
        return self.__nodes.keys()

    @property
    def devices(self):
        """Iterable of (name_string, DeviceSpec) for all devices."""
        return self.__devices.items()

    def _filter(self, device_type):
        return ((n, d) for n, d in self.__devices.items()
                if d.device_type is device_type)

    @property
    def cpu_devices(self):
        return self._filter(DeviceType.CPU)

    @property
    def gpu_devices(self):
        return self._filter(DeviceType.GPU)

    @property
    def tpu_devices(self):
        return self._filter(DeviceType.TPU)

    @property
    def accelerator_devices(self):
        """GPU + TPU devices; what replicas are placed on."""
        return ((n, d) for n, d in self.__devices.items()
                if d.device_type is not DeviceType.CPU)

    @property
    def num_accelerators(self):
        return sum(1 for _ in self.accelerator_devices)

    def num_accelerators_on(self, address):
        return sum(1 for _, d in self.accelerator_devices
                   if d.host_address == address)

    @property
    def num_cpus(self):
        return sum(1 for _ in self.cpu_devices)

    @property
    def network_bandwidth(self):
        """Per-node bandwidth map (GBE)."""
        return dict(self.__network_bandwidth)

    @property
    def ssh_config_map(self):
        return self.__ssh_config_map

    def ssh_config(self, address):
        name = self.__nodes[address].get('ssh_config')
        return self.__ssh_config_map.get(name)

    @property
    def node_cpu_devices(self):
        """address -> [cpu name strings]."""
        out = {}
        for n, d in self.cpu_devices:
            out.setdefault(d.host_address, []).append(n)
        return out

    @property
    def node_accelerator_devices(self):
        """address -> [accelerator name strings]."""
        out = {}
        for n, d in self.accelerator_devices:
            out.setdefault(d.host_address, []).append(n)
        return out

    def __repr__(self):
        return '<ResourceSpec chief=%s nodes=%d accelerators=%d>' % (
            self.chief, len(self.__nodes), self.num_accelerators)
