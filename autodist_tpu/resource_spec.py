"""Cluster resource specification.

TPU-native re-design of reference ``autodist/resource_spec.py:45-331``.
Parses the same YAML format (nodes with address / cpus / gpus / chief /
ssh_config / network_bandwidth, plus an ``ssh:`` config map) and extends it
with a first-class ``tpus`` device type and ICI/DCN topology hints used by
the mesh builder.

Device strings keep the reference's ``<address>:<TYPE>:<index>`` format
(resolver.py:47-67) so strategy protos remain human-readable.
"""
import os
from enum import Enum

import yaml

from autodist_tpu.utils import logging

DEFAULT_NETWORK_BANDWIDTH = 1  # GBE, reference resource_spec.py:210-215


class DeviceType(Enum):
    """Device categories; the rebuild adds TPU as a first-class type."""
    CPU = 0
    GPU = 1
    TPU = 2


#: Device kinds a ``topology.device_kind`` hint may name. Matching is by
#: substring, like bench.py's peak-FLOPs table ('v5e' matches 'tpu v5e').
#: First match wins, so the more specific v5p/v5e come before v5.
KNOWN_DEVICE_KINDS = ('v6', 'v5p', 'v5e', 'v5', 'v4', 'v3', 'v2',
                      'gpu', 'cpu')

#: Per-device-KIND ICI defaults (bandwidth GB/s, latency us): coarse
#: per-device effective ring bandwidth from public figures, refining
#: the per-TYPE default below when ``topology.device_kind`` names a
#: generation but no explicit bandwidth is given.
_ICI_BY_KIND = {
    'v6': (220.0, 1.0),
    'v5p': (180.0, 1.0),
    'v5e': (80.0, 1.0),
    'v5': (80.0, 1.0),
    'v4': (100.0, 1.0),
    'v3': (70.0, 1.0),
    'v2': (50.0, 1.0),
    'gpu': (60.0, 3.0),
    'cpu': (10.0, 5.0),
}

#: Per-device-type link defaults (bandwidth GB/s, latency us) used when a
#: spec carries no explicit ``topology:`` hints. ICI numbers are
#: per-device effective ring bandwidth (conservative public figures);
#: the CPU "ici" is host-memory traffic between virtual devices.
_ICI_DEFAULTS = {
    DeviceType.TPU: (100.0, 1.0),
    DeviceType.GPU: (60.0, 3.0),
    DeviceType.CPU: (10.0, 5.0),
}
_DCN_DEFAULT_LATENCY_US = 30.0

#: Per-device-KIND roofline peaks: (dense bf16 peak FLOP/s, peak HBM
#: bandwidth GB/s) from public spec sheets — the denominator of the
#: device-plane MFU/roofline accounting (telemetry/roofline.py).
#: Matching follows KNOWN_DEVICE_KINDS (substring, first match wins).
#: ``None`` entries mean "no meaningful peak": a CPU host's virtual
#: devices have no spec-sheet FLOPs ceiling, so MFU degrades to an
#: explicit null instead of a number against a made-up denominator.
#: bench.py's headline-MFU table reads the same entries.
PEAKS_BY_KIND = {
    'v6': (918e12, 1640.0),
    'v5p': (459e12, 2765.0),
    'v5e': (197e12, 819.0),
    'v5': (197e12, 819.0),
    'v4': (275e12, 1228.0),
    'v3': (123e12, 900.0),
    'v2': (46e12, 700.0),
    'gpu': (125e12, 900.0),
    'cpu': (None, None),
}


class Topology:
    """Validated ICI/DCN link model for the strategy simulator.

    Built from a spec's optional top-level ``topology:`` block::

        topology:
          ici_bandwidth_gbps: 100   # GB/s per device, intra-slice
          ici_latency_us: 1
          dcn_bandwidth_gbps: 12.5  # GB/s per device, cross-slice/node
          dcn_latency_us: 30
          device_kind: v5e          # optional, one of KNOWN_DEVICE_KINDS
          peak_flops: 1.97e14       # optional, dense bf16 FLOP/s/chip
          peak_hbm_gbps: 819        # optional, HBM GB/s/chip

    Missing fields default from the spec's device types (ICI) and the
    per-node ``network_bandwidth`` (DCN: GBE is gigaBITs, so /8); the
    roofline peaks default from the ``device_kind`` row of
    :data:`PEAKS_BY_KIND` and may resolve to None (CPU hosts have no
    meaningful peak — MFU reports an explicit null, never a number
    against an invented denominator). All fields are validated at
    parse time — the simulator and the roofline observatory consume
    them blindly.
    """

    _NUMERIC_FIELDS = ('ici_bandwidth_gbps', 'ici_latency_us',
                       'dcn_bandwidth_gbps', 'dcn_latency_us')
    _PEAK_FIELDS = ('peak_flops', 'peak_hbm_gbps')

    def __init__(self, info, accel_type, min_net_bandwidth_gbe,
                 multi_node):
        info = dict(info or {})
        for field in self._NUMERIC_FIELDS + self._PEAK_FIELDS:
            val = info.get(field)
            if val is None:
                continue
            if not isinstance(val, (int, float)) or \
                    isinstance(val, bool) or val <= 0:
                raise ValueError(
                    'topology.%s must be a positive number, got %r'
                    % (field, val))
        kind = info.get('device_kind')
        matched_kind = None
        if kind is not None:
            k = str(kind).lower()
            matched_kind = next((known for known in KNOWN_DEVICE_KINDS
                                 if known in k), None)
            if matched_kind is None:
                raise ValueError(
                    'topology.device_kind %r is not a known device type '
                    '(known: %s)' % (kind, ', '.join(KNOWN_DEVICE_KINDS)))
        unknown = set(info) - set(self._NUMERIC_FIELDS) \
            - set(self._PEAK_FIELDS) - {'device_kind'}
        if unknown:
            raise ValueError(
                'Unknown topology field(s) %s (known: %s, %s, '
                'device_kind)'
                % (sorted(unknown), ', '.join(self._NUMERIC_FIELDS),
                   ', '.join(self._PEAK_FIELDS)))
        # device_kind refines the ICI defaults by TPU generation
        if matched_kind is not None:
            ici_bw, ici_lat = _ICI_BY_KIND[matched_kind]
        else:
            ici_bw, ici_lat = _ICI_DEFAULTS[accel_type]
        self.device_kind = str(kind).lower() if kind is not None else ''
        self.ici_bandwidth_gbps = float(
            info.get('ici_bandwidth_gbps', ici_bw))
        self.ici_latency_us = float(info.get('ici_latency_us', ici_lat))
        self.dcn_bandwidth_gbps = float(
            info.get('dcn_bandwidth_gbps',
                     max(min_net_bandwidth_gbe, 0.001) / 8.0))
        self.dcn_latency_us = float(
            info.get('dcn_latency_us', _DCN_DEFAULT_LATENCY_US))
        # roofline peaks: explicit fields override the per-kind table;
        # with no matched kind the type default is 'gpu' / 'cpu' class
        if matched_kind is not None:
            peak_flops, peak_hbm = PEAKS_BY_KIND[matched_kind]
        elif accel_type is DeviceType.TPU:
            peak_flops, peak_hbm = PEAKS_BY_KIND['v5e']
        elif accel_type is DeviceType.GPU:
            peak_flops, peak_hbm = PEAKS_BY_KIND['gpu']
        else:
            peak_flops, peak_hbm = PEAKS_BY_KIND['cpu']
        pf = info.get('peak_flops', peak_flops)
        ph = info.get('peak_hbm_gbps', peak_hbm)
        self.peak_flops = float(pf) if pf is not None else None
        self.peak_hbm_gbps = float(ph) if ph is not None else None
        self.multi_node = bool(multi_node)
        # Re-validate the RESOLVED link constants, not just the raw
        # fields: the simulator divides by link() bandwidth with no
        # guard (CostModelParams.from_topology), and the per-field
        # check above admits NaN (NaN <= 0 is False) while defaulted
        # values come from arithmetic on per-node bandwidths. Fail at
        # parse time with the field named, like the hint validation.
        import math
        for field in self._NUMERIC_FIELDS:
            val = getattr(self, field)
            if not math.isfinite(val) or val <= 0:
                raise ValueError(
                    'topology.%s must resolve to a positive finite '
                    'number, got %r' % (field, val))
        # roofline peaks get the same resolved check, except that None
        # (no meaningful peak for this device kind — CPU hosts) is a
        # legitimate resolution the MFU accounting degrades on
        for field in self._PEAK_FIELDS:
            val = getattr(self, field)
            if val is not None and (not math.isfinite(val) or val <= 0):
                raise ValueError(
                    'topology.%s must resolve to a positive finite '
                    'number (or be omitted), got %r' % (field, val))

    def peaks(self):
        """(peak FLOP/s, peak HBM bytes/s) — either may be None when
        the device kind has no meaningful spec-sheet peak (MFU then
        reports an explicit null). The ``AUTODIST_ROOFLINE_PEAKS`` env
        override (validated at parse time in const.py) takes precedence
        over both the explicit fields and the per-kind defaults, like
        the other traced-program overrides."""
        from autodist_tpu.const import ENV
        forced = ENV.AUTODIST_ROOFLINE_PEAKS.val
        pf, ph = self.peak_flops, self.peak_hbm_gbps
        if forced:
            pf = forced.get('flops', pf)
            ph = forced.get('hbm_gbps', ph)
        return pf, (ph * 1e9 if ph is not None else None)

    def link(self, cross_node=False):
        """(bytes/s, seconds) for one link class.

        ``cross_node=True`` prices the DCN (cross-slice / cross-host)
        path; else the intra-slice ICI path.
        """
        if cross_node:
            return (self.dcn_bandwidth_gbps * 1e9,
                    self.dcn_latency_us * 1e-6)
        return (self.ici_bandwidth_gbps * 1e9,
                self.ici_latency_us * 1e-6)

    def __repr__(self):
        return ('<Topology ici=%.1fGB/s,%.1fus dcn=%.2fGB/s,%.1fus%s>'
                % (self.ici_bandwidth_gbps, self.ici_latency_us,
                   self.dcn_bandwidth_gbps, self.dcn_latency_us,
                   ' multi-node' if self.multi_node else ''))


class DeviceSpec:
    """One addressable device: ``<host>:<TYPE>:<index>``."""

    def __init__(self, host_address, device_index=0,
                 device_type=DeviceType.CPU):
        self.host_address = host_address
        self.device_index = int(device_index)
        self.device_type = device_type

    @property
    def name_string(self):
        return '%s:%s:%d' % (self.host_address, self.device_type.name,
                             self.device_index)

    def __repr__(self):
        return '<DeviceSpec %s>' % self.name_string

    def __eq__(self, other):
        return isinstance(other, DeviceSpec) and \
            self.name_string == other.name_string

    def __hash__(self):
        return hash(self.name_string)

    @classmethod
    def from_string(cls, name_string):
        """Parse ``host:TYPE:index`` back into a DeviceSpec."""
        host, type_name, index = name_string.rsplit(':', 2)
        return cls(host, int(index), DeviceType[type_name])


class SSHConfig:
    """SSH connection info for one config-map entry.

    Parity with reference resource_spec.py:280-318 (username, port,
    key_file, python_venv, shared environment variables).
    """

    def __init__(self, info):
        self.username = info.get('username', '')
        self.port = info.get('port', 22)
        self.key_file = info.get('key_file')
        self.python_venv = info.get('python_venv', '')
        self.env = dict(info.get('shared_envs', {}))


class SSHConfigMap(dict):
    """Named SSH configs: ``{conf_name: SSHConfig}``."""

    def __init__(self, info):
        super().__init__({name: SSHConfig(conf)
                          for name, conf in (info or {}).items()})


class ResourceSpec:
    """Parsed cluster description.

    Accepts the reference YAML schema plus:

    - ``tpus: [i, ...]`` per node (TPU chips on that host), or
      ``tpus: auto`` to discover via ``jax.local_devices()`` at runtime;
    - top-level ``mesh:`` hints (``{data: 4, model: 2, ...}``) consumed by
      the strategy compiler when building the jax.sharding.Mesh;
    - ``coordinator:`` address override for jax.distributed.
    """

    def __init__(self, resource_file=None, resource_info=None):
        self.__devices = {}          # name_string -> DeviceSpec
        self.__nodes = {}            # address -> node dict
        self.__chief_address = None
        self.__ssh_config_map = SSHConfigMap({})
        self.__network_bandwidth = {}
        self.mesh_hint = {}
        self.coordinator_address = None
        self.__topology = None
        self.__topology_info = {}

        if resource_file is not None:
            if not os.path.isfile(resource_file):
                raise FileNotFoundError(
                    'Resource spec file not found: %s' % resource_file)
            with open(resource_file, 'r') as f:
                resource_info = yaml.safe_load(f)
        if resource_info is None:
            raise ValueError('Must provide resource_file or resource_info')
        self._parse(resource_info)

    # -- parsing ----------------------------------------------------------
    def _parse(self, info):
        nodes = info.get('nodes')
        if not nodes:
            raise ValueError("Resource spec needs at least one node "
                             "under 'nodes:'")
        self.mesh_hint = dict(info.get('mesh', {}))
        self.coordinator_address = info.get('coordinator')
        self.__ssh_config_map = SSHConfigMap(info.get('ssh'))

        for node in nodes:
            address = str(node['address'])
            if address in self.__nodes:
                raise ValueError('Duplicate node address %s' % address)
            self.__nodes[address] = node
            if node.get('chief', False):
                if self.__chief_address is not None:
                    raise ValueError('Only one node may be chief')
                self.__chief_address = address
            host_cpu = DeviceSpec(address, 0, DeviceType.CPU)
            self.__devices[host_cpu.name_string] = host_cpu
            for i in node.get('cpus', []):
                if int(i) == 0:
                    continue
                d = DeviceSpec(address, i, DeviceType.CPU)
                self.__devices[d.name_string] = d
            for i in node.get('gpus', []):
                d = DeviceSpec(address, i, DeviceType.GPU)
                self.__devices[d.name_string] = d
            tpus = node.get('tpus', [])
            if tpus == 'auto':
                tpus = self._discover_local_tpus()
            for i in tpus:
                d = DeviceSpec(address, i, DeviceType.TPU)
                self.__devices[d.name_string] = d
            bw = node.get('network_bandwidth')
            if bw is None:
                logging.warning(
                    'Network bandwidth missing for node %s; defaulting to '
                    '%d GBE', address, DEFAULT_NETWORK_BANDWIDTH)
                bw = DEFAULT_NETWORK_BANDWIDTH
            elif not isinstance(bw, (int, float)) or \
                    isinstance(bw, bool) or bw <= 0:
                raise ValueError(
                    'nodes[%s].network_bandwidth must be a positive '
                    'number, got %r' % (address, bw))
            self.__network_bandwidth[address] = bw

        if len(self.__nodes) == 1:
            self.__chief_address = next(iter(self.__nodes))
        if self.__chief_address is None:
            raise ValueError('Must specify one chief node in a '
                             'multi-node spec')
        # topology hints are validated eagerly (parse time), not at
        # first .topology access: the simulator consumes them blindly
        self.__topology_info = dict(info.get('topology', {}) or {})
        self.__topology = Topology(
            self.__topology_info, self._accel_type(),
            min(self.__network_bandwidth.values()),
            multi_node=len(self.__nodes) > 1)

    def _accel_type(self):
        """Dominant accelerator DeviceType (for topology defaults)."""
        types = {d.device_type for _, d in self.__devices.items()}
        for t in (DeviceType.TPU, DeviceType.GPU):
            if t in types:
                return t
        return DeviceType.CPU

    @staticmethod
    def _discover_local_tpus():
        import jax
        return [d.id for d in jax.local_devices()
                if d.platform in ('tpu', 'axon')]

    # -- accessors (parity with resource_spec.py:80-158) ------------------
    @property
    def chief(self):
        """Chief node address."""
        return self.__chief_address

    @property
    def nodes(self):
        """Iterable of node addresses."""
        return self.__nodes.keys()

    @property
    def devices(self):
        """Iterable of (name_string, DeviceSpec) for all devices."""
        return self.__devices.items()

    def _filter(self, device_type):
        return ((n, d) for n, d in self.__devices.items()
                if d.device_type is device_type)

    @property
    def cpu_devices(self):
        return self._filter(DeviceType.CPU)

    @property
    def gpu_devices(self):
        return self._filter(DeviceType.GPU)

    @property
    def tpu_devices(self):
        return self._filter(DeviceType.TPU)

    @property
    def accelerator_devices(self):
        """GPU + TPU devices; what replicas are placed on."""
        return ((n, d) for n, d in self.__devices.items()
                if d.device_type is not DeviceType.CPU)

    @property
    def num_accelerators(self):
        return sum(1 for _ in self.accelerator_devices)

    def num_accelerators_on(self, address):
        return sum(1 for _, d in self.accelerator_devices
                   if d.host_address == address)

    @property
    def num_cpus(self):
        return sum(1 for _ in self.cpu_devices)

    @property
    def network_bandwidth(self):
        """Per-node bandwidth map (GBE)."""
        return dict(self.__network_bandwidth)

    @property
    def topology(self):
        """Validated :class:`Topology` (ICI/DCN bandwidth+latency hints).

        Always present: explicit ``topology:`` fields override, the rest
        defaults from the spec's device types and node bandwidths.
        """
        return self.__topology

    @property
    def ssh_config_map(self):
        return self.__ssh_config_map

    def ssh_config(self, address):
        name = self.__nodes[address].get('ssh_config')
        return self.__ssh_config_map.get(name)

    @property
    def node_cpu_devices(self):
        """address -> [cpu name strings]."""
        out = {}
        for n, d in self.cpu_devices:
            out.setdefault(d.host_address, []).append(n)
        return out

    @property
    def node_accelerator_devices(self):
        """address -> [accelerator name strings]."""
        out = {}
        for n, d in self.accelerator_devices:
            out.setdefault(d.host_address, []).append(n)
        return out

    def __repr__(self):
        return '<ResourceSpec chief=%s nodes=%d accelerators=%d>' % (
            self.chief, len(self.__nodes), self.num_accelerators)
