"""autodist_tpu: a TPU-native distributed-training compiler.

A from-scratch rebuild of the AutoDist design (strategy IR + compiler +
runtime; see /root/reference) on jax/XLA: strategies assign per-variable
synchronization (PS or AllReduce), partitioning, and placement; the
compiler lowers them to shardings + collectives over a ``jax.sharding``
device mesh, and a single fused XLA program per step replaces per-op graph
rewriting.

Typical use (mirrors reference README.md:11-25)::

    import autodist_tpu as ad
    autodist = ad.AutoDist(resource_spec_file, ad.AllReduce(128))
    with autodist.scope():
        W = ad.Variable(5.0, name='W')
        b = ad.Variable(0.0, name='b')
        x = ad.placeholder(shape=[None])
        loss = ad.ops.reduce_mean(ad.ops.square(W * x + b - y))
        train_op = ad.optimizers.SGD(0.01).minimize(loss)
    sess = autodist.create_distributed_session()
    sess.run([loss, train_op], {x: batch_x})
"""
from autodist_tpu.autodist import AutoDist, get_default_autodist  # noqa: F401
from autodist_tpu.frontend import ops  # noqa: F401
from autodist_tpu.frontend import optimizers  # noqa: F401
from autodist_tpu.frontend.graph import (  # noqa: F401
    Graph, Placeholder, Variable, gradients, placeholder)
from autodist_tpu.graph_item import GraphItem  # noqa: F401
from autodist_tpu.resource_spec import ResourceSpec  # noqa: F401
from autodist_tpu.strategy import (  # noqa: F401
    PS, AllReduce, Parallax, PartitionedAR, PartitionedPS,
    PSLoadBalancing, RandomAxisPartitionAR, UnevenPartitionedPS)

__version__ = '0.1.0'
