"""Constants and environment-flag system.

TPU-native re-design of the reference's ``autodist/const.py`` (see
/root/reference/autodist/const.py:32-89): working directories, name-scope
prefixes, the port range used by the multi-process launcher, and a typed
``ENV`` enum of environment flags that are explicitly propagated to worker
processes by the coordinator.
"""
import os
from enum import Enum

# Working directories ------------------------------------------------------
# Hyphenated on purpose: an importable name here would shadow the package
# as a namespace package for any process whose cwd is /tmp.
DEFAULT_WORKING_DIR = '/tmp/autodist-tpu'
DEFAULT_SERIALIZATION_DIR = os.path.join(DEFAULT_WORKING_DIR, 'strategies')
DEFAULT_LOG_DIR = os.path.join(DEFAULT_WORKING_DIR, 'logs')
DEFAULT_TRACE_DIR = os.path.join(DEFAULT_WORKING_DIR, 'traces')
DEFAULT_GRAPH_DUMP_DIR = os.path.join(DEFAULT_WORKING_DIR, 'graphs')
DEFAULT_CHECKPOINT_DIR = os.path.join(DEFAULT_WORKING_DIR, 'checkpoints')

# Port range for the coordination service / distributed runtime
# (reference uses 15000-16000 for tf.Server grpc ports, const.py:38).
DEFAULT_PORT_RANGE = iter(range(15000, 16000))
# jax.distributed coordinator and the native coord service are distinct
# endpoints; keep their default ports distinct too.
DEFAULT_JAX_COORD_PORT = 14999
DEFAULT_COORD_PORT = 14998

# Mesh axis names used by the strategy compiler. The reference only has a
# replica ("data") dimension; the TPU rebuild exposes the full set.
AXIS_DATA = 'data'
AXIS_MODEL = 'model'
AXIS_PIPELINE = 'pipe'
AXIS_SEQUENCE = 'seq'
AXIS_EXPERT = 'expert'
ALL_AXES = (AXIS_DATA, AXIS_MODEL, AXIS_PIPELINE, AXIS_SEQUENCE, AXIS_EXPERT)

# Name-scope prefixes (parity with const.py:41-51).
AUTODIST_PREFIX = 'AutoDist-'
AUTODIST_REPLICA_PREFIX = AUTODIST_PREFIX + 'Replica-'
AUTODIST_TO_DELETE_SCOPE = 'to-delete'

MAX_INT32 = 2 ** 31 - 1

# Gradient-bucketing defaults. A merged AllReduce group is packed into
# byte-capped buckets (parallel/plan.py pack_buckets) so the first
# bucket's collective issues while earlier layers' backward compute is
# still producing gradients, instead of one model-sized concat that
# serializes behind the whole backward pass. The cap derives from the
# strategy's ``chunk_size`` (tensors per merged group, the reference
# AllReduce knob) at BUCKET_BYTES_PER_CHUNK each — the default
# 128 * 256 KiB = 32 MiB sits in the band where TPU ICI is
# bandwidth-bound rather than latency-bound. ``AUTODIST_BUCKET_BYTES``
# overrides the cap directly.
DEFAULT_CHUNK_SIZE = 128
BUCKET_BYTES_PER_CHUNK = 256 << 10


def _positive_float(name, raw, default):
    """Validated env parse: a strictly positive float."""
    if not raw:
        return default
    val = float(raw)
    if val <= 0:
        raise ValueError('%s must be > 0; got %r' % (name, raw))
    return val


def _min_int(name, raw, default, lo):
    """Validated env parse: an integer >= ``lo``."""
    if not raw:
        return default
    val = int(raw)
    if val < lo:
        raise ValueError('%s must be >= %d; got %r' % (name, lo, raw))
    return val


def _frac(name, raw, default):
    """Validated env parse: a float in [0, 1]."""
    if raw is None or raw == '':
        return default
    val = float(raw)
    if not 0.0 <= val <= 1.0:
        raise ValueError('%s must be in [0, 1]; got %r' % (name, raw))
    return val


def _max_workers(name, raw):
    """Validated env parse for the elastic scale-up ceiling: an integer
    >= the live ``AUTODIST_MIN_WORKERS`` floor (the two bounds must
    describe a non-empty membership band). The default stays above any
    explicitly raised floor."""
    lo = ENV.AUTODIST_MIN_WORKERS.val
    if not raw:
        return max(64, lo)
    val = int(raw)
    if val < lo:
        raise ValueError(
            '%s must be >= AUTODIST_MIN_WORKERS (%d); got %r'
            % (name, lo, raw))
    return val


def _roofline_peaks(name, raw):
    """Validated env parse for the roofline peak-table override:
    ``flops=<FLOP/s>[,hbm_gbps=<GB/s>]`` (either key alone is fine).
    Returns ``{}`` when unset, else a dict with the given keys as
    positive finite floats — a malformed override must fail at parse
    time naming the field, not mid-bench as a nonsense MFU."""
    import math
    if not raw:
        return {}
    out = {}
    for part in raw.split(','):
        part = part.strip()
        if not part:
            continue
        key, sep, val = part.partition('=')
        key = key.strip()
        if not sep or key not in ('flops', 'hbm_gbps'):
            raise ValueError(
                "%s entries must be flops=<FLOP/s> or hbm_gbps=<GB/s>; "
                'got %r' % (name, part))
        try:
            fval = float(val)
        except ValueError:
            raise ValueError('%s.%s must be a number; got %r'
                             % (name, key, val)) from None
        if not math.isfinite(fval) or fval <= 0:
            raise ValueError('%s.%s must be a positive finite number; '
                             'got %r' % (name, key, val))
        out[key] = fval
    return out


def _choice(name, raw, default, allowed):
    """Validated env parse: one of a closed set of strings."""
    if not raw:
        return default
    if raw not in allowed:
        raise ValueError('%s must be one of %s; got %r'
                         % (name, '|'.join(allowed), raw))
    return raw


class ENV(Enum):
    """Typed environment flags, each with a default-producing lambda.

    Mirrors reference const.py:55-89. ``val`` parses the raw env var into a
    typed value. Flags are explicitly forwarded to launched worker
    processes by :mod:`autodist_tpu.runtime.coordinator`.
    """

    AUTODIST_WORKER = (lambda v: v if v else '',)                    # worker address; empty => chief
    AUTODIST_STRATEGY_ID = (lambda v: v if v else '',)               # strategy id to load on workers
    AUTODIST_MIN_LOG_LEVEL = (lambda v: v if v else 'INFO',)
    AUTODIST_IS_TESTING = (lambda v: (v == 'True' or v == '1'),)
    AUTODIST_DEBUG_REMOTE = (lambda v: (v == 'True' or v == '1'),)
    SYS_DATA_PATH = (lambda v: v if v else '',)
    SYS_RESOURCE_PATH = (lambda v: v if v else '',)
    # TPU-native additions:
    AUTODIST_PROCESS_ID = (lambda v: int(v) if v else 0,)            # jax.distributed process index
    AUTODIST_NUM_PROCESSES = (lambda v: int(v) if v else 1,)
    AUTODIST_COORDINATOR_ADDR = (lambda v: v if v else '',)          # host:port for jax.distributed
    AUTODIST_COORD_SERVICE_ADDR = (lambda v: v if v else '',)        # host:port for native coord service
    AUTODIST_RUN_ID = (lambda v: v if v else '',)                    # launcher-issued run nonce (namespaces coord keys)
    AUTODIST_DUMP_GRAPHS = (lambda v: (v == 'True' or v == '1'),)    # dump jaxpr/HLO per phase
    # loose-mode failure detection: a peer whose heartbeat is older than
    # this many seconds is declared dead while we wait on the staleness
    # gate (0 disables). Keep it longer than the slowest expected step.
    AUTODIST_HEARTBEAT_TIMEOUT = (lambda v: float(v) if v else 60.0,)
    # loose-mode PS data plane: comma-separated host:port list of PS
    # endpoints (one coord-service instance each). Unset = single
    # endpoint on the coord service itself. Variables land on the
    # endpoint their strategy reduction_destination maps to — the
    # multi-server placement the reference gets from one tf.Server per
    # node (utils/server_starter.py:48-75).
    AUTODIST_PS_ENDPOINTS = (lambda v: v if v else '',)
    # wire dtype for PS tensor frames: f32 (default), bf16 (half the
    # bytes; values rounded to bf16 on the wire, kept f32 at rest) or
    # i8 (block-quantized ~quarter bytes, PUSH direction only — pulls
    # and stores ride f32, and the session carries an error-feedback
    # residual per pushed delta; docs/design/quantized-wire.md).
    AUTODIST_PS_WIRE_DTYPE = (lambda v: v if v else 'f32',)
    # PS frame chunking: tensors above this many wire bytes move as
    # ranged chunks (all B* updates are elementwise, so chunked
    # application is exact). 0 disables chunking.
    AUTODIST_PS_CHUNK_BYTES = (lambda v: int(v) if v else 64 << 20,)
    # Row-sparse PS pushes (runtime/session.py _push_ps_deltas): a
    # sparse-flagged variable's delta ships as indices+rows (BSADD)
    # when its touched-row fraction is at or below this threshold —
    # lossless, because the dropped rows' delta is exactly zero. Above
    # it (or at 0.0, which disables the sparse plane) the dense BADD
    # path is used. 0.5 default: beyond half the rows the index
    # overhead outweighs the dense saving.
    AUTODIST_SPARSE_PUSH_MAX_FRAC = \
        (lambda v: _frac('AUTODIST_SPARSE_PUSH_MAX_FRAC', v, 0.5),)
    # Row-sparse proxy refresh: after a sparse push, the local proxy
    # cache refreshes only the pushed rows (BGETROWS); every Nth
    # refresh of a variable falls back to a FULL fetch so rows other
    # workers touched converge. 0 = never full-refresh (single-worker
    # runs, where nobody else writes).
    AUTODIST_SPARSE_FULL_REFRESH_EVERY = \
        (lambda v: _min_int('AUTODIST_SPARSE_FULL_REFRESH_EVERY', v,
                            64, lo=0),)
    # shared secret for the coord-service handshake: when set, the
    # service challenges every connection with a nonce and requires
    # HMAC-SHA256(token, nonce) before any command. Empty = open
    # (loopback-only deployments). Forwarded to workers like the other
    # flags; never passed on argv.
    AUTODIST_COORD_TOKEN = (lambda v: v if v else '',)
    # alternative token transport: path to a file holding the secret.
    # The ssh coordinator ships the token this way (a mode-0600 file
    # copied like the strategy) because env assignments ride the remote
    # command line, which is world-readable in `ps` on the worker host.
    AUTODIST_COORD_TOKEN_FILE = (lambda v: v if v else '',)
    # opt-in space-to-depth stem transform for narrow-channel stride-2
    # stem convs (measured neutral on v5e — BASELINE.md round-5; kept
    # for TPU generations where stems bind). Forwarded to launched
    # workers (coordinator _FORWARDED_FLAGS) so every traced host
    # agrees — divergent HLO across SPMD hosts deadlocks.
    AUTODIST_S2D_STEM = (lambda v: (v == 'True' or v == '1'),)
    # byte cap for fused gradient all-reduce buckets (0 = derive from
    # the strategy's chunk_size; see const.BUCKET_BYTES_PER_CHUNK).
    AUTODIST_BUCKET_BYTES = (lambda v: int(v) if v else 0,)
    # XLA overlap flags (latency-hiding scheduler + async collectives,
    # runtime/session.py setup) are enabled when gradient bucketing is
    # active; '0'/'False' opts out.
    AUTODIST_XLA_OVERLAP = (lambda v: not (v == '0' or v == 'False'),)
    # PS data plane torn-read retry budget (coord_client.vget): attempt
    # cap and base backoff for reads raced by concurrent pushes.
    AUTODIST_PS_TORN_RETRIES = (lambda v: int(v) if v else 100,)
    AUTODIST_PS_TORN_BACKOFF_S = (lambda v: float(v) if v else 0.01,)
    # torn-read stall window (coord_client.vget/vmget): how long a pull
    # waits for an in-flight chunked write whose version has stopped
    # advancing before declaring the writer dead. Must cover one full
    # chunk frame's encode+wire time; tests shrink it.
    AUTODIST_PS_STALL_TIMEOUT_S = \
        (lambda v: _positive_float('AUTODIST_PS_STALL_TIMEOUT_S', v,
                                   10.0),)
    # loose-mode PS pipeline depth (runtime/session.py): 1 = the serial
    # pull -> step -> push data plane (bit-exact legacy semantics);
    # 2 = one step of overlap — step N's delta push + publish and step
    # N+1's variable pull run on a background pipeline thread, hidden
    # behind N's host tail. Values > 2 clamp to 2 (a pull must follow
    # the previous push of the same variable, so at most one step can
    # be in flight without breaking read-your-writes).
    AUTODIST_PS_PIPELINE_DEPTH = \
        (lambda v: _min_int('AUTODIST_PS_PIPELINE_DEPTH', v, 1, lo=1),)
    # loose-mode peer-failure policy (runtime/session.py): what a
    # surviving worker does when a peer misses heartbeats past
    # AUTODIST_HEARTBEAT_TIMEOUT while it waits on the staleness gate.
    #   fail    - raise (the pre-recovery fail-fast behavior; default)
    #   exclude - fence the dead peer's writer generation, drop it from
    #             the gate membership (epoch bump) and keep training,
    #             bounded below by AUTODIST_MIN_WORKERS
    #   restart - keep waiting while the Coordinator supervises a
    #             capped-backoff restart of the dead worker; raise only
    #             once the supervisor marks it permanently failed
    AUTODIST_PEER_FAILURE_POLICY = \
        (lambda v: _choice('AUTODIST_PEER_FAILURE_POLICY', v, 'fail',
                           ('fail', 'exclude', 'restart')),)
    # floor for policy=exclude: a membership that would drop below this
    # many live workers fails instead of shrinking further.
    AUTODIST_MIN_WORKERS = \
        (lambda v: _min_int('AUTODIST_MIN_WORKERS', v, 1, lo=1),)
    # ceiling for elastic scale-UP: a live JOIN (or an autoscale
    # decision) that would grow the membership past this many workers
    # is refused. Validated >= AUTODIST_MIN_WORKERS at parse time; the
    # launch quorum itself is not bounded by it (it caps joins only).
    AUTODIST_MAX_WORKERS = \
        (lambda v: _max_workers('AUTODIST_MAX_WORKERS', v),)
    # marks a process as a live JOINer into an already-running loose-
    # mode namespace: the session skips the launch-cohort rendezvous,
    # claims a fresh worker slot at the control plane (the admit
    # handshake — runtime/session.py admit_worker), pulls current
    # params from the PS and adopts the published step floor. Set by
    # Coordinator.scale_up on the processes it launches; never set on
    # the launch cohort.
    AUTODIST_ELASTIC_JOIN = (lambda v: (v == 'True' or v == '1'),)
    # policy=restart: how many supervised restarts one worker gets
    # (capped exponential backoff between attempts) before the
    # coordinator marks it permanently failed and aborts the run.
    AUTODIST_MAX_WORKER_RESTARTS = \
        (lambda v: _min_int('AUTODIST_MAX_WORKER_RESTARTS', v, 3, lo=0),)
    # policy=restart: how long survivors wait at the staleness gate for
    # ONE dead peer's supervised replacement to start beating again
    # before giving up. The gate's own window re-arms while a restart
    # is pending (respawn + rejoin + recompile can legitimately exceed
    # it); this is the backstop against a silently dead supervisor —
    # the normal abort path is the supervisor's failed marker. Covers
    # the full restart budget: every backoff plus a cold XLA compile.
    AUTODIST_RESTART_WAIT_S = \
        (lambda v: _positive_float('AUTODIST_RESTART_WAIT_S', v,
                                   1800.0),)
    # chief-side auto-checkpoint backstop for loose-mode recovery: save
    # the chief's variable state every N train steps through
    # checkpoint.CheckpointManager (async, off the critical path).
    # 0 disables (default).
    AUTODIST_AUTO_CHECKPOINT_EVERY = \
        (lambda v: _min_int('AUTODIST_AUTO_CHECKPOINT_EVERY', v, 0,
                            lo=0),)
    # deterministic fault-injection plan (utils/faultline.py): inline
    # JSON, or @/path/to/plan.json. Empty = no faults. Only honored
    # when the process explicitly installs a FaultLine (chaos tests,
    # bench recovery A/B) — production sessions never read it.
    AUTODIST_FAULT_PLAN = (lambda v: v if v else '',)
    # Block size (elements) for block-quantized int8 wire formats: the
    # Int8RingCompressor's bucket/ring quantization and the PS data
    # plane's 'i8' wire dtype both carry ONE f32 scale per block of
    # this many int8 values (EQuARX-style; per-block scales bound an
    # outlier's damage to its own block instead of the whole bucket).
    # Forwarded to launched workers (coordinator _FORWARDED_FLAGS):
    # every traced host must agree on the block layout — divergent HLO
    # across SPMD hosts deadlocks, and a PS frame encoded with one
    # block size decodes with the size carried in its own header.
    AUTODIST_QUANT_BLOCK = \
        (lambda v: _min_int('AUTODIST_QUANT_BLOCK', v, 256, lo=8),)
    # Topology-aware hierarchical collectives: the number of node
    # groups the data axis is split into for two-level schedules
    # (intra-node reduce-scatter -> inter-node all-reduce -> intra-node
    # all-gather, parallel/plan.py). 0 (default) = infer node groups
    # from the mesh devices (process/slice index); >= 2 forces that
    # many CONTIGUOUS equal groups — the CPU-mesh test/bench override.
    # Forwarded to launched workers (coordinator _FORWARDED_FLAGS):
    # the group layout is part of the traced program, and divergent
    # HLO across SPMD hosts deadlocks.
    AUTODIST_HIERARCHY_NODES = \
        (lambda v: _min_int('AUTODIST_HIERARCHY_NODES', v, 0, lo=0),)
    # Cross-replica weight-update sharding override (parallel/plan.py,
    # arXiv:2004.13336): '' (default) defers to each strategy's
    # AllReduceSynchronizer.weight_update_sharding knob; 'auto',
    # 'always' or 'never' overrides it globally — 'always' forces the
    # reduce-scatter + shard-local fused update + bucketed param
    # all-gather schedule wherever it is lowerable (uncompressed-wire
    # AR buckets on an n>1 mesh), 'never' forces the legacy replicated
    # update, 'auto' defers to the shared cost-model decision
    # (simulator.cost_model.choose_update_sharding: freed opt-slot HBM
    # vs exposed all-gather time). Forwarded to launched workers
    # (coordinator _FORWARDED_FLAGS): the schedule AND the optimizer-
    # slot layout are part of the traced program — divergent HLO
    # across SPMD hosts deadlocks.
    AUTODIST_WEIGHT_UPDATE_SHARDING = \
        (lambda v: _choice('AUTODIST_WEIGHT_UPDATE_SHARDING', v, '',
                           ('auto', 'always', 'never')),)
    # Execute chief re-plans (elastic scale-up re-ranks) instead of
    # only recording them: the session migrates its live state to the
    # re-ranked strategy through the device-side resharding path
    # (parallel/reshard.py) at the next step boundary. Default off —
    # the PR 6 predicted-vs-kept audit trail is unchanged unless the
    # operator opts in.
    AUTODIST_EXECUTE_REPLAN = (lambda v: (v == 'True' or v == '1'),)
    # Epoch-swap handshake bounds (runtime/swap_keys.py, docs/design/
    # epoch-swap.md): how long the chief waits for the peer ack quorum
    # on a staged plan before cancelling the stage, how long it backs
    # off before re-staging, and how many cancel-and-retry rounds it
    # attempts before degrading to an audit-only re-plan entry.
    # Forwarded to launched workers (coordinator _FORWARDED_FLAGS):
    # peers bound their ready-marker wait with the same ack timeout,
    # and a cohort split on the bound would strand slow members at the
    # swap boundary.
    AUTODIST_SWAP_ACK_TIMEOUT_S = \
        (lambda v: _positive_float('AUTODIST_SWAP_ACK_TIMEOUT_S', v,
                                   60.0),)
    AUTODIST_SWAP_RETRY_BACKOFF_S = \
        (lambda v: _positive_float('AUTODIST_SWAP_RETRY_BACKOFF_S', v,
                                   5.0),)
    AUTODIST_SWAP_MAX_RETRIES = \
        (lambda v: _min_int('AUTODIST_SWAP_MAX_RETRIES', v, 3, lo=0),)
    # opt-in DenseNet dense-block form: preallocated buffer +
    # dynamic-update-slice instead of per-layer concat (O(L) vs O(L^2)
    # copy traffic; exactness tested, on-chip A/B pending — see
    # BASELINE.md). Forwarded like the other tracing flags: divergent
    # HLO across SPMD hosts deadlocks.
    AUTODIST_DENSENET_DUS = (lambda v: (v == 'True' or v == '1'),)
    # opt-in fused conv+BN Pallas kernel (models/vision.py; measured
    # neutral-to-negative on v5e, BASELINE.md round-6 — kept for TPU
    # generations where the BN passes bind) and its row-count ceiling
    # (huge early-stage activations pay more in layout-conversion
    # copies than the fused kernel saves). Forwarded like the other
    # tracing flags: the kernel choice is part of the traced program,
    # and divergent HLO across SPMD hosts deadlocks.
    AUTODIST_FUSED_CONV = (lambda v: (v == 'True' or v == '1'),)
    # row ceiling for the fused kernel; 0 = no limit (validated >= 0)
    AUTODIST_FUSED_CONV_MAX_ROWS = \
        (lambda v: _min_int('AUTODIST_FUSED_CONV_MAX_ROWS', v, 120000,
                            lo=0),)
    # pipeline-parallel 1F1B variant='auto' threshold (parallel/
    # pipeline.py): stash (keep boundary activations) when the stash
    # fits under this many MiB, else remat. The variant is part of the
    # traced program, so every pipeline host must agree — forwarded
    # like the other tracing flags.
    AUTODIST_PP_STASH_LIMIT_MB = \
        (lambda v: _positive_float('AUTODIST_PP_STASH_LIMIT_MB', v,
                                   2048.0),)
    # Unified telemetry plane (telemetry/, docs/design/
    # observability.md): '1'/'True' enables the span/metrics registry
    # — step/gate/pull/push spans in the session, per-RPC spans in the
    # coord client, bucket-emission tags in the plan — and the
    # cross-worker batch push to the PS telemetry namespace. Disabled
    # (default) the API is zero-cost no-ops. Forwarded: a cohort
    # timeline needs every worker emitting, not just the chief.
    AUTODIST_TELEMETRY = (lambda v: (v == 'True' or v == '1'),)
    # Where flight-recorder dumps and Chrome trace exports land
    # (telemetry.flight.telemetry_dir; empty = <working dir>/telemetry).
    AUTODIST_TELEMETRY_DIR = (lambda v: v if v else '',)
    # Bound on every telemetry buffer (span/event rings, numeric
    # series): telemetry must never grow without bound on a long run.
    AUTODIST_TELEMETRY_MAX_SPANS = \
        (lambda v: _min_int('AUTODIST_TELEMETRY_MAX_SPANS', v, 4096,
                            lo=64),)
    # How often (train steps) a loose-mode worker batch-pushes its
    # drained span records to the <ns>/telemetry/ namespace; 0 = only
    # at close. The push rides the background pipeline cadence, one
    # vset per batch.
    AUTODIST_TELEMETRY_PUSH_EVERY = \
        (lambda v: _min_int('AUTODIST_TELEMETRY_PUSH_EVERY', v, 8,
                            lo=0),)
    # Ring capacity of the always-on crash flight recorder
    # (telemetry/flight.py): the last N control-plane events (fence
    # binds, epoch bumps, step publishes, exclusions, admit phases,
    # replan stage/swap, slowdown/recovered verdicts) dumped to disk
    # on failure triggers.
    AUTODIST_FLIGHT_RECORDER_EVENTS = \
        (lambda v: _min_int('AUTODIST_FLIGHT_RECORDER_EVENTS', v, 512,
                            lo=16),)
    # Online performance sentry (telemetry/monitor.py): what the
    # chief's CohortMonitor does with straggler verdicts.
    #   off    - no monitor at all (statistics included)
    #   warn   - verdicts logged + slowdown/recovered events recorded
    #            in the flight recorder ring (default)
    #   advise - additionally marks non-victim culprits as
    #            exclude_candidate in health_report's perf section.
    # Detection is observability, NEVER actuation: the PR 4 peer-
    # failure policy machinery stays the sole actuator — this knob
    # deliberately stops at 'advise'.
    AUTODIST_STRAGGLER_POLICY = \
        (lambda v: _choice('AUTODIST_STRAGGLER_POLICY', v, 'warn',
                           ('off', 'warn', 'advise')),)
    # Rolling-window sample bound (train steps) of the monitor's
    # per-worker robust statistics (median/MAD of step wall and the
    # per-phase splits). Detection itself reads a short recent-median
    # inside this window so a straggler surfaces within a few steps of
    # onset, not half a window later.
    AUTODIST_MONITOR_WINDOW = \
        (lambda v: _min_int('AUTODIST_MONITOR_WINDOW', v, 32, lo=4),)
    # Continuous cost-model recalibration cadence (train steps): every
    # N steps the chief refits the link alpha-beta constants from live
    # telemetry (data-plane RPC spans as point-to-point samples) and
    # hands the measured constants to _replan_for_world's re-rank.
    # 0 disables (default) — re-ranks then price with analytic
    # constants, exactly the pre-monitor behavior.
    AUTODIST_RECALIBRATE_EVERY = \
        (lambda v: _min_int('AUTODIST_RECALIBRATE_EVERY', v, 0, lo=0),)
    # Device-plane roofline observatory (telemetry/roofline.py):
    # '1'/'True' turns on per-step MFU/regime accounting in the session
    # — FLOPs + bytes-accessed pulled once per compiled step
    # (cost_analysis() on the lowered program, cached per compilation),
    # divided by the measured step wall and the topology's peak table,
    # emitted as the 'mfu' / roofline telemetry series plus
    # mfu_regression flight events. Off (default) = zero per-step cost.
    # Forwarded: a cohort roofline needs every worker accounting, and
    # divergent sampling cadence would skew cross-worker comparison.
    AUTODIST_ROOFLINE = (lambda v: (v == 'True' or v == '1'),)
    # Sampling cadence (train steps) of the per-step roofline
    # accounting — the wall-clock divide and series append run every
    # Nth executed train step (the cost-analysis pull is once per
    # compilation regardless).
    AUTODIST_ROOFLINE_EVERY = \
        (lambda v: _min_int('AUTODIST_ROOFLINE_EVERY', v, 1, lo=1),)
    # Peak-table override: 'flops=<FLOP/s>,hbm_gbps=<GB/s>' (either key
    # alone works) replaces the resolved Topology peaks — for device
    # kinds the table lags, or derated-clock deployments. Validated at
    # parse time; forwarded so every worker grades MFU against the
    # same denominator.
    AUTODIST_ROOFLINE_PEAKS = \
        (lambda v: _roofline_peaks('AUTODIST_ROOFLINE_PEAKS', v),)
    # Local-SGD window length H (runtime/session.py, docs/design/
    # local-sgd.md): 0 (default) defers to the strategy's per-var
    # PSSynchronizer.local_steps; >= 1 overrides it globally — workers
    # take H local optimizer steps between PS sync rounds, pushing the
    # window's averaged parameter delta once per round. H=1 is today's
    # every-step loose push, bit-identical. Forwarded to launched
    # workers (coordinator _FORWARDED_FLAGS): the staleness gate counts
    # sync ROUNDS under H>1, so every loose worker must agree on the
    # window length or the gates deadlock against each other.
    AUTODIST_LOCAL_STEPS = \
        (lambda v: _min_int('AUTODIST_LOCAL_STEPS', v, 0, lo=0),)
    # Local-SGD window merge rule: on (default) scales each worker's
    # window delta by 1/num_workers before the push so the sum-based
    # PS delta wire lands on the MEAN of the workers' windows ("average"
    # in the FedAvg sense). '0'/'False' pushes the raw window sum —
    # the pinned divergence counterexample in analysis/data_plane_model
    # (W workers overshoot the mean by ~W x); exposed only for A/B and
    # the model checker, never recommended. Forwarded with
    # AUTODIST_LOCAL_STEPS: all workers must agree on the merge rule or
    # the merged state is a mix of scaled and unscaled deltas.
    AUTODIST_LOCAL_SGD_AVERAGE = \
        (lambda v: not (v == '0' or v == 'False'),)
    # Read-only serving tier (serving/, docs/design/serving.md).
    # Publish-step poll cadence of a ServingReplica: how often the
    # refresh loop re-reads the cohort's published floor to decide
    # whether a fresh dense snapshot is worth pulling. Seconds.
    AUTODIST_SERVE_POLL_S = \
        (lambda v: _positive_float('AUTODIST_SERVE_POLL_S', v, 0.5),)
    # Staleness bound a replica ADVERTISES (steps): a served snapshot
    # whose pinned step trails the current published floor by more than
    # this counts as a staleness violation in serve_stats — the serving
    # tier never blocks training to enforce it, it only grades itself.
    AUTODIST_SERVE_STALENESS_BOUND = \
        (lambda v: _min_int('AUTODIST_SERVE_STALENESS_BOUND', v, 8,
                            lo=0),)
    # Sparse row cache capacity (rows, across all embedding tables a
    # replica serves). LRU eviction past this.
    AUTODIST_SERVE_ROW_CACHE_ROWS = \
        (lambda v: _min_int('AUTODIST_SERVE_ROW_CACHE_ROWS', v, 65536,
                            lo=1),)
    # Sparse row cache TTL (seconds): a cached row older than this is
    # re-fetched on its next lookup — the freshness knob for hot rows
    # that training keeps pushing (a snapshot version bump flushes the
    # cache wholesale regardless of TTL).
    AUTODIST_SERVE_ROW_TTL_S = \
        (lambda v: _positive_float('AUTODIST_SERVE_ROW_TTL_S', v, 5.0),)
    # Epoch-consistent snapshot retry budget: how many seqlock rounds
    # (pin -> pull -> validate) a replica attempts before keeping its
    # previous snapshot for this poll cycle. Each retry means a writer
    # raced the pull; the old snapshot stays servable throughout.
    AUTODIST_SERVE_SNAPSHOT_RETRIES = \
        (lambda v: _min_int('AUTODIST_SERVE_SNAPSHOT_RETRIES', v, 8,
                            lo=1),)
    # Serving pull wire dtype override: '' (default) rides the run's
    # AUTODIST_PS_WIRE_DTYPE; 'f32' | 'bf16' force a pull dtype for the
    # replica fleet alone (readers fanning out over DCN may want bf16
    # snapshots while trainers stay f32); 'i8' is accepted but pulls
    # ride f32 — the blockscale wire is push-only (quantized-wire.md).
    AUTODIST_SERVE_WIRE = \
        (lambda v: _choice('AUTODIST_SERVE_WIRE', v, '',
                           ('f32', 'bf16', 'i8')),)

    @property
    def val(self):
        """Return the typed value of this environment flag."""
        return self.value[0](os.environ.get(self.name))
