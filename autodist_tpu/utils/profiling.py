"""Per-op profile aggregation over a captured trace.

The reference exposes a chrome-trace timeline (``runner.py:64-75``);
this framework additionally ships the analysis layer that turned raw
traces into the round-3/4 performance diagnoses: aggregate the
device's ``XLA Ops`` timeline into a per-op / per-category time
breakdown, directly from the ``.xplane.pb`` a ``RunOptions`` trace or
``jax.profiler.trace`` wrote.

Parsing rules that matter (learned the hard way — an early analysis
miscategorized by substring-matching whole event names):

- categorize on the op NAME ONLY (the text before ``' = '``): XLA event
  names embed the full instruction INCLUDING operand lists, so a fusion
  consuming a custom-call's output also contains the string
  'custom-call';
- use the sync ``XLA Ops`` line; ``Async XLA Ops`` durations overlap
  and must not be summed.

Usage::

    sess.run(fetches, feed, options=RunOptions(trace_level=FULL_TRACE))
    report = per_op_breakdown(options.trace_dir)
    print(format_breakdown(report))

Beyond XLA traces, :func:`ps_overlap_report` attributes the loose-mode
PS data plane's wire time between the critical path and the background
pipeline thread (``AUTODIST_PS_PIPELINE_DEPTH``), from the phase
counters every loose session keeps (``Session.ps_stats``).
"""
import glob
import os
import re
from collections import defaultdict

from autodist_tpu.utils import logging

_CATEGORY_RULES = (
    ('pallas-kernel', re.compile(r'pallas|custom-call')),
    ('convolution', re.compile(r'^convolution')),
    ('collective', re.compile(
        r'^(all-reduce|all-gather|reduce-scatter|collective-permute|'
        r'all-to-all)')),
    ('copy', re.compile(r'^copy')),
    ('while(scan)', re.compile(r'^while')),
    ('reduce-fusion', re.compile(r'reduce.*fusion|fusion.*reduce')),
    ('reshape/layout', re.compile(r'^(reshape|transpose|bitcast)')),
    ('fusion', re.compile(r'fusion')),
    ('dot', re.compile(r'dot')),
)


def _op_head(event_name):
    """The op's own name — the text before ' = '. XLA event names embed
    the full instruction including operand lists, so categorizing on
    anything more than the head misattributes (a fusion consuming a
    custom-call's output contains 'custom-call')."""
    return event_name.split(' = ')[0]


def _categorize(event_name):
    base = re.sub(r'[.\d]+$', '', _op_head(event_name).strip().lstrip('%'))
    for cat, pat in _CATEGORY_RULES:
        if pat.search(base):
            return cat
    return 'other:' + base[:24]


def per_op_breakdown(trace_dir, line_name='XLA Ops'):
    """Aggregate a profiler trace into per-op and per-category times.

    Args:
        trace_dir: directory a ``jax.profiler`` trace was written to
            (searched recursively for ``*.xplane.pb``).
        line_name: the timeline to aggregate (default the synchronous
            per-op line).

    Returns dict with ``total_ns``, ``by_category`` ({name: ns}), and
    ``top_ops`` ([(full op text, ns, count)] sorted by time). Empty
    when no trace/processor plane is found.
    """
    files = sorted(glob.glob(os.path.join(trace_dir, '**', '*.xplane.pb'),
                             recursive=True), key=os.path.getmtime)
    if not files:
        if os.path.isdir(trace_dir):
            logging.warning(
                'profiling: trace dir %s exists but holds no '
                '*.xplane.pb; returning empty breakdown', trace_dir)
        return {}
    try:
        from jax.profiler import ProfileData
        pd = ProfileData.from_file(files[-1])
    except Exception as e:   # noqa: BLE001 - degrade, never raise:
        # calibration/bench consumers run on CPU-fallback hosts whose
        # traces may be partial or whose jax lacks ProfileData
        logging.warning('profiling: cannot parse trace %s (%s: %s); '
                        'returning empty breakdown', files[-1],
                        type(e).__name__, e)
        return {}
    # the busiest device plane's per-op line (real hardware traces);
    # CPU-backend traces carry only host execution lines, so fall back
    # to the busiest line anywhere — a coarse program-level view rather
    # than a per-op decomposition
    best, best_total = None, -1
    for device_only in (True, False):
        for plane in pd.planes:
            is_device = plane.name.startswith('/device:')
            # pass 1: device planes' per-op line; pass 2 (CPU-backend
            # traces): busiest HOST line only — never a device line of
            # a different name, which could be the overlapping-duration
            # 'Async XLA Ops' timeline this module must not sum
            if device_only != is_device:
                continue
            for line in plane.lines:
                if device_only and line.name != line_name:
                    continue
                tot = sum(e.duration_ns for e in line.events)
                if tot > best_total:
                    best, best_total = line, tot
        if best is not None:
            break
    if best is None:
        logging.warning(
            "profiling: trace in %s has no '%s' (or host) timeline; "
            'returning empty breakdown', trace_dir, line_name)
        return {}
    by_cat = defaultdict(int)
    by_op = defaultdict(lambda: [0, 0])
    for ev in best.events:
        by_cat[_categorize(ev.name)] += ev.duration_ns
        slot = by_op[ev.name]
        slot[0] += ev.duration_ns
        slot[1] += 1
    top = sorted(((name, ns, cnt) for name, (ns, cnt) in by_op.items()),
                 key=lambda t: -t[1])
    return {'total_ns': sum(by_cat.values()),
            'by_category': dict(sorted(by_cat.items(),
                                       key=lambda kv: -kv[1])),
            'top_ops': top}


def bucket_report(plan, trace_dir=None):
    """Per-bucket accounting for a bucketed-sync execution plan.

    ``plan.last_bucket_stats`` (recorded at trace time by
    ``ExecutionPlan.sync_gradients``) gives the byte layout: one entry
    per emitted collective with its kind, group, dtype and byte count.
    Bucket ``bytes`` are RAW tensor bytes; each entry additionally gets
    a ``wire_bytes`` field here (``cost_model.wire_bytes`` applied to
    its compressor/dtype) — under a compressed wire (bf16 cast, int8
    blocks) the raw figure overstates what actually moves by 2–4x, and
    the report exists to show the wire. With ``trace_dir`` (a captured
    profile), each collective category's measured device time is
    attached, so the overlap the bucketing exists for is auditable:
    total collective ns vs total step ns, and the per-bucket wire
    bytes feeding it.

    Returns ``{'buckets': [...], 'num_buckets', 'total_bytes',
    'total_wire_bytes', 'max_bucket_bytes', 'collective_ns',
    'total_ns'}`` (the *_ns fields only when a trace is given and
    parseable).
    """
    from autodist_tpu.simulator.cost_model import wire_bytes
    stats = [dict(b) for b in
             (getattr(plan, 'last_bucket_stats', []) or [])]
    for b in stats:
        b['wire_bytes'] = wire_bytes(b.get('bytes', 0), b.get('dtype'),
                                     b.get('compressor'))
    out = {
        'buckets': stats,
        'num_buckets': len(stats),
        'total_bytes': sum(b.get('bytes', 0) for b in stats),
        'total_wire_bytes': sum(b['wire_bytes'] for b in stats),
        'max_bucket_bytes': max([b.get('bytes', 0) for b in stats],
                                default=0),
    }
    if trace_dir:
        rep = per_op_breakdown(trace_dir)
        if rep:
            out['collective_ns'] = rep['by_category'].get('collective', 0)
            out['total_ns'] = rep['total_ns']
            if stats and not out['collective_ns']:
                logging.warning(
                    'profiling: bucket_report joined a trace with ZERO '
                    'collective time against a plan that emitted %d '
                    'bucket(s) — the trace did not capture the sync '
                    'program (empty here is a mismatch, not overlap)',
                    len(stats))
    return out


def collective_timeline(trace_dir, line_name='XLA Ops',
                        expected_collectives=0):
    """Per-collective-op durations from a captured trace.

    Filters :func:`per_op_breakdown`'s top_ops down to collective-
    category ops (all-reduce / reduce-scatter / all-gather /
    collective-permute / all-to-all, sync or ``-start``/``-done``
    halves): one row per distinct op — with bucketed gradient sync that
    is one row per bucket — as ``[(op text, ns, count)]`` sorted by
    time. The per-bucket latency view of the overlap scheduler.

    ``expected_collectives`` disambiguates the silent-empty path: a
    run that EMITTED buckets (count known statically from
    ``strategy.adapter.grad_bucket_layout`` or the plan's
    ``last_bucket_stats``) whose trace parses to zero collective rows
    is a parsing/capture mismatch, not a no-collective program — the
    two used to return identically-empty lists, which made a broken
    tiered calibration read as a legitimately-flat run (PR 8). With a
    non-zero expectation the mismatch is logged loudly; 0 keeps the
    legacy quiet degradation for callers with no static count.
    """
    rep = per_op_breakdown(trace_dir, line_name=line_name)
    if not rep:
        # per_op_breakdown already warned with the specific cause;
        # callers (calibration) degrade on the empty timeline
        if expected_collectives:
            logging.warning(
                'profiling: the plan emitted %d collective(s) but the '
                'trace in %s yielded NO parseable timeline — this is '
                'a capture/parsing failure, not a no-collective run; '
                'calibration will silently keep analytic constants',
                expected_collectives, trace_dir)
        return []
    rows = []
    for name, ns, cnt in rep['top_ops']:
        base = _op_head(name).strip().lstrip('%')
        if re.match(r'(all-reduce|all-gather|reduce-scatter|'
                    r'collective-permute|all-to-all)(-start|-done)?',
                    re.sub(r'[.\d]+$', '', base)):
            rows.append((name, ns, cnt))
    if not rows and expected_collectives:
        logging.warning(
            'profiling: the plan emitted %d collective(s) but the '
            "trace's '%s' timeline (%d ops) parsed to ZERO collective "
            'rows — a run with collectives whose trace reads as '
            '"no collectives" (the calibrate/no-op ambiguity that '
            'broke tiered calibration in PR 8); check the traced line '
            'name and that the trace covered a synced step',
            expected_collectives, line_name, len(rep['top_ops']))
    return rows


def ps_overlap_report(ps_stats):
    """Attribute the loose-mode PS data plane's wire time to the
    critical path vs the background pipeline.

    ``ps_stats`` is :attr:`Session.ps_stats` (whose ``pipeline`` block
    carries the per-train-step phase averages). Wire seconds recorded
    by the transfer/pipeline threads count as *hidden* except for the
    portion the main thread measurably blocked on (joins of the
    background push and of the prefetched pull) — that exposed share is
    the only wire time a step actually pays, and ``overlap_frac`` is
    the hidden fraction. At depth 1 every wire second is exposed by
    construction (overlap_frac == 0).

    Returns ``{'depth', 'train_steps', 'pull_s', 'step_s', 'push_s',
    'wire_s', 'exposed_wire_s', 'hidden_wire_s', 'overlap_frac'}``
    (per-step seconds), or ``{}`` when the session never trained in
    loose mode.
    """
    pipe = (ps_stats or {}).get('pipeline') or {}
    if not pipe.get('train_steps'):
        # zero-train-step snapshot (eval-only session, or a report
        # taken before the first gated step landed): nothing to
        # attribute — and nothing to divide by
        return {}
    # every field defaulted: a snapshot taken mid-replan (the plan
    # swap clears compiled steps but the phase dict survives) or from
    # an older/partial stats payload must degrade to zeros, not
    # KeyError/ZeroDivisionError
    pull_s = pipe.get('pull_s', 0.0)
    push_s = pipe.get('push_s', 0.0)
    wire = pull_s + push_s
    exposed = min(pipe.get('exposed_wait_s', 0.0), wire)
    overlap = pipe.get('overlap_frac')
    if overlap is None:
        overlap = (1.0 - exposed / wire) if wire > 0 else 0.0
    return {
        'depth': pipe.get('depth', 1),
        'train_steps': pipe['train_steps'],
        'pull_s': pull_s,
        'step_s': pipe.get('step_s', 0.0),
        'push_s': push_s,
        'wire_s': wire,
        'exposed_wire_s': exposed,
        'hidden_wire_s': max(0.0, wire - exposed),
        'overlap_frac': overlap,
    }


def ps_sparse_report(ps_stats):
    """The row-sparse PS plane's counters plus derived ratios.

    ``ps_stats`` is :attr:`Session.ps_stats`; its ``sparse`` block
    counts sparse pushes, rows pushed, dense bytes avoided, zero-push
    skips and row/full proxy refreshes (docs/design/sparse-ps.md).
    Adds ``avoided_frac`` — the fraction of would-have-been wire bytes
    the sparse plane (and the zero-delta skip) saved: avoided /
    (avoided + bytes actually moved). Returns ``{}`` when the session
    kept no sparse counters (non-loose, or pre-sparse-plane stats)."""
    sparse = dict((ps_stats or {}).get('sparse') or {})
    if not sparse:
        return {}
    moved = (ps_stats or {}).get('bytes', 0)
    avoided = sparse.get('dense_bytes_avoided', 0)
    sparse['avoided_frac'] = (
        avoided / float(avoided + moved) if avoided + moved else 0.0)
    return sparse


def format_ps_sparse(report):
    """Human-readable rendering of :func:`ps_sparse_report`."""
    if not report:
        return '(no sparse-plane counters)'
    return ('sparse pushes %d (%d rows)  zero-skips %d  refreshes '
            '%d row / %d full  avoided %.1f MB (%.0f%% of would-be '
            'wire)' % (report.get('sparse_pushes', 0),
                       report.get('rows_pushed', 0),
                       report.get('zero_push_skips', 0),
                       report.get('row_refreshes', 0),
                       report.get('full_refreshes', 0),
                       report.get('dense_bytes_avoided', 0) / 1e6,
                       100.0 * report.get('avoided_frac', 0.0)))


def health_report(health_stats, faultline=None, autoscale=None,
                  serving=None):
    """Recovery + elasticity observability: one record per run of
    everything the elastic machinery did — so every recovery AND every
    membership change is auditable, not anecdotal.

    ``health_stats`` is :attr:`Session.health_stats` (policy, fencing
    generation, membership epoch, live world size, missed beats,
    exclusions, rejoins, recovery wall times, observed joins, the
    session's own admit record when it live-JOINed, the chief's
    strategy re-rank decisions, auto-checkpoints). ``faultline`` is an
    armed :class:`~autodist_tpu.utils.faultline.FaultLine` (or its
    ``events`` list) whose injected faults are attached — join-path
    faults (the ``join_*`` kinds) are also counted separately, so a
    chaos run's report pairs "what was injected on the admit handshake"
    with "what membership did about it". ``autoscale`` is an
    :class:`~autodist_tpu.runtime.coordinator.AutoscaleController` (or
    its ``decisions`` list): decisions taken and skipped ride the
    report. Connection-retry counts come from the process-wide
    ``coord_client.RETRY_STATS``. ``serving`` is a
    :class:`~autodist_tpu.serving.ServingFleet` (or its
    :meth:`~autodist_tpu.serving.ServingFleet.stats` dict): the
    read-only replica fleet's serve stats (QPS, lookup latency
    percentiles, snapshot staleness, row-cache hit rate, wire bytes)
    ride the same record — train-while-serve runs audit both planes
    in one place.

    Returns ``{}`` when the session never ran in loose mode (no
    recovery machinery to report on).
    """
    from autodist_tpu.runtime.coord_client import RETRY_STATS
    hs = dict(health_stats or {})
    if not hs:
        return {}
    events = faultline if isinstance(faultline, (list, tuple)) \
        else getattr(faultline, 'events', [])
    decisions = autoscale if isinstance(autoscale, (list, tuple)) \
        else list(getattr(autoscale, 'decisions', ()))
    recovery = list(hs.get('recovery_wall_s', ()))
    admitted = hs.get('admitted')
    return {
        'policy': hs.get('policy', 'fail'),
        'generation': hs.get('generation', 0),
        'epoch': hs.get('epoch', 0),
        'epoch_bumps': hs.get('epoch_bumps', 0),
        'num_workers': hs.get('num_workers', 1),
        'world': hs.get('world', hs.get('num_workers', 1)),
        'active_workers': hs.get('active_workers',
                                 hs.get('num_workers', 1)),
        'missed_beats': hs.get('missed_beats', 0),
        # per-entry dict() snapshots: the session mutates these entry
        # dicts in place from its background threads (a replan entry
        # grows 'migration' fields when _execute_replan lands), and a
        # report consumer iterating a half-joined entry mid-mutation
        # must at worst see a stale copy, never a dict changing size
        # under it
        'exclusions': [dict(e) for e in hs.get('exclusions', ())],
        'rejoins': list(hs.get('rejoins', ())),
        'restarts_observed': len(hs.get('rejoins', ())),
        'recovery_wall_s': recovery,
        'max_recovery_wall_s': max(recovery) if recovery else 0.0,
        # elastic scale-up: joins this process OBSERVED (epoch at
        # admission), its own admit record (wall time) if it joined,
        # and the chief's predicted-vs-kept re-rank decisions
        'joins': [dict(j) for j in hs.get('joins', ())],
        'admitted': dict(admitted) if admitted else None,
        'admit_wall_s': (admitted or {}).get('admit_wall_s', 0.0),
        'replans': [dict(r) for r in hs.get('replans', ())],
        'autoscale': {
            'decisions': decisions,
            'taken': sum(1 for d in decisions
                         if d.get('action') == 'scale_up'),
            # deliberate skips and infrastructure failures are
            # DIFFERENT audit outcomes — never lump them
            'skipped': sum(1 for d in decisions
                           if d.get('action') == 'skipped'),
            'failed': sum(1 for d in decisions
                          if d.get('action') == 'failed'),
        },
        # online performance sentry (telemetry/monitor.py): rolling
        # cohort stats, active straggler verdicts with phase
        # attribution (exclude candidates under policy=advise), the
        # slowdown/recovered transition audit and the recalibration
        # trajectory. {} when the chief ran no monitor.
        'perf': dict(hs.get('perf') or {}),
        'auto_checkpoints': hs.get('auto_checkpoints', 0),
        # read-only serving tier (serving/): {} when no replica fleet
        # was attached to the run
        'serving': dict(serving if isinstance(serving, dict)
                        else (serving.stats() if serving is not None
                              else {})),
        'connect_retries': RETRY_STATS['connect_retries'],
        'injected_faults': [
            {'kind': e['kind'], 'line': e.get('line', '')}
            for e in events],
        'injected_join_faults': sum(
            1 for e in events if e['kind'].startswith('join_')),
    }


def format_health(report):
    """Human-readable rendering of :func:`health_report`."""
    if not report:
        return '(no loose-mode session: nothing to report)'
    lines = ['policy=%s generation=%d epoch=%d  membership %d/%d '
             '(world %d)'
             % (report['policy'], report['generation'], report['epoch'],
                report['active_workers'], report['num_workers'],
                report.get('world', report['num_workers']))]
    lines.append('  missed beats: %d   connect retries: %d   '
                 'auto-checkpoints: %d'
                 % (report['missed_beats'], report['connect_retries'],
                    report['auto_checkpoints']))
    if report.get('admitted'):
        adm = report['admitted']
        lines.append('  joined as %s at epoch %d (admit %.3fs, adopted '
                     'step %d)' % (adm.get('worker'),
                                   adm.get('epoch', -1),
                                   adm.get('admit_wall_s', 0.0),
                                   adm.get('adopted_step', 0)))
    for j in report.get('joins', ()):
        lines.append('  observed join: %s at epoch %d'
                     % (j.get('worker'), j.get('epoch', -1)))
    for r in report.get('replans', ()):
        if r.get('migrated'):
            # a half-joined entry (snapshot taken between the
            # migrated flag and the migration detail landing) degrades
            # to placeholders, never a crash
            mig = r.get('migration') or {}
            status = ' [MIGRATED to %s in %.3fs via reshard %s]' % (
                mig.get('builder', '?'), mig.get('wall_s') or 0.0,
                (mig.get('reshard') or {}).get('kinds', {}))
        elif r.get('migration_error'):
            status = ' [migration failed: %s]' % r['migration_error']
        elif r.get('migration_skipped'):
            status = ' [migration skipped: %s]' % r['migration_skipped']
        elif r.get('migration_staged'):
            status = ' [migration staged: %s]' % r['migration_staged']
        else:
            status = ''
        lines.append('  replan @world=%d: predicted %s vs kept %s%s%s'
                     % (r.get('world', -1),
                        r.get('predicted', '?'),
                        r.get('kept') or '(hand-picked)',
                        ' [error: %s]' % r['error']
                        if r.get('error') else '', status))
    auto = report.get('autoscale') or {}
    if auto.get('decisions'):
        lines.append('  autoscale: %d taken / %d skipped / %d failed'
                     % (auto.get('taken', 0), auto.get('skipped', 0),
                        auto.get('failed', 0)))
    srv = report.get('serving') or {}
    if srv.get('replicas'):
        lines.append(
            '  serving: %d replica(s)  %.0f qps  lookup p50 %.2fms '
            'p99 %.2fms  staleness %d/%d steps  row-cache hit %.0f%%  '
            'wire %.1fMB'
            % (srv.get('replicas', 0), srv.get('qps', 0.0),
               srv.get('lookup_p50_ms', 0.0),
               srv.get('lookup_p99_ms', 0.0),
               srv.get('staleness_steps', 0),
               srv.get('staleness_bound_steps', 0),
               100.0 * srv.get('row_cache_hit_rate', 0.0),
               srv.get('wire_bytes', 0) / 1e6))
        if srv.get('staleness_violations'):
            lines.append('    STALENESS VIOLATIONS: %d snapshot(s) '
                         'served beyond the bound'
                         % srv['staleness_violations'])
    perf = report.get('perf') or {}
    if perf.get('workers'):
        lines.append(
            '  perf: cohort step %.1fms over %d workers  (%d slowdown '
            '/ %d recovered, %d recalibration(s), policy=%s)'
            % (1e3 * perf.get('step_time_s', 0.0),
               len(perf['workers']), perf.get('slowdowns', 0),
               perf.get('recoveries', 0),
               len(perf.get('recalibrations', ())),
               perf.get('policy', '?')))
        for v in perf.get('verdicts', ()):
            lines.append(
                '    straggler %s: %s %.1fms vs %.1fms — %d%% of '
                'excess in %s ⇒ %s%s'
                % (v.get('worker'), v.get('statistic', '?'),
                   1e3 * v.get('stat_s', 0.0),
                   1e3 * v.get('baseline_s', 0.0),
                   int(100 * (v.get('phase_shares') or {}).get(
                       v.get('attributed_phase'), 0.0)),
                   v.get('attributed_phase'),
                   v.get('classification'),
                   ' [exclude candidate]'
                   if v.get('exclude_candidate') else ''))
    for ex in report['exclusions']:
        lines.append('  excluded %s at epoch %d'
                     % (ex.get('worker'), ex.get('epoch', -1)))
    for w, s in zip(report['rejoins'], report['recovery_wall_s']):
        lines.append('  %s rejoined after %.1fs' % (w, s))
    for f in report['injected_faults']:
        lines.append('  injected: %s (%s)' % (f['kind'], f['line']))
    return '\n'.join(lines)


def format_ps_overlap(report):
    """Human-readable rendering of :func:`ps_overlap_report`."""
    if not report:
        return '(no loose-mode train steps)'
    return ('depth=%d steps=%d  per-step: pull %.1fms | step %.1fms | '
            'push %.1fms  wire %.1fms (%.1fms exposed)  overlap %.0f%%'
            % (report['depth'], report['train_steps'],
               report['pull_s'] * 1e3, report['step_s'] * 1e3,
               report['push_s'] * 1e3, report['wire_s'] * 1e3,
               report['exposed_wire_s'] * 1e3,
               100.0 * report['overlap_frac']))


def format_breakdown(report, top_n=10, name_width=100):
    """Human-readable rendering of :func:`per_op_breakdown`."""
    if not report:
        return '(no trace data)'
    total = max(report['total_ns'], 1)
    lines = ['total %.2f ms' % (total / 1e6)]
    for cat, ns in report['by_category'].items():
        lines.append('  %6.2f%% %10.2f ms  %s'
                     % (100.0 * ns / total, ns / 1e6, cat))
    lines.append('top ops:')
    for name, ns, cnt in report['top_ops'][:top_n]:
        lines.append('  %8.2f ms x%-4d %s'
                     % (ns / 1e6, cnt, name[:name_width]))
    return '\n'.join(lines)
