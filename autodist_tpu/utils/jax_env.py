"""Honor JAX platform env vars on images whose sitecustomize pins them.

Some environments register a PJRT plugin and pin ``JAX_PLATFORMS`` at
interpreter startup, silently ignoring the standard
``JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=N``
incantation; ``jax.config.update`` after import is the reliable
override. Shared by bench.py, examples/_common.py, and any user script
that wants the documented env vars to actually work.
"""
import os
import re


def apply_jax_env_overrides():
    import jax

    plat = os.environ.get('JAX_PLATFORMS')
    if plat:
        try:
            jax.config.update('jax_platforms', plat)
        except RuntimeError:
            pass   # backend already initialized
    m = re.search(r'xla_force_host_platform_device_count=(\d+)',
                  os.environ.get('XLA_FLAGS', ''))
    if m:
        try:
            jax.config.update('jax_num_cpu_devices', int(m.group(1)))
        except RuntimeError:
            pass
