"""Honor JAX platform env vars on images whose sitecustomize pins them.

Some environments register a PJRT plugin and pin ``JAX_PLATFORMS`` at
interpreter startup, silently ignoring the standard
``JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=N``
incantation; ``jax.config.update`` after import is the reliable
override. Shared by bench.py, examples/_common.py, and any user script
that wants the documented env vars to actually work.
"""
import os
import re


def force_cpu_host_devices(n=8):
    """Arm the n-virtual-device CPU fallback BEFORE jax's backend
    initializes: append ``xla_force_host_platform_device_count`` to
    ``XLA_FLAGS`` if absent (flags are read once at backend init).
    Shared by bench.py's UNAVAILABLE fallback and tools/simulate.py;
    tests/conftest.py keeps its own copy on purpose (the test bootstrap
    must not depend on package imports). Callers import jax afterwards
    and, on images whose sitecustomize pins the platform, also call
    :func:`apply_jax_env_overrides`.
    """
    if 'xla_force_host_platform_device_count' not in \
            os.environ.get('XLA_FLAGS', ''):
        os.environ['XLA_FLAGS'] = (
            os.environ.get('XLA_FLAGS', '') +
            ' --xla_force_host_platform_device_count=%d' % n).strip()


def apply_jax_env_overrides():
    import jax

    plat = os.environ.get('JAX_PLATFORMS')
    if plat:
        try:
            jax.config.update('jax_platforms', plat)
        except RuntimeError:
            pass   # backend already initialized
    m = re.search(r'xla_force_host_platform_device_count=(\d+)',
                  os.environ.get('XLA_FLAGS', ''))
    if m:
        try:
            jax.config.update('jax_num_cpu_devices', int(m.group(1)))
        except (RuntimeError, AttributeError):
            # older jax spells this XLA_FLAGS only; the env var above
            # already covers it when set before backend init
            pass


# XLA flags that let bucketed gradient collectives actually overlap the
# backward pass: the latency-hiding scheduler reorders independent
# collectives ahead of compute, and async collective fusion turns each
# bucket's all-reduce into a start/done pair compute can run between.
# LIBTPU_INIT_ARGS is read once at libtpu initialization and ignored by
# CPU/GPU backends, so setting it is safe on any host.
OVERLAP_FLAGS = ('--xla_tpu_enable_latency_hiding_scheduler=true '
                 '--xla_tpu_enable_async_collective_fusion=true')


def setup_overlap_flags():
    """Arm the XLA overlap flags for bucketed gradient synchronization.

    Called at session setup when the execution plan has fused-AllReduce
    (bucketed) variables; ``AUTODIST_XLA_OVERLAP=0`` opts out. The flags
    are appended to ``LIBTPU_INIT_ARGS`` only if absent. libtpu reads
    the variable once at backend init, so when the backend is already
    up the setting reaches only processes launched after this point
    (the coordinator forwards the variable to workers); returns the
    flag string applied, or '' when opted out / already present.
    """
    from autodist_tpu.const import ENV
    if not ENV.AUTODIST_XLA_OVERLAP.val:
        return ''
    cur = os.environ.get('LIBTPU_INIT_ARGS', '')
    missing = [f for f in OVERLAP_FLAGS.split()
               if f.split('=')[0] not in cur]
    if not missing:
        return ''
    os.environ['LIBTPU_INIT_ARGS'] = \
        (cur + ' ' + ' '.join(missing)).strip()
    return ' '.join(missing)
