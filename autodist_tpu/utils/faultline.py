"""Deterministic fault injection for the loose-mode control/data plane.

The recovery machinery (epoch-fenced membership, peer-failure policies,
supervised restarts — see docs/design/fault-tolerance.md) is only
trustworthy if every failure mode it claims to survive can be produced
ON DEMAND, identically, in CI. This module is that producer: a
:class:`FaultPlan` is a seeded, serializable schedule of faults, and a
:class:`FaultLine` arms one plan in one process through the
:class:`~autodist_tpu.runtime.coord_client.CoordClient` send hook —
every request frame headed for the wire passes through it, so faults
fire at exact, reproducible protocol points rather than "roughly when a
sleep elapses".

Fault kinds (each a dict in ``FaultPlan.faults``):

- ``kill_worker`` ``{worker, step, mode: exit|raise, exit_code}`` —
  the process dies the moment worker ``worker``'s published step
  counter would reach ``step`` (watched on the wire: the ``INCR`` of
  ``step/<worker>``). ``exit`` is a real crash (``os._exit``, no
  cleanup, no done marker — what the liveness layer must detect);
  ``raise`` throws :class:`InjectedFault` for in-process tests. The
  step's delta push has already landed when the publish fires, so the
  semantics are "crashed after pushing step k, before publishing it".
- ``drop_conn`` ``{match, at}`` — the ``at``-th frame containing
  ``match`` raises ``OSError`` instead of being sent.
- ``close_conn`` ``{match, at}`` — same, but the socket is closed
  first (the peer observes EOF, not just a failed caller).
- ``delay_conn`` ``{match, at, seconds}`` — the matching frame is
  delayed (slow-network emulation).
- ``torn_frame`` ``{match, at}`` — a matching whole-tensor BSET/BADD
  is rewritten as the FIRST CHUNK of a larger write whose continuation
  never comes, and the connection is dead afterwards: the
  died-mid-chunked-push signature readers must surface as a
  stalled-odd-version error instead of returning torn data.
- ``stalled_writer`` ``{match, at, seconds}`` — a CONTINUATION chunk
  (a ranged B* frame with offset > 0) is held for ``seconds`` before
  sending: readers see odd version parity that eventually resolves —
  the slow-but-alive writer the stall-timeout logic must NOT kill.
- ``join_drop`` / ``join_delay`` / ``join_kill`` ``{at, seconds,
  mode}`` — the admit-handshake faults (live scale-up,
  ``runtime/session.py admit_worker``): the ``at``-th frame of THIS
  process's join handshake (default match ``join/`` — the world-claim
  INCRs; override ``match`` to target the step adoption or the epoch
  bump) is dropped (OSError), delayed, or is the process's death point
  (``exit`` = ``os._exit``, the real killed-mid-admit; ``raise`` =
  :class:`InjectedFault` for in-process tests). The membership
  machinery must absorb all three, and the handshake's epoch-bump-
  before-step-publish ordering makes every window benign: a death
  BEFORE the epoch bump leaves an invisible leaked ordinal with no
  step counter (harmless — nothing of it reaches any gate), a death
  AFTER it leaves a visible member with no beat, which the never-beat
  rule declares dead and the exclude path releases within one
  heartbeat window.

Frame counts, step thresholds and the plan seed make every fault
deterministic; ``FaultPlan.random`` derives a full plan from one seed
so a chaos suite can sweep seeds without hand-writing schedules. Plans
serialize to JSON and ride ``AUTODIST_FAULT_PLAN`` (inline JSON or
``@/path``) into launched worker processes — which install them
EXPLICITLY via :meth:`FaultLine.from_env`; production sessions never
read the flag.
"""
import json
import os
import time
from collections import defaultdict

import numpy as np

from autodist_tpu.const import ENV
from autodist_tpu.utils import logging

FAULT_KINDS = ('kill_worker', 'drop_conn', 'close_conn', 'delay_conn',
               'torn_frame', 'stalled_writer', 'join_drop',
               'join_delay', 'join_kill')

# the join_* kinds default their match to the admit handshake's
# world-claim frames; no field is strictly required
JOIN_MATCH_DEFAULT = 'join/'

_REQUIRED = {
    'kill_worker': ('worker', 'step'),
    'drop_conn': ('match',),
    'close_conn': ('match',),
    'delay_conn': ('match',),
    'torn_frame': ('match',),
    'stalled_writer': ('match',),
    'join_drop': (),
    'join_delay': (),
    'join_kill': (),
}


class InjectedFault(RuntimeError):
    """A ``kill_worker`` fault with ``mode='raise'`` fired."""


class FaultPlan:
    """A seeded, serializable schedule of faults.

    ``faults`` is a list of dicts (see module docstring for the
    per-kind fields); ``seed`` names the plan (and drives
    :meth:`random`). Plans are immutable value objects: arming state
    (fired flags, match counts) lives in :class:`FaultLine`.
    """

    def __init__(self, faults=(), seed=0):
        self.seed = int(seed)
        self.faults = []
        for f in faults:
            f = dict(f)
            kind = f.get('kind')
            if kind not in FAULT_KINDS:
                raise ValueError('unknown fault kind %r (one of %s)'
                                 % (kind, '|'.join(FAULT_KINDS)))
            missing = [k for k in _REQUIRED[kind] if k not in f]
            if missing:
                raise ValueError('fault %r missing field(s) %s'
                                 % (kind, missing))
            if 'at' in f and int(f['at']) < 1:
                raise ValueError('fault %r: "at" is 1-based' % kind)
            self.faults.append(f)

    def to_json(self):
        return json.dumps({'seed': self.seed, 'faults': self.faults},
                          sort_keys=True)

    @classmethod
    def from_json(cls, text):
        d = json.loads(text)
        return cls(d.get('faults', ()), seed=d.get('seed', 0))

    @classmethod
    def from_env(cls):
        """The plan configured in ``AUTODIST_FAULT_PLAN`` (inline JSON
        or ``@/path/to/plan.json``), or an empty plan when unset."""
        raw = ENV.AUTODIST_FAULT_PLAN.val
        if not raw:
            return cls()
        if raw.startswith('@'):
            with open(raw[1:]) as f:
                raw = f.read()
        return cls.from_json(raw)

    @classmethod
    def random(cls, seed, workers, steps, kinds=('kill_worker',)):
        """Derive a deterministic plan from one seed: for each kind,
        the target worker and firing point are drawn from a seeded RNG
        — a chaos sweep is then just a range of seeds."""
        rng = np.random.RandomState(seed)
        faults = []
        for kind in kinds:
            worker = workers[int(rng.randint(len(workers)))]
            at = int(rng.randint(1, max(2, steps)))
            if kind == 'kill_worker':
                faults.append({'kind': kind, 'worker': worker,
                               'step': at, 'mode': 'exit'})
            elif kind == 'delay_conn':
                faults.append({'kind': kind, 'worker': worker,
                               'match': 'BGET', 'at': at,
                               'seconds': 0.02 * (1 + int(
                                   rng.randint(4)))})
            elif kind == 'stalled_writer':
                faults.append({'kind': kind, 'worker': worker,
                               'match': 'BSET', 'at': at,
                               'seconds': 0.1 * (1 + int(
                                   rng.randint(3)))})
            elif kind.startswith('join_'):
                f = {'kind': kind, 'worker': worker,
                     'at': 1 + int(rng.randint(2))}
                if kind == 'join_delay':
                    f['seconds'] = 0.02 * (1 + int(rng.randint(4)))
                elif kind == 'join_kill':
                    f['mode'] = 'raise'
                faults.append(f)
            else:   # drop_conn / close_conn / torn_frame
                faults.append({'kind': kind, 'worker': worker,
                               'match': 'BADD', 'at': at})
        return cls(faults, seed=seed)


def _parse_publish(line):
    """``(step key, delta)`` when ``line`` is a step-publishing INCR."""
    if not line.startswith('INCR '):
        return None
    parts = line.split()
    if len(parts) != 3:
        return None
    try:
        delta = int(parts[2])
    except ValueError:
        return None
    return (parts[1], delta) if delta > 0 else None


def _continuation_offset(line):
    """The declared offset of a ranged B* frame (``... <off> <total>``),
    or None for whole-tensor frames. BSADD ranges count rows; the
    offset semantics (0 = opening chunk) are identical."""
    parts = line.split()
    if parts and parts[0] == 'BSADD':
        if len(parts) < 7:
            return None
    elif len(parts) < 6 or parts[0] not in ('BSET', 'BADD'):
        return None
    try:
        return int(parts[-2])
    except ValueError:
        return None


class FaultLine:
    """Arms one :class:`FaultPlan` in this process (context manager).

    Installs the class-wide ``CoordClient.fault_hook``; every fired
    fault is appended to :attr:`events` (kind, the frame that
    triggered it, a wall-clock stamp) so chaos tests and
    ``profiling.health_report`` can assert exactly what was injected.
    ``worker`` names this process (``'p0'``...): connection faults
    carrying a ``worker`` field arm only in that worker's process;
    ``kill_worker`` always matches on the wire key instead.
    """

    def __init__(self, plan, worker=None):
        self.plan = plan
        self.worker = worker
        self.events = []
        self._steps = {}                      # step key -> tracked total
        self._match_counts = defaultdict(int)  # fault idx -> seen frames
        self._fired = set()                   # fault idxs fired (once)
        self._dead = set()                    # id(client)s killed by torn_frame
        self._installed = False

    @classmethod
    def from_env(cls, worker=None):
        return cls(FaultPlan.from_env(), worker=worker)

    def install(self):
        from autodist_tpu.runtime.coord_client import CoordClient
        if CoordClient.fault_hook is not None:
            raise RuntimeError('another FaultLine is already installed '
                               'in this process')
        CoordClient.fault_hook = self._hook
        self._installed = True
        if self.plan.faults:
            logging.warning('faultline armed (%d fault(s), seed %d)',
                            len(self.plan.faults), self.plan.seed)
        return self

    def uninstall(self):
        from autodist_tpu.runtime.coord_client import CoordClient
        if self._installed:
            CoordClient.fault_hook = None
            self._installed = False

    def __enter__(self):
        return self.install()

    def __exit__(self, *exc):
        self.uninstall()

    def _record(self, fault, line):
        self.events.append({'kind': fault['kind'], 'fault': dict(fault),
                            'line': line[:96], 'time': time.time()})

    # -- the CoordClient send hook ----------------------------------------
    def _hook(self, client, line, payload):
        if id(client) in self._dead:
            raise OSError('faultline: connection dead (writer died '
                          'after a torn frame)')
        pub = _parse_publish(line)
        replacement = None
        for idx, fault in enumerate(self.plan.faults):
            kind = fault['kind']
            if kind == 'kill_worker':
                if pub is None or not pub[0].endswith(
                        'step/' + fault['worker']):
                    continue
                total = self._steps.get(pub[0], 0) + pub[1]
                self._steps[pub[0]] = total
                from autodist_tpu.runtime.coord_client import \
                    CLEAN_CLOSE_STEP
                if total >= CLEAN_CLOSE_STEP:
                    # a clean-close / exclusion RELEASE of the counter
                    # (Session.close, _exclude_peer), not the worker
                    # reaching its death step — and possibly published
                    # by a SURVIVOR on the victim's behalf: firing here
                    # would kill the wrong process at the wrong moment
                    continue
                if idx in self._fired or total < int(fault['step']):
                    continue
                self._fired.add(idx)
                self._record(fault, line)
                if fault.get('mode', 'exit') == 'raise':
                    raise InjectedFault(
                        'faultline: worker %s killed at step %d'
                        % (fault['worker'], fault['step']))
                logging.warning('faultline: hard-killing worker %s at '
                                'step %d', fault['worker'],
                                fault['step'])
                os._exit(int(fault.get('exit_code', 137)))
            # connection faults: scoped to this process when the fault
            # names a worker
            if fault.get('worker') and fault['worker'] != self.worker:
                continue
            # join_* kinds default their match to the admit handshake's
            # world-claim frames (session.admit_worker)
            match = fault.get('match') or (
                JOIN_MATCH_DEFAULT if kind.startswith('join_') else '')
            if match not in line:
                continue
            if kind == 'stalled_writer':
                off = _continuation_offset(line)
                if not off:   # only a mid-sequence chunk can stall
                    continue
            self._match_counts[idx] += 1
            if idx in self._fired or \
                    self._match_counts[idx] != int(fault.get('at', 1)):
                continue
            self._fired.add(idx)
            self._record(fault, line)
            if kind == 'join_drop':
                raise OSError('faultline: dropped join-handshake frame '
                              '%r' % line[:64])
            if kind == 'join_kill':
                if fault.get('mode', 'exit') == 'raise':
                    raise InjectedFault(
                        'faultline: worker killed mid-admit (frame %r)'
                        % line[:64])
                logging.warning('faultline: hard-killing worker during '
                                'the admit handshake (frame %r)',
                                line[:64])
                os._exit(int(fault.get('exit_code', 137)))
            if kind == 'join_delay':
                time.sleep(float(fault.get('seconds', 0.05)))
                continue
            if kind == 'drop_conn':
                raise OSError('faultline: dropped connection before %r'
                              % line.split()[0])
            if kind == 'close_conn':
                try:
                    client._sock.close()
                except OSError:
                    pass
                raise OSError('faultline: closed connection before %r'
                              % line.split()[0])
            if kind == 'delay_conn':
                time.sleep(float(fault.get('seconds', 0.05)))
            elif kind == 'stalled_writer':
                time.sleep(float(fault.get('seconds', 0.5)))
            elif kind == 'torn_frame':
                replacement = self._tear(client, line, payload)
        return replacement

    def _tear(self, client, line, payload):
        """Rewrite a whole-tensor BSET/BADD (or whole-push BSADD) as
        the opening chunk of a write twice its size, then kill the
        connection: the canonical died-mid-chunked-push wreckage
        (version parity stays odd until the reader's stall timeout
        declares the writer dead). A BSADD's range counts ROWS, so the
        phantom continuation is another <nrows> rows."""
        parts = line.split()
        if parts and parts[0] == 'BSADD' and len(parts) == 5:
            nrows = int(parts[2])
            self._dead.add(id(client))
            return ('%s 0 %d' % (line, 2 * nrows), payload)
        if len(parts) != 4 or parts[0] not in ('BSET', 'BADD'):
            logging.warning('faultline: torn_frame matched a non-whole-'
                            'tensor frame %r; leaving it intact',
                            line[:64])
            return None
        nbytes = int(parts[2])
        elems = nbytes // (2 if parts[3] == 'bf16' else 4)
        self._dead.add(id(client))
        return ('%s 0 %d' % (line, 2 * elems), payload)
