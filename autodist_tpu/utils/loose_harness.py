"""Single-process loose-mode harness bootstrap.

Loose mode is a multi-process mode; driving its PS data plane from ONE
process needs a subtle env dance: the strategy build must see 2
processes (the mode decision) while the session sees 1 (no peers to
barrier with) — the same data plane either way. bench.py's ps-pipeline
A/B and tests/test_async_ps.py both ride this helper so the dance
lives in exactly one place.
"""
import os
from contextlib import contextmanager

_KNOBS = ('AUTODIST_COORD_SERVICE_ADDR', 'AUTODIST_PS_PIPELINE_DEPTH',
          'AUTODIST_NUM_PROCESSES', 'AUTODIST_PROCESS_ID')


@contextmanager
def single_process_loose_env(coord_port, depth):
    """Environment bootstrap for a single-process loose-mode run
    against the coord service on localhost ``coord_port`` at PS
    pipeline ``depth``.

    Yields a zero-arg callable to invoke AFTER ``autodist._build()``
    (which must see 2 processes → loose mode) and BEFORE
    ``create_distributed_session()`` (which must see 1 → no peers to
    barrier with). Every touched knob is restored on exit, and any
    process-default AutoDist singleton is cleared so this instance
    owns the scope.
    """
    from autodist_tpu import autodist as ad_mod
    saved = {k: os.environ.get(k) for k in _KNOBS}
    ad_mod._DEFAULT_AUTODIST.clear()
    try:
        # an earlier AutoDist in this process claimed chief identity via
        # os.environ.setdefault(AUTODIST_PROCESS_ID, '0'); a leftover
        # value would make THIS instance look externally-launched and
        # join a 2-party ctrl/init barrier nobody else attends
        os.environ.pop('AUTODIST_PROCESS_ID', None)
        os.environ['AUTODIST_COORD_SERVICE_ADDR'] = \
            '127.0.0.1:%d' % coord_port
        os.environ['AUTODIST_PS_PIPELINE_DEPTH'] = str(depth)
        os.environ['AUTODIST_NUM_PROCESSES'] = '2'

        def session_sees_one_process():
            os.environ['AUTODIST_NUM_PROCESSES'] = '1'

        yield session_sees_one_process
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
