"""Single-process loose-mode harness bootstrap.

Loose mode is a multi-process mode; driving its PS data plane from ONE
process needs a subtle env dance: the strategy build must see 2
processes (the mode decision) while the session sees 1 (no peers to
barrier with) — the same data plane either way. bench.py's ps-pipeline
A/B and tests/test_async_ps.py both ride this helper so the dance
lives in exactly one place.

This module also hosts :func:`ack_staged_swaps`, the swap-handshake
half of a SIMULATED peer: tests and benches that fake a cohort member
with a bare coord client (publish step, heartbeat, release) must also
speak the epoch-swap ack protocol or the chief's ack quorum would
never fill.  One helper, called from every simulated-peer loop, keeps
that protocol in one place too.
"""
import os
from contextlib import contextmanager

_KNOBS = ('AUTODIST_COORD_SERVICE_ADDR', 'AUTODIST_PS_PIPELINE_DEPTH',
          'AUTODIST_NUM_PROCESSES', 'AUTODIST_PROCESS_ID')


@contextmanager
def single_process_loose_env(coord_port, depth):
    """Environment bootstrap for a single-process loose-mode run
    against the coord service on localhost ``coord_port`` at PS
    pipeline ``depth``.

    Yields a zero-arg callable to invoke AFTER ``autodist._build()``
    (which must see 2 processes → loose mode) and BEFORE
    ``create_distributed_session()`` (which must see 1 → no peers to
    barrier with). Every touched knob is restored on exit, and any
    process-default AutoDist singleton is cleared so this instance
    owns the scope.
    """
    from autodist_tpu import autodist as ad_mod
    saved = {k: os.environ.get(k) for k in _KNOBS}
    ad_mod._DEFAULT_AUTODIST.clear()
    try:
        # an earlier AutoDist in this process claimed chief identity via
        # os.environ.setdefault(AUTODIST_PROCESS_ID, '0'); a leftover
        # value would make THIS instance look externally-launched and
        # join a 2-party ctrl/init barrier nobody else attends
        os.environ.pop('AUTODIST_PROCESS_ID', None)
        os.environ['AUTODIST_COORD_SERVICE_ADDR'] = \
            '127.0.0.1:%d' % coord_port
        os.environ['AUTODIST_PS_PIPELINE_DEPTH'] = str(depth)
        os.environ['AUTODIST_NUM_PROCESSES'] = '2'

        def session_sees_one_process():
            os.environ['AUTODIST_NUM_PROCESSES'] = '1'

        yield session_sees_one_process
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def ack_staged_swaps(client, ns, worker, seen):
    """One poll of the epoch-swap handshake for a SIMULATED peer.

    Call from the simulated peer's publish loop.  ``seen`` is a
    mutable set of generations this peer already acked (owned by the
    caller so the helper stays stateless).  Any newly staged
    generation is acked unconditionally — a bare-client peer has no
    mesh to validate the plan against, and these harness peers exist
    to exercise the chief's staging/arming machinery, not the
    validator.  Returns ``(gen, boundary)`` of the latest armed
    generation (``(0, 0)`` if none) so a caller that wants to stop
    publishing near the boundary can.
    """
    from autodist_tpu.runtime import swap_keys
    gen = swap_keys.current_gen(client, ns)
    if gen <= 0:
        return 0, 0
    if gen not in seen:
        # plan may already be cancelled by the time we look; only a
        # visible payload earns an ack (matches the real peer, which
        # keys every decision off the plan's presence)
        if swap_keys.read_plan(client, ns, gen) is not None:
            swap_keys.write_ack(client, ns, gen, worker)
            seen.add(gen)
    return gen, swap_keys.read_boundary(client, ns, gen)
