"""utils subpackage."""
