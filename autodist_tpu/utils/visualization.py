"""Per-phase program dumps (reference visualization_util.py:24-36).

The reference writes the TF graph to TensorBoard after each transform
phase. The TPU equivalent of "the graph at each phase" is the jaxpr
(after trace) and the HLO (after lowering): ``log_program`` writes both
under ``/tmp/autodist-tpu/graphs/<run>/<phase>.{jaxpr,hlo}.txt`` when
``AUTODIST_DUMP_GRAPHS`` is set, giving the same build-pipeline
observability (0-original capture, 1-lowered step, ...).
"""
import os
import time

import jax

from autodist_tpu.const import DEFAULT_GRAPH_DUMP_DIR, ENV
from autodist_tpu.utils import logging

_RUN_DIR = None


def _run_dir():
    global _RUN_DIR
    if _RUN_DIR is None:
        _RUN_DIR = os.path.join(DEFAULT_GRAPH_DUMP_DIR,
                                time.strftime('%Y%m%d-%H%M%S'))
        os.makedirs(_RUN_DIR, exist_ok=True)
    return _RUN_DIR


def log_program(fn, args, phase, kwargs=None, static_argnums=()):
    """Dump jaxpr + (best-effort) HLO of ``fn(*args)`` for one phase."""
    if not ENV.AUTODIST_DUMP_GRAPHS.val:
        return None
    kwargs = kwargs or {}
    out_dir = _run_dir()
    base = os.path.join(out_dir, phase)
    try:
        jaxpr = jax.make_jaxpr(fn, static_argnums=static_argnums)(
            *args, **kwargs)
        with open(base + '.jaxpr.txt', 'w') as f:
            f.write(str(jaxpr))
    except Exception as e:  # noqa: BLE001 - diagnostics must not kill runs
        logging.warning('jaxpr dump failed for %s: %s', phase, e)
    try:
        lowered = jax.jit(fn, static_argnums=static_argnums).lower(
            *args, **kwargs)
        with open(base + '.hlo.txt', 'w') as f:
            f.write(lowered.as_text())
    except Exception as e:  # noqa: BLE001
        logging.warning('HLO dump failed for %s: %s', phase, e)
    logging.info('Dumped program phase %r under %s', phase, out_dir)
    return base


def log_text(content, phase):
    """Dump a text artifact (captured graph, strategy, plan) for one
    build phase (reference dumps the graph at 4 transform phases,
    graph_transformer.py:62-90)."""
    if not ENV.AUTODIST_DUMP_GRAPHS.val:
        return None
    base = os.path.join(_run_dir(), phase)
    with open(base + '.txt', 'w') as f:
        f.write(str(content))
    logging.info('Dumped %r under %s', phase, _run_dir())
    return base


def log_compiled(lowered_or_compiled, phase):
    """Dump an already-lowered/compiled jax artifact's HLO text."""
    if not ENV.AUTODIST_DUMP_GRAPHS.val:
        return None
    base = os.path.join(_run_dir(), phase)
    try:
        with open(base + '.hlo.txt', 'w') as f:
            f.write(lowered_or_compiled.as_text())
        logging.info('Dumped %r HLO under %s', phase, _run_dir())
    except Exception as e:  # noqa: BLE001
        logging.warning('HLO dump failed for %s: %s', phase, e)
    return base
