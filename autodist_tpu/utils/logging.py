"""Framework logger.

Re-design of reference ``autodist/utils/logging.py:33-107``: a dedicated
``autodist_tpu`` logger writing PID-stamped records to stderr and to a
timestamped file under ``/tmp/autodist_tpu/logs``; verbosity controlled by
the ``AUTODIST_MIN_LOG_LEVEL`` env flag.
"""
import logging as _logging
import os
import sys
import threading
import time

from autodist_tpu.const import DEFAULT_LOG_DIR, ENV

_logger = None
_logger_lock = threading.Lock()

_FMT = '%(asctime)s %(levelname)s %(process)d ' \
       '%(filename)s:%(lineno)d] %(message)s'


def get_logger():
    """Return the singleton framework logger (double-checked locking)."""
    global _logger
    if _logger:
        return _logger
    with _logger_lock:
        if _logger:
            return _logger
        logger = _logging.getLogger('autodist_tpu')
        logger.propagate = False
        level = ENV.AUTODIST_MIN_LOG_LEVEL.val.upper()
        logger.setLevel(level if hasattr(_logging, level) else 'INFO')
        fmt = _logging.Formatter(_FMT)
        sh = _logging.StreamHandler(sys.stderr)
        sh.setFormatter(fmt)
        logger.addHandler(sh)
        try:
            os.makedirs(DEFAULT_LOG_DIR, exist_ok=True)
            fh = _logging.FileHandler(
                os.path.join(DEFAULT_LOG_DIR, '%d.log' % int(time.time())))
            fh.setFormatter(fmt)
            logger.addHandler(fh)
        except OSError:  # read-only fs etc. -- stderr logging still works
            pass
        _logger = logger
        return _logger


def set_verbosity(level):
    """Set the logger level by name or numeric value."""
    get_logger().setLevel(level)


def debug(msg, *args, **kwargs):
    get_logger().debug(msg, *args, **kwargs)


def info(msg, *args, **kwargs):
    get_logger().info(msg, *args, **kwargs)


def warning(msg, *args, **kwargs):
    get_logger().warning(msg, *args, **kwargs)


def error(msg, *args, **kwargs):
    get_logger().error(msg, *args, **kwargs)
