"""Bridge: reference-style strategies over functional (pytree) models.

The reference's builders analyze a captured tf.Graph (SURVEY.md §2.1);
the functional path has no graph, just a param pytree with logical-axis
metadata. :class:`PytreeGraphItem` adapts that pytree to the GraphItem
interface the builders consume (``trainable_var_op_to_var`` +
``is_sparse``), so ALL eight builders run unchanged on functional models.

:func:`apply_strategy_to_trainer_shardings` then lowers the built
strategy onto Trainer shardings: a variable the strategy partitions gets
its state sharded over the ``data`` axis along the strategy's partition
axis (the ZeRO realization of PS placement; SURVEY.md §7 design
translation table), while AllReduce variables stay replicated (GSPMD
inserts the gradient psum).
"""
import numpy as np

from jax.sharding import NamedSharding, PartitionSpec as P

import jax

from autodist_tpu.const import AXIS_DATA
from autodist_tpu.strategy.base import PSSynchronizer
from autodist_tpu.utils import logging


class FunctionalModel:
    """Zero-touch adapter for third-party functional models.

    The reference distributes *unmodified* user Keras/TF code by
    monkey-patching TF internals (``autodist/patch.py:96-197``, cases
    c1/c3/c5/c7). The functional equivalent needs no patching: wrap the
    user's own ``init_fn(rng) -> params`` and ``loss_fn(params, batch)
    -> scalar`` (flax, haiku, or plain jax — anything producing a param
    pytree) plus an OPTIONAL logical-axes pytree, and the result speaks
    the Trainer/strategy model protocol:

        import flax.linen as nn
        mod = nn.Dense(128)
        model = FunctionalModel(
            init_fn=lambda rng: mod.init(rng, example)['params'],
            loss_fn=lambda p, b: loss_of(mod.apply({'params': p}, b)),
            axes={'kernel': ('embed', 'mlp'), 'bias': (None,)})
        trainer = trainer_from_strategy(model, optax.adam(1e-3),
                                        PSLoadBalancing())

    ``axes`` leaves are logical-axis tuples (one entry per dim); missing
    ``axes`` means every param is unannotated (replicated until a
    strategy or ZeRO shards it). An optional ``apply_fn`` is carried for
    serving/export convenience.
    """

    def __init__(self, init_fn, loss_fn, axes=None, apply_fn=None):
        self._init_fn = init_fn
        self._loss_fn = loss_fn
        self._axes = axes
        self.apply = apply_fn

    def init(self, rng):
        return self._init_fn(rng)

    def loss(self, params, batch):
        return self._loss_fn(params, batch)

    def axes(self):
        if self._axes is not None:
            return self._axes
        shapes = jax.eval_shape(self._init_fn, jax.random.PRNGKey(0))
        return jax.tree.map(lambda l: (None,) * len(l.shape), shapes)


class _VarLike:
    """Duck-typed Variable for strategy builders (shape/dtype/name)."""

    def __init__(self, name, shape, dtype, sparse=False):
        self.name = name
        self.shape = tuple(int(d) for d in shape)
        self.dtype = np.dtype(dtype)
        self.sparse_read = sparse

    @property
    def nbytes(self):
        n = self.dtype.itemsize
        for d in self.shape:
            n *= d
        return n


class PytreeGraphItem:
    """GraphItem facade over a functional model's param pytree.

    Variables are named by their pytree path (``'blocks/mlp/up/kernel'``).
    A leaf whose logical axes include ``vocab`` is flagged sparse —
    embedding tables get gather-style (IndexedSlices-like) gradients,
    which is what Parallax keys its dense/sparse split on
    (parallax_strategy.py:38-70).
    """

    def __init__(self, model, rng=None):
        self.model = model
        shapes = jax.eval_shape(model.init,
                                rng if rng is not None
                                else jax.random.PRNGKey(0))
        axes = model.axes()
        self._vars = {}
        flat_s = _flatten_with_paths(shapes)
        flat_a = dict(_flatten_with_paths(
            axes, is_leaf=lambda x: x is None or (
                isinstance(x, tuple) and
                all(isinstance(a, (str, type(None))) for a in x))))
        for path, leaf in flat_s:
            ax = flat_a.get(path) or ()
            self._vars[path] = _VarLike(
                path, leaf.shape, leaf.dtype,
                sparse='vocab' in ax)

    @property
    def trainable_var_op_to_var(self):
        return self._vars

    def is_sparse(self, var):
        return var.sparse_read

    def var_by_name(self, name):
        return self._vars[name]

    def prepare(self):
        return self


def _flatten_with_paths(tree, is_leaf=None):
    flat, _ = jax.tree_util.tree_flatten_with_path(tree, is_leaf=is_leaf)
    out = []
    for path, leaf in flat:
        name = '/'.join(str(getattr(k, 'key', getattr(k, 'idx', k)))
                        for k in path)
        out.append((name, leaf))
    return out


def apply_strategy_to_shardings(strategy, graph_item, shardings, mesh):
    """Refine a Trainer sharding tree according to a built Strategy.

    Partitioned (PS or AR) variables: state shards over ``data`` along the
    strategy's partition axis when divisible. Plain PS variables with no
    partitioning stay replicated (a single logical server is the
    degenerate shard). Returns a new sharding pytree.
    """
    nodes = {n.var_name: n for n in strategy.node_config}
    flat = dict(_flatten_with_paths(shardings,
                                    is_leaf=lambda x: isinstance(
                                        x, NamedSharding)))
    dp = mesh.shape.get(AXIS_DATA, 1)
    out = {}
    for name, sharding in flat.items():
        node = nodes.get(name)
        out[name] = sharding
        if node is None or dp <= 1:
            continue
        var = graph_item.var_by_name(name)
        axis = node.partition_axis
        if axis is None:
            continue
        spec = list(sharding.spec) + [None] * (len(var.shape) -
                                               len(sharding.spec))
        if spec[axis] is None and var.shape[axis] % dp == 0 and \
                var.shape[axis] >= dp:
            spec[axis] = AXIS_DATA
            out[name] = NamedSharding(mesh, P(*spec))
        else:
            logging.debug('Cannot shard %s axis %d over data (%s)',
                          name, axis, var.shape)
    # rebuild the tree in the original structure
    leaves, treedef = jax.tree_util.tree_flatten(
        shardings, is_leaf=lambda x: isinstance(x, NamedSharding))
    names = [n for n, _ in _flatten_with_paths(
        shardings, is_leaf=lambda x: isinstance(x, NamedSharding))]
    return jax.tree_util.tree_unflatten(
        treedef, [out[n] for n in names])


def grad_bucket_layout(strategy, graph_item):
    """Byte-capped gradient-bucket layout for a strategy's AllReduce vars.

    The same packing the execution plan applies at trace time
    (``parallel.plan.pack_buckets``: same-(group, compressor, spec)
    variables, reverse production order, cap from the synchronizer's
    ``chunk_size`` / ``AUTODIST_BUCKET_BYTES``), computed statically
    from the strategy + variable shapes so callers (bench reporting,
    tooling) can audit the layout without tracing a step. Returns
    ``[{'group', 'vars': [names], 'bytes'}]`` in emission order.
    """
    from autodist_tpu.const import DEFAULT_CHUNK_SIZE
    from autodist_tpu.parallel.plan import bucket_bytes_cap, pack_buckets
    from autodist_tpu.strategy.base import AllReduceSynchronizer

    # mirror sync_gradients' fusable filter and grouping key exactly:
    # only stateless compressors fuse (stateful ones reduce per-var),
    # the key includes the gradient dtype (mixed-dtype groups split),
    # the hierarchical knob (mixed flat/two-level members split) and
    # the weight-update-sharding knob (mixed replicated/sharded-update
    # members split — their emissions differ in kind, not just shape)
    groups = {}   # (group, compressor, spec, dtype, hier, wus) -> items
    for node in strategy.node_config:
        sync = node.synchronizer if not node.part_config \
            else node.part_config[0]
        if not isinstance(sync, AllReduceSynchronizer):
            continue
        if sync.compressor not in ('NoneCompressor',
                                   'HorovodCompressor'):
            continue
        try:
            var = graph_item.var_by_name(node.var_name)
        except KeyError:
            continue
        nbytes = int(np.prod(var.shape or (1,))) * \
            np.dtype(var.dtype).itemsize
        wus = getattr(sync, 'weight_update_sharding', 'never') or \
            'never'
        if getattr(var, 'sparse_read', False):
            wus = 'ineligible'   # mirror VarPlan's row-lazy exclusion
        groups.setdefault(
            (sync.group, sync.compressor, sync.spec,
             str(np.dtype(var.dtype)),
             getattr(sync, 'hierarchical', 'auto') or 'auto', wus),
            []).append(
            (node.var_name, nbytes, getattr(sync, 'chunk_size', 0)))
    out = []
    for (group, *_), items in sorted(groups.items(), reverse=True):
        chunk = max(c for _, _, c in items)
        cap = bucket_bytes_cap(chunk)
        rev = [(name, nbytes) for name, nbytes, _ in reversed(items)]
        sizes = dict(rev)
        for bucket in pack_buckets(rev, cap,
                                   chunk or DEFAULT_CHUNK_SIZE):
            out.append({'group': group, 'vars': list(bucket),
                        'bytes': sum(sizes[n] for n in bucket)})
    return out


def trainer_from_strategy(model, optimizer, strategy_builder,
                          resource_spec=None, spec=None, **kw):
    """Build a Trainer whose state shardings follow a reference-style
    strategy built by ``strategy_builder`` over the model's pytree."""
    from autodist_tpu.api import Trainer
    from autodist_tpu.resource_spec import ResourceSpec

    gi = PytreeGraphItem(model)
    if resource_spec is None:
        import jax as _jax
        n = len(_jax.devices())
        resource_spec = ResourceSpec(resource_info={'nodes': [{
            'address': 'localhost', 'chief': True, 'cpus': [0],
            'gpus': list(range(n)), 'network_bandwidth': 100}]})
    strategy = strategy_builder.build(gi, resource_spec)
    trainer = Trainer(model, optimizer, spec=spec, **kw)
    trainer.param_shardings = apply_strategy_to_shardings(
        strategy, gi, trainer.param_shardings, trainer.mesh)
    trainer.strategy = strategy
    trainer.grad_buckets = grad_bucket_layout(strategy, gi)
    return trainer
