"""Strategy layer: representation + builders (reference autodist/strategy/)."""
from autodist_tpu.strategy.base import (  # noqa: F401
    AllReduceSynchronizer, GraphConfig, PSSynchronizer, Strategy,
    StrategyBuilder, StrategyCompiler, StrategyNode, byte_size_load_fn)
from autodist_tpu.strategy.builders import (  # noqa: F401
    PS, AllReduce, AutoStrategy, Parallax, PartitionedAR, PartitionedPS,
    PSLoadBalancing, RandomAxisPartitionAR, UnevenPartitionedPS)
from autodist_tpu.strategy.adapter import (  # noqa: F401
    FunctionalModel, PytreeGraphItem, trainer_from_strategy)
