"""The concrete strategy builders.

One-to-one with the reference's ``autodist/strategy/`` directory:

- :class:`PS`                   — ps_strategy.py:40-56
- :class:`PSLoadBalancing`      — ps_lb_strategy.py:64-117
- :class:`PartitionedPS`        — partitioned_ps_strategy.py:60-135
- :class:`UnevenPartitionedPS`  — uneven_partition_ps_strategy.py:125-133
- :class:`AllReduce`            — all_reduce_strategy.py:38-90
- :class:`PartitionedAR`        — partitioned_all_reduce_strategy.py:71-118
- :class:`RandomAxisPartitionAR`— random_axis_partition_all_reduce_strategy.py:96-141
- :class:`Parallax`             — parallax_strategy.py:38-70

plus the cost-model-driven selector (the upstream ``simulator/``
package's role):

- :class:`AutoStrategy` — simulates every candidate above with
  :mod:`autodist_tpu.simulator` and returns the predicted-cheapest plan
  that fits the memory budget.

Builders only *choose* per-variable synchronization/partitioning/placement;
the lowering to mesh shardings and collectives happens in
:mod:`autodist_tpu.parallel.compiler`.
"""
from math import ceil

import numpy as np

from autodist_tpu.const import ENV
from autodist_tpu.utils import logging
from autodist_tpu.strategy.base import (
    AllReduceSynchronizer, PSSynchronizer, Strategy, StrategyBuilder,
    StrategyNode, byte_size_load_fn)


def replica_devices(resource_spec):
    """Replica device list: accelerators, else the node's CPUs
    (reference all_reduce_strategy.py:52-56)."""
    reps = [k for k, _ in resource_spec.accelerator_devices]
    accel_nodes = {d.host_address
                   for _, d in resource_spec.accelerator_devices}
    for node, cpus in resource_spec.node_cpu_devices.items():
        if node not in accel_nodes:
            reps.extend(cpus)
    return reps


# shard-count rules live with the partitioner math
# (kernels/partitioner.py mirrors reference kernel/partitioner.py)
from autodist_tpu.kernels.partitioner import (                   # noqa: E402
    smallest_non_divisor as _smallest_non_divisor,
    smallest_nontrivial_divisor as _smallest_nontrivial_divisor)


class PS(StrategyBuilder):
    """All variables on a single parameter server (the first CPU device)."""

    def __init__(self, local_proxy_variable=False, sync=True, staleness=0,
                 shared_optimizer=False, local_steps=1):
        self._local_proxy_variable = local_proxy_variable
        self._sync = sync
        self._staleness = staleness
        self._shared_optimizer = shared_optimizer
        self._local_steps = local_steps

    def build(self, graph_item, resource_spec):
        s = Strategy()
        s.graph_config.replicas = replica_devices(resource_spec)
        reduction_device = next(iter(resource_spec.cpu_devices))[0]
        for var in graph_item.trainable_var_op_to_var.values():
            s.node_config.append(StrategyNode(
                var_name=var.name,
                synchronizer=PSSynchronizer(
                    reduction_destination=reduction_device,
                    local_replication=self._local_proxy_variable,
                    sync=self._sync,
                    staleness=self._staleness,
                    shared_optimizer=self._shared_optimizer,
                    local_steps=self._local_steps)))
        return s


class PSLoadBalancing(StrategyBuilder):
    """Greedy byte-size bin-packing of variables onto all PS devices."""

    def __init__(self, local_proxy_variable=False, sync=True, staleness=0,
                 shared_optimizer=False, local_steps=1):
        self._local_proxy_variable = local_proxy_variable
        self._sync = sync
        self._staleness = staleness
        self._shared_optimizer = shared_optimizer
        self._local_steps = local_steps
        self.loads = {}

    def build(self, graph_item, resource_spec):
        s = Strategy()
        s.graph_config.replicas = replica_devices(resource_spec)
        self.loads = {k: 0.0 for k, _ in resource_spec.cpu_devices}
        for var in graph_item.trainable_var_op_to_var.values():
            s.node_config.append(self._gen_ps_node_config(var))
        return s

    def _gen_ps_node_config(self, var):
        min_ps = min(self.loads, key=self.loads.get)
        self.loads[min_ps] += byte_size_load_fn(var)
        return StrategyNode(
            var_name=var.name,
            synchronizer=PSSynchronizer(
                reduction_destination=min_ps,
                local_replication=self._local_proxy_variable,
                sync=self._sync,
                staleness=self._staleness,
                shared_optimizer=self._shared_optimizer,
                local_steps=self._local_steps))


class PartitionedPS(StrategyBuilder):
    """Axis-0 partitioning onto load-balanced PSes."""

    def __init__(self, local_proxy_variable=False, sync=True, staleness=0,
                 shared_optimizer=False, local_steps=1):
        self._local_proxy_variable = local_proxy_variable
        self._sync = sync
        self._staleness = staleness
        self._shared_optimizer = shared_optimizer
        self._local_steps = local_steps
        self.loads = {}

    def build(self, graph_item, resource_spec):
        s = Strategy()
        s.graph_config.replicas = replica_devices(resource_spec)
        self.loads = {k: 0.0 for k, _ in resource_spec.cpu_devices}
        for var in graph_item.trainable_var_op_to_var.values():
            s.node_config.append(self._gen_node_config(var))
        return s

    def get_num_shards(self, var):
        if len(var.shape) == 0:
            return 1
        return _smallest_nontrivial_divisor(int(var.shape[0]))

    def _gen_node_config(self, var):
        if len(self.loads) <= 1 and not ENV.AUTODIST_IS_TESTING.val:
            num_shards = 1       # single PS: don't partition (ref :81-87)
        else:
            num_shards = self.get_num_shards(var)
        sorted_ps = sorted(self.loads, key=self.loads.get)
        if num_shards > len(sorted_ps):
            sorted_ps = sorted_ps * ceil(num_shards / len(sorted_ps))
        targets = sorted_ps[:num_shards]
        for ps in targets:
            self.loads[ps] += byte_size_load_fn(var) / num_shards

        def ps_sync(dest):
            return PSSynchronizer(
                reduction_destination=dest,
                local_replication=self._local_proxy_variable,
                sync=self._sync, staleness=self._staleness,
                shared_optimizer=self._shared_optimizer,
                local_steps=self._local_steps)

        if num_shards == 1:
            return StrategyNode(var_name=var.name,
                                synchronizer=ps_sync(targets[0]))
        partition_list = [1] * max(len(var.shape), 1)
        partition_list[0] = min(num_shards, int(var.shape[0]))
        return StrategyNode(
            var_name=var.name,
            partitioner=','.join(str(p) for p in partition_list),
            part_config=[ps_sync(t) for t in targets])


class UnevenPartitionedPS(PartitionedPS):
    """Same placement, but shard count = smallest non-divisor so shard
    sizes are uneven (exercises uneven-split paths)."""

    def get_num_shards(self, var):
        if len(var.shape) == 0:
            return 1
        return _smallest_non_divisor(int(var.shape[0]))


class AllReduce(StrategyBuilder):
    """All dense variables via grouped collective all-reduce."""

    def __init__(self, chunk_size=128, all_reduce_spec='AUTO',
                 compressor='NoneCompressor', hierarchical='auto',
                 weight_update_sharding='never'):
        if chunk_size < 1:
            raise ValueError('The chunk_size must be greater than zero.')
        self.chunk_size = chunk_size
        self.all_reduce_spec = all_reduce_spec
        self.compressor = compressor
        self.hierarchical = hierarchical
        self.weight_update_sharding = weight_update_sharding

    def build(self, graph_item, resource_spec):
        s = Strategy()
        s.graph_config.replicas = replica_devices(resource_spec)
        for i, var in enumerate(
                graph_item.trainable_var_op_to_var.values()):
            s.node_config.append(StrategyNode(
                var_name=var.name,
                synchronizer=AllReduceSynchronizer(
                    spec=self.all_reduce_spec,
                    compressor=self.compressor,
                    group=i // self.chunk_size,
                    chunk_size=self.chunk_size,
                    hierarchical=self.hierarchical,
                    weight_update_sharding=self.weight_update_sharding)))
        return s


class PartitionedAR(StrategyBuilder):
    """Axis-0 partitioning, each shard synced by all-reduce."""

    def __init__(self, chunk_size=128, all_reduce_spec='AUTO',
                 compressor='NoneCompressor', hierarchical='auto',
                 weight_update_sharding='never'):
        self.chunk_size = chunk_size
        self.all_reduce_spec = all_reduce_spec
        self.compressor = compressor
        self.hierarchical = hierarchical
        self.weight_update_sharding = weight_update_sharding

    def build(self, graph_item, resource_spec):
        s = Strategy()
        s.graph_config.replicas = replica_devices(resource_spec)
        counter = 0
        for var in graph_item.trainable_var_op_to_var.values():
            node, used = self._gen_node_config(var, counter)
            counter += used
            s.node_config.append(node)
        return s

    def _num_shards_and_axis(self, var, graph_item=None):
        if len(var.shape) == 0:
            return 1, 0
        return _smallest_nontrivial_divisor(int(var.shape[0])), 0

    def _gen_node_config(self, var, counter):
        num_shards, axis = self._num_shards_and_axis(var)

        def ar(i):
            return AllReduceSynchronizer(
                spec=self.all_reduce_spec, compressor=self.compressor,
                group=(counter + i) // self.chunk_size,
                chunk_size=self.chunk_size,
                hierarchical=self.hierarchical,
                weight_update_sharding=self.weight_update_sharding)

        if num_shards <= 1:
            return StrategyNode(var_name=var.name,
                                synchronizer=ar(0)), 1
        partition_list = [1] * len(var.shape)
        partition_list[axis] = num_shards
        return StrategyNode(
            var_name=var.name,
            partitioner=','.join(str(p) for p in partition_list),
            part_config=[ar(i) for i in range(num_shards)]), num_shards


class RandomAxisPartitionAR(PartitionedAR):
    """Partition along a random non-1 axis (axis 0 forced for sparse)."""

    def __init__(self, chunk_size=128, seed=None, **kwargs):
        super().__init__(chunk_size, **kwargs)
        self._rng = np.random.RandomState(seed)
        self._graph_item = None

    def build(self, graph_item, resource_spec):
        self._graph_item = graph_item
        return super().build(graph_item, resource_spec)

    def _num_shards_and_axis(self, var, graph_item=None):
        if len(var.shape) == 0:
            return 1, 0
        non_one = [i for i, d in enumerate(var.shape) if d > 1]
        if not non_one:
            return 1, 0
        if self._graph_item is not None and \
                self._graph_item.is_sparse(var):
            axis = 0
        else:
            axis = non_one[int(self._rng.randint(0, len(non_one)))]
        return _smallest_nontrivial_divisor(int(var.shape[axis])), axis


class AutoStrategy(StrategyBuilder):
    """Cost-model-driven selector: simulate, rank, pick (the tenth
    builder — the reference paper's *automatic* strategy synthesis).

    ``build()`` enumerates candidate strategies (every concrete builder
    plus its chunk_size / compressor / partition knobs), prices each
    with the α-β cost model over the resource spec's ICI/DCN topology
    hints, prunes candidates whose predicted per-device peak bytes
    exceed ``memory_budget_bytes``, and returns the cheapest remaining
    plan. The prediction rides on ``Strategy.cost``.

    Args:
        memory_budget_bytes: per-device memory budget; candidates
            predicted above it are pruned. None = no pruning.
        optimizer_slots: f32 optimizer slot tensors per param for the
            memory estimate (2 = Adam, 1 = momentum SGD, 0 = SGD).
        candidates: override ``[(name, builder_factory)]`` list
            (default :func:`simulator.search.default_candidates`).
        cost_params: :class:`CostModelParams` override (e.g. from a
            previous calibration).
        trace_dir: optional profiler trace of a short real run; α-β
            constants are refined from its collective timeline before
            ranking (measured mode). Degrades to analytic constants
            when the trace has no collectives (CPU fallback).
        num_replicas: override the replica count the simulator prices
            (default: the spec's accelerator count).
        sparse_lookups_per_replica: expected embedding rows one replica
            looks up per step — batch-derived (pass the per-replica
            batch size, or batch x ids-per-example); prices sparse
            variables' PS traffic by touched rows instead of full size.
        drift_table: entry-labeled drift table from the roofline
            observatory (``telemetry.roofline.drift_table``, or a
            BENCH record's ``roofline.drift`` block — it carries the
            samples). Preferred over ``trace_dir``: tiers are labeled
            by schedule entry rather than the replica-groups
            heuristic, and samples carry full buffer bytes, so the
            refit β is exact for reduce-scatter/all-gather rows too
            (``calibrate.calibrate_from_drift``).
    """

    def __init__(self, memory_budget_bytes=None, optimizer_slots=2,
                 candidates=None, cost_params=None, trace_dir=None,
                 num_replicas=None, sparse_lookups_per_replica=4096,
                 drift_table=None):
        self._budget = memory_budget_bytes
        self._optimizer_slots = optimizer_slots
        self._candidates = candidates
        self._cost_params = cost_params
        self._trace_dir = trace_dir
        self._num_replicas = num_replicas
        self._sparse_lookups = sparse_lookups_per_replica
        # entry-labeled drift table from a previous run's roofline
        # observatory (telemetry.roofline.drift_table): preferred over
        # trace_dir — its samples are tier-labeled by schedule entry
        # (not the replica-groups heuristic) and carry full buffer
        # bytes (not HLO result shapes), so the refit β is exact for
        # reduce-scatter/all-gather rows too
        self._drift_table = drift_table
        # populated by build() for audits / bench reporting
        self.last_ranked = []
        self.last_infeasible = []

    def build(self, graph_item, resource_spec):
        from autodist_tpu.simulator import search
        from autodist_tpu.simulator.calibrate import (
            calibrate_from_drift, calibrate_from_trace)
        from autodist_tpu.simulator.cost_model import CostModelParams

        n = self._num_replicas
        if n is None:
            n = len(replica_devices(resource_spec))
        params = self._cost_params or CostModelParams.from_topology(
            resource_spec.topology)
        if self._drift_table is not None:
            from autodist_tpu.simulator.cost_model import num_node_groups
            k = num_node_groups(resource_spec=resource_spec,
                                num_replicas=n)
            params = calibrate_from_drift(
                params, self._drift_table, n,
                devices_per_node=n // k if k > 1 else n)
        elif self._trace_dir:
            from autodist_tpu.simulator.cost_model import num_node_groups
            k = num_node_groups(resource_spec=resource_spec,
                                num_replicas=n)
            params = calibrate_from_trace(
                params, self._trace_dir, n,
                cross_node=resource_spec.topology.multi_node,
                devices_per_node=n // k if k > 1 else 0)
        feasible, infeasible = search.rank(
            graph_item, resource_spec, candidates=self._candidates,
            memory_budget_bytes=self._budget, params=params,
            num_replicas=n, optimizer_slots=self._optimizer_slots,
            sparse_lookups_per_replica=self._sparse_lookups)
        self.last_ranked = feasible
        self.last_infeasible = infeasible
        if not feasible:
            detail = '; '.join('%s (%s)' % (c.name, c.error)
                               for c in infeasible[:4])
            if self._budget is not None and any(
                    c.report is not None for c in infeasible):
                msg = ('no candidate fits the %d-byte memory budget '
                       'over %d replicas' % (self._budget, n))
            else:
                msg = ('every candidate failed to build over %d '
                       'replicas' % n)
            raise ValueError('AutoStrategy: %s: %s'
                             % (msg, detail or 'no candidates'))
        best = feasible[0]
        logging.info('AutoStrategy picked %s (predicted step %.4g ms, '
                     'peak %.1f MiB) over %d feasible / %d pruned',
                     best.name,
                     best.report.predicted_step_time_s * 1e3,
                     best.report.predicted_peak_bytes / (1 << 20),
                     len(feasible), len(infeasible))
        return best.strategy


class Parallax(StrategyBuilder):
    """Hybrid: dense vars → AllReduce, sparse vars → load-balanced PS
    (arXiv:1808.02621; parallax_strategy.py:38-70)."""

    def __init__(self, chunk_size=128, local_proxy_variable=False,
                 sync=True, staleness=0, all_reduce_spec='AUTO',
                 compressor='NoneCompressor', shared_optimizer=False,
                 hierarchical='auto', weight_update_sharding='never',
                 local_steps=1):
        self.chunk_size = chunk_size
        self.all_reduce_spec = all_reduce_spec
        self.compressor = compressor
        self.hierarchical = hierarchical
        self.weight_update_sharding = weight_update_sharding
        self._local_proxy_variable = local_proxy_variable
        self._sync = sync
        self._staleness = staleness
        self._shared_optimizer = shared_optimizer
        self._local_steps = local_steps

    def build(self, graph_item, resource_spec):
        s = Strategy()
        s.graph_config.replicas = replica_devices(resource_spec)
        loads = {k: 0.0 for k, _ in resource_spec.cpu_devices}
        dense_count = 0
        for var in graph_item.trainable_var_op_to_var.values():
            if graph_item.is_sparse(var):
                min_ps = min(loads, key=loads.get)
                loads[min_ps] += byte_size_load_fn(var)
                s.node_config.append(StrategyNode(
                    var_name=var.name,
                    synchronizer=PSSynchronizer(
                        reduction_destination=min_ps,
                        local_replication=self._local_proxy_variable,
                        sync=self._sync, staleness=self._staleness,
                        shared_optimizer=self._shared_optimizer,
                        local_steps=self._local_steps)))
            else:
                s.node_config.append(StrategyNode(
                    var_name=var.name,
                    synchronizer=AllReduceSynchronizer(
                        spec=self.all_reduce_spec,
                        compressor=self.compressor,
                        group=dense_count // self.chunk_size,
                        chunk_size=self.chunk_size,
                        hierarchical=self.hierarchical,
                        weight_update_sharding=self.
                        weight_update_sharding)))
                dense_count += 1
        return s
