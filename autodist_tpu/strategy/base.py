"""Strategy representation, builder ABC, and compiler.

Mirrors the reference strategy language (``autodist/proto/strategy.proto:
30-69``, ``synchronizers.proto:24-56``, ``autodist/strategy/base.py``):
per-variable ``Node{var_name, synchronizer, partitioner, part_config[]}``
plus a ``GraphConfig{replicas[]}``. Serialization is JSON on disk under
``/tmp/autodist_tpu/strategies/<id>`` (reference serializes protobuf under
``/tmp/autodist/strategies``, base.py:78-99).

The TPU compiler step (reference ``StrategyCompiler``, base.py:120-168)
resolves abstract device strings and additionally binds each node to a
``jax.sharding`` PartitionSpec over the framework mesh — that binding is
performed later by :mod:`autodist_tpu.parallel.compiler`; here we keep the
strategy hardware-agnostic.
"""
import hashlib
import json
import os
import uuid
from dataclasses import dataclass, field, asdict

from autodist_tpu.const import DEFAULT_SERIALIZATION_DIR
from autodist_tpu.utils import logging


# -- synchronizer configs (synchronizers.proto parity) ----------------------

@dataclass
class PSSynchronizer:
    """Parameter-server-style sync (synchronizers.proto:24-37).

    On TPU this lowers to sharded-state (ZeRO-like) updates: gradients are
    reduce-scattered to the shard owner(s) given by ``reduction_destination``
    and updated parameters are all-gathered — push/pull without a literal
    server. ``sync=False`` / ``staleness>0`` engage the bounded-staleness
    pipeline (delayed gradient application windows).

    ``hierarchical`` governs the two-level lowering of the ZeRO halves
    (the gradient reduce-scatter and the param all-gather) on
    multi-node meshes, routed through the same
    ``cost_model.choose_hierarchical`` decision the AR buckets use:
    'auto' (default — the cost model decides per emission), 'never'
    (always the flat collective) or 'always'. Legacy strategies
    deserialize to 'auto'; single-node meshes are the degenerate flat
    case either way.
    """
    reduction_destination: str = ''
    local_replication: bool = False
    sync: bool = True
    staleness: int = 0
    hierarchical: str = 'auto'    # auto | never | always
    # loose mode: run the optimizer step ON the PS with service-resident
    # slot state shared by all workers (the reference re-creates the
    # optimizer over PS-resident variables, kernel/partitioner.py:570-573,
    # and places the update op on the PS, ps_synchronizer.py:175-176).
    # Supported for the SGD family (plain/momentum); other optimizers
    # fall back to worker-local slots with a logged note.
    shared_optimizer: bool = False
    # local-SGD window length H: workers take H local optimizer steps,
    # then push the AVERAGED parameter delta accumulated over the window
    # (delta/num_workers, so the merged PS state lands on the mean of
    # the workers' windows — a raw sum overshoots by the worker count)
    # and pull the merged state. 1 (default, and what legacy strategies
    # deserialize to) is today's every-step loose push, bit-identical.
    # Only the loose PS data plane honors H>1; shared_optimizer is
    # incompatible (the PS-resident update consumes per-step deltas).
    local_steps: int = 1
    kind: str = 'PS'


@dataclass
class AllReduceSynchronizer:
    """Collective all-reduce sync (synchronizers.proto:40-56).

    ``spec`` picks the collective lowering: AUTO lets XLA choose the ICI
    algorithm (the NCCL/RING distinction of the reference collapses into
    XLA's scheduler); RING forces a ppermute ring (useful cross-slice).
    ``compressor`` names a gradient compressor class; ``group`` merges
    same-group variables into one fused collective (reference: scoped
    allocator; here: concatenated flat-bucket all-reduce).
    ``chunk_size`` carries the builder's grouping bound so the execution
    plan can derive its per-bucket byte cap (parallel/plan.py): fused
    groups are further packed into byte-capped buckets so collectives
    overlap the backward pass instead of serializing behind it. 0 means
    "unspecified" (legacy strategies) and falls back to
    const.DEFAULT_CHUNK_SIZE.
    ``hierarchical`` governs two-level (intra-node reduce-scatter ->
    inter-node all-reduce -> intra-node all-gather) bucket emission on
    multi-node meshes: 'auto' (default — the simulator's cost model
    decides per bucket; flat is the degenerate single-node case),
    'never' (always the flat ring) or 'always' (force two-level where
    node groups exist). Legacy strategies deserialize to 'auto'.
    ``weight_update_sharding`` governs cross-replica sharding of the
    optimizer update itself (arXiv:2004.13336): instead of every
    replica running the full update over replicated slots, the fused
    gradient bucket is reduce-SCATTERED, each replica updates its 1/n
    shard with shard-resident optimizer slots, and the updated params
    ride one bucketed all-gather — freeing ~(n-1)/n of the opt-slot
    HBM at the cost of an exposed param-phase all-gather. 'never'
    (default — the legacy replicated update), 'always', or 'auto'
    (the shared ``cost_model.choose_update_sharding`` decision prices
    the all-gather exposure against the freed memory). Only
    NoneCompressor (uncompressed-wire), non-RING buckets shard, and
    sparse-read (row-lazy) variables never do — the flat shard layout
    cannot preserve LazyAdam/LazyMomentum row semantics; the
    ``AUTODIST_WEIGHT_UPDATE_SHARDING`` env knob overrides globally.
    """
    spec: str = 'AUTO'            # AUTO | RING
    compressor: str = 'NoneCompressor'
    group: int = 0
    chunk_size: int = 0
    hierarchical: str = 'auto'    # auto | never | always
    weight_update_sharding: str = 'never'   # never | auto | always
    kind: str = 'AllReduce'


_SYNC_KINDS = {'PS': PSSynchronizer, 'AllReduce': AllReduceSynchronizer}


@dataclass
class StrategyNode:
    """Per-variable config (strategy.proto:30-55).

    ``partitioner`` is the reference's shard string, e.g. ``"2,1"`` = two
    shards along axis 0. ``part_config`` holds one synchronizer per shard.
    """
    var_name: str = ''
    synchronizer: object = None
    partitioner: str = ''
    part_config: list = field(default_factory=list)

    @property
    def num_shards(self):
        if not self.partitioner:
            return 1
        p = 1
        for s in self.partitioner.split(','):
            p *= int(s)
        return p

    @property
    def partition_axis(self):
        """The single active partition axis, or None (partitioner.py:94-150)."""
        if not self.partitioner:
            return None
        for axis, s in enumerate(self.partitioner.split(',')):
            if int(s) > 1:
                return axis
        return None


@dataclass
class GraphConfig:
    """Replica devices (strategy.proto:58-69)."""
    replicas: list = field(default_factory=list)


class Strategy:
    """A built strategy: id + per-var node configs + graph config."""

    def __init__(self, strategy_id=None):
        self.id = strategy_id or uuid.uuid4().hex[:16]
        self.path = os.path.join(DEFAULT_SERIALIZATION_DIR, self.id)
        self.node_config = []      # list[StrategyNode]
        self.graph_config = GraphConfig()
        # predicted-cost metadata attached by the simulator (AutoStrategy
        # / simulator.search): {'builder', 'predicted_step_time_s',
        # 'predicted_peak_bytes', ...}. None for hand-built strategies.
        # Rides serialization so workers and audits see what the chief
        # predicted.
        self.cost = None

    # -- (de)serialization ------------------------------------------------
    def to_dict(self):
        def enc_sync(s):
            return asdict(s) if s is not None else None

        out = {
            'id': self.id,
            'node_config': [{
                'var_name': n.var_name,
                'synchronizer': enc_sync(n.synchronizer),
                'partitioner': n.partitioner,
                'part_config': [enc_sync(p) for p in n.part_config],
            } for n in self.node_config],
            'graph_config': {'replicas': list(self.graph_config.replicas)},
        }
        if self.cost is not None:
            out['cost'] = dict(self.cost)
        return out

    @classmethod
    def from_dict(cls, d):
        def dec_sync(sd):
            if sd is None:
                return None
            return _SYNC_KINDS[sd.get('kind', 'AllReduce')](**sd)

        s = cls(strategy_id=d['id'])
        for nd in d['node_config']:
            node = StrategyNode(
                var_name=nd['var_name'],
                synchronizer=dec_sync(nd['synchronizer']),
                partitioner=nd.get('partitioner', ''),
                part_config=[dec_sync(p) for p in nd.get('part_config', [])])
            s.node_config.append(node)
        s.graph_config = GraphConfig(
            replicas=list(d['graph_config']['replicas']))
        s.cost = dict(d['cost']) if d.get('cost') is not None else None
        return s

    def serialize(self):
        """Write to disk so worker processes can load it by id."""
        os.makedirs(DEFAULT_SERIALIZATION_DIR, exist_ok=True)
        with open(self.path, 'w') as f:
            json.dump(self.to_dict(), f, sort_keys=True, indent=1)
        return self.path

    @classmethod
    def deserialize(cls, strategy_id):
        path = os.path.join(DEFAULT_SERIALIZATION_DIR, strategy_id)
        with open(path, 'r') as f:
            return cls.from_dict(json.load(f))

    def __str__(self):
        return json.dumps(self.to_dict(), indent=1, sort_keys=True)

    def __eq__(self, other):
        return isinstance(other, Strategy) and \
            self.to_dict() == other.to_dict()

    def __hash__(self):
        return hash(json.dumps(self.to_dict(), sort_keys=True))


class StrategyBuilder:
    """ABC for strategy builders (reference base.py:102-117)."""

    def build(self, graph_item, resource_spec):
        """Generate a Strategy from the captured program + cluster."""
        raise NotImplementedError


class StrategyCompiler:
    """Resolve device strings and prune stateless vars (base.py:120-168).

    The heavier mesh/sharding binding happens in
    :class:`autodist_tpu.parallel.compiler.ExecutionPlanBuilder`; this class
    keeps reference parity for the string-level compilation step.
    """

    def __init__(self, graph_item):
        self._graph_item = graph_item
        self._device_resolver = None

    def set_device_resolver(self, resolver):
        self._device_resolver = resolver
        return self

    def prune(self, strategy):
        """Drop node configs for variables this graph does not have
        (reference base.py:137-168 prunes stateless vars). Idempotent;
        callers may prune early (e.g. before the execution-mode decision)
        and still pass the result through :meth:`compile`."""
        known = set(self._graph_item.trainable_var_op_to_var.keys())
        kept = [n for n in strategy.node_config if n.var_name in known]
        dropped = [n.var_name for n in strategy.node_config
                   if n.var_name not in known]
        if dropped:
            logging.debug('Pruned stateless/unknown vars from strategy: %s',
                          dropped)
        strategy.node_config = kept
        return strategy

    def _resolve_devices(self, strategy):
        if self._device_resolver is None:
            return strategy
        strategy.graph_config.replicas = [
            self._device_resolver(d) for d in strategy.graph_config.replicas]
        for node in strategy.node_config:
            for sync in [node.synchronizer] + list(node.part_config):
                if isinstance(sync, PSSynchronizer) and \
                        sync.reduction_destination:
                    sync.reduction_destination = self._device_resolver(
                        sync.reduction_destination)
        return strategy

    def compile(self, strategy):
        strategy = self.prune(strategy)
        strategy = self._resolve_devices(strategy)
        return strategy


def byte_size_load_fn(var):
    """Estimated byte size of a variable (reference ps_lb_strategy.py:86-117)."""
    import numpy as np
    dtype = np.dtype(var.dtype)
    size = dtype.itemsize
    shape = var.shape
    if len(shape) == 0:
        return size
    if shape[0] is None:
        # unknown batch-like dim: assume a modest default like the reference
        shape = (128,) + tuple(shape[1:])
    n = 1
    for d in shape:
        n *= int(d)
    return n * size
