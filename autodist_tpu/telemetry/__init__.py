"""Unified telemetry plane: spans, metrics, cross-worker aggregation
and the crash flight recorder.

Three layers (docs/design/observability.md):

- :mod:`~autodist_tpu.telemetry.core` — the low-overhead span/event
  API and metrics registry (``AUTODIST_TELEMETRY`` gates it; disabled
  = zero-cost no-ops);
- :mod:`~autodist_tpu.telemetry.aggregate` — workers batch-push span
  records to a ``telemetry/`` namespace over the existing PS tensor
  wire; the chief assembles the cohort timeline and exports Chrome
  ``trace_event`` JSON (``tools/trace_view.py``);
- :mod:`~autodist_tpu.telemetry.flight` — the always-on bounded ring
  of control-plane events, dumped on failure triggers and replayed
  through the protocol model by
  :mod:`autodist_tpu.analysis.conformance`;
- :mod:`~autodist_tpu.telemetry.monitor` — the online performance
  sentry: a chief-side streaming consumer of the span batches issuing
  straggler verdicts with phase attribution, recording
  ``slowdown``/``recovered`` flight events, feeding the autoscale
  step-time signal and continuously recalibrating the cost model's
  link constants;
- :mod:`~autodist_tpu.telemetry.roofline` — the device-plane roofline
  observatory: per-step MFU/regime accounting from the compiled
  step's cost analysis against the topology's validated peak table,
  HBM measured-vs-estimated drift, and the per-entry
  achieved-vs-predicted collective drift table (joined on schedule
  entry ids) that ``calibrate.calibrate_from_drift`` fits.
"""
from autodist_tpu.telemetry.aggregate import (chrome_trace,
                                              collect_new_records,
                                              collect_records,
                                              decode_records,
                                              encode_records,
                                              push_records,
                                              step_timeline)
from autodist_tpu.telemetry.core import Telemetry, get, reset
from autodist_tpu.telemetry.flight import (FlightRecorder, load_dump,
                                           recorder, telemetry_dir)
from autodist_tpu.telemetry.flight import reset as reset_recorder
from autodist_tpu.telemetry.monitor import (CohortMonitor,
                                            format_snapshot,
                                            phase_medians,
                                            phase_splits)
from autodist_tpu.telemetry.roofline import (RooflineTracker,
                                             classify_regime, cost_of,
                                             drift_table,
                                             format_drift_table,
                                             memory_drift, memory_of)

__all__ = ['Telemetry', 'get', 'reset', 'FlightRecorder', 'recorder',
           'reset_recorder', 'telemetry_dir', 'load_dump',
           'encode_records', 'decode_records', 'push_records',
           'collect_records', 'collect_new_records', 'chrome_trace',
           'step_timeline', 'CohortMonitor', 'phase_splits',
           'phase_medians', 'format_snapshot', 'RooflineTracker',
           'classify_regime', 'cost_of', 'memory_of', 'memory_drift',
           'drift_table', 'format_drift_table']
