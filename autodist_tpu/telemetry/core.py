"""Low-overhead span / counter / gauge registry — the process-local
half of the telemetry plane.

Every subsystem shipped since PR 1 grew its own ad-hoc stats dict
(``ps_stats``, ``health_stats``, the bucket/overlap/sparse/health
reports in :mod:`autodist_tpu.utils.profiling`) — all worker-local,
none exportable, none captured when a run dies. This module is the
shared substrate they now feed: timed **spans** (``with tel.span(
'push_deltas', step=3):``), point **events**, monotonic **counters**,
last-value **gauges** and bounded numeric **series** (e.g. the uniform
per-step wall series ``Session.run`` records), all in one registry a
worker can snapshot (:meth:`Telemetry.metrics_snapshot`), batch-push
over the PS plane (:mod:`autodist_tpu.telemetry.aggregate`) and embed
in BENCH records.

Cost contract (the tentpole's overhead budget):

- **disabled** (``AUTODIST_TELEMETRY`` unset, the default): zero-cost
  no-ops — ``span()`` returns one shared null context manager (no
  allocation, no clock read) and every other recording call returns
  after a single attribute check;
- **enabled**: one ``perf_counter`` pair + one bounded-deque append
  per span (~3 us measured); batch pushes ride the session's
  dedicated background lane, never the step's critical path. ≤ 2%
  step time on the CPU smoke, measured by ``bench.bench_telemetry``'s
  per-record decomposition (records/step x measured record cost +
  the on-path drain share of a push — the raw on-vs-off wall delta
  is recorded as context but is scheduler noise at ms-scale steps).

Buffers are bounded (``AUTODIST_TELEMETRY_MAX_SPANS``): telemetry must
never grow without bound on a long run — old spans fall off the front
once drained batches stop being pushed.

Thread safety: recording calls take a small lock (the session's
pipeline/heartbeat threads and ``TransferPool`` workers all record);
the lock is only reached when telemetry is enabled.
"""
import threading
import time
from collections import deque

from autodist_tpu.const import ENV


class _NullSpan:
    """The disabled-path context manager: one shared instance, no
    state, so ``tel.span(...)`` costs an attribute check and nothing
    else when telemetry is off."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _Span:
    """One live span: records its duration into the registry on exit."""

    __slots__ = ('_tel', 'name', 'tags', '_t0')

    def __init__(self, tel, name, tags):
        self._tel = tel
        self.name = name
        self.tags = tags

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, *exc):
        t1 = time.perf_counter()
        if exc_type is not None:
            self.tags['error'] = exc_type.__name__
        self._tel._record_span(self.name, self._t0, t1 - self._t0,
                               self.tags)
        return False


class Telemetry:
    """The per-process telemetry registry.

    Use the module-level singleton (:func:`get`) — one registry per
    process is the point: the session's step loop, the coord client's
    RPCs and the plan's bucket emission all land in the same buffers,
    so one snapshot/batch covers the whole worker.
    """

    def __init__(self, enabled=None, max_spans=None):
        self.enabled = (ENV.AUTODIST_TELEMETRY.val
                        if enabled is None else bool(enabled))
        cap = (ENV.AUTODIST_TELEMETRY_MAX_SPANS.val
               if max_spans is None else int(max_spans))
        self._lock = threading.Lock()
        # wall anchor: span t0s are perf_counter offsets mapped onto
        # the wall clock ONCE here, so cross-worker aggregation can
        # place spans on a shared (wall) axis without per-span
        # time.time() calls on the hot path
        self._anchor_wall = time.time()
        self._anchor_perf = time.perf_counter()
        self._spans = deque(maxlen=cap)
        self._events = deque(maxlen=cap)
        # cumulative per-span-name aggregates: survive both the ring
        # bound and drain_spans (the periodic batch push), like the
        # series' count/total — the snapshot must describe the whole
        # run, not just the undrained tail
        self._span_agg = {}
        self.counters = {}
        self.gauges = {}
        self._series = {}
        self._series_cap = cap

    # -- recording ---------------------------------------------------------
    def span(self, name, **tags):
        """A timed context manager. Tags ride the record verbatim
        (keep them small scalars: step=, worker=, cmd=, bytes=)."""
        if not self.enabled:
            return _NULL_SPAN
        return _Span(self, name, tags)

    def record_span(self, name, t0, dur, **tags):
        """Record an already-measured span (``t0`` a ``perf_counter``
        value, ``dur`` seconds) — for callers that only know after the
        fact whether the interval deserves a span (e.g. ``Session.run``
        tagging only executed train steps)."""
        if not self.enabled:
            return
        self._record_span(name, t0, dur, tags)

    def _record_span(self, name, t0, dur, tags):
        rec = {'name': name,
               't0': self._anchor_wall + (t0 - self._anchor_perf),
               'dur': dur}
        if tags:
            rec['tags'] = tags
        with self._lock:
            self._spans.append(rec)
            agg = self._span_agg.setdefault(
                name, {'count': 0, 'total_s': 0.0})
            agg['count'] += 1
            agg['total_s'] += dur

    def event(self, name, **tags):
        """A point (instant) event."""
        if not self.enabled:
            return
        rec = {'name': name, 't0': self._anchor_wall +
               (time.perf_counter() - self._anchor_perf)}
        if tags:
            rec['tags'] = tags
        with self._lock:
            self._events.append(rec)

    def count(self, name, delta=1):
        """Bump a monotonic counter."""
        if not self.enabled:
            return
        with self._lock:
            self.counters[name] = self.counters.get(name, 0) + delta

    def gauge(self, name, value):
        """Set a last-value gauge."""
        if not self.enabled:
            return
        with self._lock:
            self.gauges[name] = value

    def observe(self, name, value):
        """Append to a bounded numeric series (count/total survive the
        ring bound, so means stay exact over the whole run)."""
        if not self.enabled:
            return
        with self._lock:
            s = self._series.get(name)
            if s is None:
                s = self._series[name] = {
                    'values': deque(maxlen=self._series_cap),
                    'count': 0, 'total': 0.0}
            s['values'].append(value)
            s['count'] += 1
            s['total'] += value

    # -- reading -----------------------------------------------------------
    def series_values(self, name):
        """The retained values of one series (most recent
        ``AUTODIST_TELEMETRY_MAX_SPANS``), oldest first."""
        with self._lock:
            s = self._series.get(name)
            return list(s['values']) if s else []

    def drain_spans(self):
        """Pop every buffered span + event record (the batch the
        session pushes to the PS telemetry namespace)."""
        with self._lock:
            out = list(self._spans) + list(self._events)
            self._spans.clear()
            self._events.clear()
        return out

    def metrics_snapshot(self):
        """One JSON-serializable snapshot of the whole registry:
        counters, gauges, per-series stats and per-span-name
        aggregates. Embedded in every BENCH record
        (``bench.bench_telemetry``) and in the chief's cohort
        timeline."""
        with self._lock:
            by_name = {}
            for name, agg in self._span_agg.items():
                by_name[name] = {
                    'count': agg['count'],
                    'total_s': round(agg['total_s'], 6),
                    'mean_s': round(agg['total_s'] / agg['count'], 6)}
            series = {}
            for name, s in self._series.items():
                vals = list(s['values'])
                series[name] = {
                    'count': s['count'],
                    'total': round(s['total'], 6),
                    'mean': round(s['total'] / s['count'], 6)
                    if s['count'] else 0.0,
                    'last': vals[-1] if vals else None}
            return {'enabled': self.enabled,
                    'counters': dict(self.counters),
                    'gauges': dict(self.gauges),
                    'series': series,
                    'spans': by_name,
                    'buffered_spans': len(self._spans),
                    'buffered_events': len(self._events)}


_SINGLETON = None
_SINGLETON_LOCK = threading.Lock()


def get():
    """The process-wide registry (created on first use; the enabled
    flag is read from ``AUTODIST_TELEMETRY`` at creation — tests that
    flip the env call :func:`reset`)."""
    global _SINGLETON
    tel = _SINGLETON
    if tel is None:
        with _SINGLETON_LOCK:
            tel = _SINGLETON
            if tel is None:
                tel = _SINGLETON = Telemetry()
    return tel


def reset():
    """Drop the singleton so the next :func:`get` re-reads the env
    (test/bench A/B hook; production processes never need it)."""
    global _SINGLETON
    with _SINGLETON_LOCK:
        _SINGLETON = None
