"""Cross-worker telemetry aggregation over the PS plane itself.

Workers batch-push their drained span/event records to a
``<ns>/telemetry/`` namespace on the coord service through the
EXISTING tensor wire (``vset``/``vmget`` — no new protocol command:
records serialize to JSON, the bytes ride a float32 tensor frame), and
the chief assembles a cohort-wide timeline it can export as Chrome
``trace_event`` JSON (``tools/trace_view.py``) or summarize into a
metrics block for BENCH records.

Wire format of one batch (the value under
``<ns>/telemetry/<worker>/b<seq>``):

- float32[0] = the JSON byte length ``n`` as a little-endian u32
  REINTERPRETED as float32 (a float-valued length would silently lose
  integer precision past 2^24 bytes — same trick as the i8 blockscale
  frame header);
- float32[1:] = the UTF-8 JSON bytes of the record list, zero-padded
  to a multiple of 4 and reinterpreted as float32.

Pushes and fetches pin ``wire='f32'`` explicitly: the frame is raw
bytes wearing a float costume, and a lossy session-wide wire setting
(bf16/i8) would corrupt it.

A per-worker batch counter ``<ns>/telemetry/<worker>/batches`` makes
collection race-free without listing keys: the chief reads the counter
(delta-0 ``INCR``) and fetches ``b1..bN``. Keys live INSIDE the run
namespace on purpose — run-end purge cleans them up with everything
else, so the chief collects before bumping its ``closed`` counter.
"""
import json

import numpy as np

from autodist_tpu.utils import logging


def encode_records(records):
    """Record list -> the float32 batch tensor described above."""
    import struct
    raw = json.dumps(records, separators=(',', ':')).encode('utf-8')
    pad = (-len(raw)) % 4
    buf = struct.pack('<I', len(raw)) + raw + b'\0' * pad
    return np.frombuffer(buf, dtype='<f4').copy()


def decode_records(arr):
    """The inverse of :func:`encode_records` (None/empty -> [])."""
    if arr is None or getattr(arr, 'size', 0) < 1:
        return []
    buf = np.ascontiguousarray(
        np.asarray(arr, dtype=np.float32)).tobytes()
    import struct
    n = struct.unpack('<I', buf[:4])[0]
    return json.loads(buf[4:4 + n].decode('utf-8'))


def push_records(client, ns, worker, records):
    """Push one batch of records for ``worker``; returns the wire
    bytes moved (0 when there was nothing to push)."""
    if not records:
        return 0
    enc = encode_records(records)
    seq = client.incr('%s/telemetry/%s/batches' % (ns, worker), 1)
    client.vset('%s/telemetry/%s/b%d' % (ns, worker, seq), enc,
                wire='f32')
    return int(enc.size * 4)


def collect_records(client, ns, workers):
    """Chief-side cohort collection: every pushed batch of every named
    worker, each record tagged with its ``worker``. Missing batches
    (a worker that never pushed, a partially-landed final batch) are
    skipped, never fatal — collection runs at close/bench time and
    must not take down the run it summarizes."""
    out = []
    for worker in workers:
        try:
            n = client.incr('%s/telemetry/%s/batches' % (ns, worker), 0)
            if not n:
                continue
            specs = [('%s/telemetry/%s/b%d' % (ns, worker, i), None)
                     for i in range(1, n + 1)]
            for arr in client.vmget(specs, wire='f32'):
                for rec in decode_records(arr):
                    rec.setdefault('worker', worker)
                    out.append(rec)
        except Exception as e:  # noqa: BLE001 - best-effort summary
            logging.warning(
                'telemetry collection for %s/%s failed: %s: %s', ns,
                worker, type(e).__name__, e)
    out.sort(key=lambda r: r.get('t0', 0.0))
    return out


def collect_new_records(client, ns, workers, cursor):
    """Incremental cohort collection for the online monitor: only the
    batches pushed SINCE the previous call, judged per worker against
    ``cursor`` (``{worker: last consumed batch seq}``, updated in
    place) — the chief polls every few steps, so re-reading the whole
    batch history each time would grow the poll cost linearly with run
    length. Never fatal; and unlike :func:`collect_records` (which
    re-reads the full range every call) a batch missing from the
    middle of the range is NOT skipped: ``push_records`` bumps the
    atomic counter BEFORE the tensor write lands, so a poll racing an
    in-flight push sees the seq but not yet the bytes — the cursor
    only advances past batches that actually decoded, and the
    consumed prefix stops at the first gap so the in-flight batch is
    retried next poll instead of dropped forever."""
    out = []
    for worker in workers:
        try:
            n = client.incr('%s/telemetry/%s/batches' % (ns, worker), 0)
            last = int(cursor.get(worker, 0))
            if n <= last:
                continue
            specs = [('%s/telemetry/%s/b%d' % (ns, worker, i), None)
                     for i in range(last + 1, n + 1)]
            consumed = last
            for seq, arr in zip(range(last + 1, n + 1),
                                client.vmget(specs, wire='f32')):
                if arr is None:
                    # counter-bumped but not yet written: stop the
                    # consumed prefix here; this and any later batch
                    # re-fetch next poll (ingestion is step-keyed, so
                    # nothing downstream double-counts either way)
                    break
                for rec in decode_records(arr):
                    rec.setdefault('worker', worker)
                    out.append(rec)
                consumed = seq
            cursor[worker] = consumed
        except Exception as e:  # noqa: BLE001 - best-effort stream
            logging.warning(
                'incremental telemetry collection for %s/%s failed: '
                '%s: %s', ns, worker, type(e).__name__, e)
    out.sort(key=lambda r: r.get('t0', 0.0))
    return out


def _worker_ordinal(worker):
    try:
        return int(str(worker).lstrip('p'))
    except ValueError:
        return abs(hash(worker)) % 10000


def chrome_trace(records, flight_events=None):
    """Cohort records -> Chrome ``trace_event`` JSON (the dict; dump
    with ``json.dump``). One trace *process* per worker (named via
    metadata events) so per-worker step spans line up as rows;
    durations become ``ph='X'`` complete events, point events
    ``ph='i'`` instants; tags (step=, cmd=, ...) ride ``args`` so the
    viewer's search finds spans by step id. ``flight_events`` (a
    flight-recorder ring) is attached as instant events on a
    ``control-plane`` thread."""
    # the zero origin comes from whatever events exist — a flight-
    # events-only trace (dump files fed to trace_view with no span
    # batches) must still start near t=0, not at the raw epoch
    stamps = [r['t0'] for r in records if r.get('t0') is not None]
    stamps += [e['wall'] for e in (flight_events or [])
               if e.get('wall') is not None]
    t_min = min(stamps) if stamps else 0.0
    events = []
    seen_pids = {}
    for rec in records:
        worker = rec.get('worker', 'p0')
        pid = _worker_ordinal(worker)
        if pid not in seen_pids:
            seen_pids[pid] = worker
            events.append({'name': 'process_name', 'ph': 'M',
                           'pid': pid, 'tid': 0,
                           'args': {'name': 'worker %s' % worker}})
        ev = {'name': rec.get('name', '?'),
              'pid': pid, 'tid': 0,
              'ts': (rec.get('t0', t_min) - t_min) * 1e6,
              'args': dict(rec.get('tags') or {})}
        if 'dur' in rec:
            ev['ph'] = 'X'
            ev['dur'] = rec['dur'] * 1e6
        else:
            ev['ph'] = 'i'
            ev['s'] = 't'
        events.append(ev)
    for e in (flight_events or []):
        events.append({
            'name': e.get('kind', '?'), 'ph': 'i', 's': 'p',
            'pid': _worker_ordinal(e.get('worker_self', 'p0')),
            'tid': 1,
            'ts': (e.get('wall', t_min) - t_min) * 1e6,
            'args': {k: v for k, v in e.items()
                     if k not in ('t', 'wall')}})
    return {'traceEvents': events, 'displayTimeUnit': 'ms'}


def step_timeline(records):
    """Cohort-wide per-step summary: ``{step: {worker: step span
    seconds}}`` over the ``step`` spans — the table the chief logs and
    BENCH embeds (per-worker step spans aligned on step ids)."""
    out = {}
    for rec in records:
        tags = rec.get('tags') or {}
        if rec.get('name') != 'step' or 'step' not in tags:
            continue
        out.setdefault(int(tags['step']), {})[
            rec.get('worker', 'p0')] = round(rec.get('dur', 0.0), 6)
    return out
