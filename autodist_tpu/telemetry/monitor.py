"""Online performance sentry: live straggler detection with phase
attribution + continuous cost-model recalibration.

PR 10 built the telemetry plane (step/phase spans, cross-worker
aggregation over the PS wire, the crash flight recorder) but nothing
consumed it ONLINE: a straggling worker was only visible post-mortem
in a Chrome trace, the autoscale policy ran on a step-time signal
nobody computed, and the simulator's α-β constants were refit only
when someone ran ``calibrate.py`` by hand. This module is the
consumer — a chief-side :class:`CohortMonitor` that streams the
existing span batches and turns them into decisions:

- **rolling robust statistics** (median/MAD) of per-worker step wall
  and per-phase splits (gate-wait / pull / compute / push / pipeline —
  the spans the session already emits), warm-up steps excluded from
  every baseline (a long XLA recompile must not read as straggling);
- **straggler verdicts with phase attribution**: the detection
  statistic is per-worker WORK time (step wall minus gate-wait) — under
  a bounded-staleness gate one slow worker inflates EVERY wall within a
  staleness window, so wall-only detection would accuse the whole
  cohort or nobody. A work-slow worker is a culprit, attributed to the
  phase carrying its excess ("86% of the excess is push ⇒ link or
  host"); a wall-slow-but-work-fast worker is an ``upstream_victim``
  (its excess is gate-wait: it is WAITING on the culprit, not causing
  the slowdown) and is never an exclude candidate;
- **slowdown / recovered flight events**: every verdict transition
  lands in the crash flight recorder ring, so a crash dump carries the
  perf context leading up to it, and
  :mod:`autodist_tpu.analysis.conformance` replays the new kinds under
  the same truncation rules as every absence-based invariant;
- **continuous recalibration**: every data-plane RPC span is a link
  sample (``t ≈ α + B·β`` — the point-to-point cost shape
  ``calibrate.fit_alpha_beta`` already inverts), so the monitor refits
  the cost model's link constants from live traffic on the
  ``AUTODIST_RECALIBRATE_EVERY`` cadence and hands measured — not
  analytic — constants to the chief's ``_replan_for_world`` re-rank.
  ``recalibrate_from_timeline`` accepts a real profiler trace's
  collective timeline for the per-tier fit when one exists.

Detection is OBSERVABILITY, never actuation: the
``AUTODIST_STRAGGLER_POLICY`` knob stops at ``advise`` (verdicts
surface in ``health_report`` with an ``exclude_candidate`` flag); the
PR 4 peer-failure policy machinery remains the sole actuator.

Surfacing: ``tools/monitor.py`` (live/offline CLI), the
``health_report`` perf section, and ``bench.bench_monitor`` (the
detection-latency / false-positive / overhead A/B in every BENCH
record). ``tools/trace_view.py --json`` renders per-phase columns
through the SAME :func:`phase_splits` implementation, pinned by a
shared test, so the CLI and the verdicts cannot drift.
"""
import statistics
import threading
import time
from collections import OrderedDict, deque

from autodist_tpu.const import ENV
from autodist_tpu.utils import logging

#: span name -> phase column. THE phase-split mapping: the monitor's
#: verdicts and ``tools/trace_view.py --json`` both read phases through
#: :func:`phase_splits`, so a renamed session span breaks one shared
#: test instead of silently desynchronizing the two consumers.
PHASE_OF = {
    'staleness_gate': 'gate',
    'pull_vars': 'pull',
    'push_deltas': 'push',
    'pipeline_wait': 'pipeline',
}

#: the derived columns, in render order ('step' is the whole wall)
PHASES = ('gate', 'pull', 'push', 'pipeline', 'compute')

#: classification per dominant excess phase. 'host_compute' is the
#: host-side default; when the worker's roofline observatory
#: (telemetry/roofline.py) has reported a device regime for it, the
#: verdict refines to 'compute_bound' / 'memory_bound' — the
#: device-plane attribution the runbook's MFU section keys on.
_CLASSIFY = {
    'gate': 'upstream_victim',      # waiting on someone else's step
    'pull': 'link_or_host',
    'push': 'link_or_host',
    'pipeline': 'link_or_host',
    'compute': 'host_compute',
}

#: roofline regime -> refined compute-phase classification
_REGIME_CLASSIFY = {'compute': 'compute_bound',
                    'memory': 'memory_bound'}


def _median(vals):
    return statistics.median(vals) if vals else 0.0


def phase_splits(records):
    """Cohort span records -> ``{worker: {step: {phase: seconds}}}``.

    One entry per (worker, step) carrying the ``step`` wall plus the
    gate / pull / push / pipeline phase durations and the derived
    ``compute`` remainder (``step`` minus the measured phases, clamped
    at zero — at pipeline depth 2 the push overlaps the next step's
    window, so the subtraction is a uniform approximation across
    workers, which is all the cross-worker EXCESS comparison needs).
    Records without a ``step`` tag or a duration are skipped.
    """
    out = {}
    for rec in records:
        tags = rec.get('tags') or {}
        if 'step' not in tags or 'dur' not in rec:
            continue
        name = rec.get('name')
        phase = 'step' if name == 'step' else PHASE_OF.get(name)
        if phase is None:
            continue
        worker = rec.get('worker') or tags.get('worker') or 'p0'
        try:
            step = int(tags['step'])
        except (TypeError, ValueError):
            continue
        d = out.setdefault(worker, {}).setdefault(step, {})
        d[phase] = d.get(phase, 0.0) + float(rec['dur'])
    for steps in out.values():
        for d in steps.values():
            if 'step' in d:
                d['compute'] = max(
                    0.0, d['step'] - sum(d.get(p, 0.0) for p in
                                         ('gate', 'pull', 'push',
                                          'pipeline')))
    return out


def phase_medians(records, warmup_steps=0):
    """Per-worker per-phase medians over cohort span records:
    ``{worker: {'steps': n, 'step': med, 'gate': med, ...}}`` — the
    aggregate columns ``tools/trace_view.py --json`` renders and the
    baseline table the monitor's attribution compares against. Steps
    at or below ``warmup_steps`` are excluded (compile noise)."""
    out = {}
    for worker, steps in phase_splits(records).items():
        rows = {st: d for st, d in steps.items() if st > warmup_steps}
        if not rows:
            continue
        agg = {'steps': len(rows)}
        for phase in ('step',) + PHASES:
            vals = [d[phase] for d in rows.values() if phase in d]
            if vals:
                agg[phase] = round(_median(vals), 6)
        out[worker] = agg
    return out


class CohortMonitor:
    """Streaming consumer of the cohort's span batches: rolling robust
    per-worker statistics, straggler verdicts with phase attribution,
    slowdown/recovered flight events, the autoscale step-time signal,
    and continuous α-β recalibration.

    Chief-side in production (:attr:`Session.monitor`); also usable
    offline — :meth:`ingest` takes any record list (``tools/
    monitor.py`` feeds it files), and ``client``/``ns``/``workers``
    are only needed for :meth:`poll`'s live incremental collection.

    Args:
        client: a :class:`CoordClient` for live polling (optional).
        ns: the run namespace live batches are pushed under.
        workers: worker-name list, or a zero-arg callable returning the
            LIVE membership (exclusions drop out of baselines).
        window: rolling-stat sample bound per worker
            (``AUTODIST_MONITOR_WINDOW``).
        detect_samples: how many most-recent samples the detection
            median uses — small so a straggler surfaces within a few
            steps of onset instead of half a window later.
        warmup_steps: steps at or below this id never enter baselines
            (compile/warm-up; the PR 6 lesson — a long recompile must
            not read as straggling).
        mad_threshold: culprit gate, in scaled MADs of the other
            workers' work times (only applied when >= 3 workers give
            the MAD meaning).
        min_ratio: culprit/victim gate as a ratio over the median of
            the OTHER workers (leave-one-out — the straggler must not
            drag its own baseline).
        min_excess_s: absolute excess floor; microsecond jitter on a
            microsecond baseline is not a slowdown.
        confirmations: consecutive detection rounds before a verdict
            ISSUES (anti-flap hysteresis): one noisy window — a
            post-compile step, a GC pause — must not fire a slowdown
            event that recovers on the next poll. Costs at most
            ``confirmations`` poll rounds of latency, well inside the
            5-step detection budget.
        policy: ``off`` | ``warn`` | ``advise``
            (``AUTODIST_STRAGGLER_POLICY``); ``off`` keeps statistics
            but issues no verdicts, ``advise`` marks non-victim
            culprits ``exclude_candidate`` in the snapshot. Detection
            never actuates either way.
        flight: the :class:`FlightRecorder` verdict transitions land
            in (default: the process singleton).
    """

    def __init__(self, client=None, ns=None, workers=None, window=None,
                 detect_samples=5, warmup_steps=2, mad_threshold=3.0,
                 min_ratio=1.5, min_excess_s=1e-3, min_samples=3,
                 confirmations=2, policy=None, flight=None,
                 local_worker=None):
        self._client = client
        self._ns = ns
        self._workers = workers
        self.window = int(window or ENV.AUTODIST_MONITOR_WINDOW.val)
        self.detect_samples = max(1, int(detect_samples))
        self.warmup_steps = int(warmup_steps)
        self.mad_threshold = float(mad_threshold)
        self.min_ratio = float(min_ratio)
        self.min_excess_s = float(min_excess_s)
        self.min_samples = max(1, int(min_samples))
        self.confirmations = max(1, int(confirmations))
        self.policy = policy if policy is not None else \
            ENV.AUTODIST_STRAGGLER_POLICY.val
        if flight is None:
            from autodist_tpu.telemetry import flight as _flight
            flight = _flight.recorder()
        self._flight = flight
        # the local worker's batches are TAPPED at drain time
        # (:meth:`ingest_local`) instead of fetched back off the wire:
        # the chief's own batches are the cohort's biggest, and
        # re-reading + JSON-decoding them every poll was the poll
        # cost's bulk. Poll skips this worker in the wire collection.
        self.local_worker = local_worker
        self._pending_local = deque(maxlen=16384)
        self._lock = threading.Lock()
        # per-worker bounded {step: seconds} maps — keyed by step so a
        # record seen twice (the chief observes its own step locally
        # AND pushes it to the wire) can never double-count
        self._walls = {}     # worker -> OrderedDict[step -> wall]
        self._phases = {}    # worker -> OrderedDict[step -> {phase: s}]
        # worker -> latest roofline record (regime, mfu, hbm_frac):
        # fed by observe_roofline (the chief's own tracker) and by
        # 'roofline' telemetry events riding the span batches (every
        # other worker's) — refines host_compute verdicts into
        # compute_bound / memory_bound
        self._roofline = {}
        self._cursor = {}    # worker -> last consumed batch seq
        self._active = {}    # worker -> live verdict dict
        self._pending = {}   # worker -> consecutive detection count
        # bounded like every other telemetry buffer (a flapping
        # borderline worker on a week-long run must not grow the
        # transition audit — and the snapshot that serializes it —
        # without bound)
        self.events = deque(maxlen=256)
        self._link_samples = deque(maxlen=max(64, 8 * self.window))
        self._params = None              # latest refit CostModelParams
        self.recalibrations = deque(maxlen=128)  # the drift trajectory
        self.last_step = 0
        self.polls = 0
        self.poll_s = 0.0                # monitor overhead accounting
        self.records_ingested = 0

    # -- ingestion ---------------------------------------------------------
    def _bounded(self, table, worker):
        d = table.setdefault(worker, OrderedDict())
        while len(d) > self.window:
            d.popitem(last=False)
        return d

    def observe_step(self, worker, step, wall):
        """Record one locally-measured step wall (the chief's own steps
        — its batches land on the wire too, but only on the push
        cadence; local observation keeps its baseline current)."""
        if step <= self.warmup_steps:
            return
        with self._lock:
            self._bounded(self._walls, worker)[int(step)] = float(wall)
            self.last_step = max(self.last_step, int(step))

    def reset_baselines(self):
        """Drop every rolling window, pending confirmation, active
        verdict and per-worker roofline regime — the batch cursor,
        link samples, recalibration state and event audit survive.
        Operators call this after a known disturbance (a replan swap,
        a membership change, a checkpoint restore) so pre-disturbance
        samples cannot seed false verdicts — or steer a
        compute/memory-bound refinement with the OLD program's regime
        — against the new steady state."""
        with self._lock:
            self._walls.clear()
            self._phases.clear()
            self._pending.clear()
            self._active.clear()
            self._roofline.clear()

    def observe_roofline(self, worker, record):
        """Record a worker's latest roofline sample
        (``RooflineTracker.observe_step``'s record): its regime
        refines that worker's compute-phase straggler verdicts into
        compute_bound / memory_bound. The chief calls this for its
        own tracker; remote workers' samples arrive as ``roofline``
        telemetry events through :meth:`ingest`."""
        if not record:
            return
        with self._lock:
            self._roofline[worker] = dict(record)

    def ingest(self, records):
        """Feed cohort span records (the ``telemetry.aggregate``
        schema): step walls and phase splits enter the rolling windows
        (warm-up steps excluded), every data-plane RPC span becomes a
        link sample for :meth:`recalibrate`, and ``roofline`` events
        update the per-worker device-regime table."""
        if not records:
            return
        splits = phase_splits(records)
        with self._lock:
            self.records_ingested += len(records)
            for rec in records:
                if rec.get('name') != 'roofline':
                    continue
                tags = rec.get('tags') or {}
                worker = rec.get('worker') or tags.get('worker')
                if worker:
                    self._roofline[worker] = dict(tags)
            for worker, steps in splits.items():
                walls = self._bounded(self._walls, worker)
                phases = self._bounded(self._phases, worker)
                for step, d in sorted(steps.items()):
                    if step <= self.warmup_steps:
                        continue
                    if 'step' in d:
                        walls[step] = d['step']
                    phases[step] = dict(phases.get(step, {}), **d)
                    self.last_step = max(self.last_step, step)
            for rec in records:
                if rec.get('name') not in ('rpc', 'rpc_batch'):
                    continue
                tags = rec.get('tags') or {}
                dur = rec.get('dur')
                frames = max(1, int(tags.get('frames', 1) or 1))
                if not dur or dur <= 0:
                    continue
                # one point-to-point transfer ≈ α + B·β: exactly the
                # 'collective-permute' cost shape the calibration
                # least-squares already inverts (group size 2 = one
                # hop). Batches amortize to per-frame samples.
                self._link_samples.append(
                    (float(tags.get('bytes', 0) or 0) / frames,
                     'collective-permute', float(dur) / frames, 2))

    def ingest_local(self, records):
        """Zero-wire tap for the local worker's just-drained batch:
        the session hands the records here at push time (they still go
        to the wire for the cohort trace), and :meth:`poll` ingests
        them without fetching + JSON-decoding them back — the local
        worker's batches are the biggest, and re-reading them was the
        poll cost's bulk. Thread-safe (the depth-2 pipeline thread
        pushes)."""
        if not records:
            return
        with self._lock:
            self._pending_local.extend(records)

    def poll(self):
        """Live incremental collection: fetch every batch pushed since
        the previous poll (per-worker cursor on the atomic batch
        counter — nothing is re-read; the local worker's batches come
        from the :meth:`ingest_local` tap instead of the wire), ingest
        it, refresh verdicts. Returns the new-record count. Wall time
        spent here accumulates on :attr:`poll_s` — the monitor's own
        overhead is part of the telemetry budget it polices."""
        if self._client is None or self._ns is None:
            raise RuntimeError('CohortMonitor.poll() needs client + ns '
                               '(offline use feeds ingest() directly)')
        t0 = time.perf_counter()
        workers = self._workers() if callable(self._workers) \
            else list(self._workers or [])
        # membership pruning: a worker gone from the LIVE list (an
        # exclusion) must not keep skewing baselines with its frozen
        # last samples — drop its windows and any open verdict
        # silently (its departure story is the exclusion machinery's,
        # not a 'recovered' transition)
        current = set(workers)
        with self._lock:
            for w in [w for w in self._walls if w not in current]:
                self._walls.pop(w, None)
                self._phases.pop(w, None)
                self._pending.pop(w, None)
                self._active.pop(w, None)
        with self._lock:
            local = list(self._pending_local)
            self._pending_local.clear()
        from autodist_tpu.telemetry.aggregate import collect_new_records
        records = collect_new_records(
            self._client, self._ns,
            [w for w in workers if w != self.local_worker],
            self._cursor)
        self.ingest(local)
        self.ingest(records)
        self.update_verdicts()
        self.polls += 1
        self.poll_s += time.perf_counter() - t0
        return len(records) + len(local)

    # -- rolling robust statistics ----------------------------------------
    def worker_stats(self):
        """Per-worker rolling statistics over the RECENT detection
        window (the last ``detect_samples`` steps): median wall,
        median WORK (wall minus gate-wait — the detection statistic),
        and per-phase medians from the same steps. Recent-window
        everywhere on purpose: the phase medians feed the verdict's
        attribution, and a full-window phase median would lag the wall
        statistic by half a window — a straggler detected 3 steps
        after onset would be attributed against mostly-healthy phase
        samples and land on the wrong phase. The full ``window`` is
        the retention bound (:meth:`snapshot` reports its size)."""
        with self._lock:
            walls = {w: dict(d) for w, d in self._walls.items()}
            phases = {w: dict(d) for w, d in self._phases.items()}
        out = {}
        for worker, d in walls.items():
            recent_steps = sorted(d)[-self.detect_samples:]
            recent_walls = [d[s] for s in recent_steps]
            ph = phases.get(worker, {})
            work = [max(0.0, d[s] - ph.get(s, {}).get('gate', 0.0))
                    for s in recent_steps]
            stat = {
                'samples': len(d),
                'last_step': max(d) if d else 0,
                'wall_s': _median(recent_walls),
                'work_s': _median(work),
                'phases': {},
            }
            for phase in PHASES:
                vals = [ph[s][phase] for s in recent_steps
                        if phase in ph.get(s, {})]
                if vals:
                    stat['phases'][phase] = _median(vals)
            out[worker] = stat
        return out

    def _attribute(self, worker, stats, phases=PHASES):
        """Excess decomposition for one worker vs the median of the
        OTHERS, per phase: shares, the dominant phase, and the
        classification the runbook keys on. ``phases`` narrows the
        decomposition — a WORK verdict attributes over the non-gate
        phases (its statistic already subtracted gate-wait; under a
        staleness gate the culprit's own gate time also inflates as
        the cohort convoys behind it, and letting that pollute the
        attribution would label every culprit a victim)."""
        mine = stats[worker]['phases']
        excess = {}
        for phase in phases:
            others = [s['phases'][phase]
                      for w, s in stats.items()
                      if w != worker and phase in s['phases']]
            if phase in mine and others:
                excess[phase] = max(0.0, mine[phase] - _median(others))
            elif phase in mine:
                excess[phase] = mine[phase]
        total = sum(excess.values())
        shares = {p: (v / total if total > 0 else 0.0)
                  for p, v in excess.items()}
        attributed = max(shares, key=shares.get) if shares else 'compute'
        return {
            'phase_excess_s': {p: round(v, 6)
                               for p, v in excess.items()},
            'phase_shares': {p: round(v, 4) for p, v in shares.items()},
            'attributed_phase': attributed,
            'classification': _CLASSIFY.get(attributed, 'link_or_host'),
        }

    def update_verdicts(self):
        """Recompute verdicts from the rolling statistics and record
        every transition (``slowdown`` on issue, ``recovered`` on
        clearance) into the flight recorder. Policy ``off`` clears and
        issues nothing; single-worker cohorts never self-accuse (there
        is no peer baseline to be slow against)."""
        if self.policy == 'off':
            return []
        stats = self.worker_stats()
        eligible = {w: s for w, s in stats.items()
                    if s['samples'] >= self.min_samples}
        verdicts = {}
        if len(eligible) >= 2:
            for worker, s in eligible.items():
                others = [o for w, o in eligible.items() if w != worker]
                v = self._judge(worker, s, others, stats)
                if v is not None:
                    verdicts[worker] = v
        # a victim presupposes a culprit: a worker whose excess is all
        # gate-wait with NO work-slow worker anywhere is waiting on
        # host tails / the input pipeline, not on a straggler — drop
        # victim (wall-statistic) verdicts in rounds where nobody is
        # actually work-slow, so an input-bound cohort never
        # self-accuses
        if not any(v['statistic'] == 'work' for v in verdicts.values()):
            verdicts = {}
        with self._lock:
            # hysteresis: a detection must repeat `confirmations`
            # consecutive rounds before it ISSUES — one noisy window
            # must not fire a slowdown that recovers next poll
            detected = set(verdicts)
            for worker in list(self._pending):
                if worker not in detected:
                    self._pending.pop(worker)
            confirmed = set(self._active)
            for worker in detected:
                if worker in self._active:
                    confirmed.add(worker)
                    continue
                n = self._pending.get(worker, 0) + 1
                self._pending[worker] = n
                if n >= self.confirmations:
                    confirmed.add(worker)
                    self._pending.pop(worker, None)
            verdicts = {w: v for w, v in verdicts.items()
                        if w in confirmed}
            now_slow = set(verdicts)
            was_slow = set(self._active)
            for worker in sorted(now_slow - was_slow):
                v = verdicts[worker]
                self._flight.record(
                    'slowdown', worker=worker, step=v['step'],
                    phase=v['attributed_phase'],
                    classification=v['classification'],
                    mad_score=v['mad_score'], ratio=v['ratio'])
                self.events.append(dict(v, kind='slowdown'))
                logging.warning(
                    'monitor: %s is slow at step %d — %.1fms vs cohort '
                    '%.1fms (%.1f MADs, ratio %.2f), %d%% of the '
                    'excess is %s ⇒ %s', worker, v['step'],
                    v['stat_s'] * 1e3, v['baseline_s'] * 1e3,
                    v['mad_score'], v['ratio'],
                    int(100 * v['phase_shares'].get(
                        v['attributed_phase'], 0.0)),
                    v['attributed_phase'], v['classification'])
            for worker in sorted(was_slow - now_slow):
                step = self.last_step
                self._flight.record('recovered', worker=worker,
                                    step=step)
                self.events.append({'kind': 'recovered',
                                    'worker': worker, 'step': step})
                logging.info('monitor: %s recovered by step %d',
                             worker, step)
                self._active.pop(worker, None)
            for worker, v in verdicts.items():
                self._active[worker] = v
            return list(self._active.values())

    def _judge(self, worker, s, others, stats):
        """One worker against the leave-one-out cohort baseline.
        Culprit: WORK time (wall minus gate-wait) beyond the ratio +
        MAD gates. Victim: wall slow but work fast — its excess is
        gate-wait, it is waiting on the culprit."""
        def gates(mine, baseline, devs):
            if baseline < 0 or mine - baseline < self.min_excess_s:
                return None, None
            ratio = mine / max(baseline, 1e-9)
            mad = 1.4826 * _median(devs) if len(devs) >= 2 else 0.0
            score = (mine - baseline) / mad if mad > 1e-12 \
                else float('inf')
            if ratio < self.min_ratio:
                return None, None
            if len(devs) >= 2 and score < self.mad_threshold:
                return None, None
            return ratio, score

        work_base = _median([o['work_s'] for o in others])
        work_devs = [abs(o['work_s'] - work_base) for o in others]
        ratio, score = gates(s['work_s'], work_base, work_devs)
        kind, stat, base = 'work', s['work_s'], work_base
        if ratio is None:
            wall_base = _median([o['wall_s'] for o in others])
            wall_devs = [abs(o['wall_s'] - wall_base) for o in others]
            ratio, score = gates(s['wall_s'], wall_base, wall_devs)
            if ratio is None:
                return None
            kind, stat, base = 'wall', s['wall_s'], wall_base
        att = self._attribute(
            worker, stats,
            phases=tuple(p for p in PHASES if p != 'gate')
            if kind == 'work' else PHASES)
        if kind == 'wall' and att['attributed_phase'] != 'gate':
            # wall-slow but neither work-slow nor gate-dominated:
            # coupled slowdown noise, not an accusable verdict
            return None
        verdict = {
            'worker': worker,
            'step': s['last_step'],
            'statistic': kind,
            'stat_s': round(stat, 6),
            'baseline_s': round(base, 6),
            'wall_s': round(s['wall_s'], 6),
            'work_s': round(s['work_s'], 6),
            'excess_s': round(stat - base, 6),
            'ratio': round(ratio, 3),
            'mad_score': round(min(score, 999.0), 2),
        }
        verdict.update(att)
        if kind == 'wall':
            verdict['classification'] = 'upstream_victim'
        elif verdict['classification'] == 'host_compute':
            # device-plane refinement: when the roofline observatory
            # has a regime for this worker, a compute-phase excess is
            # attributable to the device roofline (compute_bound /
            # memory_bound) instead of the host-side catch-all —
            # which knob acts on it differs (docs/design/roofline.md)
            roof = self._roofline.get(worker)
            regime = (roof or {}).get('roofline_regime') or \
                (roof or {}).get('regime')
            refined = _REGIME_CLASSIFY.get(regime)
            if refined:
                verdict['classification'] = refined
                verdict['roofline'] = {
                    'regime': regime,
                    'mfu': roof.get('mfu'),
                    'hbm_frac': roof.get('hbm_frac'),
                    'step': roof.get('step'),
                }
        verdict['exclude_candidate'] = bool(
            self.policy == 'advise' and
            verdict['classification'] != 'upstream_victim')
        return verdict

    def verdicts(self):
        """The currently-active verdicts (list of dicts)."""
        with self._lock:
            return [dict(v) for v in self._active.values()]

    # -- the closed loops --------------------------------------------------
    def metrics(self):
        """The autoscale policy's sampled metrics: ``step_time_s`` is
        the cohort median of per-worker recent median walls — the
        signal ``autoscale_policy(step_time_target_s=...)`` compares,
        wired via ``AutoscaleController(metrics_source=...)``."""
        stats = self.worker_stats()
        walls = [s['wall_s'] for s in stats.values() if s['samples']]
        if not walls:
            return {}
        return {'step_time_s': _median(walls),
                'straggler_verdicts': len(self._active)}

    def add_link_sample(self, nbytes, seconds, frames=1):
        """Record one measured point-to-point transfer (tests / custom
        feeds; live ingestion does this from RPC spans)."""
        frames = max(1, int(frames))
        with self._lock:
            self._link_samples.append(
                (float(nbytes) / frames, 'collective-permute',
                 float(seconds) / frames, 2))

    def recalibrate(self, base_params, num_replicas=2, cross_node=False,
                    step=None, min_link_samples=8):
        """Refit the link α-β from the accumulated live samples onto a
        copy of ``base_params`` (the tier ``cross_node`` selects — the
        same convention as ``calibrate.calibrate_from_timeline``).
        Returns the refit params (also kept as
        :meth:`calibrated_params`) or None when the fit is degenerate
        (too few samples, or all the same size), leaving the previous
        calibration in place. Every successful refit appends to
        :attr:`recalibrations` — the drift trajectory."""
        import dataclasses

        from autodist_tpu.simulator import calibrate
        with self._lock:
            samples = list(self._link_samples)
        if len(samples) < min_link_samples:
            return None
        fit = calibrate.fit_alpha_beta(samples, max(2, num_replicas))
        if fit is None:
            logging.info('monitor: recalibration fit degenerate over '
                         '%d link samples; keeping previous constants',
                         len(samples))
            return None
        alpha, beta = fit
        if cross_node:
            params = dataclasses.replace(
                base_params, alpha_dcn_s=alpha,
                beta_dcn_s_per_byte=beta, calibrated=True)
        else:
            params = dataclasses.replace(
                base_params, alpha_ici_s=alpha,
                beta_ici_s_per_byte=beta, calibrated=True)
        a0, b0 = base_params.link(cross_node=cross_node)
        rec = {'step': step if step is not None else self.last_step,
               'tier': 'DCN' if cross_node else 'ICI',
               'alpha_s': round(alpha, 9),
               'beta_s_per_byte': beta,
               'samples': len(samples),
               'beta_vs_analytic': round(beta / b0, 4) if b0 else None,
               'alpha_vs_analytic': round(alpha / a0, 4) if a0 else None}
        with self._lock:
            self._params = params
            self.recalibrations.append(rec)
        logging.info(
            'monitor: recalibrated %s tier from %d live link samples: '
            'alpha=%.3gs beta=%.3gs/B (%.2fx analytic beta)',
            rec['tier'], rec['samples'], alpha, beta,
            rec['beta_vs_analytic'] or 0.0)
        return params

    def recalibrate_from_timeline(self, base_params, timeline,
                                  num_replicas, cross_node=False,
                                  devices_per_node=0, step=None):
        """Per-tier refit from a REAL collective timeline (a captured
        profiler trace) — ``calibrate.calibrate_from_timeline`` does
        the math; the monitor keeps the result + trajectory entry like
        :meth:`recalibrate`."""
        from autodist_tpu.simulator import calibrate
        params = calibrate.calibrate_from_timeline(
            base_params, timeline, num_replicas,
            cross_node=cross_node, devices_per_node=devices_per_node)
        if not getattr(params, 'calibrated', False):
            return None
        with self._lock:
            self._params = params
            self.recalibrations.append({
                'step': step if step is not None else self.last_step,
                'tier': 'per-tier (timeline)',
                'alpha_s': params.alpha_dcn_s if cross_node
                else params.alpha_ici_s,
                'beta_s_per_byte': params.beta_dcn_s_per_byte
                if cross_node else params.beta_ici_s_per_byte,
                'samples': len(timeline or [])})
        return params

    def calibrated_params(self, default=None):
        """The latest refit :class:`CostModelParams` (``default`` when
        no refit has landed yet) — what ``_replan_for_world`` prices
        re-ranks with so growth re-plans use measured link constants."""
        with self._lock:
            return self._params if self._params is not None else default

    # -- reporting ---------------------------------------------------------
    def snapshot(self):
        """JSON-able state for ``health_report``'s perf section, BENCH
        records and the CLI: policy, per-worker rolling stats, active
        verdicts, the slowdown/recovered transition audit, the
        recalibration trajectory and the monitor's own overhead."""
        stats = self.worker_stats()
        workers = {}
        for worker, s in sorted(stats.items()):
            workers[worker] = {
                'samples': s['samples'],
                'last_step': s['last_step'],
                'wall_s': round(s['wall_s'], 6),
                'work_s': round(s['work_s'], 6),
                'phases': {p: round(v, 6)
                           for p, v in s['phases'].items()},
            }
        with self._lock:
            return {
                'policy': self.policy,
                'window': self.window,
                'warmup_steps': self.warmup_steps,
                'last_step': self.last_step,
                'workers': workers,
                'verdicts': [dict(v) for v in self._active.values()],
                'events': [dict(e) for e in self.events],
                'slowdowns': sum(1 for e in self.events
                                 if e['kind'] == 'slowdown'),
                'recoveries': sum(1 for e in self.events
                                  if e['kind'] == 'recovered'),
                'recalibrations': [dict(r)
                                   for r in self.recalibrations],
                'roofline': {w: dict(r)
                             for w, r in sorted(
                                 self._roofline.items())},
                'step_time_s': round(_median(
                    [s['wall_s'] for s in stats.values()]), 6)
                if stats else 0.0,
                'polls': self.polls,
                'poll_s': round(self.poll_s, 6),
                'records_ingested': self.records_ingested,
            }


def format_snapshot(snap):
    """Human-readable cohort table + verdicts (``tools/monitor.py``
    and chief-side logging)."""
    if not snap or not snap.get('workers'):
        return '(no monitor samples)'
    lines = ['policy=%s window=%d last_step=%d  cohort step time '
             '%.1fms' % (snap.get('policy', '?'),
                         snap.get('window', 0),
                         snap.get('last_step', 0),
                         1e3 * snap.get('step_time_s', 0.0))]
    header = ('  %-6s %6s %9s %9s' % ('worker', 'steps', 'wall', 'work')
              + ''.join(' %9s' % p for p in PHASES))
    lines.append(header)
    for worker, s in snap['workers'].items():
        row = '  %-6s %6d %8.1fms %8.1fms' % (
            worker, s['samples'], 1e3 * s['wall_s'], 1e3 * s['work_s'])
        for p in PHASES:
            v = s['phases'].get(p)
            row += ' %8.1fms' % (1e3 * v) if v is not None \
                else ' %9s' % '-'
        lines.append(row)
    for v in snap.get('verdicts', []):
        lines.append(
            '  VERDICT %s: %s %.1fms vs %.1fms (%.1f MADs, ratio '
            '%.2f) — %d%% of excess in %s ⇒ %s%s'
            % (v['worker'], v['statistic'], 1e3 * v['stat_s'],
               1e3 * v['baseline_s'], v['mad_score'], v['ratio'],
               int(100 * v['phase_shares'].get(
                   v['attributed_phase'], 0.0)),
               v['attributed_phase'], v['classification'],
               ' [exclude candidate]' if v.get('exclude_candidate')
               else ''))
    if not snap.get('verdicts'):
        lines.append('  no active verdicts')
    for r in snap.get('recalibrations', []):
        lines.append(
            '  recalibrated %s @step %s: alpha=%.3gs beta=%.3gs/B '
            '(%s samples)' % (r.get('tier'), r.get('step'),
                              r.get('alpha_s', 0.0),
                              r.get('beta_s_per_byte', 0.0),
                              r.get('samples', '?')))
    return '\n'.join(lines)
