"""Device-plane roofline observatory: per-step MFU accounting, HBM
high-water attribution and per-entry achieved-vs-predicted collective
drift.

The PR 10/11 telemetry plane observes HOST-side wall time only, so
"comms-bound vs compute-bound vs memory-bound" was a guess and the
simulator's predicted-vs-measured drift was one aggregate ratio that
could not say WHICH schedule entry is mispriced. This module is the
device-plane twin:

- **per-step MFU** (:func:`cost_of` + :func:`classify_regime` +
  :class:`RooflineTracker`): FLOPs and bytes-accessed pulled from the
  compiled step (``cost_analysis()`` on the lowered program, cached
  per compilation, graceful ``None`` degradation when the backend
  does not report), divided by the measured step wall and the
  topology's validated peak table
  (:data:`autodist_tpu.resource_spec.PEAKS_BY_KIND` /
  ``Topology.peaks()``) into an ``mfu`` + ``roofline_regime``
  (compute|memory|comms-bound) telemetry series and MFU-regression
  flight events;
- **HBM high-water attribution** (:func:`memory_of` +
  :func:`memory_drift`): ``memory_analysis()`` argument/temp bytes
  joined per variable class against
  ``cost_model.memory_footprint``'s layout-aware estimate — that
  estimate drives AutoStrategy's budget pruning, so drift here means
  WRONG PRUNING, and this makes it a number instead of folklore;
- **per-entry collective drift** (:func:`drift_table`): every traced
  bucket/chunk carries its ``static_collective_schedule`` entry id
  (``plan.assign_entry_ids``); the traced collective timeline
  (``profiling.collective_timeline``) is joined back to entries and
  reported as achieved bytes/s per link tier vs the α-β prediction —
  a per-entry drift table ``calibrate.calibrate_from_drift`` fits
  from (entry-labeled samples carry the schedule's FULL buffer bytes,
  fixing the unlabeled path's reduce-scatter result-shape mis-scale)
  and :class:`~autodist_tpu.telemetry.monitor.CohortMonitor` uses to
  extend slowdown attribution with compute/memory-bound verdicts.

Everything degrades explicitly, never silently: a CPU-fallback host
gets ``mfu: None`` with a named reason (no meaningful peak), a
trace with no device timeline gets ``achieved_s: None`` rows, and the
whole module never raises mid-bench for a missing backend feature.

Surfacing: ``tools/roofline.py`` (offline record/trace input,
``--json``), the ``roofline`` block in every BENCH record
(``bench.bench_roofline``), and the session's per-step series under
``AUTODIST_ROOFLINE`` / ``AUTODIST_ROOFLINE_EVERY``.
"""
import math
import statistics
import threading
import weakref
from collections import deque

from autodist_tpu.const import ENV
from autodist_tpu.utils import logging

# -- compiled-program introspection (graceful None degradation) -----------

#: id(program) -> cached cost dict. Entries are evicted by a weakref
#: finalizer when the program object supports one; the cache is
#: bounded in practice by the number of distinct compilations a
#: process performs (the same bound Session._cache already lives
#: under).
_COST_CACHE = {}
_COST_LOCK = threading.Lock()


def cost_of(program):
    """FLOPs + bytes-accessed of a lowered/compiled step, cached per
    compilation.

    ``program`` is anything with ``cost_analysis()`` — a
    ``jax.stages.Lowered`` (cheap: no backend compile) or a
    ``Compiled``. Returns ``{'flops': float|None,
    'bytes_accessed': float|None}``; both ``None`` when the backend
    does not report (the degradation path a CPU-fallback bench rides
    without raising). The analysis runs ONCE per program object —
    repeated per-step sampling hits the cache.
    """
    key = id(program)
    with _COST_LOCK:
        hit = _COST_CACHE.get(key)
    if hit is not None:
        return dict(hit)
    out = {'flops': None, 'bytes_accessed': None}
    try:
        cost = program.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else {}
        flops = float(cost.get('flops', 0.0) or 0.0)
        nbytes = float(cost.get('bytes accessed',
                                cost.get('bytes_accessed', 0.0)) or 0.0)
        out['flops'] = flops if flops > 0 else None
        out['bytes_accessed'] = nbytes if nbytes > 0 else None
    except Exception as e:   # noqa: BLE001 - degrade, never raise:
        # roofline accounting must not take down the step it observes
        logging.debug('roofline: cost_analysis unavailable (%s: %s)',
                      type(e).__name__, e)
    with _COST_LOCK:
        _COST_CACHE[key] = dict(out)
    try:
        weakref.finalize(program, _COST_CACHE.pop, key, None)
    except TypeError:
        pass   # not weakref-able: entry stays, bounded by compilations
    return out


_MEM_FIELDS = ('argument_size_in_bytes', 'output_size_in_bytes',
               'temp_size_in_bytes', 'alias_size_in_bytes',
               'generated_code_size_in_bytes')


def memory_of(program):
    """Per-device memory stats of a COMPILED step, or None.

    Reads ``memory_analysis()`` (XLA ``CompiledMemoryStats``):
    argument/output/temp/alias/code bytes plus a derived
    ``live_bytes`` high-water proxy (arguments + temps + outputs
    minus donated aliases — the resident set the budget pruning's
    estimate must cover). None when the backend does not report.
    """
    try:
        ma = program.memory_analysis()
    except Exception as e:   # noqa: BLE001 - degrade, never raise
        logging.debug('roofline: memory_analysis unavailable (%s: %s)',
                      type(e).__name__, e)
        return None
    if ma is None:
        return None
    out = {}
    for field in _MEM_FIELDS:
        val = getattr(ma, field, None)
        if val is not None:
            out[field] = int(val)
    if not out:
        return None
    out['live_bytes'] = (out.get('argument_size_in_bytes', 0) +
                         out.get('temp_size_in_bytes', 0) +
                         out.get('output_size_in_bytes', 0) -
                         out.get('alias_size_in_bytes', 0))
    return out


# -- regime classification -------------------------------------------------

def classify_regime(flops, bytes_accessed, wall_s, peak_flops,
                    peak_hbm_bps, comms_s=None):
    """One step's roofline record.

    ``mfu`` = flops / peak_flops / wall (the model-FLOPs-utilization
    definition bench.py's headline uses); ``hbm_frac`` the analogous
    bytes-accessed / peak-HBM fraction; ``comms_frac`` = exposed comms
    seconds / wall when the caller measured them. ``roofline_regime``
    is the largest of the computable fractions — the bound the step is
    actually pressed against — and is None (with ``regime_reason``)
    when nothing is computable. ``mfu`` is an explicit None with
    ``mfu_null_reason`` naming the missing input (cost analysis
    absent, no peak for this device kind, zero wall) — a CPU-fallback
    record is well-formed, never a crash and never a number against an
    invented denominator.
    """
    rec = {'wall_s': round(float(wall_s), 6) if wall_s else 0.0,
           'flops': flops, 'bytes_accessed': bytes_accessed,
           'mfu': None, 'hbm_frac': None, 'comms_frac': None,
           'roofline_regime': None}
    fracs = {}
    if not wall_s or wall_s <= 0:
        rec['mfu_null_reason'] = 'no measured step wall'
        rec['regime_reason'] = 'no measured step wall'
        return rec
    if flops is None:
        rec['mfu_null_reason'] = \
            'cost_analysis() reported no flops (backend does not report)'
    elif peak_flops is None:
        rec['mfu_null_reason'] = ('no peak-FLOPs table entry for this '
                                  'device kind (CPU fallback)')
    else:
        rec['mfu'] = round(flops / peak_flops / wall_s, 6)
        fracs['compute'] = rec['mfu']
    if bytes_accessed is not None and peak_hbm_bps:
        rec['hbm_frac'] = round(
            bytes_accessed / peak_hbm_bps / wall_s, 6)
        fracs['memory'] = rec['hbm_frac']
    if comms_s is not None:
        rec['comms_frac'] = round(
            min(max(float(comms_s), 0.0), wall_s) / wall_s, 6)
        fracs['comms'] = rec['comms_frac']
    if fracs:
        rec['roofline_regime'] = max(fracs, key=fracs.get)
    else:
        rec['regime_reason'] = ('neither compute nor memory peak is '
                                'computable on this backend')
    return rec


class RooflineTracker:
    """Per-step MFU/regime accounting for one worker.

    Sampled every ``every`` executed train steps
    (``AUTODIST_ROOFLINE_EVERY``): each sample classifies the step
    against the peak table (:func:`classify_regime`), lands on the
    telemetry registry (``mfu`` / ``hbm_frac`` series, the
    ``roofline_regime`` gauge) and is checked against a rolling MFU
    baseline — a sample below ``regression_frac`` of the baseline
    median records an ``mfu_regression`` flight event, so a
    mid-run efficiency cliff is post-mortem evidence, not folklore.
    The cost-analysis pull is the caller's (cached per compilation via
    :func:`cost_of`); the per-sample work here is arithmetic plus one
    bounded-deque append.
    """

    def __init__(self, peak_flops=None, peak_hbm_bps=None, every=None,
                 tel=None, flight=None, worker='p0',
                 regression_frac=0.8, baseline_window=16):
        self.peak_flops = peak_flops
        self.peak_hbm_bps = peak_hbm_bps
        self.every = max(1, int(every or ENV.AUTODIST_ROOFLINE_EVERY.val))
        if tel is None:
            from autodist_tpu.telemetry import core as _core
            tel = _core.get()
        if flight is None:
            from autodist_tpu.telemetry import flight as _flight
            flight = _flight.recorder()
        self._tel = tel
        self._flight = flight
        self.worker = worker
        self.regression_frac = float(regression_frac)
        self._baseline = deque(maxlen=max(4, int(baseline_window)))
        self.records = deque(maxlen=256)
        self.samples = 0
        self.regressions = 0

    def observe_step(self, step, wall_s, cost=None, comms_s=None):
        """Account one executed train step; returns the roofline
        record for sampled steps, None off-cadence. ``cost`` is
        :func:`cost_of`'s dict for the step's compiled program (None =
        full degradation: the record still forms, ``mfu`` explains
        itself)."""
        if step % self.every:
            return None
        cost = cost or {'flops': None, 'bytes_accessed': None}
        rec = classify_regime(cost.get('flops'),
                              cost.get('bytes_accessed'), wall_s,
                              self.peak_flops, self.peak_hbm_bps,
                              comms_s=comms_s)
        rec['step'] = int(step)
        self.records.append(rec)
        self.samples += 1
        if self._tel.enabled:
            if rec['mfu'] is not None:
                self._tel.observe('mfu', rec['mfu'])
            if rec['hbm_frac'] is not None:
                self._tel.observe('hbm_frac', rec['hbm_frac'])
            if rec['roofline_regime']:
                self._tel.gauge('roofline_regime',
                                rec['roofline_regime'])
            self._tel.count('roofline/steps_sampled')
            # the cross-worker surface: the sample rides the span
            # batches as a point event, so the chief's CohortMonitor
            # learns every worker's regime (its compute/memory-bound
            # verdict refinement), not just its own
            self._tel.event('roofline', worker=self.worker,
                            step=int(step), mfu=rec['mfu'],
                            hbm_frac=rec['hbm_frac'],
                            comms_frac=rec['comms_frac'],
                            roofline_regime=rec['roofline_regime'])
        if rec['mfu'] is not None:
            if len(self._baseline) >= 4:
                base = statistics.median(self._baseline)
                if base > 0 and rec['mfu'] < self.regression_frac * base:
                    self.regressions += 1
                    self._flight.record(
                        'mfu_regression', worker=self.worker,
                        step=int(step), mfu=rec['mfu'],
                        baseline_mfu=round(base, 6),
                        regime=rec['roofline_regime'])
                    if self._tel.enabled:
                        self._tel.count('roofline/mfu_regressions')
                    logging.warning(
                        'roofline: MFU regression at step %d — %.1f%% '
                        'vs rolling baseline %.1f%% (regime %s)',
                        step, 100 * rec['mfu'], 100 * base,
                        rec['roofline_regime'])
            self._baseline.append(rec['mfu'])
        return rec

    def snapshot(self):
        """JSON-serializable summary: latest record, rolling MFU
        median, sample/regression counts."""
        mfus = [r['mfu'] for r in self.records if r['mfu'] is not None]
        last = dict(self.records[-1]) if self.records else None
        return {'samples': self.samples,
                'regressions': self.regressions,
                'every': self.every,
                'mfu_median': round(statistics.median(mfus), 6)
                if mfus else None,
                'last': last}


# -- HBM high-water attribution -------------------------------------------

def memory_drift(measured, estimate):
    """Join measured per-device memory against the cost model's
    layout-aware estimate, per variable class.

    ``measured`` is :func:`memory_of`'s dict (or None on backends that
    do not report); ``estimate`` is
    ``cost_model.memory_footprint``'s dict. The join maps the
    estimate's classes onto what the compiled program actually
    allocates: resident state (params + optimizer slots) lives in the
    ARGUMENT buffers (donated across steps), transients (grads +
    bucket staging) in TEMP. ``drift_ratio`` is measured/estimated —
    above 1 the estimate is too low (budget pruning ADMITS configs
    that do not fit), below 1 too high (pruning REJECTS configs that
    do). Returns a well-formed record with ``available: False`` + a
    reason instead of raising when measurement is absent.
    """
    est = dict(estimate or {})
    est_state = est.get('params_bytes', 0) + est.get(
        'optimizer_bytes', 0)
    est_transient = est.get('grads_bytes', 0) + est.get(
        'bucket_staging_bytes', 0)
    out = {'available': bool(measured), 'estimated': est,
           'estimated_total_bytes': est.get(
               'total_bytes', est_state + est_transient)}
    if not measured:
        out['reason'] = ('memory_analysis() unavailable on this '
                         'backend — estimate unverified, not wrong')
        out['drift_ratio'] = None
        return out
    meas_state = measured.get('argument_size_in_bytes', 0)
    meas_transient = measured.get('temp_size_in_bytes', 0)
    meas_total = measured.get('live_bytes',
                              meas_state + meas_transient)

    def ratio(m, e):
        return round(m / e, 4) if e else None

    out['measured'] = dict(measured)
    out['measured_total_bytes'] = meas_total
    out['drift_ratio'] = ratio(meas_total,
                               out['estimated_total_bytes'])
    out['classes'] = {
        'state': {'measured_bytes': meas_state,
                  'estimated_bytes': est_state,
                  'drift_ratio': ratio(meas_state, est_state)},
        'transient': {'measured_bytes': meas_transient,
                      'estimated_bytes': est_transient,
                      'drift_ratio': ratio(meas_transient,
                                           est_transient)},
    }
    return out


# -- per-entry collective drift -------------------------------------------

#: schedule kind -> the HLO op name its flat lowering produces
_HLO_KIND = {'all_reduce': 'all-reduce',
             'psum_scatter': 'reduce-scatter',
             'all_gather': 'all-gather'}


def expected_subrows(entry, num_replicas, multi_node=False):
    """The HLO timeline rows ONE schedule entry should produce:
    ``[(hlo_kind, result_bytes, tier, group_size, full_bytes)]``.

    ``result_bytes`` is what the HLO instruction's RESULT shape
    carries (the figure ``profiling.collective_timeline`` rows parse
    to — a reduce-scatter's result is the 1/g shard, an all-gather's
    the full buffer); ``full_bytes`` the entry's full wire buffer for
    that phase, which is what an α-β fit must invert through. Flat
    entries produce one row on the tier the mesh implies (a flat
    collective spans nodes by construction on a multi-node mesh);
    two-level (``hier``) entries produce their intra/inter phases on
    the ICI/DCN tiers explicitly — the entry-label advantage over the
    replica-groups heuristic. Returns ``[]`` for entries whose
    lowering is not joinable by shape (sparse kinds are
    data-dependent; the int8 ring rides per-hop collective-permutes).
    """
    from autodist_tpu.simulator.cost_model import wire_bytes
    n = max(1, int(num_replicas))
    kind = entry['kind']
    if kind not in _HLO_KIND:
        return []
    if entry.get('compressor') == 'Int8RingCompressor':
        return []
    wb = wire_bytes(entry['bytes'], entry.get('dtype'),
                    entry.get('compressor'))
    hier = int(entry.get('hier', 0))
    flat_tier = 'dcn' if multi_node else 'ici'
    if hier <= 1:
        if kind == 'all_reduce':
            return [('all-reduce', wb, flat_tier, n, wb)]
        if kind == 'psum_scatter':
            return [('reduce-scatter', wb // n, flat_tier, n, wb)]
        return [('all-gather', wb, flat_tier, n, wb)]
    k = hier
    g = max(1, n // k)
    chunk = wb // g
    if kind == 'all_reduce':
        # intra RS (result = 1/g shard) -> inter AR over one owner per
        # node (result = the chunk) -> intra AG (result = full buffer)
        return [('reduce-scatter', chunk, 'ici', g, wb),
                ('all-reduce', chunk, 'dcn', k, chunk),
                ('all-gather', wb, 'ici', g, wb)]
    if kind == 'psum_scatter':
        # intra RS then inter RS of the owned chunk
        return [('reduce-scatter', chunk, 'ici', g, wb),
                ('reduce-scatter', chunk // k, 'dcn', k, chunk)]
    # all_gather half: inter AG of this device's chunk, then intra AG
    return [('all-gather', chunk, 'dcn', k, chunk),
            ('all-gather', wb, 'ici', g, wb)]


def _timeline_rows(timeline):
    """Parsed ``(hlo_kind, result_bytes, seconds_per_occurrence)``
    rows from a ``profiling.collective_timeline`` list (async
    ``-start`` halves dropped, like calibration)."""
    from autodist_tpu.simulator.calibrate import _result_bytes_and_kind
    rows = []
    for name, ns, cnt in timeline or []:
        bk = _result_bytes_and_kind(name)
        if bk is None or not cnt or ns <= 0:
            continue
        rows.append((bk[1], bk[0], ns / 1e9 / cnt))
    return rows


def _subrow_link_model(hlo_kind, group, full_b, tier, params):
    """(wire bytes moved, predicted seconds) of ONE expected
    sub-collective under the BARE link model — the exact hop/byte
    multipliers ``calibrate._kind_factors`` gives ``fit_alpha_beta``
    (one source: a factor tweak landing in calibrate alone cannot
    silently diverge the tier view from the fit that consumes its
    samples). Deliberately α-β phases only, no HBM-pass terms: the
    tier aggregate grades the LINK constants the calibration refits,
    while the per-entry ``predicted_s`` column keeps the full
    ``cost_model.entry_time`` model (boundary/cast/quantize passes
    included)."""
    from autodist_tpu.simulator.calibrate import _kind_factors
    m = max(2, int(group))
    hops, frac = _kind_factors(hlo_kind, m)
    alpha, beta = params.link(cross_node=(tier == 'dcn'))
    return frac * full_b, hops * alpha + frac * full_b * beta


def drift_table(schedule, timeline, num_replicas, params=None,
                multi_node=False, match_tolerance=4.0):
    """Join a traced collective timeline back to schedule entries —
    the per-entry achieved-vs-predicted drift table.

    Args:
        schedule: ``static_collective_schedule`` entries (with
            ``entry_id``; re-stamped here if absent).
        timeline: ``profiling.collective_timeline`` rows from the same
            run's trace (empty = every entry degrades to
            ``achieved_s: None``, explicitly).
        num_replicas, multi_node: the mesh shape the schedule ran on.
        params: :class:`CostModelParams` for the predicted column
            (analytic defaults when None).
        match_tolerance: max result-bytes ratio between a timeline row
            and the sub-row it may satisfy (greedy nearest-size match
            per HLO kind — bucket layouts differ by construction, so
            exact-size joins would be brittle across padding).

    Returns ``{'entries': [...], 'tiers': {...}, 'matched_rows',
    'unmatched_rows', 'worst_drift_ratio', 'num_replicas'}``. Each
    entry row carries ``entry_id`` (round-trips to the static
    schedule), predicted seconds (``cost_model.entry_time`` — the
    SAME pricing ``predict()`` sums), achieved seconds (None +
    ``note`` when unjoinable), ``drift_ratio`` = achieved/predicted,
    and the per-phase tier labels. ``tiers`` aggregates achieved vs
    predicted bytes/s per link class over the MATCHED sub-rows only
    (both sides of the ratio cover the same row set — a trace missing
    an entry must not skew the tier view) under the bare α-β link
    model (:func:`_subrow_link_model`, the same factors the
    calibration fit inverts); the per-entry ``predicted_s`` column
    keeps the full :func:`cost_model.entry_time` model. The
    ``samples`` are what ``calibrate.calibrate_from_drift`` fits.
    """
    from autodist_tpu.parallel.plan import assign_entry_ids
    from autodist_tpu.simulator.cost_model import (CostModelParams,
                                                   entry_time)
    if params is None:
        params = CostModelParams()
    n = max(1, int(num_replicas))
    schedule = [dict(e) for e in schedule]
    if any('entry_id' not in e for e in schedule):
        assign_entry_ids(schedule)
    rows = _timeline_rows(timeline)
    unmatched = [True] * len(rows)
    out_entries = []
    tier_acc = {'ici': {'wire_bytes': 0.0, 'seconds': 0.0,
                        'predicted_seconds': 0.0, 'rows': 0},
                'dcn': {'wire_bytes': 0.0, 'seconds': 0.0,
                        'predicted_seconds': 0.0, 'rows': 0}}
    samples = []   # entry-labeled (tier, full_bytes, hlo_kind, s, group)
    worst = None
    for e in schedule:
        predicted_s, wb = entry_time(e, n, params,
                                     cross_node=multi_node)
        row = {'entry_id': e['entry_id'], 'kind': e['kind'],
               'phase': e.get('phase'), 'vars': e.get('vars'),
               'bytes': e.get('bytes'), 'wire_bytes': wb,
               'hier': int(e.get('hier', 0)),
               'compressor': e.get('compressor'),
               'predicted_s': round(predicted_s, 9),
               'achieved_s': None, 'drift_ratio': None,
               'achieved_bytes_per_s': None, 'tiers': []}
        subrows = expected_subrows(e, n, multi_node=multi_node)
        if not subrows:
            row['note'] = ('not joinable by result shape (sparse '
                           'kinds are data-dependent; the int8 ring '
                           'rides per-hop collective-permutes)')
            out_entries.append(row)
            continue
        achieved = 0.0
        moved = 0.0
        matched = 0
        for hlo_kind, result_b, tier, group, full_b in subrows:
            row['tiers'].append(tier)
            best, best_err = None, None
            for j, (rk, rb, _) in enumerate(rows):
                if not unmatched[j] or rk != hlo_kind or rb <= 0 \
                        or result_b <= 0:
                    continue
                err = abs(math.log(rb / result_b))
                if err <= math.log(match_tolerance) and \
                        (best is None or err < best_err):
                    best, best_err = j, err
            if best is None:
                continue
            unmatched[best] = False
            matched += 1
            t = rows[best][2]
            achieved += t
            frac_bytes, pred_t = _subrow_link_model(
                hlo_kind, group, full_b, tier, params)
            moved += frac_bytes
            # MATCHED sub-rows only, on both sides of the divide: a
            # partially-joined trace must compare achieved and
            # predicted over the same row set, or the tier ratio is
            # skewed by exactly the entries the trace missed
            acc = tier_acc[tier]
            acc['wire_bytes'] += frac_bytes
            acc['seconds'] += t
            acc['predicted_seconds'] += pred_t
            acc['rows'] += 1
            samples.append((tier, full_b, hlo_kind, t, group))
        if matched == len(subrows) and achieved > 0:
            row['achieved_s'] = round(achieved, 9)
            row['drift_ratio'] = round(achieved / predicted_s, 4) \
                if predicted_s > 0 else None
            row['achieved_bytes_per_s'] = round(moved / achieved, 1)
            if row['drift_ratio'] is not None and \
                    (worst is None or row['drift_ratio'] > worst):
                worst = row['drift_ratio']
        elif matched:
            row['note'] = ('partial join: %d of %d phases matched '
                           'in the trace' % (matched, len(subrows)))
        else:
            row['note'] = 'no matching timeline rows in the trace'
        out_entries.append(row)
    tiers = {}
    for tier, acc in tier_acc.items():
        if not acc['rows']:
            continue
        tiers[tier] = {
            'rows': acc['rows'],
            'wire_bytes': int(acc['wire_bytes']),
            'achieved_bytes_per_s': round(
                acc['wire_bytes'] / acc['seconds'], 1)
            if acc['seconds'] > 0 else None,
            'predicted_bytes_per_s': round(
                acc['wire_bytes'] / acc['predicted_seconds'], 1)
            if acc['predicted_seconds'] > 0 else None,
        }
    return {'entries': out_entries,
            'tiers': tiers,
            'samples': samples,
            'matched_rows': sum(1 for u in unmatched if not u),
            'unmatched_rows': sum(1 for u in unmatched if u),
            'worst_drift_ratio': worst,
            'num_replicas': n}


def format_drift_table(table, max_rows=20):
    """Human-readable rendering of :func:`drift_table`."""
    lines = ['%-44s %6s %12s %12s %8s' % ('entry', 'tier',
                                          'pred (us)', 'ach (us)',
                                          'drift')]
    lines.append('-' * len(lines[0]))
    for row in table['entries'][:max_rows]:
        ach = '%12.1f' % (row['achieved_s'] * 1e6) \
            if row['achieved_s'] is not None else '%12s' % '-'
        drift = '%8.2f' % row['drift_ratio'] \
            if row['drift_ratio'] is not None else '%8s' % '-'
        lines.append('%-44s %6s %12.1f %s %s'
                     % (row['entry_id'][:44],
                        '+'.join(sorted(set(row['tiers']))) or '-',
                        row['predicted_s'] * 1e6, ach, drift))
    extra = len(table['entries']) - max_rows
    if extra > 0:
        lines.append('  ... %d more entries' % extra)
    for tier, agg in sorted(table.get('tiers', {}).items()):
        lines.append(
            '%s: achieved %s vs predicted %s bytes/s over %d rows'
            % (tier.upper(),
               '%.3g' % agg['achieved_bytes_per_s']
               if agg['achieved_bytes_per_s'] else '-',
               '%.3g' % agg['predicted_bytes_per_s']
               if agg['predicted_bytes_per_s'] else '-', agg['rows']))
    if table.get('worst_drift_ratio') is not None:
        lines.append('worst per-entry drift: %.2fx'
                     % table['worst_drift_ratio'])
    return '\n'.join(lines)
