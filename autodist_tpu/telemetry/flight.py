"""The crash flight recorder: an always-on bounded ring buffer of
control-plane events, dumped to disk on failure triggers.

Chaos flakes die exactly when the evidence is needed: the ad-hoc stats
dicts the run kept are gone with the process, and the coord service
only holds the *current* state, not the ordering that produced it. The
flight recorder keeps the last ``AUTODIST_FLIGHT_RECORDER_EVENTS``
control-plane events (fence binds, epoch bumps, step publishes,
exclusions, admit phases, replan stage/swap) in a ring buffer — cheap
enough to leave on unconditionally (one locked deque append per event;
these are control-plane RPCs, not per-tensor hot-path work) — and
writes the ring to a JSON dump when a failure trigger fires:

- a :class:`~autodist_tpu.runtime.coord_client.FencedWriteError`
  surfacing in ``Session.run`` (this process is a zombie);
- a peer exclusion (``Session._exclude_peer`` — somebody died);
- an executed re-plan refusal or failure;
- an unclean ``Session.close()`` (a failed final push).

The dump is the input to the post-hoc conformance checker
(:mod:`autodist_tpu.analysis.conformance`), which replays the event
trace through the protocol model's invariants — closing the loop
between the PR 9 model checker and the live system: chaos tests assert
real runs produce model-conformant traces.

Event schema (one dict per event)::

    {'seq': int,        # monotone per-process sequence number
     't': float,        # perf_counter at record time
     'wall': float,     # wall clock at record time
     'kind': str,       # e.g. 'step_publish', 'exclude_claim'
     ...kind fields}    # small scalars only (worker=, step=, epoch=)

The recorder never raises out of :meth:`record` or :meth:`dump`: a
broken disk must not take down the run the recorder exists to explain.
"""
import json
import os
import threading
import time
from collections import deque

from autodist_tpu.const import DEFAULT_WORKING_DIR, ENV
from autodist_tpu.utils import logging


def telemetry_dir():
    """Where dumps and trace exports land
    (``AUTODIST_TELEMETRY_DIR``, default under the working dir)."""
    return ENV.AUTODIST_TELEMETRY_DIR.val or \
        os.path.join(DEFAULT_WORKING_DIR, 'telemetry')


class FlightRecorder:
    """Bounded ring of control-plane events + the dump trigger."""

    def __init__(self, capacity=None):
        cap = (ENV.AUTODIST_FLIGHT_RECORDER_EVENTS.val
               if capacity is None else int(capacity))
        self._lock = threading.Lock()
        self._ring = deque(maxlen=cap)
        self._seq = 0
        self._dump_seq = 0
        self._ctx = {}           # ns/worker, set by the session
        self.last_dump_path = None
        self.dumps = []          # [(reason, path)] audit

    def set_context(self, **ctx):
        """Attach run identity (``ns=``, ``worker=``, ``generation=``)
        to future dumps — the session calls this once it knows who it
        is."""
        with self._lock:
            self._ctx.update({k: v for k, v in ctx.items()
                              if v is not None})

    def record(self, kind, **fields):
        """Append one control-plane event (never raises)."""
        try:
            with self._lock:
                self._seq += 1
                ev = {'seq': self._seq, 't': time.perf_counter(),
                      'wall': time.time(), 'kind': kind}
                ev.update(fields)
                self._ring.append(ev)
        except Exception:  # noqa: BLE001 - the recorder must not kill
            pass           # the run it observes

    def events(self):
        """A snapshot of the retained ring (oldest first)."""
        with self._lock:
            return [dict(ev) for ev in self._ring]

    def dump(self, reason, path=None):
        """Write the ring to a JSON dump; returns the path (or None on
        failure — logged, never raised). Each trigger writes its OWN
        file (sequence-stamped) so a later trigger cannot overwrite
        the first failure's evidence."""
        try:
            with self._lock:
                events = [dict(ev) for ev in self._ring]
                ctx = dict(self._ctx)
                self._dump_seq += 1
                seq = self._dump_seq
            if path is None:
                os.makedirs(telemetry_dir(), exist_ok=True)
                path = os.path.join(
                    telemetry_dir(), 'flightrec-%s-%s-%d-%d.json'
                    % (ctx.get('ns', 'run'), ctx.get('worker', 'p'),
                       os.getpid(), seq))
            payload = {'reason': reason, 'dumped_at': time.time(),
                       'pid': os.getpid(), 'context': ctx,
                       'events': events}
            tmp = path + '.tmp'
            with open(tmp, 'w') as f:
                json.dump(payload, f, indent=1)
            os.replace(tmp, path)
            with self._lock:
                self.last_dump_path = path
                self.dumps.append((reason, path))
            logging.warning(
                'flight recorder: dumped %d control-plane events to %s '
                '(trigger: %s)', len(events), path, reason)
            return path
        except Exception as e:  # noqa: BLE001 - never kill the run
            logging.warning('flight recorder dump failed (%s): %s: %s',
                            reason, type(e).__name__, e)
            return None


def load_dump(path):
    """Read a dump back: ``(events, meta)`` — the conformance checker's
    input format. Raises ``ValueError`` for JSON that is not a dump
    (e.g. a span-record batch list fed to ``--conformance``), so CLI
    callers report it as a finding instead of dying on an
    AttributeError."""
    with open(path) as f:
        payload = json.load(f)
    if not isinstance(payload, dict) or 'events' not in payload:
        raise ValueError(
            'not a flight-recorder dump (expected a JSON object with '
            "an 'events' list; got %s)" % type(payload).__name__)
    events = payload.get('events', [])
    meta = {k: v for k, v in payload.items() if k != 'events'}
    return events, meta


_RECORDER = None
_RECORDER_LOCK = threading.Lock()


def recorder():
    """The process-wide flight recorder (always on)."""
    global _RECORDER
    rec = _RECORDER
    if rec is None:
        with _RECORDER_LOCK:
            rec = _RECORDER
            if rec is None:
                rec = _RECORDER = FlightRecorder()
    return rec


def reset():
    """Drop the singleton (test isolation hook)."""
    global _RECORDER
    with _RECORDER_LOCK:
        _RECORDER = None
