"""On-demand builder for the framework's native (C++) components.

Sources live in ``autodist_tpu/native/`` (inside the package so installed
wheels ship them); binaries/libraries are cached under
``/tmp/autodist-tpu/native/<source-hash>/`` so rebuilds happen only when
the source changes. Uses plain g++ (present in the supported images); a
``make``-based flow is equivalent (see autodist_tpu/native/Makefile).
"""
import hashlib
import os
import subprocess

from autodist_tpu.const import DEFAULT_WORKING_DIR
from autodist_tpu.utils import logging

NATIVE_SRC_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              'native')
NATIVE_CACHE_DIR = os.path.join(DEFAULT_WORKING_DIR, 'native')


def _src_path(name):
    return os.path.join(NATIVE_SRC_DIR, name)


def build(source_name, output_name=None, shared=False, extra_flags=()):
    """Compile ``native/<source_name>`` and return the artifact path."""
    src = _src_path(source_name)
    # -O3: the data-plane element loops (bf16 wire conversion, BADD
    # accumulate, BSTEP update rules) need the auto-vectorizer, which
    # gcc enables only at -O3; at -O2 the scalar bf16 loop was slow
    # enough to erase the wire-byte saving under multi-worker
    # contention (BASELINE.md bf16 row).
    cmd = ['g++', '-O3', '-std=c++17', '-pthread']
    if shared:
        cmd += ['-shared', '-fPIC']
    cmd += list(extra_flags)
    # cache key = source bytes AND the compile command: a flag change
    # must rebuild byte-identical sources (a warm cache otherwise
    # silently pins old-flag binaries forever)
    h = hashlib.sha256()
    with open(src, 'rb') as f:
        h.update(f.read())
    h.update('\x00'.join(cmd).encode())
    digest = h.hexdigest()[:16]
    out_name = output_name or os.path.splitext(source_name)[0]
    if shared:
        out_name += '.so'
    out_dir = os.path.join(NATIVE_CACHE_DIR, digest)
    out = os.path.join(out_dir, out_name)
    if os.path.exists(out):
        return out
    os.makedirs(out_dir, exist_ok=True)
    cmd = cmd + [src, '-o', out]
    logging.info('Building native component: %s', ' '.join(cmd))
    subprocess.run(cmd, check=True)
    return out
