"""On-demand builder for the framework's native (C++) components.

Sources live in ``autodist_tpu/native/`` (inside the package so installed
wheels ship them); binaries/libraries are cached under
``/tmp/autodist-tpu/native/<source-hash>/`` so rebuilds happen only when
the source changes. Uses plain g++ (present in the supported images); a
``make``-based flow is equivalent (see autodist_tpu/native/Makefile).
"""
import hashlib
import os
import subprocess

from autodist_tpu.const import DEFAULT_WORKING_DIR
from autodist_tpu.utils import logging

NATIVE_SRC_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              'native')
NATIVE_CACHE_DIR = os.path.join(DEFAULT_WORKING_DIR, 'native')


def _src_path(name):
    return os.path.join(NATIVE_SRC_DIR, name)


def build(source_name, output_name=None, shared=False, extra_flags=()):
    """Compile ``native/<source_name>`` and return the artifact path."""
    src = _src_path(source_name)
    with open(src, 'rb') as f:
        digest = hashlib.sha256(f.read()).hexdigest()[:16]
    out_name = output_name or os.path.splitext(source_name)[0]
    if shared:
        out_name += '.so'
    out_dir = os.path.join(NATIVE_CACHE_DIR, digest)
    out = os.path.join(out_dir, out_name)
    if os.path.exists(out):
        return out
    os.makedirs(out_dir, exist_ok=True)
    cmd = ['g++', '-O2', '-std=c++17', '-pthread']
    if shared:
        cmd += ['-shared', '-fPIC']
    cmd += list(extra_flags) + [src, '-o', out]
    logging.info('Building native component: %s', ' '.join(cmd))
    subprocess.run(cmd, check=True)
    return out
