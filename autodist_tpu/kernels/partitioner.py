"""Variable partitioning math (reference kernel/partitioner.py).

The reference's ``VariablePartitioner`` performs GraphDef surgery to
split variables/optimizer slots/gradients into shard variables
(partitioner.py:349-714). Under SPMD none of that surgery exists — a
"partitioned variable" is an array with a sharded dimension — so this
module keeps the *decision* layer with reference-compatible semantics:

- :class:`PartitionerConfig`: parse/serialize the ``"2,1"`` shard-spec
  strings (one active axis only, partitioner.py:38-150);
- shard-size computation incl. the uneven case (UnevenPartitionedPS
  splits N into k parts where k need not divide N — numpy
  ``array_split`` semantics);
- the logical<->sharded index mapping used by sparse (embedding-row)
  updates (partitioner.py:660-684 splits IndexedSlices by index range).
"""
import numpy as np


class PartitionerConfig:
    """One variable's partition spec, e.g. '4,1' = 4 shards on axis 0."""

    def __init__(self, partition_str='', partition_list=None):
        if partition_list is not None:
            self.partition_list = [int(p) for p in partition_list]
        elif partition_str:
            self.partition_list = [int(p) for p in
                                   partition_str.split(',')]
        else:
            self.partition_list = []
        active = [i for i, p in enumerate(self.partition_list) if p > 1]
        if len(active) > 1:
            raise ValueError(
                'Only one partition axis is supported (got %r)'
                % (self.partition_list,))
        self.axis = active[0] if active else None
        self.num_shards = self.partition_list[self.axis] if active else 1

    @property
    def partition_str(self):
        return ','.join(str(p) for p in self.partition_list)

    def __eq__(self, other):
        return isinstance(other, PartitionerConfig) and \
            self.partition_list == other.partition_list

    def __repr__(self):
        return '<PartitionerConfig %s>' % (self.partition_str or '1')

    # -- shard geometry ----------------------------------------------------
    def shard_sizes(self, dim_size):
        """Per-shard sizes along the active axis (uneven allowed;
        np.array_split semantics: larger shards first)."""
        if self.axis is None:
            return [int(dim_size)]
        base, rem = divmod(int(dim_size), self.num_shards)
        return [base + (1 if i < rem else 0)
                for i in range(self.num_shards)]

    def shard_offsets(self, dim_size):
        sizes = self.shard_sizes(dim_size)
        return list(np.cumsum([0] + sizes[:-1]))

    def shard_shapes(self, shape):
        if self.axis is None:
            return [tuple(shape)]
        out = []
        for size in self.shard_sizes(shape[self.axis]):
            s = list(shape)
            s[self.axis] = size
            out.append(tuple(s))
        return out

    def split(self, array):
        """Split a host array into shard arrays (axis 0 of the spec)."""
        if self.axis is None:
            return [array]
        return np.array_split(array, self.num_shards, axis=self.axis)

    def merge(self, shards):
        """Inverse of split — reassemble the logical array."""
        if self.axis is None:
            (only,) = shards
            return only
        return np.concatenate(shards, axis=self.axis)

    # -- sparse index mapping (embedding rows) ----------------------------
    def shard_of_index(self, indices, dim_size):
        """Shard id + local row for each logical row index
        (reference splits IndexedSlices by index range,
        partitioner.py:660-684)."""
        if self.axis != 0:
            raise ValueError('sparse partitioning requires axis 0')
        offsets = np.asarray(self.shard_offsets(dim_size) +
                             [int(dim_size)])
        indices = np.asarray(indices)
        shard = np.searchsorted(offsets, indices, side='right') - 1
        local = indices - offsets[shard]
        return shard, local


def smallest_nontrivial_divisor(n):
    """min k>=2 dividing n, else n (partitioned_ps_strategy.py:126-134)."""
    for i in range(2, n):
        if n % i == 0:
            return i
    return n


def smallest_non_divisor(n):
    """min k>=2 NOT dividing n (uneven_partition_ps_strategy.py:125-133)."""
    for i in range(2, n):
        if n % i != 0:
            return i
    return n
