"""Pallas TPU flash attention: block-tiled exact attention, fwd + bwd.

The reference has no attention kernels at all (its models are TF graphs;
SURVEY.md §2.3 lists no TP/SP) — this is TPU-native greenfield, the block
primitive promised by parallel/ring_attention.py. Algorithm is the public
flash-attention-2 recipe: the score matrix is never materialized in HBM;
each (Q-block × KV-block) tile runs on the MXU with an online-softmax
accumulator held in VMEM scratch, and the backward pass recomputes P from
the saved logsumexp instead of storing it.

Layout: q/k/v are [batch, heads, seq, head_dim]; the grid is
(batch, heads, q-blocks, kv-blocks) with the kv dimension innermost and
sequential ("arbitrary") so the VMEM accumulators carry across kv steps;
batch/heads/q-blocks are parallel. Causal masking is by global position,
and fully-masked tiles are skipped with predication (the classic ~2x
saving on causal attention).

On non-TPU backends the same kernels run in Pallas interpret mode, so the
CPU test mesh exercises the identical code path (tests/test_flash_attention.py).
"""
import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from autodist_tpu.kernels.pallas_compat import \
    CompilerParams as _CompilerParams

NEG_INF = -1e30   # same masking constant as parallel/ring_attention.py
_LANES = 128      # TPU lane width: m/l scratch replicate across lanes


def _pick_block(seq, target):
    for b in (target, 1024, 512, 256, 128, 64, 32, 16, 8):
        if b <= target and seq % b == 0 and b <= seq:
            return b
    return None


def _default_blocks(seq):
    """Measured-on-v5e block heuristic: small tiles pay grid overhead at
    long seq, so scale tile size with the sequence (q-block, kv-block)."""
    if seq <= 256:
        return 128, 128
    if seq <= 1024:
        return 256, 512
    return 512, 1024


def supports(shape, block=128):
    """Whether flash_attention can run for [B, H, S, D] (S divisible
    into >=8-row blocks)."""
    s = shape[2]
    return _pick_block(s, block) is not None


# Measured crossover vs XLA's fused attention on v5e: at short seq the
# whole score matrix fits on-chip and XLA's fusion wins; the kernel wins
# once [S, S] spills to HBM (isolated fwd+bwd bf16: 1.2x at 2k, 28x at
# 8k). Round-5 END-TO-END check on bert_large (remat, scanned layers)
# moved the threshold from 1024 to 512: full-model tokens/s at seq 512
# is ~10% HIGHER with the kernel (34.3k vs 31.0k at B=96) while seq
# 128/256 strongly favor XLA (45.8k vs 32.6k; 40.2k vs 26.6k) — under
# remat the attention recompute doubles the [S,S] traffic, which the
# kernel avoids earlier than the isolated crossover suggested.
MIN_KERNEL_SEQ = 512


def preferred(shape):
    """True when the Pallas kernel is expected to beat XLA's fused
    attention for this [B, H, S, D] shape."""
    return shape[2] >= MIN_KERNEL_SEQ and supports(shape)


def _interpret_default():
    return jax.default_backend() != 'tpu'



def _causal_mask(s, qi, ki, bq, bk):
    """Apply the global-position causal mask to one [bq, bk] score tile."""
    qpos = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
    kpos = ki * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    return jnp.where(qpos >= kpos, s, NEG_INF)


def _tile_live(qi, ki, bq, bk):
    """False only for tiles strictly above the causal diagonal
    (fully masked -> safe to skip)."""
    return qi * bq + bq - 1 >= ki * bk


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_scr, m_scr, l_scr,
                *, sm_scale, causal, bq, bk, nk):
    qi, ki = pl.program_id(2), pl.program_id(3)

    @pl.when(ki == 0)
    def _init():
        acc_scr[:] = jnp.zeros_like(acc_scr)
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)

    def tile():
        q = q_ref[0, 0]                       # [bq, D]
        k = k_ref[0, 0]                       # [bk, D]
        v = v_ref[0, 0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale   # [bq, bk]
        if causal:
            s = _causal_mask(s, qi, ki, bq, bk)
        m_prev = m_scr[:, :1]                                 # [bq, 1]
        l_prev = l_scr[:, :1]
        m_blk = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_blk)
        p = jnp.exp(s - m_new)                                # [bq, bk]
        alpha = jnp.exp(m_prev - m_new)
        l_new = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    if causal:
        @pl.when(_tile_live(qi, ki, bq, bk))
        def _():
            tile()
    else:
        tile()

    @pl.when(ki == nk - 1)
    def _emit():
        l = jnp.maximum(l_scr[:, :1], 1e-30)
        o_ref[0, 0] = (acc_scr[:] / l).astype(o_ref.dtype)
        lse_ref[0, 0] = (m_scr[:, :1] + jnp.log(l))


def _fwd(q, k, v, causal, sm_scale, bq, bk, interpret):
    b, h, s, d = q.shape
    nq, nk = s // bq, s // bk
    kernel = functools.partial(_fwd_kernel, sm_scale=sm_scale,
                               causal=causal, bq=bq, bk=bk, nk=nk)
    o, lse = pl.pallas_call(
        kernel,
        grid=(b, h, nq, nk),
        in_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, bk, d), lambda b, h, i, j: (b, h, j, 0)),
            pl.BlockSpec((1, 1, bk, d), lambda b, h, i, j: (b, h, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, bq, 1), lambda b, h, i, j: (b, h, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, s, d), q.dtype),
            jax.ShapeDtypeStruct((b, h, s, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, d), jnp.float32),
            pltpu.VMEM((bq, _LANES), jnp.float32),
            pltpu.VMEM((bq, _LANES), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=('parallel', 'parallel', 'parallel',
                                 'arbitrary')),
        interpret=interpret,
    )(q, k, v)
    return o, lse


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------

def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
               dq_scr, *, sm_scale, causal, bq, bk, nk):
    qi, ki = pl.program_id(2), pl.program_id(3)

    @pl.when(ki == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    def tile():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0]
        lse = lse_ref[0, 0]                                   # [bq, 1]
        delta = delta_ref[0, 0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale
        if causal:
            s = _causal_mask(s, qi, ki, bq, bk)
        p = jnp.exp(s - lse)                                  # [bq, bk]
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)               # [bq, bk]
        ds = p * (dp - delta) * sm_scale
        dq_scr[:] = dq_scr[:] + jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        @pl.when(_tile_live(qi, ki, bq, bk))
        def _():
            tile()
    else:
        tile()

    @pl.when(ki == nk - 1)
    def _emit():
        dq_ref[0, 0] = dq_scr[:].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, dk_scr, dv_scr,
                *, sm_scale, causal, bq, bk, nq):
    ki, qi = pl.program_id(2), pl.program_id(3)

    @pl.when(qi == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    def tile():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0]
        lse = lse_ref[0, 0]
        delta = delta_ref[0, 0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale
        if causal:
            s = _causal_mask(s, qi, ki, bq, bk)
        p = jnp.exp(s - lse)                                  # [bq, bk]
        dv_scr[:] = dv_scr[:] + jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)               # [bk, D]
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * sm_scale
        dk_scr[:] = dk_scr[:] + jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        @pl.when(_tile_live(qi, ki, bq, bk))
        def _():
            tile()
    else:
        tile()

    @pl.when(qi == nq - 1)
    def _emit():
        dk_ref[0, 0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_scr[:].astype(dv_ref.dtype)


def _bwd(q, k, v, o, lse, do, causal, sm_scale, bq, bk, interpret):
    b, h, s, d = q.shape
    nq, nk = s // bq, s // bk
    # delta = rowsum(dO * O): tiny elementwise reduce, XLA fuses it
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1, keepdims=True)                   # [B, H, S, 1]

    qkv_spec = [
        pl.BlockSpec((1, 1, bq, d), lambda b, h, i, j: (b, h, i, 0)),
        pl.BlockSpec((1, 1, bk, d), lambda b, h, i, j: (b, h, j, 0)),
        pl.BlockSpec((1, 1, bk, d), lambda b, h, i, j: (b, h, j, 0)),
        pl.BlockSpec((1, 1, bq, d), lambda b, h, i, j: (b, h, i, 0)),
        pl.BlockSpec((1, 1, bq, 1), lambda b, h, i, j: (b, h, i, 0)),
        pl.BlockSpec((1, 1, bq, 1), lambda b, h, i, j: (b, h, i, 0)),
    ]
    dq = pl.pallas_call(
        functools.partial(_dq_kernel, sm_scale=sm_scale, causal=causal,
                          bq=bq, bk=bk, nk=nk),
        grid=(b, h, nq, nk),
        in_specs=qkv_spec,
        out_specs=pl.BlockSpec((1, 1, bq, d),
                               lambda b, h, i, j: (b, h, i, 0)),
        out_shape=jax.ShapeDtypeStruct((b, h, s, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=('parallel', 'parallel', 'parallel',
                                 'arbitrary')),
        interpret=interpret,
    )(q, k, v, do, lse, delta)

    # dk/dv: grid iterates q-blocks innermost for each kv-block
    kv_first_spec = [
        pl.BlockSpec((1, 1, bq, d), lambda b, h, j, i: (b, h, i, 0)),
        pl.BlockSpec((1, 1, bk, d), lambda b, h, j, i: (b, h, j, 0)),
        pl.BlockSpec((1, 1, bk, d), lambda b, h, j, i: (b, h, j, 0)),
        pl.BlockSpec((1, 1, bq, d), lambda b, h, j, i: (b, h, i, 0)),
        pl.BlockSpec((1, 1, bq, 1), lambda b, h, j, i: (b, h, i, 0)),
        pl.BlockSpec((1, 1, bq, 1), lambda b, h, j, i: (b, h, i, 0)),
    ]
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, sm_scale=sm_scale, causal=causal,
                          bq=bq, bk=bk, nq=nq),
        grid=(b, h, nk, nq),
        in_specs=kv_first_spec,
        out_specs=[
            pl.BlockSpec((1, 1, bk, d), lambda b, h, j, i: (b, h, j, 0)),
            pl.BlockSpec((1, 1, bk, d), lambda b, h, j, i: (b, h, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, s, d), k.dtype),
            jax.ShapeDtypeStruct((b, h, s, d), v.dtype),
        ],
        scratch_shapes=[pltpu.VMEM((bk, d), jnp.float32),
                        pltpu.VMEM((bk, d), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=('parallel', 'parallel', 'parallel',
                                 'arbitrary')),
        interpret=interpret,
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# custom-vjp wrapper
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, causal, sm_scale, bq, bk, interpret):
    o, _ = _fwd(q, k, v, causal, sm_scale, bq, bk, interpret)
    return o


def _flash_fwd(q, k, v, causal, sm_scale, bq, bk, interpret):
    o, lse = _fwd(q, k, v, causal, sm_scale, bq, bk, interpret)
    return o, (q, k, v, o, lse)


def _flash_bwd(causal, sm_scale, bq, bk, interpret, res, do):
    q, k, v, o, lse = res
    return _bwd(q, k, v, o, lse, do, causal, sm_scale, bq, bk, interpret)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, causal=True, sm_scale=None, block_q=None,
                    block_k=None, interpret=None):
    """Exact attention over [batch, heads, seq, head_dim] tensors.

    Differentiable (custom VJP, flash backward). Requires ``seq`` to
    split into uniform blocks (``supports()``); callers fall back to the
    jnp path otherwise. Block sizes default to a measured seq-dependent
    heuristic. ``interpret`` defaults to True off-TPU so the same kernel
    code runs on the CPU test mesh.
    """
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    dq_blk, dk_blk = _default_blocks(q.shape[2])
    bq = _pick_block(q.shape[2], block_q or dq_blk)
    bk = _pick_block(q.shape[2], block_k or dk_blk)
    if bq is None or bk is None:
        raise ValueError('flash_attention: seq %d not blockable; check '
                         'supports() first' % q.shape[2])
    if interpret is None:
        interpret = _interpret_default()
    return _flash(q, k, v, causal, float(sm_scale), bq, bk, interpret)
