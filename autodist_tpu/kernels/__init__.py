"""Pallas TPU kernels (flash/ring attention, quantized collectives, embeddings)."""
