"""Pallas TPU fused pointwise-conv + BatchNorm kernel.

The round-3 xplane profile of the ResNet-101 step (BASELINE.md) showed
the step is activation-bandwidth-bound: 42% layout-copy waits and 36%
BatchNorm moment reductions — each BN site costs one full HBM read of a
multi-hundred-MB activation, and the normalize+relu another read+write.
The reference has no kernels at all (its conv perf came from cuDNN via
the TF runtime); this is the TPU-native answer: a 1x1 convolution IS a
matmul ``[B*H*W, Cin] x [Cin, Cout]``, so the BBN work rides the MXU
pass:

- **epilogue**: per-channel moment sums (sum y, sum y^2) accumulate in
  f32 from the MXU accumulator while the tile is still in VMEM — the
  BN-statistics pass over the conv output costs ZERO extra HBM traffic;
- **prologue**: the PREVIOUS BatchNorm's normalize+affine+ReLU
  (``relu(x*a + b)``, per-input-channel a/b) applies to each input tile
  on the way into the MXU — the consumer-side elementwise pass also
  vanishes.

Backward is a hand-written vjp in plain XLA ops (two MXU matmuls plus
fused elementwise) — dW = xn^T dY and dx = dY W^T are already
MXU-shaped, so the custom kernel is only needed where XLA could not
fuse: the forward's stats+normalize traffic.

Like kernels/flash_attention.py, the same kernel runs in Pallas
interpret mode on non-TPU backends so the CPU test mesh exercises the
identical code path.
"""
import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from autodist_tpu.kernels.pallas_compat import \
    CompilerParams as _CompilerParams


def _interpret_default():
    return jax.default_backend() != 'tpu'


def supports(n_rows, c_in, c_out, block_n=None):
    """Whether the fused kernel can serve [N, Cin] x [Cin, Cout]:
    lane-aligned outputs (the stats accumulators live per output
    channel), sublane-aligned inputs (Mosaic pads the contraction to
    128 lanes — DenseNet's growth-32 concats ride the kernel at some
    lane waste, still a large win over the extra HBM passes), and a row
    count divisible into tiles (padded rows would corrupt the moment
    sums)."""
    bn = block_n or _pick_block_n(n_rows)
    return (c_in % 8 == 0 and c_out % 128 == 0 and bn is not None)


def _pick_block_n(n_rows):
    for b in (512, 256, 128, 64, 32, 16, 8):
        if n_rows % b == 0 and b <= n_rows:
            return b
    return None


def _pick_block_cout(c_out):
    for b in (512, 256, 128):
        if c_out % b == 0 and b <= c_out:
            return b
    return c_out


def _kernel(x_ref, w_ref, a_ref, b_ref, y_ref, s1_ref, s2_ref, *,
            prologue, prologue_relu, want_stats, out_dtype):
    # grid = (n_out_tiles, m_tiles): m is the INNER (sequential) dim so
    # the per-out-channel moment accumulators stay resident in VMEM for
    # a whole column strip while the W tile for that strip loads once.
    i = pl.program_id(1)
    x = x_ref[...]
    if prologue:
        xn = x.astype(jnp.float32) * a_ref[...] + b_ref[...]
        if prologue_relu:
            xn = jnp.maximum(xn, 0.0)
        x = xn.astype(x_ref.dtype)
    acc = jax.lax.dot_general(
        x, w_ref[...], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    y_ref[...] = acc.astype(out_dtype)

    # stats outputs are ALWAYS initialized (want_stats=False promises
    # zeros, not uninitialized memory)
    @pl.when(i == 0)
    def _init():
        s1_ref[...] = jnp.zeros_like(s1_ref)
        s2_ref[...] = jnp.zeros_like(s2_ref)
    if want_stats:
        # moment sums from the f32 accumulator, free of HBM traffic
        s1_ref[...] += jnp.sum(acc, axis=0, keepdims=True)
        s2_ref[...] += jnp.sum(acc * acc, axis=0, keepdims=True)


def _fwd_call(x2d, w, a, b, prologue_relu, want_stats, out_dtype,
              block_n, interpret):
    n, c_in = x2d.shape
    c_out = w.shape[1]
    bm = block_n or _pick_block_n(n)
    bco = _pick_block_cout(c_out)
    prologue = a is not None
    if a is None:
        a = jnp.ones((1, c_in), jnp.float32)
        b = jnp.zeros((1, c_in), jnp.float32)
    grid = (c_out // bco, n // bm)
    kernel = functools.partial(
        _kernel, prologue=prologue, prologue_relu=prologue_relu,
        want_stats=want_stats, out_dtype=out_dtype)
    y, s1, s2 = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, c_in), lambda j, i: (i, 0)),
            pl.BlockSpec((c_in, bco), lambda j, i: (0, j)),
            pl.BlockSpec((1, c_in), lambda j, i: (0, 0)),
            pl.BlockSpec((1, c_in), lambda j, i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((bm, bco), lambda j, i: (i, j)),
            pl.BlockSpec((1, bco), lambda j, i: (0, j)),
            pl.BlockSpec((1, bco), lambda j, i: (0, j)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, c_out), out_dtype),
            jax.ShapeDtypeStruct((1, c_out), jnp.float32),
            jax.ShapeDtypeStruct((1, c_out), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=('arbitrary', 'arbitrary')),
        interpret=interpret,
    )(x2d, w.astype(x2d.dtype), a.reshape(1, c_in).astype(jnp.float32),
      b.reshape(1, c_in).astype(jnp.float32))
    return y, s1.reshape(c_out), s2.reshape(c_out)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def _fused(x2d, w, a, b, prologue_relu, want_stats, out_dtype, block_n,
           interpret):
    return _fwd_call(x2d, w, a, b, prologue_relu, want_stats, out_dtype,
                     block_n, interpret)


def _fused_fwd(x2d, w, a, b, prologue_relu, want_stats, out_dtype,
               block_n, interpret):
    out = _fwd_call(x2d, w, a, b, prologue_relu, want_stats, out_dtype,
                    block_n, interpret)
    y, _, _ = out
    return out, (x2d, w, a, b, y)


def _fused_bwd(prologue_relu, want_stats, out_dtype, block_n, interpret,
               res, cts):
    """Plain-XLA vjp: two MXU matmuls + fused elementwise.

    With outputs (y, s1, s2), s1 = sum_rows(y), s2 = sum_rows(y^2), the
    effective output cotangent is dY = dy + ds1 + 2*y*ds2 (broadcast
    over rows); then dW = xn^T dY, dxn = dY W^T, and the prologue
    (relu(x*a+b)) backprops elementwise with xn recomputed (cheap; XLA
    fuses it into the matmul operand).

    Every [N, C]-sized intermediate stays in the ACTIVATION dtype (bf16
    in the benchmark configs) — f32 is reserved for [C] vectors and
    reduction accumulators. An f32 dY/xn here doubles the backward's
    HBM bytes and triggers layout-copy storms on the stage-1/-2
    activations (round-4 profile: multi-hundred-MB f32 copies)."""
    x2d, w, a, b, y = res
    dy, ds1, ds2 = cts
    cdt = x2d.dtype  # activation/MXU dtype
    dY = dy.astype(cdt)
    if want_stats:
        dY = dY + ds1.astype(cdt)[None, :] + \
            y.astype(cdt) * (2.0 * ds2).astype(cdt)[None, :]
    if a is not None:
        av = a.reshape(1, -1).astype(cdt)
        bv = b.reshape(1, -1).astype(cdt)
        xn = x2d * av + bv
        if prologue_relu:
            xn = jnp.maximum(xn, 0)
        xn_c = xn
    else:
        xn_c = x2d
    dw = jax.lax.dot_general(xn_c, dY, (((0,), (0,)), ((), ())),
                             preferred_element_type=jnp.float32)
    dxn = jax.lax.dot_general(dY, w.astype(cdt),
                              (((1,), (1,)), ((), ())),
                              preferred_element_type=jnp.float32)
    dxn = dxn.astype(cdt)
    if a is not None:
        if prologue_relu:
            dxn = jnp.where(xn > 0, dxn, 0)
        dx = dxn * av
        # convert+multiply+reduce fuse into ONE bf16 HBM read with f32
        # register math (no f32 [N, C] temporary)
        da = jnp.sum(dxn.astype(jnp.float32) * x2d.astype(jnp.float32),
                     axis=0, dtype=jnp.float32)
        db = jnp.sum(dxn.astype(jnp.float32), axis=0,
                     dtype=jnp.float32)
        da = da.reshape(a.shape).astype(a.dtype)
        db = db.reshape(b.shape).astype(b.dtype)
    else:
        dx = dxn
        da = None
        db = None
    return dx, dw.astype(w.dtype), da, db


_fused.defvjp(_fused_fwd, _fused_bwd)


def fused_pointwise(x, w, scale=None, bias=None, prologue_relu=False,
                    want_stats=True, out_dtype=None, stride=1,
                    block_n=None, interpret=None):
    """Fused 1x1 conv (+ BN prologue/epilogue) on NHWC input.

    Args:
        x: [B, H, W, Cin] activations.
        w: [Cin, Cout] pointwise kernel (a [1, 1, Cin, Cout] HWIO conv
            kernel reshaped).
        scale, bias: optional per-Cin normalize+affine applied to ``x``
            on the way into the MXU (the PREVIOUS BatchNorm's folded
            coefficients); ``prologue_relu`` applies ReLU after.
        want_stats: also return (sum y, sum y^2) per output channel,
            accumulated in the epilogue (the NEXT BatchNorm's moments).
        stride: 1x1 conv stride (spatial subsample before the matmul).
        out_dtype: output dtype (defaults to x.dtype).

    Returns:
        ``(y [B, H', W', Cout], s1 [Cout], s2 [Cout])``; s1/s2 are
        zeros when ``want_stats=False``.
    """
    if interpret is None:
        interpret = _interpret_default()
    if stride != 1:
        x = x[:, ::stride, ::stride, :]
    batch, hh, ww, c_in = x.shape
    n = batch * hh * ww
    out_dtype = out_dtype or x.dtype
    y, s1, s2 = _fused(x.reshape(n, c_in), w,
                       None if scale is None else scale,
                       None if scale is None else bias,
                       bool(prologue_relu), bool(want_stats),
                       jnp.dtype(out_dtype), block_n, bool(interpret))
    return y.reshape(batch, hh, ww, w.shape[1]), s1, s2
