"""Hand-scheduled training BatchNorm: minimum HBM passes, pure XLA.

The round-4 per-op profile of the ResNet-101 step (v5e, batch 256)
showed ~60% of step time in BatchNorm-related reductions, and — the
actionable part — XLA emitted E[x] (convert+reduce) and E[x^2]
(multiply+reduce) as SEPARATE fusions: two full HBM reads of every
activation per BN site, plus more in the autodiff backward. This module
rewrites training BN as a ``jax.custom_vjp`` whose passes are counted
by hand:

- forward: ONE variadic reduce computes both moment sums in a single
  read (a single Reduce HLO cannot be split), then one read+write for
  the folded normalize+affine;
- backward: ONE variadic reduce for (sum dy, sum dy*x) — d_gamma is
  recovered from them without a separate pass — then one read of
  (dy, x) for dx. The classic BN gradient
  ``dx = g*rsqrt(var+eps) * (dy - (db + xhat*dg)/n)`` fuses into that
  single elementwise pass.

Everything [B,H,W,C]-sized stays in the activation dtype (bf16 in the
benchmark configs); f32 lives only in [C] vectors and reduce
accumulators. The reference gets its BN from cuDNN via the TF runtime
(SURVEY.md §2.2); this is the TPU-native equivalent, at the XLA graph
level where the conv emitter's layouts are undisturbed (a Pallas
variant was measured slower end-to-end: kernel-boundary layout copies
outweigh the saved passes).

Returns (y, mean, var) — mean/var feed the EMA state channel, which is
deliberately non-differentiable (reference semantics: moving statistics
are not part of the loss); their cotangents are ignored.
"""
import functools

import jax
import jax.numpy as jnp
from jax import lax


def _moment_sums(x):
    """(sum x, sum x^2) over all but the channel axis, in ONE pass
    (a single variadic Reduce HLO; f32 accumulation from the
    activation dtype)."""
    xf = x.astype(jnp.float32)
    return lax.reduce(
        (xf, xf * xf), (jnp.float32(0), jnp.float32(0)),
        lambda c, v: (c[0] + v[0], c[1] + v[1]),
        tuple(range(x.ndim - 1)))


def _sum_dy_dyx(dy, x):
    """(sum dy, sum dy*x) per channel in ONE pass."""
    dyf = dy.astype(jnp.float32)
    return lax.reduce(
        (dyf, dyf * x.astype(jnp.float32)),
        (jnp.float32(0), jnp.float32(0)),
        lambda c, v: (c[0] + v[0], c[1] + v[1]),
        tuple(range(dy.ndim - 1)))


@jax.custom_vjp
def moments(x):
    """Differentiable single-pass batch moments: (E[x], E[x^2]) over
    all but the channel axis. One variadic Reduce HLO = one HBM read
    (JAX cannot autodiff a variadic ``lax.reduce``, hence the
    closed-form vjp: d/dx = (dE1 + 2x*dE2)/n)."""
    n = x.size // x.shape[-1]
    s1, s2 = _moment_sums(x)
    return s1 / n, s2 / n


def _moments_fwd(x):
    return moments(x), x


def _moments_bwd(x, cts):
    d1, d2 = cts
    n = x.size // x.shape[-1]
    dt = x.dtype
    dx = (d1 / n).astype(dt) + x * (2.0 * d2 / n).astype(dt)
    return (dx,)


moments.defvjp(_moments_fwd, _moments_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def batch_norm_train(x, scale, bias, eps):
    """Training-mode BN over the leading axes of NHWC ``x``; returns
    ``(y, mean, var)`` with y in x's dtype and batch statistics in f32.
    """
    y, mean, var, _ = _bn_fwd_impl(x, scale, bias, eps)
    return y, mean, var


def _bn_fwd_impl(x, scale, bias, eps):
    n = x.size // x.shape[-1]
    s1, s2 = _moment_sums(x)
    mean = s1 / n
    var = jnp.maximum(s2 / n - mean * mean, 0.0)
    a = scale * lax.rsqrt(var + eps)
    b = bias - mean * a
    dt = x.dtype
    y = x * a.astype(dt) + b.astype(dt)
    return y, mean, var, a


def _bn_fwd(x, scale, bias, eps):
    y, mean, var, a = _bn_fwd_impl(x, scale, bias, eps)
    return (y, mean, var), (x, scale, mean, var)


def _bn_bwd(eps, res, cts):
    x, scale, mean, var = res
    dy = cts[0]   # d_mean/d_var cotangents ignored: EMA state channel
    n = x.size // x.shape[-1]
    inv = lax.rsqrt(var + eps)
    sdy, sdyx = _sum_dy_dyx(dy, x)
    db = sdy
    # d_gamma = sum dy*xhat = (sum dy*x - mean*sum dy) * inv
    dg = (sdyx - mean * sdy) * inv
    # dx = gamma*inv * (dy - (db + xhat*dg)/n), with
    # xhat = (x - mean)*inv, folded to ONE multiply-add in x:
    #   dx = k1*dy + k2*x + k3  (per-channel k's)
    g_inv = scale * inv
    k1 = g_inv
    k2 = -g_inv * dg * inv / n
    k3 = -g_inv * (db - dg * inv * mean) / n
    dt = x.dtype
    dx = dy * k1.astype(dt) + x * k2.astype(dt) + k3.astype(dt)
    return dx, dg.astype(scale.dtype), db.astype(scale.dtype)


batch_norm_train.defvjp(_bn_fwd, _bn_bwd)
