"""Pallas API-drift shims shared by the TPU kernels.

jax renamed ``pltpu.TPUCompilerParams`` to ``pltpu.CompilerParams``;
this image's 0.4.x jax only has the old spelling. One alias here keeps
every kernel file on whichever the running jax provides (tier-1
triage, ISSUE 5).
"""
from jax.experimental.pallas import tpu as pltpu

CompilerParams = getattr(pltpu, 'CompilerParams', None) or \
    pltpu.TPUCompilerParams
