"""checkpoint subpackage."""
