"""Checkpointing in logical (unsharded) layout.

Reference semantics (autodist/checkpoint/saver.py:50-57, proven by
restoring into vanilla TF in cases/c0.py:124-132): checkpoints written
under ANY distribution strategy have the original single-device variable
layout, so they are interchangeable between strategies and with
non-distributed runs. The TPU rebuild keeps that contract: sharded
``jax.Array``s are gathered per-leaf to host logical layout and written
to a self-contained directory (``manifest.json`` + one ``.npy`` per
tensor). Restore works into any Trainer/Session regardless of mesh —
arrays are re-placed according to the live sharding.

Format notes: .npy per leaf (not one .npz) keeps writes streamable and
lets a future native writer parallelize per-tensor IO; orbax can read
the same trees via ``to_pytree``/``from_pytree`` if users prefer its
async machinery.
"""
import json
import os
import shutil

import numpy as np

import jax

from autodist_tpu.utils import logging


def _leaf_paths(tree):
    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    out = []
    for path, leaf in flat:
        name = '/'.join(str(getattr(k, 'key', getattr(k, 'idx', k)))
                        for k in path)
        out.append((name, leaf))
    return out, treedef


def save_pytree(path, tree, step=None, overwrite=True):
    """Write a pytree of arrays to ``path`` in logical layout."""
    tmp = path + '.tmp'
    if os.path.exists(tmp):
        shutil.rmtree(tmp)
    os.makedirs(tmp)
    flat, _ = _leaf_paths(tree)
    manifest = {'format': 'autodist_tpu.ckpt.v1', 'step': step,
                'tensors': {}}
    for name, leaf in flat:
        host = np.asarray(jax.device_get(leaf))
        fname = name.replace('/', '.') + '.npy'
        np.save(os.path.join(tmp, fname), host)
        manifest['tensors'][name] = {
            'file': fname, 'shape': list(host.shape),
            'dtype': str(host.dtype)}
    with open(os.path.join(tmp, 'manifest.json'), 'w') as f:
        json.dump(manifest, f, indent=1, sort_keys=True)
    if os.path.exists(path):
        if not overwrite:
            raise FileExistsError(path)
        shutil.rmtree(path)
    os.rename(tmp, path)
    logging.info('Saved checkpoint (%d tensors) to %s',
                 len(manifest['tensors']), path)
    return path


def load_pytree(path, like=None):
    """Load a checkpoint directory.

    With ``like`` (a structural template pytree), returns the same
    structure; leaves are host arrays. Without it, returns a flat
    {name: array} dict.
    """
    with open(os.path.join(path, 'manifest.json')) as f:
        manifest = json.load(f)
    tensors = {name: np.load(os.path.join(path, meta['file']))
               for name, meta in manifest['tensors'].items()}
    if like is None:
        return tensors, manifest.get('step')
    flat, treedef = _leaf_paths(like)
    leaves = []
    for name, leaf in flat:
        if name not in tensors:
            raise KeyError('Checkpoint %s missing tensor %r' %
                           (path, name))
        want = tuple(getattr(leaf, 'shape', ()))
        got = tensors[name].shape
        if want and tuple(got) != want:
            raise ValueError('Shape mismatch for %r: ckpt %s vs model %s'
                             % (name, got, want))
        leaves.append(tensors[name])
    return jax.tree_util.tree_unflatten(treedef, leaves), \
        manifest.get('step')


def save_pytree_orbax(path, tree, step=None):
    """Orbax (tensorstore) backend: sharded, async-flushed writes — the
    production path for large multi-host states. Step metadata rides in
    a sidecar (orbax's own metadata stores the tree structure)."""
    import orbax.checkpoint as ocp
    ckptr = ocp.StandardCheckpointer()
    if os.path.exists(path):
        shutil.rmtree(path)
    ckptr.save(os.path.abspath(path),
               jax.tree.map(jnp_or_np_asarray, tree))
    ckptr.wait_until_finished()
    with open(path + '.step', 'w') as f:
        json.dump({'step': step}, f)
    logging.info('Saved orbax checkpoint to %s', path)
    return path


def jnp_or_np_asarray(x):
    return x if hasattr(x, 'dtype') else np.asarray(x)


def load_pytree_orbax(path, like=None):
    import orbax.checkpoint as ocp
    ckptr = ocp.StandardCheckpointer()
    tree = ckptr.restore(os.path.abspath(path), target=like)
    step = None
    if os.path.exists(path + '.step'):
        with open(path + '.step') as f:
            step = json.load(f).get('step')
    return tree, step


class CheckpointManager:
    """Step-numbered checkpoints with retention (keep latest k).

    ``backend='npy'`` (default) writes the self-contained
    manifest + .npy layout; ``backend='orbax'`` delegates tensor IO to
    orbax/tensorstore (sharded files, async flush) while keeping the
    same directory/retention/latest-step contract.

    ``async_save=True`` makes ``save`` non-blocking: the values are
    snapshotted to host (npy) or handed to orbax's async checkpointer
    (which copies device->host before returning, so donated buffers are
    safe) and the file write overlaps subsequent training steps. At
    most one save is in flight; a new ``save``, ``restore``, or
    ``wait_until_finished`` drains the previous one first.
    """

    def __init__(self, directory, max_to_keep=3, backend='npy',
                 async_save=False):
        if backend not in ('npy', 'orbax'):
            raise ValueError('backend must be npy or orbax: %r' % backend)
        self.directory = directory
        self.max_to_keep = max_to_keep
        self.backend = backend
        self.async_save = async_save
        self._async_ckptr = None   # orbax AsyncCheckpointer (lazy)
        self._pending = None       # npy writer thread
        self._pending_error = None
        self._pending_sidecar = None
        os.makedirs(directory, exist_ok=True)

    def _ckpt_path(self, step):
        return os.path.join(self.directory, 'ckpt-%d' % step)

    def all_steps(self):
        steps = []
        for name in os.listdir(self.directory):
            if name.startswith('ckpt-') and not name.endswith('.tmp'):
                try:
                    steps.append(int(name.split('-', 1)[1]))
                except ValueError:
                    pass
        return sorted(steps)

    def latest_step(self):
        steps = self.all_steps()
        return steps[-1] if steps else None

    def save(self, step, tree):
        if self.async_save:
            return self._save_async(step, tree)
        save_fn = save_pytree_orbax if self.backend == 'orbax' \
            else save_pytree
        path = save_fn(self._ckpt_path(step), tree, step=step)
        self._retain()
        return path

    def _retain(self):
        for old in self.all_steps()[:-self.max_to_keep]:
            shutil.rmtree(self._ckpt_path(old))
            sidecar = self._ckpt_path(old) + '.step'
            if os.path.exists(sidecar):
                os.remove(sidecar)

    def _save_async(self, step, tree):
        self.wait_until_finished()   # one save in flight at a time
        path = self._ckpt_path(step)
        if self.backend == 'orbax':
            import orbax.checkpoint as ocp
            if self._async_ckptr is None:
                self._async_ckptr = ocp.AsyncCheckpointer(
                    ocp.StandardCheckpointHandler())
            if os.path.exists(path):
                shutil.rmtree(path)
            # blocks only for the device->host copy; the file flush
            # continues in the background while training proceeds
            self._async_ckptr.save(
                os.path.abspath(path),
                args=ocp.args.StandardSave(
                    jax.tree.map(jnp_or_np_asarray, tree)))
            # sidecar is written AFTER the flush is durable (in
            # wait_until_finished) — a crash mid-flush must not leave a
            # sidecar claiming a checkpoint that never finalized
            self._pending_sidecar = (path, step)
        else:
            # snapshot to host NOW (subsequent steps may donate the
            # device buffers), write in a daemon thread
            host = jax.tree.map(
                lambda x: np.asarray(jax.device_get(x)), tree)

            def write():
                try:
                    save_pytree(path, host, step=step)
                except Exception as e:   # noqa: BLE001 - surfaced on join
                    # also log NOW: if the process exits without a
                    # drain, the stored error would vanish silently
                    logging.error('async checkpoint write to %s '
                                  'failed: %s', path, e)
                    self._pending_error = e
            import threading
            # non-daemon: an un-drained save still completes at
            # interpreter exit instead of dying mid-write
            self._pending = threading.Thread(target=write, daemon=False)
            self._pending.start()
        # retention sees only FINISHED checkpoints (the in-flight dir
        # may not exist yet), so transiently max_to_keep+1 can exist
        self._retain()
        return path

    def wait_until_finished(self):
        """Drain any in-flight async save (raises its error, if any),
        then re-apply retention — the drained save was invisible to the
        retention pass that ran when it started."""
        if self._async_ckptr is not None:
            sidecar = getattr(self, '_pending_sidecar', None)
            try:
                self._async_ckptr.wait_until_finished()
            finally:
                # a failed flush must not leave stale pending-sidecar
                # state for a later drain to misattribute
                self._pending_sidecar = None
            if sidecar is not None:
                path, step = sidecar
                if os.path.exists(path):   # flush finalized the dir
                    with open(path + '.step', 'w') as f:
                        json.dump({'step': step}, f)
        if self._pending is not None:
            self._pending.join()
            self._pending = None
            if self._pending_error is not None:
                err, self._pending_error = self._pending_error, None
                raise err
        if self.async_save:
            self._retain()

    def close(self):
        """Drain in-flight saves and release the async checkpointer's
        worker resources. Safe to call multiple times."""
        self.wait_until_finished()
        if self._async_ckptr is not None:
            self._async_ckptr.close()
            self._async_ckptr = None

    def restore(self, like=None, step=None):
        self.wait_until_finished()
        step = step if step is not None else self.latest_step()
        if step is None:
            return None, None
        load_fn = load_pytree_orbax if self.backend == 'orbax' \
            else load_pytree
        tree, _ = load_fn(self._ckpt_path(step), like=like)
        return tree, step


# -- reference-parity Saver over the DSL Session --------------------------

class Saver:
    """tf.train.Saver-shaped facade for the DSL/session path.

    Reference contract (checkpoint/saver.py:85-133): construct before the
    distributed session; save/restore run against the session's variables
    and produce single-node-layout checkpoints.
    """

    def __init__(self, var_list=None, max_to_keep=5):
        from autodist_tpu.frontend import graph as fe
        self._graph = fe.get_default_graph()
        self._vars = ({v.name: v for v in var_list} if var_list
                      else dict(self._graph.variables))
        self._max_to_keep = max_to_keep
        self._graph.savers.append(self)

    def save(self, sess, save_path, global_step=None):
        tree = {name: sess.get_variable_value(name)
                for name in self._vars}
        path = save_path if global_step is None \
            else '%s-%d' % (save_path, global_step)
        return save_pytree(path, tree, step=global_step)

    def restore(self, sess, save_path):
        tensors, _ = load_pytree(save_path)
        for name in self._vars:
            if name not in tensors:
                raise KeyError('Checkpoint missing variable %r' % name)
            sess.load_variable_value(name, tensors[name])
        logging.info('Restored %d variables from %s',
                     len(self._vars), save_path)


class SavedModelBuilder:
    """Export a servable bundle (reference saved_model_builder.py:24-64).

    ``signature_def_map`` maps a signature name to ``(outputs, inputs)``:
    ``outputs`` a fetch node (or list of them) from the captured graph,
    ``inputs`` the placeholders it consumes. Each signature's forward
    subgraph is re-traced as a pure function of (params, *inputs) and
    serialized with ``jax.export`` (StableHLO) next to the variables —
    a fresh process reloads and serves it with only jax + numpy
    (:mod:`autodist_tpu.checkpoint.export`), matching the reference's
    loadable-SavedModel contract (tests/checkpoint/test_saved_model.py:
    26-29). Without signatures only variables + metadata are written.
    """

    def __init__(self, export_dir):
        self.export_dir = export_dir
        self._saved = False

    def add_meta_graph_and_variables(self, sess, tags,
                                     signature_def_map=None):
        self._sess = sess
        self._tags = list(tags)
        self._signatures = signature_def_map or {}
        return self

    def save(self):
        if self._saved:
            raise RuntimeError('SavedModelBuilder.save called twice')
        from autodist_tpu.frontend import graph as fe
        tree = {name: np.asarray(self._sess.get_variable_value(name))
                for name in self._sess._graph_item.graph.variables}
        for i, (sig_name, (outputs, inputs)) in \
                enumerate(self._signatures.items()):
            out_nodes = outputs if isinstance(outputs, (list, tuple)) \
                else [outputs]
            out_nodes = [o.read() if isinstance(o, fe.Variable) else o
                         for o in out_nodes]
            for o in out_nodes:
                if isinstance(o, fe.ApplyGradients):
                    raise ValueError(
                        'signature %r exports a train op; servable '
                        'signatures must be forward-only' % sig_name)
            in_phs = list(inputs)

            def make_fn(nodes, phs):
                def fn(params, *feeds):
                    env = fe.Env(dict(params), dict(zip(phs, feeds)))
                    return [fe.evaluate(n, env) for n in nodes]
                return fn

            from autodist_tpu.checkpoint.export import export_servable
            export_servable(
                make_fn(out_nodes, in_phs), tree,
                [(ph.shape, ph.dtype) for ph in in_phs],
                self.export_dir, signature=sig_name, tags=self._tags,
                input_names=[ph.name for ph in in_phs],
                write_params=(i == 0))
        if not self._signatures:
            save_pytree(os.path.join(self.export_dir, 'variables'), tree)
            meta = {'format': 'autodist_tpu.saved_model.v1',
                    'tags': self._tags, 'signatures': {}}
            with open(os.path.join(self.export_dir, 'saved_model.json'),
                      'w') as f:
                json.dump(meta, f, indent=1, sort_keys=True)
        self._saved = True
        return self.export_dir
