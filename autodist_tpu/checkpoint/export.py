"""Servable export on ``jax.export`` / StableHLO.

Reference parity: ``SavedModelBuilder`` writes a bundle another process
can load and serve (``autodist/checkpoint/saved_model_builder.py:24-64``,
proven by ``tests/checkpoint/test_saved_model.py:26-29`` reloading it in
a fresh session). The TPU-native bundle is:

    export_dir/
      saved_model.json            # format, tags, per-signature metadata
      module.<signature>.shlo     # jax.export serialized artifact
      variables/                  # logical-layout params (manifest + .npy)

The ``.shlo`` blob is a self-describing, versioned StableHLO artifact:
serving needs only ``jax`` + ``numpy`` — no framework import — via

    module = jax.export.deserialize(open(blob, 'rb').read())
    outs = module.call(params_dict, *inputs)

where ``params_dict`` is the flat ``{name: array}`` dict from
``variables/`` (plain dicts are pytrees with deterministic sorted-key
order, so the call convention is stable). Input batch dims declared
polymorphic (``None`` in a placeholder shape) are exported as symbolic
dimensions, so the served module accepts any batch size.
"""
import json
import os

import numpy as np

import jax
from jax import export as jax_export

from autodist_tpu.checkpoint.saver import load_pytree, save_pytree
from autodist_tpu.utils import logging

_FORMAT = 'autodist_tpu.saved_model.v1'


def _input_spec(shape, dtype, scope, sym_names, input_idx,
                shared_batch_dim):
    """ShapeDtypeStruct for one input; ``None`` dims become symbolic
    (shared scope, so one symbol name = one dimension variable)."""
    dims = []
    for i, d in enumerate(tuple(shape or ())):
        if d is None:
            # With shared_batch_dim every leading None dim is the SAME
            # symbol 'b' (inputs of one batch must agree at call time);
            # without it each input's leading dim is independent
            # ('b<input index>'). Later unknown dims each get their own.
            if i == 0:
                name = 'b' if shared_batch_dim else 'b%d' % input_idx
            else:
                name = 'd%d' % len(sym_names)
            sym_names.add(name)
            dims.append(jax_export.symbolic_shape(name, scope=scope)[0])
        else:
            dims.append(int(d))
    return jax.ShapeDtypeStruct(tuple(dims), np.dtype(dtype))


def export_servable(fn, params, input_shapes, path,
                    signature='serving_default', tags=('serve',),
                    platforms=('cpu', 'tpu'), input_names=None,
                    write_params=True, shared_batch_dim=True):
    """Export ``fn(params, *inputs) -> list of outputs`` as a servable
    bundle.

    Args:
        fn: pure function of (params pytree, *input arrays).
        params: pytree of host/device arrays (saved to ``variables/``).
        input_shapes: list of (shape, dtype); ``None`` dims symbolic.
        path: export directory (created; existing signatures preserved).
        signature: name of this entrypoint.
        platforms: lowering targets baked into the artifact.
        input_names: optional names recorded in the metadata.
        shared_batch_dim: True (default) asserts every input's leading
            ``None`` dim is the SAME batch dimension (they must agree at
            call time — the usual one-batch signature). Pass False for
            signatures whose inputs carry genuinely independent dynamic
            leading dims (each gets its own symbol).
    """
    os.makedirs(path, exist_ok=True)
    scope = jax_export.SymbolicScope()
    sym_names = set()
    specs = [_input_spec(s, d, scope, sym_names, i, shared_batch_dim)
             for i, (s, d) in enumerate(input_shapes)]
    host_params = jax.tree.map(lambda a: np.asarray(jax.device_get(a)),
                               params)
    param_specs = jax.tree.map(
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), host_params)
    exported = jax_export.export(
        jax.jit(fn), platforms=list(platforms))(param_specs, *specs)
    module_file = 'module.%s.shlo' % signature
    with open(os.path.join(path, module_file), 'wb') as f:
        f.write(exported.serialize())
    if write_params:
        # variables/ is signature-independent; multi-signature bundles
        # pass write_params=False after the first export
        save_pytree(os.path.join(path, 'variables'), host_params)

    meta_path = os.path.join(path, 'saved_model.json')
    meta = {'format': _FORMAT, 'tags': list(tags), 'signatures': {}}
    if os.path.exists(meta_path):
        with open(meta_path) as f:
            old = json.load(f)
        if old.get('format') == _FORMAT:
            meta['signatures'] = old.get('signatures', {})
    meta['signatures'][signature] = {
        'module_file': module_file,
        'platforms': list(platforms),
        'inputs': [{'name': (input_names[i] if input_names else
                             'input_%d' % i),
                    'shape': [None if not isinstance(d, int) else d
                              for d in spec.shape],
                    'dtype': str(spec.dtype)}
                   for i, spec in enumerate(specs)],
        'call_convention':
            'module.call(flat_params_dict, *inputs) -> flat outputs',
        'shared_batch_dim': bool(shared_batch_dim),
    }
    with open(meta_path, 'w') as f:
        json.dump(meta, f, indent=1, sort_keys=True)
    logging.info('Exported servable signature %r to %s', signature, path)
    return path


def load_servable(path, signature='serving_default'):
    """Load a servable bundle; returns ``serve(*inputs)`` with the
    saved params bound. (Convenience wrapper — a fresh process can do
    the same with only jax + numpy, see the module docstring.)"""
    with open(os.path.join(path, 'saved_model.json')) as f:
        meta = json.load(f)
    if meta.get('format') != _FORMAT:
        raise ValueError('%s is not an %s bundle' % (path, _FORMAT))
    sig = meta['signatures'][signature]
    with open(os.path.join(path, sig['module_file']), 'rb') as f:
        module = jax_export.deserialize(f.read())
    params, _ = load_pytree(os.path.join(path, 'variables'))

    def serve(*inputs):
        return module.call(params, *inputs)

    return serve
