"""LRU + TTL row cache for the serving tier's sparse embedding plane.

Dense variables refresh as whole-model snapshots (replica.py), but an
embedding table is exactly the variable a full pull cannot afford —
the NCF table is the model's bulk, and lookups touch a few thousand
rows per query batch. Hot rows therefore live here: keyed
``(table, row)``, evicted LRU past the capacity, expired past the TTL
so training's pushes keep reaching served values, and flushed
wholesale on every dense snapshot version bump (a row cached against
snapshot step S served next to step S' dense weights would be the
sparse flavor of a mixed-version read).

Accounting is part of the contract, not a debugging afterthought:
``hits``/``misses``/``evictions``/``expirations``/``invalidations``
feed ``serve_stats`` -> ``profiling.health_report`` -> bench.
"""
import collections
import time

from autodist_tpu.const import ENV


class RowCache:
    """LRU row cache with per-entry TTL.

    ``capacity_rows``/``ttl_s`` default from the
    ``AUTODIST_SERVE_ROW_CACHE_ROWS`` / ``AUTODIST_SERVE_ROW_TTL_S``
    knobs; ``clock`` is injectable (tests drive TTL expiry without
    sleeping). Values are stored as-is (numpy rows); the cache never
    copies — callers must not mutate returned rows.
    """

    def __init__(self, capacity_rows=None, ttl_s=None, clock=None):
        self.capacity_rows = (ENV.AUTODIST_SERVE_ROW_CACHE_ROWS.val
                              if capacity_rows is None
                              else int(capacity_rows))
        if self.capacity_rows < 1:
            raise ValueError('RowCache capacity must be >= 1; got %d'
                             % self.capacity_rows)
        self.ttl_s = (ENV.AUTODIST_SERVE_ROW_TTL_S.val
                      if ttl_s is None else float(ttl_s))
        self._clock = clock or time.monotonic
        # (table, row) -> (value, stamp); OrderedDict end = most recent
        self._entries = collections.OrderedDict()
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.expirations = 0
        self.invalidations = 0

    def __len__(self):
        return len(self._entries)

    def get(self, table, row):
        """The cached row, or None (miss). An entry past the TTL is a
        miss AND an expiration — it is dropped here so the caller's
        re-fetch re-inserts it with a fresh stamp."""
        key = (table, int(row))
        entry = self._entries.get(key)
        if entry is None:
            self.misses += 1
            return None
        value, stamp = entry
        if self._clock() - stamp > self.ttl_s:
            del self._entries[key]
            self.expirations += 1
            self.misses += 1
            return None
        self._entries.move_to_end(key)
        self.hits += 1
        return value

    def put(self, table, row, value):
        """Insert/refresh one row; evicts the least-recently-used
        entry past capacity."""
        key = (table, int(row))
        if key in self._entries:
            self._entries.move_to_end(key)
        self._entries[key] = (value, self._clock())
        while len(self._entries) > self.capacity_rows:
            self._entries.popitem(last=False)
            self.evictions += 1

    def invalidate_all(self):
        """Flush every entry — the dense-snapshot version bump hook.
        Counted separately from expirations: a bump flushing 60k warm
        rows and a TTL quietly expiring them are different stories."""
        n = len(self._entries)
        self._entries.clear()
        if n:
            self.invalidations += 1
        return n

    @property
    def hit_rate(self):
        total = self.hits + self.misses
        return self.hits / total if total else 0.0

    def stats(self):
        return {'rows': len(self._entries),
                'capacity_rows': self.capacity_rows,
                'ttl_s': self.ttl_s,
                'hits': self.hits, 'misses': self.misses,
                'evictions': self.evictions,
                'expirations': self.expirations,
                'invalidations': self.invalidations,
                'hit_rate': self.hit_rate}
