"""Read-only serving tier over the PS data plane (ISSUE 17).

The first consumer of the data plane that is not a trainer: a
``Session``-less replica fleet that serves lookup+forward queries
against the LIVE training namespace while the cohort keeps pushing.
Dense variables refresh as epoch-consistent whole-model snapshots
pinned to one published step (the seqlock pin -> pull -> revalidate
protocol in :mod:`~autodist_tpu.serving.replica`); sparse embedding
tables serve through an LRU+TTL row cache backed by on-demand
``vmgetrows``. Replicas are NON-VOTING: no fence bind, no step
publish, no gate participation, invisible to
``live_members_on_plane`` — a reader's death never stalls training.

See docs/design/serving.md for the consistency contract and the
staleness model.
"""
from autodist_tpu.serving.fleet import (ServingFleet, serve_loop,
                                        serving_autoscale_policy)
from autodist_tpu.serving.replica import ServingReplica, SnapshotView
from autodist_tpu.serving.row_cache import RowCache

__all__ = ['RowCache', 'ServingFleet', 'ServingReplica', 'SnapshotView',
           'serve_loop', 'serving_autoscale_policy']
