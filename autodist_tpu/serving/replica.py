"""``ServingReplica`` — a ``Session``-less read-only client of the
live training namespace.

Two connections, two planes:

- ``_data`` — a READ-ONLY :class:`CoordClient` (``read_only=True``):
  every snapshot pull, row fetch and counter read rides it, and any
  mutating verb would raise ``ReadOnlyViolation`` locally. Never
  fence-bound: readers must never take writer generations.
- ``_ctl`` — a normal control connection for the reader's OWN keys
  only (the ``serve/world`` admit claim and ``hb/serve/...``
  heartbeats), all under serve-prefixed names the training cohort
  never scans.

Epoch-consistent dense snapshots (the seqlock protocol, trainer half
in ``Session._snap_round_open/_close``):

1. PIN — read live membership (``join/world`` minus ``excluded/``
   markers), every live writer's ``<ns>/snap/p<i>`` parity counter and
   the published floor. Any ODD parity = a sync round is mid-flight;
   this attempt is abandoned before a byte of tensor data moves.
2. PULL — one batched ``vmget`` over every dense (variable, shard)
   unit. Each tensor is individually torn-read-safe on its own; the
   seqlock adds the CROSS-tensor guarantee.
3. REVALIDATE — re-read membership and parities. Accept iff both are
   unchanged: no writer opened OR completed a sync round during the
   pull, so every tensor read belongs to the same published step (the
   floor, re-read now, which the unchanged parities prove equal to
   the pinned one). On mismatch, retry from 1 — the PREVIOUS snapshot
   stays servable throughout, so a hot write phase degrades freshness,
   never availability.

A writer that crashed mid-round leaves its parity odd until the
cohort's exclusion machinery retires it; the replica keeps serving
the last accepted snapshot and its staleness grows — the documented
trade (docs/design/serving.md): a reader NEVER blocks training, so
training's failure handling bounds the reader's staleness, not the
reverse.
"""
import threading
import time

import numpy as np

from autodist_tpu.const import ENV
from autodist_tpu.runtime.coord_client import (CLEAN_CLOSE_STEP,
                                               connect_with_retry,
                                               wire_nbytes)
from autodist_tpu.serving.row_cache import RowCache
from autodist_tpu.telemetry import core as _telemetry
from autodist_tpu.utils import logging


def _percentile(samples, q):
    """Nearest-rank percentile of an unsorted sample list (0 when
    empty) — avoids numpy interpolation-surface churn for what is a
    stats readout, not math."""
    if not samples:
        return 0.0
    s = sorted(samples)
    k = min(len(s) - 1, max(0, int(round(q / 100.0 * (len(s) - 1)))))
    return s[k]


class SnapshotView:
    """One accepted epoch-consistent dense snapshot: ``values`` maps
    variable name -> host array (shards concatenated on axis 0, the
    plane's row-sharding convention), all mutually consistent at
    published step ``step``."""

    def __init__(self, step, values, members, wire_bytes_):
        self.step = step
        self.values = values
        self.members = members
        self.wire_bytes = wire_bytes_
        self.pulled_at = time.monotonic()

    def __repr__(self):
        return ('SnapshotView(step=%d, vars=%d, members=%s)'
                % (self.step, len(self.values), self.members))


class ServingReplica:
    """One read-only serving replica of namespace ``ns``.

    ``dense_vars`` maps variable name -> shape for the whole-model
    snapshot plane; ``shard_parts`` optionally overrides a name's
    storage layout with explicit ``[(key_suffix, shape), ...]`` units
    (the trainer's ``_shard_info`` layout for PS-sharded variables).
    ``sparse_vars`` maps table name -> (rows, ncols) for the row-cache
    plane. Variables in neither map are simply not served — a replica
    serves the projection of the model its queries need.
    """

    def __init__(self, ns, dense_vars=None, sparse_vars=None,
                 address=None, name=None, staleness_bound=None,
                 snapshot_retries=None, poll_s=None, wire=None,
                 row_cache=None, shard_parts=None):
        self._ns = ns
        self.name = name or 'replica'
        self._address = address
        self._dense = dict(dense_vars or {})
        self._sparse = {t: (int(r), int(c))
                        for t, (r, c) in (sparse_vars or {}).items()}
        self._parts = dict(shard_parts or {})
        self.staleness_bound = (
            ENV.AUTODIST_SERVE_STALENESS_BOUND.val
            if staleness_bound is None else int(staleness_bound))
        self.snapshot_retries = (
            ENV.AUTODIST_SERVE_SNAPSHOT_RETRIES.val
            if snapshot_retries is None else int(snapshot_retries))
        self.poll_s = (ENV.AUTODIST_SERVE_POLL_S.val
                       if poll_s is None else float(poll_s))
        self._wire = wire if wire is not None \
            else (ENV.AUTODIST_SERVE_WIRE.val or None)
        self.row_cache = row_cache or RowCache()
        self._data = None
        self._ctl = None
        self._admit = None
        # one lock serializes the data connection: the fleet's refresh
        # loop and query callers share one socket per replica, and two
        # interleaved pipelined reads would corrupt both reply streams
        self._lock = threading.Lock()
        self.snapshot = None
        self._tel = _telemetry.get()
        # serve accounting (serve_stats): lookups, recent per-lookup
        # walls (bounded — percentiles need samples, not history),
        # snapshot protocol outcomes, wire bytes, staleness trace
        self._lookup_ms = []
        self._lookup_ms_cap = 4096
        self._t_first_lookup = None
        self._t_last_lookup = None
        self.lookups = 0
        self.rows_served = 0
        self.wire_bytes = 0
        self.snapshot_pulls = 0
        self.snapshot_retries_used = 0
        self.snapshot_rejects = 0
        self.staleness_steps = 0
        self.staleness_max_steps = 0
        self.staleness_violations = 0
        self.mixed_version_reads = 0

    # -- membership / connection ------------------------------------------
    def connect(self, deadline_s=30.0):
        """Dial the coord service: the read-only data connection plus
        the serve-plane control connection, then the NON-VOTING admit
        (``admit_reader`` — no fence, no join/world claim, no step
        publish)."""
        from autodist_tpu.runtime.session import admit_reader
        self._data = connect_with_retry(self._address,
                                        deadline_s=deadline_s,
                                        read_only=True)
        self._ctl = connect_with_retry(self._address,
                                       deadline_s=deadline_s)
        self._admit = admit_reader(self._ctl, self._ns,
                                   wait_init_s=deadline_s)
        self.name = self._admit['reader']
        return self

    def close(self):
        # under the data lock: a refresh/lookup in flight on another
        # thread finishes against live sockets, and its NEXT call sees
        # the None guard instead of a half-torn client
        with self._lock:
            for c in (self._data, self._ctl):
                if c is not None:
                    try:
                        c.close()
                    except OSError:
                        pass
            self._data = self._ctl = None

    def beat(self):
        """Serve-plane heartbeat (``hb/serve/<ns>/<reader>``) — a
        liveness signal for fleet supervision, on a prefix the
        training cohort never scans."""
        if self._ctl is not None and self._admit is not None:
            self._ctl.heartbeat('serve/%s/%s'
                                % (self._ns, self._admit['reader']))

    def _key(self, suffix):
        return '%s/%s' % (self._ns, suffix)

    def live_writers(self):
        """Live WRITER ordinals: claimed ``join/world`` slots minus
        ``excluded/`` markers — the same definition as
        ``live_members_on_plane``, via delta-0 counter reads the
        read-only connection is allowed."""
        world = self._data.incr(self._key('join/world'), 0)
        return [i for i in range(world)
                if self._data.incr('excluded/%s/p%d'
                                   % (self._ns, i), 0) == 0]

    def published_floor(self, members=None):
        """Min published step over live writers (never-published zeros
        and ``CLEAN_CLOSE_STEP`` releases skipped, like the trainer's
        own floor scans)."""
        members = self.live_writers() if members is None else members
        floor = None
        for i in members:
            step = self._data.incr(self._key('step/p%d' % i), 0)
            if step == 0 or step >= CLEAN_CLOSE_STEP:
                continue
            floor = step if floor is None else min(floor, step)
        return floor or 0

    def _snap_parities(self, members):
        return [self._data.incr(self._key('snap/p%d' % i), 0)
                for i in members]

    # -- dense snapshot plane ---------------------------------------------
    def _dense_specs(self):
        """Every (key, shape) unit of the dense snapshot, honoring
        explicit shard layouts."""
        specs = []
        layout = []
        for nm in sorted(self._dense):
            parts = self._parts.get(nm) or [('var/%s' % nm,
                                             self._dense[nm])]
            layout.append((nm, len(parts)))
            for suffix, shape in parts:
                specs.append((self._key(suffix), tuple(shape)))
        return specs, layout

    def refresh(self):
        """One snapshot poll: pull a fresh epoch-consistent dense
        snapshot if one is ready, else keep serving the current one.
        Returns True when a NEW snapshot was accepted. Every retry
        path leaves ``self.snapshot`` untouched."""
        if self._data is None:
            # closed (or never connected): surface as the connection
            # error the serve loop already logs-and-retries on
            raise OSError('%s: not connected' % self.name)
        if not self._dense:
            # row-cache-only replicas still track staleness for stats
            with self._lock:
                self._note_staleness(self.published_floor())
            return False
        specs, layout = self._dense_specs()
        with self._tel.span('serve/refresh', replica=self.name), \
                self._lock:
            staleness_noted = False
            for attempt in range(self.snapshot_retries):
                members = self.live_writers()
                parities = self._snap_parities(members)
                if any(p & 1 for p in parities):
                    # a sync round is mid-flight: abandon before any
                    # tensor byte moves — this poll's pull would be
                    # invalidated at revalidate anyway. Still grade
                    # staleness against the published floor: a writer
                    # crashed mid-round (parity stuck odd) is exactly
                    # when the replica falls behind, and the exhausted
                    # path below would otherwise never account it.
                    if not staleness_noted:
                        self._note_staleness(self.published_floor(members))
                        staleness_noted = True
                    self.snapshot_retries_used += 1
                    time.sleep(0.005 * (attempt + 1))
                    continue
                floor = self.published_floor(members)
                if self.snapshot is not None and \
                        floor <= self.snapshot.step:
                    self._note_staleness(floor)
                    return False
                arrs = self._data.vmget(specs, wire=self._wire)
                if any(a is None for a in arrs):
                    # the namespace has no full model yet (cohort
                    # still initializing): nothing to serve
                    self.snapshot_rejects += 1
                    return False
                if self.live_writers() != members or \
                        self._snap_parities(members) != parities:
                    # a writer opened/completed a round (or membership
                    # moved) during the pull: the set may mix steps —
                    # discard and retry; the old snapshot stays up
                    self.snapshot_retries_used += 1
                    continue
                values = {}
                i = 0
                for nm, nparts in layout:
                    parts = [np.asarray(arrs[i + k])
                             for k in range(nparts)]
                    i += nparts
                    values[nm] = (parts[0] if nparts == 1
                                  else np.concatenate(parts, axis=0))
                pulled = sum(
                    wire_nbytes(int(np.prod(shape)) if shape else 1,
                                self._wire)
                    for _, shape in specs)
                self.snapshot = SnapshotView(floor, values, members,
                                             pulled)
                self.wire_bytes += pulled
                self.snapshot_pulls += 1
                # an accepted dense bump flushes the sparse cache:
                # rows cached against the previous step next to new
                # dense weights would be a mixed-version serve
                self.row_cache.invalidate_all()
                self._note_staleness(floor)
                self._tel.count('serve/snapshot_pulls')
                self._tel.gauge('serve/snapshot_step', floor)
                return True
        self.snapshot_rejects += 1
        logging.debug('%s: snapshot pull kept losing to writers after '
                      '%d attempts; serving the previous snapshot',
                      self.name, self.snapshot_retries)
        return False

    def _note_staleness(self, floor):
        if self.snapshot is None:
            return
        stale = max(0, floor - self.snapshot.step)
        self.staleness_steps = stale
        self.staleness_max_steps = max(self.staleness_max_steps, stale)
        if stale > self.staleness_bound:
            self.staleness_violations += 1
        self._tel.gauge('serve/staleness_steps', stale)

    # -- query plane -------------------------------------------------------
    def lookup(self, table, indices):
        """Serve embedding rows of sparse ``table``: row cache first,
        one batched ``vmgetrows`` for the misses. Returns a
        ``[len(indices), ncols]`` float32 array."""
        t0 = time.perf_counter()
        rows, ncols = self._sparse[table]
        idx = np.asarray(indices, dtype=np.int32).reshape(-1)
        out = np.empty((idx.size, ncols), dtype=np.float32)
        with self._lock:
            return self._lookup_locked(table, idx, ncols, out, t0)

    def _lookup_locked(self, table, idx, ncols, out, t0):
        missing = []
        for j, r in enumerate(idx):
            cached = self.row_cache.get(table, int(r))
            if cached is None:
                missing.append(j)
            else:
                out[j] = cached
        if missing:
            want = np.unique(idx[missing])
            fetched = self._data.vmgetrows(
                [(self._key('var/%s' % table), want, ncols)],
                wire=self._wire)[0]
            if fetched is None:
                raise KeyError('sparse table %r is not on the plane '
                               '(key %s)' % (table,
                                             self._key('var/%s' % table)))
            by_row = {int(r): fetched[k] for k, r in enumerate(want)}
            for r, vec in by_row.items():
                self.row_cache.put(table, r, vec)
            for j in missing:
                out[j] = by_row[int(idx[j])]
            self.wire_bytes += wire_nbytes(int(want.size) * ncols,
                                           self._wire)
        wall_ms = 1e3 * (time.perf_counter() - t0)
        self.lookups += 1
        self.rows_served += idx.size
        now = time.monotonic()
        if self._t_first_lookup is None:
            self._t_first_lookup = now
        self._t_last_lookup = now
        if len(self._lookup_ms) >= self._lookup_ms_cap:
            # keep the newest window: percentiles should describe the
            # current regime, not the cold start
            self._lookup_ms = self._lookup_ms[self._lookup_ms_cap // 2:]
        self._lookup_ms.append(wall_ms)
        self._tel.observe('serve/lookup_ms', wall_ms)
        return out

    def forward(self, fn, *args, **kwargs):
        """Run a caller model function against the pinned dense
        snapshot: ``fn(values, *args, **kwargs)`` where ``values`` is
        the snapshot's name -> array dict. Raises until the first
        snapshot lands — a replica must never silently serve from
        nothing."""
        if self.snapshot is None:
            raise RuntimeError(
                '%s: no dense snapshot accepted yet (cohort still '
                'initializing, or refresh() never ran)' % self.name)
        return fn(self.snapshot.values, *args, **kwargs)

    # -- stats -------------------------------------------------------------
    def serve_stats(self):
        span = ((self._t_last_lookup - self._t_first_lookup)
                if self._t_first_lookup is not None and
                self._t_last_lookup > self._t_first_lookup else 0.0)
        return {
            'replica': self.name,
            'lookups': self.lookups,
            'rows_served': self.rows_served,
            'qps': (self.lookups / span) if span else 0.0,
            'lookup_p50_ms': _percentile(self._lookup_ms, 50),
            'lookup_p99_ms': _percentile(self._lookup_ms, 99),
            'snapshot_step': self.snapshot.step if self.snapshot
            else -1,
            'snapshot_pulls': self.snapshot_pulls,
            'snapshot_retries': self.snapshot_retries_used,
            'snapshot_rejects': self.snapshot_rejects,
            'staleness_steps': self.staleness_steps,
            'staleness_max_steps': self.staleness_max_steps,
            'staleness_bound_steps': self.staleness_bound,
            'staleness_violations': self.staleness_violations,
            'mixed_version_reads': self.mixed_version_reads,
            'row_cache_hit_rate': self.row_cache.hit_rate,
            'row_cache': self.row_cache.stats(),
            'wire_bytes': self.wire_bytes,
        }
