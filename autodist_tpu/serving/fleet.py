"""``ServingFleet`` — N replicas, one refresh loop each, zero votes.

The harness half of the serving tier: owns the replica threads (one
``serve_loop`` per replica: poll the published floor at the
``AUTODIST_SERVE_POLL_S`` cadence, refresh the dense snapshot when it
advanced, beat the serve-plane heartbeat), round-robins query traffic
across replicas, aggregates ``serve_stats`` for
``profiling.health_report``, and plugs into the existing
:class:`~autodist_tpu.runtime.coordinator.AutoscaleController`
unchanged: :meth:`metrics` is a ``metrics_source``, :meth:`scale_up`
is a ``scale_up`` callable, and :func:`serving_autoscale_policy`
turns serve QPS/latency pressure into replica growth the same way the
training policy turns step-time pressure into worker growth.

Replicas here are threads, not processes: every replica is already a
full independent client of the coord service (its own two sockets,
its own non-voting admit ordinal, its own caches), so the process
boundary adds nothing the tests or the bench need — and a REAL
deployment runs one ``ServingReplica`` per process with exactly the
same code, pointed at the same namespace.
"""
import itertools
import threading
import time

from autodist_tpu.serving.replica import ServingReplica, _percentile
from autodist_tpu.utils import logging


def serve_loop(replica, stop_event, poll_s=None, beat_every_s=1.0):
    """One replica's background duty cycle: snapshot poll + heartbeat
    until ``stop_event`` is set. Query traffic does NOT flow through
    here — lookups run on caller threads against the replica's lock.
    Errors are logged and retried next cycle: a flaky poll must not
    kill the replica while its last good snapshot is still
    servable."""
    poll_s = replica.poll_s if poll_s is None else poll_s
    last_beat = 0.0
    while not stop_event.is_set():
        try:
            replica.refresh()
            now = time.monotonic()
            if now - last_beat >= beat_every_s:
                replica.beat()
                last_beat = now
        except OSError as e:
            logging.warning('%s: serve poll failed (%s); retrying',
                            replica.name, e)
        stop_event.wait(poll_s)


def serving_autoscale_policy(qps_per_replica_target=None,
                             p99_target_ms=None, grow_by=1):
    """Autoscale policy factory for the replica fleet — the serving
    twin of ``coordinator.autoscale_policy``: grow when per-replica
    QPS exceeds ``qps_per_replica_target`` or the fleet's p99 lookup
    latency exceeds ``p99_target_ms`` (either signal suffices; unset
    signals are ignored). Returns ``policy(metrics, current_world) ->
    desired | None`` for an ``AutoscaleController`` whose
    ``metrics_source`` is :meth:`ServingFleet.metrics` and whose
    ``scale_up`` is :meth:`ServingFleet.scale_up`."""
    def policy(metrics, current_world):
        replicas = metrics.get('serve_replicas') or current_world or 1
        qps = metrics.get('serve_qps')
        p99 = metrics.get('serve_p99_ms')
        if qps_per_replica_target is not None and qps is not None \
                and qps / max(1, replicas) > qps_per_replica_target:
            return current_world + grow_by
        if p99_target_ms is not None and p99 is not None \
                and p99 > p99_target_ms:
            return current_world + grow_by
        return None
    return policy


class ServingFleet:
    """A fleet of :class:`ServingReplica` threads over one training
    namespace. ``replica_kwargs`` are forwarded to every replica
    (``dense_vars``, ``sparse_vars``, ``address``, bounds, ...)."""

    def __init__(self, ns, **replica_kwargs):
        self._ns = ns
        self._kwargs = replica_kwargs
        self.replicas = []
        self._threads = []
        self._stops = []
        self._rr = itertools.count()
        self._grow_lock = threading.Lock()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()

    # -- growth ------------------------------------------------------------
    def add_replica(self, connect_deadline_s=30.0):
        """Admit + start one replica (non-voting; the training cohort
        neither waits for it nor ever learns its name)."""
        replica = ServingReplica(self._ns, **self._kwargs)
        replica.connect(deadline_s=connect_deadline_s)
        stop = threading.Event()
        t = threading.Thread(target=serve_loop, args=(replica, stop),
                             name='serve-%s' % replica.name,
                             daemon=True)
        with self._grow_lock:
            self.replicas.append(replica)
            self._stops.append(stop)
            self._threads.append(t)
        t.start()
        return replica

    def scale_up(self, n=1):
        """``AutoscaleController``'s ``scale_up`` contract: launch
        ``n`` more replicas, return the list actually started (a
        failed admit stops the batch — the controller records what
        launched, not what was asked)."""
        started = []
        for _ in range(max(0, int(n))):
            try:
                started.append(self.add_replica())
            except (OSError, RuntimeError) as e:
                logging.warning('serving scale_up stopped at %d/%d: %s',
                                len(started), n, e)
                break
        return started

    def live_replicas(self):
        """Replica count with a live serve thread — the controller's
        ``live_world`` resync hook."""
        return sum(1 for t in self._threads if t.is_alive())

    # -- query plane -------------------------------------------------------
    def lookup(self, table, indices):
        """Round-robin a lookup across replicas."""
        if not self.replicas:
            raise RuntimeError('ServingFleet has no replicas '
                               '(add_replica/scale_up first)')
        replica = self.replicas[next(self._rr) % len(self.replicas)]
        return replica.lookup(table, indices)

    def refresh_all(self):
        """Force one synchronous snapshot poll on every replica —
        deterministic alternative to waiting out the poll cadence
        (tests and the bench's A/B legs)."""
        return [r.refresh() for r in self.replicas]

    # -- stats / autoscale wiring ------------------------------------------
    def metrics(self):
        """``AutoscaleController`` ``metrics_source`` sample: the
        serving pressure signals, named so the training policy's
        signals (``step_time_s``, ``queue_depth``) never collide."""
        per = [r.serve_stats() for r in self.replicas]
        return {
            'serve_replicas': len(per),
            'serve_qps': sum(s['qps'] for s in per),
            'serve_p99_ms': max((s['lookup_p99_ms'] for s in per),
                                default=0.0),
            'serve_staleness_steps': max(
                (s['staleness_steps'] for s in per), default=0),
        }

    def stats(self):
        """Aggregated fleet stats for ``profiling.health_report``'s
        ``serving`` section (and the bench's serving block)."""
        per = [r.serve_stats() for r in self.replicas]
        samples = []
        for r in self.replicas:
            samples.extend(r._lookup_ms)
        return {
            'replicas': len(per),
            'qps': sum(s['qps'] for s in per),
            'lookups': sum(s['lookups'] for s in per),
            'lookup_p50_ms': _percentile(samples, 50),
            'lookup_p99_ms': _percentile(samples, 99),
            'staleness_steps': max((s['staleness_steps'] for s in per),
                                   default=0),
            'staleness_max_steps': max(
                (s['staleness_max_steps'] for s in per), default=0),
            'staleness_bound_steps': max(
                (s['staleness_bound_steps'] for s in per), default=0),
            'staleness_violations': sum(
                s['staleness_violations'] for s in per),
            'mixed_version_reads': sum(
                s['mixed_version_reads'] for s in per),
            'snapshot_pulls': sum(s['snapshot_pulls'] for s in per),
            'snapshot_retries': sum(s['snapshot_retries'] for s in per),
            'row_cache_hit_rate': (
                sum(s['row_cache_hit_rate'] for s in per) / len(per)
                if per else 0.0),
            'wire_bytes': sum(s['wire_bytes'] for s in per),
            'per_replica': per,
        }

    def stop(self, timeout_s=10.0):
        """Stop every serve loop and close every connection. Safe to
        call twice; never raises on a half-dead replica."""
        for stop in self._stops:
            stop.set()
        for t in self._threads:
            t.join(timeout=timeout_s)
        for r in self.replicas:
            r.close()
