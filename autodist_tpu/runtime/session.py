"""Session: the per-step execution driver.

Replaces the reference's ``WrappedSession`` + ``Remapper``
(``autodist/runner.py:78-132``, ``autodist/remapper.py:29-313``). Where
the reference patches TF's feed/fetch expansion registry and talks to a
grpc server, the TPU session owns the training state (variables, optimizer
slots, compressor aux state) as sharded ``jax.Array``s and compiles one
fused XLA program per distinct (fetches, feed-signature) pair:

- **feed remapping** (remapper.py:109-123): feeds whose leading dim splits
  evenly across the ``data`` axis are sharded onto it; others replicated.
- **fetch remapping** (remapper.py:125-185): train ops run on all replicas
  and fetch as None; tensors with a batch ("polymorphic") dim concatenate
  across replicas; everything else returns the master replica's value.
- the whole captured program is interpreted inside ``shard_map`` over the
  mesh, so replication+synchronization compile into a single program (the
  reference's in-graph replication + collective splicing equivalent).
"""
import os
from collections import deque as _deque

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from autodist_tpu import telemetry as _telemetry
from autodist_tpu.const import (AXIS_DATA, DEFAULT_CHECKPOINT_DIR,
                                DEFAULT_TRACE_DIR, ENV)
from autodist_tpu.frontend import graph as fe
from autodist_tpu.parallel.plan import ShardedGrad
from autodist_tpu.utils import logging

# jax-version-portable shard_map (check_vma/check_rep spelling handled
# by the shared compat helper)
from autodist_tpu.parallel.axes import shard_map_compat as _shard_map


class RunOptions:
    """Shim for tf.RunOptions: trace_level triggers a profiler trace
    (reference runner.py:64-75 writes chrome traces)."""

    NO_TRACE = 0
    FULL_TRACE = 3

    def __init__(self, trace_level=0, trace_dir=None):
        self.trace_level = trace_level
        self.trace_dir = trace_dir or DEFAULT_TRACE_DIR


def assign_ps_endpoints(var_plans, endpoints):
    """Map each variable to PS endpoint indices, one PER SHARD.

    Placement honors the strategy's per-shard ``reduction_destination``s
    (reference ps_lb_strategy.py:64-83 bin-packing;
    partitioned_ps_strategy.py:89-96 places each shard of a partitioned
    variable on its own PS — ``part_config`` is consumed here, not just
    ``syncs[0]``): endpoints co-located on the destination's host are
    preferred (several on one host spread by destination ordinal);
    destinations on unknown hosts map by their ordinal among the sorted
    distinct destinations; vars without a destination hash stably.
    Returns ``{var name: [endpoint idx per shard]}`` (a 1-element list
    for unpartitioned variables). Pure function so placement is
    unit-testable and deterministic across processes.
    """
    import zlib
    n = len(endpoints)
    hosts = [h for h, _ in endpoints]
    all_dests = set()
    for p in var_plans.values():
        if not p.is_ps:
            continue
        for s in getattr(p, 'all_syncs', [p.sync]):
            d = getattr(s, 'reduction_destination', '')
            if d:
                all_dests.add(d)
    dest_ord = {d: i for i, d in enumerate(sorted(all_dests))}

    def resolve(label, sync, is_ps):
        dest = getattr(sync, 'reduction_destination', '') if is_ps else ''
        if dest:
            dhost = dest.split(':', 1)[0]
            cands = [i for i, h in enumerate(hosts) if h == dhost]
            if cands:
                return cands[dest_ord[dest] % len(cands)]
            return dest_ord[dest] % n
        return zlib.crc32(label.encode()) % n

    out = {}
    for name, p in var_plans.items():
        syncs = list(getattr(p, 'all_syncs', [p.sync]))
        nshards = getattr(p, 'num_shards', 1)
        if nshards > 1 and len(syncs) == nshards:
            out[name] = [
                resolve('%s/shard%d' % (name, i), s, p.is_ps)
                for i, s in enumerate(syncs)]
        else:
            out[name] = [resolve(name, p.sync, p.is_ps)]
    return out


def live_members_on_plane(coord, ns):
    """THE live-membership definition for namespace ``ns`` — claimed
    ordinals minus excluded slots — as ``(live, world, excluded)``.
    :func:`admit_worker`'s cap check and the coordinator's scale-up
    clamp (``Coordinator._live_world_estimate``) both ride this one
    implementation: if the definition ever changes (e.g. counting
    done/ markers), they must move together or the clamp and the
    authoritative admit-time refusal silently disagree."""
    world = coord.incr('%s/join/world' % ns, 0)
    excluded = sum(
        1 for i in range(world)
        if coord.incr('excluded/%s/p%d' % (ns, i), 0) > 0)
    return world - excluded, world, excluded


def admit_worker(coord, ns, max_workers=None, wait_init_s=120.0,
                 launch_workers=None):
    """The live scale-UP admit handshake: join worker ``coord`` into the
    RUNNING loose-mode namespace ``ns`` (the second half of elasticity —
    PR 4 made workers *leaving* survivable; this makes joining possible).

    One protocol, one place: :class:`Session` joins through it when
    ``AUTODIST_ELASTIC_JOIN`` is set, and chaos tests / ``bench.py``'s
    elastic A/B drive it with a raw client — the handshake must not be
    re-implemented per caller or the fault-injection coverage
    (``faultline``'s ``join_*`` kinds) stops meaning anything.

    Ordering is the contract (each step's placement matters):

    1. wait for ``<ns>/session/init-done`` — a join is only legal
       against a cohort whose init rendezvous completed (the world
       counter is only guaranteed seeded after it, and the chief clears
       stale markers before it).
    2. claim a worker slot: an atomic ``INCR`` of ``<ns>/join/world``
       (the same counter the launch cohort seeded to its quorum — no
       new service atomic needed). Refused when the claim would exceed
       ``AUTODIST_MAX_WORKERS``.
    3. bind the slot's fence generation BEFORE any namespace write, so
       every admit-path write is already fenceable: a joiner declared
       dead mid-admit is rejected exactly like any other zombie.
    4. compute the adopted step FLOOR: the min of live members'
       published steps (``CLEAN_CLOSE_STEP`` releases and never-
       published zeros skipped) — the one value that neither blocks the
       cohort's staleness gates (a join at step 0 would stall everyone
       at ``floor + staleness``) nor claims progress ahead of any peer.
    5. bump ``<ns>/epoch`` — MEMBERSHIP BECOMES VISIBLE FIRST, then
       the floor is published and the heartbeat baseline laid down.
       This order is the one whose failure window SELF-HEALS: a joiner
       dying after the bump is a visible member with no step/beat,
       which the never-beat rule declares dead and the exclude path
       releases within one heartbeat window. The reverse order
       (step counter before membership) leaves an INVISIBLE frozen
       counter inside the gate's prefix-min that no survivor can ever
       exclude — a permanent cohort stall with no recovery path.

    Returns ``{'worker_id', 'worker', 'world', 'generation',
    'adopted_step', 'epoch', 'admit_wall_s'}``.
    """
    import time as _time
    from autodist_tpu.runtime.coord_client import CLEAN_CLOSE_STEP
    if max_workers is None:
        max_workers = ENV.AUTODIST_MAX_WORKERS.val
    t0 = _time.monotonic()
    coord.wait_key('%s/session/init-done' % ns, timeout_s=wait_init_s)
    world_key = '%s/join/world' % ns
    # the cap bounds LIVE membership, not cumulative ordinals: the
    # monotone counter never decrements, so dead (excluded) workers
    # must hand their headroom back or a long-running job with churn
    # would ratchet itself below the ceiling it is allowed to refill.
    # (One serial INCR per ordinal: at the default 64-worker cap this
    # is a handful of round-trips paid once per admit, not per step.)
    live, before, excluded_n = live_members_on_plane(coord, ns)
    if launch_workers and before < launch_workers:
        raise RuntimeError(
            'cannot join namespace %s: its world counter (%d) is below '
            'the launch quorum (%d) — the cohort never seeded it (a '
            'stale init-done marker on a reused service, or not an '
            'elastic-capable run)' % (ns, before, launch_workers))
    if live >= max_workers:
        raise RuntimeError(
            'cannot join namespace %s: live membership (%d of %d '
            'claimed slots) is already at AUTODIST_MAX_WORKERS=%d'
            % (ns, live, before, max_workers))
    world = coord.incr(world_key, 1)
    worker_id = world - 1
    worker = 'p%d' % worker_id
    flight = _telemetry.recorder()
    flight.record('admit_claim', worker=worker, world=world, ns=ns)
    if world - excluded_n > max_workers:
        # the cap read above and the claim are separate RPCs, so two
        # concurrent joiners can both pass the pre-check; the LAST
        # claim lands over the cap. The claim cannot be rolled back
        # (the monotone counter never re-issues ordinals — a decrement
        # would hand the next joiner a colliding slot), so retire the
        # slot as already-excluded + released: any survivor that ever
        # sees it skips it without paying a heartbeat window, and the
        # live membership never exceeds the cap.
        coord.incr('excluded/%s/%s' % (ns, worker), 1)
        coord.publish_step(worker, CLEAN_CLOSE_STEP,
                           prefix='%s/step/' % ns)
        flight.record('admit_cap_retire', worker=worker, world=world)
        raise RuntimeError(
            'cannot join namespace %s: a concurrent join raced this '
            'claim past AUTODIST_MAX_WORKERS=%d (slot %s retired as '
            'excluded)' % (ns, max_workers, worker))
    # fence binding precedes every namespace write below; generation>0
    # means this SLOT was admitted before and its holder declared dead
    # (slots are never re-issued by the monotone world counter, so that
    # only happens to a supervised re-admit of this same joiner).
    fence_key = 'fence/%s/%s' % (ns, worker)
    generation = coord.incr(fence_key, 0)
    coord.fence(fence_key, generation)
    flight.record('admit_fence_bind', worker=worker,
                  generation=generation)
    floor = None
    for i in range(worker_id):
        step = coord.incr('%s/step/p%d' % (ns, i), 0)
        if step == 0 or step >= CLEAN_CLOSE_STEP:
            # never-published (a half-admitted ghost, or a cohort still
            # at step 0 — then every member reads 0 and the floor
            # degrades to 0 anyway) or a departed worker's release
            continue
        floor = step if floor is None else min(floor, step)
    # a crashed-but-not-yet-excluded peer can still be in this min, but
    # the staleness gate bounds how stale: every live counter (and so
    # any recent corpse's) is within gate_staleness of the cohort's
    # front, so adopting it costs the joiner at most `staleness` extra
    # catch-up steps — never a cohort stall
    floor = floor or 0
    # epoch bump BEFORE the step publish (see step 5 above): every
    # post-claim death must leave a VISIBLE member the exclusion
    # machinery can clean up, never an invisible counter it cannot
    epoch = coord.incr('%s/epoch' % ns, 1)
    flight.record('admit_epoch_bump', worker=worker, epoch=epoch)
    coord.publish_step(worker, floor, prefix='%s/step/' % ns)
    flight.record('admit_floor_publish', worker=worker, floor=floor)
    coord.heartbeat('%s/%s' % (ns, worker))
    wall = _time.monotonic() - t0
    logging.info(
        'admitted %s into %s at epoch %d: world %d -> %d, adopted step '
        'floor %d, generation %d (%.3fs)', worker, ns, epoch, before,
        world, floor, generation, wall)
    return {'worker_id': worker_id, 'worker': worker, 'world': world,
            'generation': generation, 'adopted_step': floor,
            'epoch': epoch, 'admit_wall_s': wall}


def admit_reader(coord, ns, wait_init_s=120.0):
    """Admit a NON-VOTING serving replica into namespace ``ns`` — the
    reader half of :func:`admit_worker`, deliberately missing every
    step that makes a worker count:

    - no fence bind: readers never take writer generations (a
      read-only data connection cannot even issue FENCE —
      :class:`~autodist_tpu.runtime.coord_client.ReadOnlyViolation`);
    - no ``join/world`` claim, no epoch bump, no step publish: the
      reader must be invisible to :func:`live_members_on_plane`, the
      staleness gates and every exclusion/quorum path — a reader dying
      mid-pull must cost the training cohort NOTHING, not even one
      heartbeat window of exclusion work.

    Readers claim ordinals on their own ``<ns>/serve/world`` counter
    (same monotone-claim idiom, disjoint key) and heartbeat under
    ``hb/serve/<ns>/r<i>`` — a SERVE-prefixed liveness plane the
    training cohort never scans. ``coord`` must be a WRITABLE control
    connection (the claim and beats are INCRs); the replica's bulk
    data pulls ride a separate read-only connection.

    Returns ``{'reader_id', 'reader', 'serve_world', 'admit_wall_s'}``.
    """
    import time as _time
    t0 = _time.monotonic()
    # same legality condition as a worker join: the world/step keys a
    # reader is about to poll are only guaranteed seeded (and stale
    # markers cleared) after the cohort's init rendezvous
    coord.wait_key('%s/session/init-done' % ns, timeout_s=wait_init_s)
    serve_world = coord.incr('%s/serve/world' % ns, 1)
    reader_id = serve_world - 1
    reader = 'r%d' % reader_id
    coord.heartbeat('serve/%s/%s' % (ns, reader))
    _telemetry.recorder().record('serve_admit', reader=reader, ns=ns,
                                 serve_world=serve_world)
    wall = _time.monotonic() - t0
    logging.info('admitted serving replica %s into %s (serve world %d, '
                 'non-voting, %.3fs)', reader, ns, serve_world, wall)
    return {'reader_id': reader_id, 'reader': reader,
            'serve_world': serve_world, 'admit_wall_s': wall}


class _LazyDefault:
    """Non-data descriptor: a class-level fallback a stub session
    built via ``__new__`` (liveness/chaos tests exercise single
    methods that way) resolves to the same process-wide value
    ``__init__`` would have bound — and which any instance assignment
    shadows. Deliberately NOT ``__getattr__``: that hook would convert
    an ``AttributeError`` escaping any Session property getter into a
    misleading ``AttributeError: <property name>``."""

    def __init__(self, factory, name):
        self._factory = factory
        self._name = name

    def __get__(self, obj, objtype=None):
        if obj is None:
            return self
        val = self._factory()
        obj.__dict__[self._name] = val
        return val


class Session:
    """Stateful driver over the functional compiled step.

    Multi-process modes:

    - **global SPMD** (sync strategies): every process joins one program
      over a multi-host mesh; gradient sync rides XLA collectives. The
      feed/fetch contract stays process-local (between-graph semantics:
      each worker feeds its own batch, fetches its own replicas' values).
    - **loose** (all-relaxed PS strategies): each process runs an
      independent local program; variables are authoritative on the native
      coord service, workers pull values / push update deltas every step
      (apply-per-push = reference staleness-mode accumulators,
      ps_synchronizer.py:387-458) gated by the bounded-staleness window.
    """

    _tel = _LazyDefault(lambda: _telemetry.get(), '_tel')
    _flight = _LazyDefault(lambda: _telemetry.recorder(), '_flight')
    _step_walls = _LazyDefault(
        lambda: _deque(maxlen=ENV.AUTODIST_TELEMETRY_MAX_SPANS.val),
        '_step_walls')
    # stub sessions (__new__) have no sentry, no telemetry push lane
    # and no roofline tracker; real ones bind in __init__
    _monitor = None
    _tel_pipe = None
    _tel_push_handle = None
    _roofline_tracker = None
    _last_step_cost = None
    _last_exec_wall = 0.0

    def __init__(self, graph_item, plan, cluster=None, coord=None):
        self._graph_item = graph_item
        self._plan = plan
        self._mesh = plan.mesh
        self._cluster = cluster
        self._coord = coord
        self._cache = {}
        self._step_count = 0
        self._round_count = 0   # completed local-SGD sync rounds
        self._closed = False
        self._loose = plan.loose
        # namespace coord-service keys by strategy id: a reused/leaked
        # service must not serve a previous run's vars or step counters.
        # (Assigned before identity: the elastic admit below claims a
        # worker slot under this namespace.)
        self._ns = getattr(plan.strategy, 'id', 'default')
        if self._loose and coord is None:
            raise RuntimeError('loose multi-process mode needs a coord '
                               'service client')
        # telemetry handles + the run boundary BEFORE the elastic
        # admit below: the admit handshake records flight events, and
        # a run_start recorded after them would wipe the only live
        # admit trail from the conformance replay (the checker resets
        # per-run tracking at every boundary). Worker identity is
        # attached once the admit has settled it.
        self._tel = _telemetry.get()
        self._flight = _telemetry.recorder()
        self._flight.set_context(ns=self._ns)
        self._flight.record('run_start', ns=self._ns)
        # -- elastic scale-UP: live JOIN into a running namespace ----------
        # AUTODIST_ELASTIC_JOIN marks this process as a joiner: it was
        # not part of the launch cohort, so its definitive identity is
        # the slot the admit handshake claims — the spawner's env
        # process id is advisory only. The env is rewritten to the
        # claimed slot so everything downstream (worker name, heartbeat
        # peers, pipeline floor loops) agrees with the control plane.
        self._joining = False
        self._admit = None
        if self._loose and ENV.AUTODIST_ELASTIC_JOIN.val:
            # launch_workers guards the never-seeded case: a stale
            # init-done marker on a reused service must refuse the
            # join, not hand out a launch-cohort ordinal (read BEFORE
            # the identity env rewrite below)
            self._admit = admit_worker(
                coord, self._ns,
                launch_workers=ENV.AUTODIST_NUM_PROCESSES.val)
            os.environ[ENV.AUTODIST_PROCESS_ID.name] = \
                str(self._admit['worker_id'])
            os.environ[ENV.AUTODIST_NUM_PROCESSES.name] = \
                str(self._admit['world'])
            self._joining = True
        self._num_workers = ENV.AUTODIST_NUM_PROCESSES.val
        self._worker_name = 'p%d' % ENV.AUTODIST_PROCESS_ID.val
        self._flight.set_context(worker=self._worker_name)
        # uniform per-step wall series: EVERY executed train step's
        # run() wall time lands here, loose or SPMD, pipelined or
        # serial (the t_step phase timing only covers loose-mode
        # paths). Bounded ring; count/total survive in the telemetry
        # series when enabled.
        self._step_walls = _deque(
            maxlen=ENV.AUTODIST_TELEMETRY_MAX_SPANS.val)
        # a joiner is never the chief: the chief seeded the PS and owns
        # the cohort rendezvous — a joiner consumes both
        self._is_chief = not ENV.AUTODIST_WORKER.val and \
            not self._joining
        # Bucketed AllReduce sync (plan.sync_gradients) only overlaps
        # the backward pass if XLA is allowed to schedule the bucket
        # collectives asynchronously — arm the latency-hiding flags
        # (opt-out: AUTODIST_XLA_OVERLAP=0). libtpu reads them at
        # backend init, so on an already-up backend they reach only
        # processes launched after this point (the coordinator forwards
        # LIBTPU_INIT_ARGS to workers).
        if not self._loose and plan.num_replicas > 1 and \
                any(p.is_ar for p in plan.var_plans.values()):
            from autodist_tpu.utils.jax_env import setup_overlap_flags
            applied = setup_overlap_flags()
            if applied:
                logging.info('Gradient bucketing active: armed XLA '
                             'overlap flags %s', applied)
        # -- elastic recovery (epoch-fenced membership) --------------------
        # Peer-failure policy: what a survivor does when a peer misses
        # heartbeats (fail = raise, exclude = fence + shrink membership,
        # restart = wait for the coordinator-supervised replacement).
        self._policy = ENV.AUTODIST_PEER_FAILURE_POLICY.val
        self._min_workers = ENV.AUTODIST_MIN_WORKERS.val
        self._excluded = set()      # peer keys dropped from membership
        self._dead_since = {}       # restart policy: key -> detect time
        self._epoch_seen = 0        # membership epoch (coord counter)
        self._generation = 0        # this worker's fencing generation
        self._fence_key = ''
        self._rejoining = False
        # live world size: the launch quorum GROWN by admitted joiners
        # (the <ns>/join/world counter). Every membership-derived
        # quantity — gate party counts, the AUTODIST_MIN_WORKERS floor,
        # pipeline peer floors, the close() purge quorum — re-evaluates
        # against this, never the launch-time count.
        self._world = self._num_workers
        self._health = {'policy': self._policy, 'missed_beats': 0,
                        'epoch_bumps': 0, 'exclusions': [],
                        'rejoins': [], 'recovery_wall_s': [],
                        'joins': [], 'replans': [],
                        'auto_checkpoints': 0}
        if self._joining:
            self._health['admitted'] = dict(self._admit)
        if self._loose:
            # every write this process makes rides connections bound to
            # its fencing generation: once a survivor (or the restart
            # supervisor) bumps our fence counter, the service rejects
            # our writes — a zombie can never corrupt post-death state.
            # fence/excluded counters live OUTSIDE the run namespace:
            # the run-end purge (close) must not unfence a zombie or
            # erase the exclusion record it may still need to observe
            self._fence_key = 'fence/%s' % self._key(self._worker_name)
            self._generation = coord.incr(self._fence_key, 0)
            coord.fence(self._fence_key, self._generation)
            self._flight.set_context(generation=self._generation)
            self._flight.record('fence_bind', worker=self._worker_name,
                                generation=self._generation)
            # generation > 0 means a previous incarnation was declared
            # dead: this process is its supervised replacement and must
            # REJOIN (skip the init barrier nobody else attends, pull
            # current params from the PS, resume at the published step).
            # A live JOINer claims a fresh slot (generation 0) instead.
            self._rejoining = self._generation > 0 and not self._joining
            if self._is_chief and not self._rejoining:
                # a reused service may hold a PREVIOUS run's init-done
                # marker (deterministic strategy id, crashed run whose
                # close-purge never ran): left in place, a joiner
                # launched before this chief could admit against the
                # stale world counter and collide with the reset below
                # — delete it FIRST (it is re-published only after this
                # run's rendezvous completes). The residual window (a
                # joiner passing wait_key before this delete) requires
                # joiners launched before the run they join, which the
                # scale-up paths never do.
                self._coord.delete(self._key('session/init-done'))
                # likewise a previous run's telemetry namespace (batch
                # keys + the atomic batch counters): the close-side
                # purge below covers the normal path, but a crashed
                # prior run whose close never ran would replay its
                # stale batches into THIS run's cohort trace — the
                # per-worker batch counter would hand the collector
                # sequence numbers that decode to the dead run's spans
                self._coord.delete_namespace(self._key('telemetry/'))
                # likewise any staged epoch-swap plan (generation
                # counter included): a crashed prior run's staged
                # generation must never be validated/acked — let alone
                # applied — by THIS run's cohort (the armed boundary
                # would compare against the dead run's step floors)
                from autodist_tpu.runtime import swap_keys
                swap_keys.purge_all(self._coord, self._ns)
                # seed the elastic world counter to the launch quorum
                # BEFORE the init rendezvous (admits wait for the
                # init-done marker, so no join can race this). A stale
                # counter on a reused service is forced back to the
                # quorum — joins are only legal against live state.
                cur = coord.incr(self._key('join/world'), 0)
                if cur != self._num_workers:
                    coord.incr(self._key('join/world'),
                               self._num_workers - cur)
            self._epoch_seen = coord.incr(self._key('epoch'), 0)
            self._refresh_membership(
                adopt_growth=self._rejoining or self._joining)
            if self._rejoining:
                self._step_count = coord.incr(
                    self._key('step/') + self._worker_name, 0)
                logging.info(
                    'rejoining as %s under generation %d at published '
                    'step %d (membership epoch %d)', self._worker_name,
                    self._generation, self._step_count, self._epoch_seen)
            elif self._joining:
                # the admit handshake already published this floor; the
                # session resumes counting from it
                self._step_count = self._admit['adopted_step']
            # under a local-SGD window the published counters hold sync
            # ROUNDS, not train steps: a (re)joiner adopts the round
            # floor and resumes at that round's first train step
            h = max(1, getattr(plan, 'local_steps', 1))
            if h > 1 and (self._rejoining or self._joining):
                self._round_count = self._step_count
                self._step_count *= h
        # -- online performance sentry (chief-side) --------------------
        # The CohortMonitor streams the cohort's span batches off the
        # telemetry namespace (poll rides the push cadence), issues
        # straggler verdicts with phase attribution, records
        # slowdown/recovered flight events, and — on the
        # AUTODIST_RECALIBRATE_EVERY cadence — refits the cost model's
        # link constants from live traffic for _replan_for_world's
        # re-rank. Chief-only (verdicts need the whole cohort's spans,
        # which only the chief collects) and telemetry-gated: with
        # AUTODIST_TELEMETRY off nobody pushes batches to consume.
        self._monitor = None
        self._recalibrate_every = ENV.AUTODIST_RECALIBRATE_EVERY.val
        self._last_recalibrate_step = 0
        if self._loose and self._is_chief and self._tel.enabled and \
                ENV.AUTODIST_STRAGGLER_POLICY.val != 'off':
            from autodist_tpu.telemetry.monitor import CohortMonitor
            self._monitor = CohortMonitor(
                client=self._coord, ns=self._ns,
                workers=lambda: ['p%d' % i
                                 for i in self._live_members()],
                flight=self._flight,
                # our own batches are tapped at drain time, never
                # fetched back off the wire (ingest_local)
                local_worker=self._worker_name)
        # -- device-plane roofline observatory (per-worker) ------------
        # AUTODIST_ROOFLINE: per-step MFU/regime accounting — FLOPs +
        # bytes-accessed from the compiled step (cost_analysis() on
        # the lowered program, computed once per compilation below)
        # over the measured wall and the topology's peak table.
        # Samples land on the telemetry series, feed the monitor's
        # compute/memory-bound verdict refinement, and a drop below
        # the rolling baseline records an mfu_regression flight event.
        self._roofline_tracker = None
        self._roofline_costs = {}
        self._last_step_cost = None
        self._last_exec_wall = 0.0
        if ENV.AUTODIST_ROOFLINE.val:
            from autodist_tpu.telemetry.roofline import RooflineTracker
            rs = getattr(cluster, '_resource_spec', None)
            topo = rs.topology if rs is not None else \
                getattr(plan, 'topology', None)
            if topo is not None:
                peak_flops, peak_hbm = topo.peaks()
            else:
                forced = ENV.AUTODIST_ROOFLINE_PEAKS.val
                peak_flops = forced.get('flops')
                peak_hbm = forced.get('hbm_gbps')
                peak_hbm = peak_hbm * 1e9 if peak_hbm else None
            self._roofline_tracker = RooflineTracker(
                peak_flops=peak_flops, peak_hbm_bps=peak_hbm,
                tel=self._tel, flight=self._flight,
                worker=self._worker_name)
        # chief-side auto-checkpoint backstop: with restarts in play the
        # PS state is authoritative, but a periodic chief snapshot
        # bounds the blast radius of losing the PS itself
        self._auto_ckpt = None
        self._auto_ckpt_every = ENV.AUTODIST_AUTO_CHECKPOINT_EVERY.val
        if self._loose and self._is_chief and self._auto_ckpt_every:
            from autodist_tpu.checkpoint.saver import CheckpointManager
            self._auto_ckpt = CheckpointManager(
                os.path.join(DEFAULT_CHECKPOINT_DIR, 'auto', self._ns),
                max_to_keep=2, async_save=True)
        # proxy variables (reference proxy_variable.py:46-190): a worker-
        # local cached copy serves reads. In SPMD programs reads are
        # already device-local, so the proxy is inherently satisfied; in
        # loose mode it is real: the pre-step PS pull is replaced by the
        # cache, refreshed from the PS after each push (the reference's
        # post-update assign, proxy_variable.py:163-190).
        self._proxy_vars = {
            name for name, p in plan.var_plans.items()
            if p.is_ps and any(getattr(s, 'local_replication', False)
                               for s in p.all_syncs)}
        self._proxy_cache = {}
        self._proxy_hits = 0
        # PS-resident optimizer (reference partitioner.py:570-573): vars
        # whose strategy asks for service-side updates with shared slots
        self._shared_opt_vars = {
            name for name, p in plan.var_plans.items()
            if p.is_ps and any(getattr(s, 'shared_optimizer', False)
                               for s in p.all_syncs)}
        self._shared_warned = set()
        self._shared_pushes = 0
        # row-sparse PS data plane (BSADD/BGETROWS): sparse-flagged 2-D
        # PS variables whose per-step delta touches few rows ship only
        # those rows. Partitioned sparse vars qualify when partitioned
        # on axis 0 (the axis the builders force for sparse vars).
        self._sparse_vars = {
            name for name, p in plan.var_plans.items()
            if p.is_ps and getattr(p.var, 'sparse_read', False)
            and len(p.var.shape) == 2
            and (p.num_shards <= 1 or p.partition_axis == 0)}
        self._sparse_stats = {
            'sparse_pushes': 0, 'rows_pushed': 0,
            'dense_bytes_avoided': 0, 'zero_push_skips': 0,
            'row_refreshes': 0, 'rows_refreshed': 0,
            'full_refreshes': 0}
        self._sparse_refresh_count = {}
        # loose-mode PS data plane: a persistent TransferPool worker
        # (own connection) per endpoint, variables placed by
        # reduction_destination (multi-server PS)
        self._pool = None
        self._ps_addrs = []
        self._ps_index = {}
        self._ps_bytes = 0
        self._ps_push_bytes = 0
        self._ps_pull_bytes = 0
        self._ps_ep_bytes = []
        self._ps_seconds = 0.0
        # quantized-push error feedback (AUTODIST_PS_WIRE_DTYPE=i8):
        # per-variable host-side residual of the mass the last push's
        # block quantization dropped, added back into the next delta
        # before classification so loose mode stays convergent. Only
        # touched by the push path (pipeline thread at depth 2 —
        # pushes are serialized through the pipeline join). Transient:
        # not checkpointed (worst case one push's quantization error
        # is lost on restart, bounded by a block's scale).
        self._push_residual = {}
        # async pipeline (AUTODIST_PS_PIPELINE_DEPTH >= 2): step N's
        # delta push + publish and step N+1's variable pull run on a
        # dedicated background thread; run() only joins the result.
        # _stats_lock guards the wire accounting those threads share
        # with the main thread.
        import threading
        self._stats_lock = threading.Lock()
        # executed re-plans (AUTODIST_EXECUTE_REPLAN): the background
        # re-rank thread STAGES a migration here; run() applies it at
        # the next step boundary (the only safe point — mid-step state
        # is half old-layout, half new).
        self._replan_lock = threading.Lock()
        self._pending_replan = None
        # epoch-swap handshake (runtime/swap_keys.py, docs/design/
        # epoch-swap.md): _pending_swap holds the staged generation
        # this member validated (and, once armed, the commit boundary
        # every member applies it at); _swap_gen_seen is the last
        # generation this member acked/nacked, _swap_applied_gen the
        # last one it applied. All guarded by _replan_lock.
        self._pending_swap = None
        self._swap_gen_seen = 0
        self._swap_applied_gen = 0
        self._pipe = None
        self._inflight = None
        self._stashed_prefetch = None
        self._pipeline_depth = 1
        # telemetry batch pushes ride their OWN background lane (one
        # TransferPool worker, own fenced connection, created lazily
        # on the first push): a telemetry batch never belongs on the
        # step's critical path — at depth 1 the serial data plane
        # would otherwise pay a full wire round trip per push cadence
        self._tel_pipe = None
        self._tel_push_handle = None
        self._ps_phase = {'pull_s': 0.0, 'push_s': 0.0, 'step_s': 0.0,
                          'exposed_wait_s': 0.0, 'train_steps': 0,
                          'sync_rounds': 0, 'discarded_prefetches': 0}
        # local-SGD window (docs/design/local-sgd.md): H local optimizer
        # steps per PS sync round. H=1 (the plan default) is today's
        # every-step loose push — NONE of the window machinery engages.
        # Under H>1 the staleness gate, the published counters and the
        # pipeline floors all count sync ROUNDS, not train steps;
        # _window_base holds the pulled values the current window's
        # delta is computed against, and _round_count the completed
        # rounds. The merge rule (average vs raw sum) is the
        # AUTODIST_LOCAL_SGD_AVERAGE knob — average scales each
        # worker's window delta by 1/W so the sum-based delta wire
        # lands on the mean of the workers' windows. (_round_count is
        # initialized with _step_count up top: the elastic admit above
        # may already have adopted a published round floor.)
        self._local_steps = max(1, getattr(plan, 'local_steps', 1))
        self._window_base = None
        if self._loose:
            self._init_ps_endpoints()
            depth = ENV.AUTODIST_PS_PIPELINE_DEPTH.val
            if depth > 2:
                logging.warning(
                    'AUTODIST_PS_PIPELINE_DEPTH=%d clamps to 2: a pull '
                    'must follow the previous push of the same variable '
                    '(read-your-writes), so at most one step can be in '
                    'flight', depth)
                depth = 2
            self._pipeline_depth = depth
            if depth > 1:
                from autodist_tpu.runtime import coord_client as cc
                coord_addr = getattr(self._coord, 'address', None)
                # the pipeline thread publishes steps through its OWN
                # control-plane connection (CoordClient sockets are not
                # thread-safe; the main thread keeps using self._coord)
                self._pipe = cc.TransferPool(
                    [lambda: self._fenced_connect(coord_addr)])
        if self._proxy_vars and not self._loose:
            logging.info(
                'local_proxy_variable on %d vars: subsumed by SPMD '
                '(variable reads are device-local in a single program)',
                len(self._proxy_vars))
        # graph-mutation guard (reference autodist.py:152-165): the
        # captured program must not grow after the session is built.
        # VariableRead nodes are excluded: they are framework-internal and
        # created lazily (fetch normalization, jit trace of Variable.read).
        self._built_node_count = self._user_node_count()
        self._init_state()
        # liveness: peers judge us by our beat counter. A background
        # beater decouples it from step cadence — a long XLA compile or
        # an inter-run data-loading phase must not read as death.
        self._hb_seen = {}
        self._rebuild_hb_peers()   # over the LIVE world, not the quorum
        self._hb_stop = None
        hb_timeout = ENV.AUTODIST_HEARTBEAT_TIMEOUT.val
        # armed whenever heartbeats are on, even alone at launch: a
        # 1-process namespace can GROW (live join), and the joiner
        # would judge this process by a beat counter nobody advances
        # between steps — a long XLA recompile would then read as death
        if self._loose and hb_timeout:
            import threading
            self._hb_stop = threading.Event()
            me = self._key(self._worker_name)
            interval = min(hb_timeout / 4.0, 10.0)
            stop = self._hb_stop

            # dial the address the MAIN client resolved (env may carry a
            # NIC address that all-local runs rewrote to loopback)
            coord_addr = getattr(self._coord, 'address', None)

            def beat_loop():
                # own client: CoordClient sockets are not thread-safe.
                # Connection failures are retried forever: a long XLA
                # compile or data stall on OUR side must not permanently
                # silence the beats and get us declared dead by peers.
                # A FENCED rejection is different: we WERE declared dead
                # and superseded — stop beating for good (a zombie must
                # not look alive to anyone).
                from autodist_tpu.runtime.coord_client import \
                    FencedWriteError, connect_with_retry
                client = None
                warned = False
                try:
                    while not stop.is_set():
                        if client is None:
                            try:
                                # SHORT op timeout: a half-open socket
                                # must surface within the heartbeat
                                # window (not the generous data-plane
                                # timeout) so the loop reconnects and
                                # keeps beating
                                client = connect_with_retry(
                                    coord_addr, deadline_s=interval,
                                    op_timeout=min(10.0, interval))
                                if self._fence_key:
                                    client.fence(self._fence_key,
                                                 self._generation)
                            except FencedWriteError:
                                logging.warning(
                                    'heartbeat thread: this worker was '
                                    'declared dead and fenced; beats '
                                    'stop here')
                                break
                            except Exception:  # noqa: BLE001 - advisory
                                if not warned:
                                    warned = True
                                    logging.warning(
                                        'heartbeat thread cannot reach '
                                        'the coord service at %s yet; '
                                        'retrying every %.0fs',
                                        coord_addr, interval)
                                if stop.wait(interval):
                                    break
                                continue
                        try:
                            client.heartbeat(me)
                        except FencedWriteError:
                            logging.warning(
                                'heartbeat thread: this worker was '
                                'declared dead and fenced; beats stop '
                                'here')
                            break
                        except OSError:
                            try:
                                client.close()
                            except OSError:
                                pass
                            client = None
                            continue
                        if stop.wait(interval):
                            break
                finally:
                    if client is not None:
                        try:
                            client.close()
                        except OSError:
                            pass

            self._hb_thread = threading.Thread(
                target=beat_loop, daemon=True, name='autodist-heartbeat')
            self._hb_thread.start()

    def _user_node_count(self):
        return sum(1 for n in self._graph_item.graph.nodes
                   if not isinstance(n, fe.VariableRead))

    def refresh_mutation_guard(self):
        """Re-baseline the mutation guard after a SANCTIONED graph
        extension — a later ``autodist.function`` trace adds nodes
        through the framework itself, which is not the user-mutation
        hazard the guard exists to catch. Optimizer slot state is
        refreshed too: the extension may have traced a train op whose
        optimizer the session had not seen at build time."""
        self._built_node_count = self._user_node_count()
        if self._refresh_opt_state():
            # compiled steps close over the opt-state pytree STRUCTURE;
            # a grown structure invalidates them (they would unzip stale
            # in_specs against the new state)
            self._cache.clear()

    def _refresh_opt_state(self):
        """Init + place optimizer slot state {uid: {var name: leaf
        state}} for any (optimizer, var) pair in the graph not already
        covered. One optimizer may appear in several ApplyGradients
        nodes — the variable sets merge rather than keeping only the
        first node's. Newly seen optimizers start with fresh slots.
        Returns True when anything was added."""
        added = False
        opt_vars = {}   # uid -> (optimizer, {var name: Variable})
        for node in self._graph_item.graph.nodes:
            if isinstance(node, fe.ApplyGradients):
                opt = node.optimizer
                _, seen = opt_vars.setdefault(opt.uid, (opt, {}))
                for _, v in node.grads_and_vars:
                    seen[v.name] = v
        for uid, (opt, seen) in opt_vars.items():
            have = self._opt_state.get(uid, {})
            missing = [v for name, v in seen.items() if name not in have]
            if not missing:
                continue
            host_vals = {v.name: np.asarray(v.init_value)
                         for v in missing}
            slots = opt.init_slot_state(missing, host_vals)
            state = self._opt_state.setdefault(uid, {})
            for vname, leafstate in slots.items():
                state[vname] = self._place_slots(vname, leafstate)
                added = True
        return added

    def _key(self, suffix):
        return '%s/%s' % (self._ns, suffix)

    def peer_step(self, process_id):
        """Another worker's published completed-step counter (0 if none)."""
        return self._coord.incr(self._key('step/') + 'p%d' % process_id, 0)

    def _active_workers(self):
        """Current gate membership size (self-inclusive): the LIVE
        world (launch quorum + admitted joiners) minus peers excluded
        under the ``exclude`` policy — re-evaluated per gate slice, so
        both shrinks and grows reach a blocked waiter mid-wait."""
        return self._world - len(self._excluded)

    def _live_members(self):
        """Worker ordinals currently in the membership (excluded peers
        dropped) — the set gate bounds and pipeline peer floors range
        over."""
        return [i for i in range(self._world)
                if self._key('p%d' % i) not in self._excluded]

    def _snap_round_open(self, client, worker):
        """Flip this worker's snapshot-parity counter
        (``<ns>/snap/<worker>``) to ODD before the sync round's first
        push frame: the serving tier's epoch-consistent snapshot pull
        (serving/replica.py) pins all live writers' parities even,
        pulls, and re-reads — any round open or completed in between
        invalidates the pull. A stale ODD counter left by a crashed
        predecessor of this slot (supervised restart) is normalized
        with a second bump: an open must always END odd or the reader
        contract inverts for the rest of the run."""
        if client.incr(self._key('snap/%s' % worker), 1) & 1 == 0:
            client.incr(self._key('snap/%s' % worker), 1)

    def _snap_round_close(self, client, worker):
        """EVEN after push + publish: the round's deltas are landed and
        counted, so a reader pinning now gets a mutually consistent
        set. Symmetric normalization with :meth:`_snap_round_open`."""
        if client.incr(self._key('snap/%s' % worker), 1) & 1:
            client.incr(self._key('snap/%s' % worker), 1)

    def _rebuild_hb_peers(self):
        me = ENV.AUTODIST_PROCESS_ID.val
        self._hb_peers = [self._key('p%d' % i)
                          for i in range(self._world) if i != me]

    def _refresh_membership(self, adopt_growth=True):
        """Adopt membership changes recorded on the control plane, in
        BOTH directions. Grows: the ``join/world`` counter advanced by
        admitted joiners (each already publishing a step counter and a
        beat before its epoch bump made it observable — see
        :func:`admit_worker`); the heartbeat peer list and, on the
        chief, the strategy re-rank (:meth:`_replan_for_world`) follow.
        Shrinks: per-worker excluded markers (atomic counters), never a
        read-modify-write list, so two survivors excluding two
        different peers concurrently cannot lose each other's update.

        ``adopt_growth=False`` is the FRESH-cohort init call: a reused
        service can hold a crashed previous run's larger counter, and
        no join can legitimately precede this run's rendezvous (admits
        wait for the init-done marker every cohort member's epoch
        baseline is read before), so a fresh member adopting a bigger
        world at init would be adopting phantom members — it starts at
        the launch quorum and learns real growth from epoch bumps.
        Rejoining replacements and live joiners DO adopt at init: the
        world they re-enter may legitimately have grown."""
        world = self._coord.incr(self._key('join/world'), 0)
        if adopt_growth and world > self._world:
            fresh = 0
            for i in range(self._world, world):
                wkey = self._key('p%d' % i)
                if self._coord.incr('excluded/%s' % wkey, 0) > 0:
                    # a slot retired at admit time (a claim raced past
                    # AUTODIST_MAX_WORKERS) or already excluded: it was
                    # never a live join and must not inflate the audit
                    # trail or trigger a re-rank
                    self._excluded.add(wkey)
                    continue
                fresh += 1
                self._health['joins'].append(
                    {'worker': 'p%d' % i, 'epoch': self._epoch_seen})
            if fresh:
                logging.info(
                    'membership grew: %d worker(s) joined at epoch %d '
                    '(world %d -> %d)', fresh, self._epoch_seen,
                    self._world, world)
            self._world = world
            self._rebuild_hb_peers()
            if self._is_chief and fresh:
                # OFF the gate's critical path: this runs inside the
                # staleness gate's failure_check, where a synchronous
                # candidate enumeration would stall the chief's step
                # publishing — and with it every peer blocked on the
                # chief's counter. The re-rank is pure bookkeeping into
                # _health, so it rides a daemon thread; health_stats
                # joins it before reporting.
                import threading
                t = threading.Thread(
                    target=self._replan_for_world, args=(world,),
                    daemon=True, name='autodist-replan')
                # a LIST, not a slot: a second grow while the first
                # re-rank still runs must not orphan it — health_stats
                # joins them all before reporting
                if not hasattr(self, '_replan_threads'):
                    self._replan_threads = []
                self._replan_threads.append(t)
                t.start()
        for i in range(self._world):
            w = 'p%d' % i
            wkey = self._key(w)
            if wkey in self._excluded:
                continue
            if self._coord.incr('excluded/%s' % wkey, 0) > 0:
                self._excluded.add(wkey)
        if self._key(self._worker_name) in self._excluded:
            self._flight.record('self_excluded',
                                worker=self._worker_name,
                                epoch=self._epoch_seen)
            self._flight.dump('self_excluded')
            raise RuntimeError(
                'this worker (%s) was declared dead and excluded from '
                'the run at epoch %d; its writes are fenced — exiting '
                'instead of training into rejected pushes'
                % (self._worker_name, self._epoch_seen))

    def _replan_for_world(self, world):
        """On admit, re-rank strategies for the NEW world size with the
        simulator (``AutoStrategy`` over the grown replica count) and
        record the predicted-vs-kept decision. By default execution
        KEEPS the current plan and this is pure audit trail; with
        ``AUTODIST_EXECUTE_REPLAN`` set, a migratable re-plan (the PS
        family, preserving the current relaxed-consistency flags so
        loose mode stays loose) is additionally STAGED here and applied
        by ``run()`` at the next step boundary through the device-side
        resharding path (:mod:`autodist_tpu.parallel.reshard`). Never
        fatal either way — a re-rank failure must not take down the
        training it advises."""
        entry = {'world': world,
                 'kept': dict(getattr(self._plan.strategy, 'cost', None)
                              or {}).get('builder', ''),
                 'migrated': False}
        try:
            rs = getattr(self._cluster, '_resource_spec', None)
            if rs is None:
                entry['skipped'] = 'no resource spec on the cluster'
            else:
                from autodist_tpu.strategy.builders import AutoStrategy
                # continuous calibration closes the loop here: when the
                # monitor has refit the link constants from live
                # traffic, the re-rank prices with MEASURED, not
                # analytic, alpha-beta — and the audit entry records
                # which constants priced it
                params = None
                if self._monitor is not None:
                    params = self._monitor.calibrated_params()
                entry['cost_constants'] = \
                    'measured' if params is not None else 'analytic'
                if params is not None:
                    a, b = params.link(
                        cross_node=rs.topology.multi_node)
                    entry['cost_alpha_beta'] = {
                        'alpha_s': a, 'beta_s_per_byte': b}
                auto = AutoStrategy(
                    num_replicas=world * max(1, self._plan.local_replicas),
                    cost_params=params)
                best = auto.build(self._graph_item, rs)
                cost = dict(getattr(best, 'cost', None) or {})
                entry['predicted'] = cost.get('builder', '')
                entry['predicted_step_time_s'] = \
                    cost.get('predicted_step_time_s')
                kept_rank = next(
                    (c.report.predicted_step_time_s
                     for c in auto.last_ranked
                     if c.name == entry['kept'] and c.report is not None),
                    None)
                entry['kept_predicted_step_time_s'] = kept_rank
                execute = ENV.AUTODIST_EXECUTE_REPLAN.val and self._loose
                logging.info(
                    're-ranked strategies for world=%d: predicted best '
                    '%s (%.4gs/step), kept %s%s', world,
                    entry['predicted'],
                    entry['predicted_step_time_s'] or float('nan'),
                    entry['kept'] or '(hand-picked)',
                    ' — staging migration through the reshard path'
                    if execute else
                    ' (AUTODIST_EXECUTE_REPLAN off: audit only)')
                if execute:
                    mig = self._build_migratable_strategy(world, rs,
                                                          params=params)
                    if mig is None:
                        entry['migration_skipped'] = \
                            'no PS-family candidate for this strategy'
                    else:
                        entry['migration_staged'] = dict(
                            getattr(mig, 'cost', None) or {}) \
                            .get('builder', '')
                        self._flight.record(
                            'replan_staged', world=world,
                            builder=entry['migration_staged'])
                        # cohort-wide epoch-swap handshake: stage the
                        # plan on the control plane, collect the peer
                        # ack quorum, arm the commit boundary — every
                        # member (chief included) applies at step B
                        # through _apply_pending_swap. Runs on this
                        # re-rank daemon thread; bounded by the
                        # AUTODIST_SWAP_* knobs.
                        self._stage_swap(mig, world, entry)
        except Exception as e:  # noqa: BLE001 - advisory, never fatal
            entry['error'] = '%s: %s' % (type(e).__name__, e)
            logging.warning('strategy re-rank for world=%d failed: %s',
                            world, entry['error'])
        self._health['replans'].append(entry)

    def _build_migratable_strategy(self, world, rs, params=None):
        """Best strategy this LIVE session can actually migrate to: the
        PS family with the current strategy's relaxed-consistency flags
        preserved (sync / staleness / shared_optimizer / proxy), so the
        re-plan stays a loose-mode strategy — switching execution MODE
        (loose <-> SPMD) live would need a new runtime, not a reshard.
        The top-ranked candidate is returned REGARDLESS of data-plane
        geometry: re-keyed shards and moved PS endpoints are legal
        because the epoch-swap handshake (:meth:`_stage_swap`) makes
        every member apply the new plan at the same step boundary and
        the chief re-keys the authoritative PS copies before anyone
        pulls under it. Returns None when the current strategy carries
        no PS sync to clone flags from, or no candidate ranks."""
        from autodist_tpu.simulator import search
        from autodist_tpu.strategy import builders as b
        from autodist_tpu.strategy.base import PSSynchronizer
        flags = None
        for node in self._plan.strategy.node_config:
            for sync in [node.synchronizer] + list(node.part_config):
                if isinstance(sync, PSSynchronizer):
                    flags = {'sync': sync.sync,
                             'staleness': sync.staleness,
                             'shared_optimizer': sync.shared_optimizer,
                             'local_proxy_variable':
                                 sync.local_replication}
                    break
            if flags is not None:
                break
        if flags is None:
            return None
        cands = [
            ('PS', lambda: b.PS(**flags)),
            ('PSLoadBalancing', lambda: b.PSLoadBalancing(**flags)),
            ('PartitionedPS', lambda: b.PartitionedPS(**flags)),
            ('UnevenPartitionedPS',
             lambda: b.UnevenPartitionedPS(**flags)),
        ]
        feasible, _ = search.rank(
            self._graph_item, rs, candidates=cands, params=params,
            num_replicas=world * max(1, self._plan.local_replicas))
        if feasible:
            return feasible[0].strategy
        logging.info(
            'executed re-plan: no PS-family candidate ranked for '
            'world=%d; keeping the current plan', world)
        return None

    def _apply_pending_replan(self):
        with self._replan_lock:
            pending, self._pending_replan = self._pending_replan, None
        if pending is not None:
            self._execute_replan(**pending)

    @staticmethod
    def _ps_geometry(plan, name):
        """Data-plane key layout for one variable under ``plan`` (the
        pure-plan form of :meth:`_shard_info`'s key list)."""
        p = plan.var_plans.get(name)
        nshards = getattr(p, 'num_shards', 1) if p is not None else 1
        if nshards > 1:
            return ['var/%s/shard%d' % (name, i) for i in range(nshards)]
        return ['var/%s' % name]

    # -- epoch-swap handshake (docs/design/epoch-swap.md) ------------------
    # The verified ordering (analysis/epoch_swap_model.py): the chief
    # STAGES plan N+1 under a generation-keyed plan key, every peer
    # validates and ACKs (any NACK cancels the stage), the chief ARMS
    # the commit marker with boundary B = prefix_min(published) +
    # gate_staleness + 2, and every member — chief included — applies
    # the staged plan at the start of step B. The boundary-safety
    # argument: a member executing step s implies every member
    # published >= s - staleness - 1, so at arm time no member has
    # started step B and every member's step-B start check observes
    # the armed marker.

    def _validate_swap_strategy(self, strategy, world):
        """Can THIS member execute ``strategy`` live? Compiles it and
        builds its :class:`ExecutionPlan` over this member's mesh (the
        same construction :meth:`_execute_replan` performs at apply
        time, so an apply-time failure is caught here, at ack time,
        where a NACK still cancels the swap cleanly). Raises on any
        plan this member would have to refuse."""
        from autodist_tpu.parallel.plan import ExecutionPlan
        from autodist_tpu.strategy.base import StrategyCompiler
        compiled = StrategyCompiler(self._graph_item).prune(strategy)
        new_plan = ExecutionPlan(
            compiled, self._graph_item, self._mesh,
            loose=self._loose, topology=self._plan.topology)
        # weight-update-sharded optimizer slots live as FLAT 1/n
        # shards; a plan flipping any variable's update-sharding needs
        # a slot-layout conversion the reshard pass (which moves
        # var-SHAPED leaves) does not perform — NACK at validation so
        # no member ever reaches a refusal after the boundary is armed
        # (PS-family candidates never set update-sharding, so this
        # only rejects hand-staged exotic plans)
        wus_moved = [
            name for name in self._graph_item.graph.variables
            if getattr(self._plan.var_plans.get(name),
                       'update_sharded', False) !=
            getattr(new_plan.var_plans.get(name),
                    'update_sharded', False)]
        if wus_moved:
            raise RuntimeError(
                'weight-update-sharding layout changes for %s — flat '
                'slot shards need their own conversion pass'
                % sorted(wus_moved)[:4])
        return compiled, new_plan

    def _live_ack_peers(self, client):
        """The peers whose ACK the staged plan needs RIGHT NOW: live
        membership (re-evaluated on every poll, so an exclusion mid-
        handshake shrinks the quorum) minus this worker, minus peers
        that closed cleanly (done marker / released step sentinel —
        a finished peer never pulls again and needs no say)."""
        from autodist_tpu.runtime.coord_client import CLEAN_CLOSE_STEP
        me = ENV.AUTODIST_PROCESS_ID.val
        out = []
        for i in self._live_members():
            if i == me:
                continue
            w = 'p%d' % i
            if client.get('done/%s' % self._key(w)) is not None:
                continue
            if client.incr(self._key('step/') + w, 0) >= \
                    CLEAN_CLOSE_STEP:
                continue
            out.append(i)
        return out

    def request_strategy_swap(self, strategy, world=None):
        """Public trigger for a cohort-wide strategy migration: runs
        the epoch-swap handshake for ``strategy`` on a background
        thread and returns the audit entry (mutated as the handshake
        progresses; ``entry['swap']`` appears once the boundary is
        armed). The swap itself lands when every member's step counter
        reaches the armed boundary. Loose mode only."""
        if not self._loose:
            raise RuntimeError('strategy swap requires loose mode')
        import threading
        world = world if world is not None else self._world
        entry = {'world': world,
                 'kept': dict(getattr(self._plan.strategy, 'cost',
                                      None) or {}).get('builder', ''),
                 'migrated': False, 'requested': True}
        self._health['replans'].append(entry)
        t = threading.Thread(
            target=self._stage_swap, args=(strategy, world, entry),
            daemon=True, name='autodist-swap-stage')
        if not hasattr(self, '_replan_threads'):
            self._replan_threads = []
        self._replan_threads.append(t)
        t.start()
        return entry

    def _stage_swap(self, strategy, world, entry):
        """Chief half of the epoch-swap handshake: stage -> collect the
        ack quorum over LIVE membership -> arm the commit boundary.
        Any NACK or an ack timeout cancels the stage (generation keys
        deleted) and retries with backoff, bounded by
        ``AUTODIST_SWAP_MAX_RETRIES``; exhausting the retries degrades
        to an audit-only entry. Runs on a background thread with its
        own fenced control-plane connection. Never fatal."""
        import time as _time

        from autodist_tpu.runtime import swap_keys
        from autodist_tpu.runtime.coord_client import CLEAN_CLOSE_STEP
        ack_timeout = ENV.AUTODIST_SWAP_ACK_TIMEOUT_S.val
        backoff = ENV.AUTODIST_SWAP_RETRY_BACKOFF_S.val
        max_retries = ENV.AUTODIST_SWAP_MAX_RETRIES.val
        builder = dict(getattr(strategy, 'cost', None)
                       or {}).get('builder', '')
        client = None
        try:
            # the staged plan must be executable HERE too: a chief
            # that arms a plan it later refuses would fork the cohort
            self._validate_swap_strategy(strategy, world)
            # own connection: this thread runs beside the main step
            # loop and CoordClient sockets are not thread-safe
            client = self._fenced_connect(
                getattr(self._coord, 'address', None))
            for attempt in range(max_retries + 1):
                gen = swap_keys.current_gen(client, self._ns) + 1
                swap_keys.stage_plan(client, self._ns, gen, world,
                                     strategy)
                self._flight.record('swap_stage', gen=gen, world=world,
                                    builder=builder)
                logging.info(
                    'epoch swap gen %d staged for world=%d (%s); '
                    'waiting for the peer ack quorum', gen, world,
                    builder or 'hand-staged')
                deadline = _time.time() + ack_timeout
                quorum, nacks = False, {}
                while _time.time() < deadline:
                    peers = self._live_ack_peers(client)
                    acked, nacks = swap_keys.read_acks(
                        client, self._ns, gen, peers)
                    if nacks:
                        break
                    if len(acked) == len(peers):
                        quorum = True
                        break
                    _time.sleep(0.05)
                if not quorum:
                    reason = 'nack' if nacks else 'ack_timeout'
                    swap_keys.cancel(client, self._ns, gen)
                    self._flight.record(
                        'swap_cancel', gen=gen, reason=reason,
                        detail=str(sorted(nacks.items()))[:256])
                    entry.setdefault('swap_cancels', []).append(
                        {'gen': gen, 'reason': reason,
                         'nacks': {('p%d' % w): r
                                   for w, r in nacks.items()}})
                    logging.warning(
                        'epoch swap gen %d cancelled (%s%s)%s', gen,
                        reason, ': %s' % nacks if nacks else '',
                        '; retrying after %.1fs' % backoff
                        if attempt < max_retries else '')
                    if attempt < max_retries:
                        _time.sleep(backoff)
                        continue
                    entry['migration_skipped'] = (
                        'epoch-swap handshake failed after %d '
                        'attempt(s): %s' % (attempt + 1, reason))
                    return
                # quorum complete: arm. Boundary floors are the LIVE
                # members' published counters (sync ROUNDS under a
                # local-SGD window — the same unit the gate and the
                # apply check use); released sentinels are skipped.
                floors = []
                for i in self._live_members():
                    f = client.incr(self._key('step/') + 'p%d' % i, 0)
                    if f < CLEAN_CLOSE_STEP:
                        floors.append(f)
                if not floors:
                    floors = [self._step_count
                              if self._local_steps == 1
                              else self._round_count]
                boundary = swap_keys.compute_boundary(
                    floors, self._plan.gate_staleness)
                swap_keys.arm(client, self._ns, gen, boundary)
                self._flight.record('swap_arm', gen=gen,
                                    boundary=boundary,
                                    floor=min(floors))
                with self._replan_lock:
                    self._pending_swap = {
                        'gen': gen, 'strategy': strategy,
                        'world': world, 'boundary': boundary,
                        'entry': entry}
                entry['swap'] = {'gen': gen, 'boundary': boundary,
                                 'attempts': attempt + 1}
                logging.info(
                    'epoch swap gen %d armed: boundary step %d '
                    '(floor %d + staleness %d + 2)', gen, boundary,
                    min(floors), self._plan.gate_staleness)
                return
        except Exception as e:  # noqa: BLE001 - advisory, never fatal
            entry['migration_skipped'] = \
                'epoch-swap staging failed: %s: %s' \
                % (type(e).__name__, e)
            logging.warning('epoch-swap staging for world=%d failed: '
                            '%s', world, entry['migration_skipped'])
        finally:
            if client is not None:
                client.close()

    def _poll_swap_stage(self):
        """Member half of the handshake, piggybacked on the staleness
        gate's failure check and on every step start: discover a newly
        staged generation (validate + ACK, or NACK), and pick up the
        armed boundary. One counter read on the fast path; never
        raises (a control-plane hiccup here must not fail the gate
        slice it rides on)."""
        if not getattr(self, '_loose', False) \
                or getattr(self, '_coord', None) is None \
                or not ENV.AUTODIST_EXECUTE_REPLAN.val:
            return
        from autodist_tpu.runtime import swap_keys
        try:
            gen = swap_keys.current_gen(self._coord, self._ns)
            if gen <= 0:
                return
            with self._replan_lock:
                pending = self._pending_swap
                if pending is not None and pending['gen'] < gen:
                    # superseded: the chief cancelled this generation
                    # and re-staged — the new one is validated below
                    self._pending_swap = pending = None
            if not self._is_chief and gen > self._swap_gen_seen and \
                    gen > self._swap_applied_gen:
                self._swap_gen_seen = gen
                staged = swap_keys.read_plan(self._coord, self._ns,
                                             gen)
                if staged is None:
                    return   # cancelled between counter and plan read
                _, world, strategy = staged
                me = ENV.AUTODIST_PROCESS_ID.val
                try:
                    self._validate_swap_strategy(strategy, world)
                except Exception as e:  # noqa: BLE001 - NACK carries it
                    reason = '%s: %s' % (type(e).__name__, e)
                    swap_keys.write_nack(self._coord, self._ns, gen,
                                         me, reason)
                    self._flight.record('swap_nack', gen=gen,
                                        worker=self._worker_name,
                                        reason=reason[:256])
                    logging.warning(
                        'epoch swap gen %d NACKed: %s', gen, reason)
                    return
                swap_keys.write_ack(self._coord, self._ns, gen, me)
                self._flight.record('swap_ack', gen=gen,
                                    worker=self._worker_name)
                with self._replan_lock:
                    self._pending_swap = pending = {
                        'gen': gen, 'strategy': strategy,
                        'world': world, 'boundary': 0, 'entry': None}
            if pending is not None and not pending['boundary']:
                b = swap_keys.read_boundary(self._coord, self._ns,
                                            pending['gen'])
                if b:
                    with self._replan_lock:
                        pending['boundary'] = b
        except Exception as e:  # noqa: BLE001 - poll must not fail
            logging.debug('epoch-swap poll failed: %s: %s',
                          type(e).__name__, e)

    def _apply_pending_swap(self):
        """Apply an armed epoch swap at the start of step B (sync
        round B under a local-SGD window). Called before anything
        touches the plan on every run; a member whose counter resumed
        PAST the boundary (supervised restart) applies on its first
        run — the chief's re-keyed PS copies are the authoritative
        state either way."""
        with self._replan_lock:
            pending = self._pending_swap
            if pending is None or not pending.get('boundary'):
                return
            h = self._local_steps
            nxt = self._step_count + 1 if h == 1 \
                else self._round_count + 1
            if nxt < pending['boundary'] or \
                    (h > 1 and self._step_count % h != 0):
                return
            self._pending_swap = None
        entry = pending.get('entry')
        if entry is None:
            # non-chief members audit the swap too (the chief's entry
            # came from its re-rank / request)
            entry = {'world': pending['world'],
                     'kept': dict(getattr(self._plan.strategy, 'cost',
                                          None) or {})
                     .get('builder', ''),
                     'migrated': False,
                     'swap': {'gen': pending['gen'],
                              'boundary': pending['boundary']}}
            self._health['replans'].append(entry)
        self._execute_replan(pending['strategy'], pending['world'],
                             entry, swap=pending)

    def _execute_replan(self, strategy, world, entry, swap=None):
        """Migrate this session's live state to a re-ranked strategy —
        the execution half of the elastic re-plan (ROADMAP item 3's
        resharding unlock). At a step boundary, atomically:

        1. build the new :class:`ExecutionPlan` over the SAME mesh;
        2. move ``_var_state`` (and every optimizer slot shaped like
           its variable) old-layout -> new-layout ON DEVICE through
           :mod:`autodist_tpu.parallel.reshard` — values are moved,
           never recomputed, so the migration is bit-exact;
        3. re-init compressor aux state whose contract changed
           (carrying entries whose compressor kept shape+keys);
        4. swap the plan and drop compiled steps.

        Without ``swap`` (legacy chief-local call) the shared data
        plane is UNTOUCHED: a migration that would change any
        variable's shard-key geometry or move it between PS endpoints
        is REFUSED (recorded as ``migration_skipped``) — live peers
        would keep using the old keys.

        With ``swap`` (an ARMED epoch-swap record: every member
        applies this plan at the same step boundary) re-keying is
        LEGAL: the chief additionally copies the authoritative PS
        values of every re-keyed variable old-keys -> new-keys (BSET
        resets the per-key accumulator state; old keys become inert —
        a mid-swap zombie's old-plan pushes land where nobody reads,
        on top of its generation fence) and publishes a ready marker
        non-chief members wait on before their first new-plan pull.
        Every member wraps the apply in a snapshot-parity open/close
        (:meth:`_snap_round_open`), so a serving replica's snapshot
        pull straddling the migration can never revalidate.

        Never fatal without ``swap``: everything fallible runs BEFORE
        the swap and the new state is built entirely on the side, so
        any failure keeps the old plan + state untouched and records
        the error on the replan audit entry. With ``swap`` a failure
        AFTER the boundary was armed re-raises instead: other members
        are applying the plan this member just failed, and training on
        silently against the old keys would fork the model.
        """
        import time as _time
        t0 = _time.perf_counter()
        old_plan = self._plan
        try:
            from autodist_tpu.parallel import reshard as reshard_mod
            from autodist_tpu.parallel.plan import ExecutionPlan
            from autodist_tpu.strategy.base import StrategyCompiler
            compiled = StrategyCompiler(self._graph_item).prune(strategy)
            new_plan = ExecutionPlan(
                compiled, self._graph_item, self._mesh,
                loose=self._loose, topology=old_plan.topology)
            # a mid-flight background push/pull rides the OLD plan's
            # placement: join it first, discard its prefetch
            if self._pipe is not None:
                pre = self._join_pipeline()
                if pre is not None:
                    self._account_prefetch_discard(pre)
            variables = list(self._graph_item.graph.variables)
            # without an armed epoch swap a re-keying migration must
            # NEVER execute — live peers would keep using the old keys
            moved_geom = [
                name for name in variables
                if self._ps_geometry(old_plan, name) !=
                self._ps_geometry(new_plan, name)] if self._loose else []
            if moved_geom and swap is None:
                entry['migration_skipped'] = (
                    'shard geometry changes for %s — re-keying a live '
                    'data plane needs cohort-wide propagation'
                    % sorted(moved_geom)[:4])
                logging.warning(
                    'executed re-plan for world=%d refused: %s', world,
                    entry['migration_skipped'])
                self._flight.record('replan_refused', world=world,
                                    reason='shard_geometry')
                self._flight.dump('replan_refusal')
                return
            # weight-update-sharded slots live as FLAT 1/n shards; a
            # plan change that flips any variable's update-sharding
            # would need a slot-layout conversion the reshard pass
            # (which moves var-SHAPED leaves) does not perform — refuse
            # rather than silently carry a mislaid slot layout
            wus_moved = [
                name for name in variables
                if getattr(old_plan.var_plans.get(name),
                           'update_sharded', False) !=
                getattr(new_plan.var_plans.get(name),
                        'update_sharded', False)]
            if wus_moved:
                entry['migration_skipped'] = (
                    'weight-update-sharding layout changes for %s — '
                    'flat slot shards need their own conversion pass'
                    % sorted(wus_moved)[:4])
                logging.warning(
                    'executed re-plan for world=%d refused: %s', world,
                    entry['migration_skipped'])
                self._flight.record('replan_refused', world=world,
                                    reason='weight_update_sharding')
                self._flight.dump('replan_refusal')
                return
            # device-side layout moves: vars + matching optimizer slots
            ops = reshard_mod.plan_reshard(old_plan, new_plan)
            fns = {op.var_name:
                   reshard_mod.reshard_fn(op, old_plan, new_plan)
                   for op in ops}
            new_vars = {
                name: fns[name](arr) if name in fns else arr
                for name, arr in self._var_state.items()}
            new_opt = {}
            for uid, by_var in self._opt_state.items():
                new_by_var = {}
                for vname, leafstate in by_var.items():
                    fn = fns.get(vname)
                    phys = old_plan.padded_shape(vname)

                    def move(leaf, fn=fn, phys=phys):
                        if fn is not None and phys is not None and \
                                hasattr(leaf, 'shape') and \
                                tuple(leaf.shape) == tuple(phys):
                            return fn(leaf)
                        return leaf
                    new_by_var[vname] = jax.tree.map(move, leafstate)
                new_opt[uid] = new_by_var
            # compressor aux state: carry entries whose contract
            # (keys + per-replica shapes) is unchanged, re-init the
            # rest — at worst one step of error feedback resets, the
            # same bound as a worker restart
            n = new_plan.num_replicas
            rep_sharding = NamedSharding(self._mesh, P(AXIS_DATA))
            new_aux = {}
            for name, vplan in new_plan.var_plans.items():
                aux = vplan.compressor.init_state(
                    np.asarray(vplan.var.init_value))
                if not aux:
                    continue
                key = 'compressor/%s' % name
                old = self._aux_state.get(key)
                if old is not None and set(old) == set(aux) and all(
                        tuple(old[k].shape[1:]) == tuple(v.shape)
                        for k, v in aux.items()):
                    new_aux[key] = old
                else:
                    new_aux[key] = {
                        k: self._put(
                            jnp.broadcast_to(jnp.asarray(v),
                                             (n,) + tuple(v.shape)),
                            rep_sharding)
                        for k, v in aux.items()}
            # new endpoint placement is computed on the side too; an
            # index that MOVES any live variable between endpoints
            # aborts like a geometry change would (peers keep dialing
            # the old endpoints) — unless an armed epoch swap makes
            # every member adopt the new placement at the boundary
            new_ps_index = self._ps_index
            moved_eps = []
            if self._loose:
                from autodist_tpu.runtime import coord_client as cc
                eps = cc.ps_endpoints()
                if eps:
                    new_ps_index = assign_ps_endpoints(
                        new_plan.var_plans, eps)
                    moved_eps = [
                        name for name in variables
                        if self._ps_index.get(name) is not None
                        and new_ps_index.get(name) !=
                        self._ps_index.get(name)]
                    if moved_eps and swap is None:
                        entry['migration_skipped'] = (
                            'endpoint placement moves for %s — '
                            'needs cohort-wide propagation'
                            % sorted(moved_eps)[:4])
                        logging.warning(
                            'executed re-plan for world=%d refused: '
                            '%s', world, entry['migration_skipped'])
                        self._flight.record(
                            'replan_refused', world=world,
                            reason='endpoint_placement')
                        self._flight.dump('replan_refusal')
                        return
            # ---- swap (everything above built on the side) ----
            # epoch swap: the data-plane re-key brackets the plan swap
            # in a snapshot-parity open/close — a serving replica's
            # epoch-consistent pull straddling the migration pins an
            # odd (or advanced) parity and can never revalidate a
            # snapshot that mixes old- and new-key reads
            rekeyed = sorted(set(moved_geom) | set(moved_eps)) \
                if swap is not None else []
            auth = {}
            if swap is not None and self._loose:
                self._snap_round_open(self._coord, self._worker_name)
            if rekeyed and self._is_chief and self._loose:
                # authoritative PS values under the OLD keys (the PS
                # copy, not this worker's possibly-stale local state,
                # is the model) — fetched before the plan swap flips
                # _shard_info to the new layout
                parts, _ = self._fetch_var_parts(rekeyed)
                for name in rekeyed:
                    pc, _keys = self._shard_info(name)
                    got = parts.get(name, [None])
                    if any(p is None for p in got):
                        # never stored (init-barrier window): the local
                        # device copy is the best value in existence
                        auth[name] = np.asarray(self._plan.unpad_host(
                            name, np.asarray(self._var_state[name])))
                    else:
                        auth[name] = got[0] if pc is None \
                            else pc.merge(got)
            self._plan = new_plan
            self._var_state = new_vars
            self._opt_state = new_opt
            self._aux_state = new_aux
            self._cache.clear()
            self._proxy_cache = {}
            self._proxy_vars = {
                name for name, p in new_plan.var_plans.items()
                if p.is_ps and any(getattr(s, 'local_replication', False)
                                   for s in p.all_syncs)}
            self._shared_opt_vars = {
                name for name, p in new_plan.var_plans.items()
                if p.is_ps and any(getattr(s, 'shared_optimizer', False)
                                   for s in p.all_syncs)}
            self._sparse_vars = {
                name for name, p in new_plan.var_plans.items()
                if p.is_ps and getattr(p.var, 'sparse_read', False)
                and len(p.var.shape) == 2
                and (p.num_shards <= 1 or p.partition_axis == 0)}
            self._ps_index = new_ps_index
            if swap is not None and self._loose:
                from autodist_tpu.runtime import swap_keys
                try:
                    if self._is_chief:
                        if auth:
                            # re-key: authoritative values land under
                            # the NEW plan's keys (BSET resets each
                            # key's accumulator/slot state wholesale);
                            # the old keys become inert — nobody reads
                            # them, zombie old-plan pushes land there
                            # harmlessly, and the run-end purge sweeps
                            # them
                            self._store_var_parts(auth)
                        swap_keys.mark_ready(self._coord, self._ns,
                                             swap['gen'])
                    elif rekeyed:
                        # the chief may reach its own boundary later
                        # than us: our first new-plan pull must not
                        # race the re-key
                        swap_keys.wait_ready(
                            self._coord, self._ns, swap['gen'],
                            ENV.AUTODIST_SWAP_ACK_TIMEOUT_S.val)
                finally:
                    self._snap_round_close(self._coord,
                                           self._worker_name)
                self._swap_applied_gen = swap['gen']
                self._flight.record(
                    'swap_apply', gen=swap['gen'],
                    worker=self._worker_name,
                    boundary=swap['boundary'],
                    step=self._step_count + 1
                    if self._local_steps == 1
                    else self._round_count + 1)
            entry['migrated'] = True
            entry['migration'] = {
                'world': world,
                'builder': dict(getattr(strategy, 'cost', None)
                                or {}).get('builder', ''),
                'strategy_id': compiled.id,
                'reshard': reshard_mod.summarize(ops),
                'rekeyed_vars': len(rekeyed),
                # bytes the re-key pushed to the NEW PS keys (the
                # authoritative-copy BSETs) — the reshard summary only
                # counts device-collective wire bytes, which are 0 for
                # a single-host re-partition
                'rekey_ps_bytes': int(sum(
                    np.asarray(v).nbytes for v in auth.values())),
                'wall_s': round(_time.perf_counter() - t0, 4)}
            self._flight.record(
                'replan_swap', world=world,
                builder=entry['migration']['builder'],
                wall_s=entry['migration']['wall_s'])
            self._tel.record_span(
                'replan_swap', t0, _time.perf_counter() - t0,
                world=world, worker=self._worker_name)
            logging.info(
                'executed re-plan for world=%d: migrated to %s in '
                '%.3fs (%s); compiled steps dropped, state moved '
                'device-side', world,
                entry['migration']['builder'] or compiled.id,
                entry['migration']['wall_s'],
                entry['migration']['reshard'])
        except Exception as e:  # noqa: BLE001 - keep the old plan
            entry['migration_error'] = '%s: %s' % (type(e).__name__, e)
            self._plan = old_plan
            logging.warning(
                'executed re-plan for world=%d failed (%s); keeping '
                'the current plan', world, entry['migration_error'])
            self._flight.record('replan_failed', world=world,
                                error=entry['migration_error'])
            self._flight.dump('replan_failure')
            if swap is not None:
                # past an armed boundary the cohort is committed: the
                # other members are applying the plan this member just
                # failed — training on against the old keys would fork
                # the model silently. Fail fast instead.
                raise

    def _exclude_peer(self, wkey, timeout):
        """Epoch-fenced exclusion of a dead peer. Every detector fences
        the zombie's writer generation FIRST — on every service it can
        write to (each PS endpoint keeps its own fence counter) —
        BEFORE the exclusion becomes observable anywhere: the moment
        any process can see the marker, the zombie's writes must
        already be rejected. Fencing is idempotent (any bump past the
        bound generation fences; concurrent detectors just bump
        further). Then exactly one survivor wins the atomic claim and
        re-bounds the membership: it releases the dead worker's step
        counter with the same ``1 << 30`` sentinel a clean close
        publishes (deleting the key instead would let any later
        delta-0 read resurrect it at zero and wedge every survivor's
        gate forever) and bumps the membership epoch so every other
        survivor adopts the shrunk quorum on its next liveness check.
        The fence/excluded counters live OUTSIDE the run namespace
        (``fence/<ns>/<w>``, ``excluded/<ns>/<w>``): they survive the
        run-end purge, so a zombie stays fenced — and its exclusion
        stays observable — after the survivors are gone."""
        w = wkey.rsplit('/', 1)[-1]
        if self._active_workers() - 1 < self._min_workers:
            raise RuntimeError(
                'worker %s missed heartbeats for > %.0fs but excluding '
                'it would leave %d live workers, below '
                'AUTODIST_MIN_WORKERS=%d — failing instead of shrinking'
                % (w, timeout, self._active_workers() - 1,
                   self._min_workers))
        fkey = 'fence/%s' % wkey
        self._pool.run([(ep, lambda c, k=fkey: c.incr(k, 1))
                        for ep in range(len(self._pool))])
        coord_addr = tuple(getattr(self._coord, 'address', ()) or ())
        if coord_addr not in [tuple(a) for a in self._ps_addrs]:
            self._coord.incr(fkey, 1)
        self._flight.record('fence_bump', worker=w,
                            by=self._worker_name)
        claim = self._coord.incr('excluded/%s' % wkey, 1)
        self._flight.record('exclude_claim', worker=w, claim=claim,
                            by=self._worker_name)
        if claim == 1:
            from autodist_tpu.runtime.coord_client import CLEAN_CLOSE_STEP
            self._coord.publish_step(w, CLEAN_CLOSE_STEP,
                                     prefix=self._key('step/'))
            self._flight.record('release', worker=w,
                                by=self._worker_name)
            self._epoch_seen = self._coord.incr(self._key('epoch'), 1)
            self._flight.record('epoch_bump', epoch=self._epoch_seen,
                                by=self._worker_name)
            self._health['epoch_bumps'] += 1
            logging.warning(
                'declared peer %s dead (no heartbeat for > %.0fs): '
                'generation fenced, excluded from membership — epoch '
                '%d, %d active workers remain', w, timeout,
                self._epoch_seen, self._active_workers() - 1)
        else:
            # another survivor won the claim; adopt its epoch
            self._epoch_seen = self._coord.incr(self._key('epoch'), 0)
        self._excluded.add(wkey)
        self._health['exclusions'].append(
            {'worker': w, 'epoch': self._epoch_seen})
        # an exclusion means somebody died — exactly when the last
        # N control-plane events are worth keeping
        self._flight.dump('exclusion:%s' % w)

    def _check_peers_alive(self):
        """Liveness + recovery policy while blocked on the staleness
        gate (reference coordinator.py:98-110 monitors hard-exit the
        chief when a worker dies; here the signal is a stalled
        coord-service beat counter, judged on this process's own clock
        — immune to cross-host clock skew). Under the default ``fail``
        policy a dead peer raises; ``exclude`` shrinks the membership
        (epoch bump + generation fencing); ``restart`` keeps waiting
        for the coordinator-supervised replacement."""
        import time as _time
        # adopt membership changes FIRST — exclusions other survivors
        # fenced in AND joins (the epoch bump is how an admitted worker
        # becomes visible). This runs even with heartbeats disabled:
        # the gate's party count must grow for a join regardless of
        # whether failure DETECTION is armed.
        epoch = self._coord.incr(self._key('epoch'), 0)
        if epoch != self._epoch_seen:
            self._health['epoch_bumps'] += epoch - self._epoch_seen
            self._epoch_seen = epoch
            self._refresh_membership()
            self._flight.record('epoch_adopt', epoch=epoch,
                                worker=self._worker_name)
            logging.warning('membership epoch advanced to %d: %d '
                            'active workers', epoch,
                            self._active_workers())
        # the epoch-swap handshake piggybacks on the gate poll: a
        # member blocked here for a whole staleness window still
        # discovers (and acks) a staged plan and picks up the armed
        # boundary without waiting for its next step start
        self._poll_swap_stage()
        timeout = ENV.AUTODIST_HEARTBEAT_TIMEOUT.val
        if not timeout:
            return
        # belt and braces alongside the background beater: a waiter is
        # trivially alive, refresh our beat on every gate slice too
        self._coord.heartbeat(self._key(self._worker_name))
        peers = [w for w in self._hb_peers if w not in self._excluded]
        dead = self._coord.dead_workers(peers, timeout, self._hb_seen)
        if dead:
            # a peer that closed its session cleanly stops beating but
            # is NOT a crash: it published a done key (Session.close)
            dead = [w for w in dead
                    if self._coord.get('done/%s' % w) is None]
        # restart policy: a peer beating again after a declared death
        # is its reborn incarnation — record the recovery wall time
        for w in list(self._dead_since):
            if w not in dead:
                wall = _time.time() - self._dead_since.pop(w)
                self._health['rejoins'].append(w.rsplit('/', 1)[-1])
                self._health['recovery_wall_s'].append(round(wall, 3))
                logging.info('peer %s is heartbeating again %.1fs '
                             'after its death was detected', w, wall)
        if not dead:
            return
        self._health['missed_beats'] += \
            sum(1 for w in dead if w not in self._dead_since)
        if self._policy == 'exclude':
            for w in dead:
                self._exclude_peer(w, timeout)
            return
        if self._policy == 'restart':
            now = _time.time()
            wait_cap = ENV.AUTODIST_RESTART_WAIT_S.val
            for w in dead:
                short = w.rsplit('/', 1)[-1]
                if self._coord.get(
                        self._key('failed/%s' % short)) is not None:
                    raise RuntimeError(
                        'worker %s exhausted its supervised restarts '
                        '(AUTODIST_MAX_WORKER_RESTARTS) and was marked '
                        'permanently failed — aborting' % short)
                if w not in self._dead_since:
                    self._dead_since[w] = now
                    logging.warning(
                        'peer %s missed heartbeats for > %.0fs; '
                        'policy=restart: waiting for its supervised '
                        'replacement', w, timeout)
                elif now - self._dead_since[w] > wait_cap:
                    # backstop for a silently dead supervisor: the
                    # normal abort is the failed marker above
                    raise RuntimeError(
                        'worker %s has been dead for %.0fs with no '
                        'supervised replacement and no failed marker '
                        '(AUTODIST_RESTART_WAIT_S=%.0f) — aborting'
                        % (short, now - self._dead_since[w], wait_cap))
            # truthy = recovery in flight: the staleness gate re-arms
            # its window instead of timing out under the supervisor
            return True
        raise RuntimeError(
            'worker(s) %s missed heartbeats for > %.0fs while this '
            'process waited on the staleness gate — failing fast '
            'instead of hanging' % (sorted(dead), timeout))

    # -- loose-mode PS endpoint placement ----------------------------------
    def _init_ps_endpoints(self):
        """Bring up the PS data plane: a persistent
        :class:`~autodist_tpu.runtime.coord_client.TransferPool` worker
        (own connection, lazily dialed) per endpoint. With
        ``AUTODIST_PS_ENDPOINTS`` set, each variable is served by the
        endpoint its strategy ``reduction_destination`` maps to — host
        match first (endpoints co-located with PS nodes), else the
        destination's ordinal among the distinct destinations — so
        PSLoadBalancing's byte-size bin-packing (reference
        ps_lb_strategy.py:64-83) decides real runtime placement, like
        the reference's one tf.Server per PS node
        (utils/server_starter.py:48-75). Without endpoints, all
        variables live on the coord service (single-PS layout; the pool
        worker dials its own connection so background transfers never
        contend with the main thread's control-plane client)."""
        from autodist_tpu.runtime import coord_client as cc
        from autodist_tpu.runtime.cluster import is_local_address
        eps = cc.ps_endpoints()
        if eps:
            # a locally-hosted endpoint may be bound to loopback
            # (all-local runs); dialing 127.0.0.1 works under either
            # bind, while the raw NIC address fails against a loopback
            # bind — same rewrite the coord-service connection applies
            # (autodist.py)
            self._ps_addrs = [
                ('127.0.0.1' if is_local_address(host) else host, port)
                for host, port in eps]
            self._ps_index = assign_ps_endpoints(self._plan.var_plans,
                                                 eps)
            counts = [0] * len(eps)
            for idxs in self._ps_index.values():
                for i in idxs:
                    counts[i] += 1
            logging.info('PS data plane: %d endpoints, variable shards '
                         'per endpoint %s', len(eps), counts)
        else:
            self._ps_addrs = [tuple(getattr(self._coord, 'address',
                                            (None, 0)))]
        self._pool = cc.TransferPool(
            [lambda addr=addr: self._fenced_connect(addr)
             for addr in self._ps_addrs])

    def _fenced_connect(self, addr):
        """Dial a data/control-plane connection bound to this worker's
        fencing generation: every write it carries is rejected by the
        service once we are declared dead and superseded."""
        from autodist_tpu.runtime import coord_client as cc
        client = cc.connect_with_retry(addr)
        if self._fence_key:
            client.fence(self._fence_key, self._generation)
        return client

    @staticmethod
    def _stable_idx(name, n):
        import zlib
        return zlib.crc32(name.encode()) % n

    def _shard_info(self, name):
        """Loose-mode transfer geometry for a variable: its
        :class:`PartitionerConfig` (None when unpartitioned) and the
        per-shard key suffixes. Partitioned variables live as one
        tensor PER SHARD on the data plane (``var/<name>/shard<i>``) so
        each shard lands on the endpoint its ``part_config`` destination
        names (reference partitioned_ps_strategy.py:89-96 + per-shard
        variables, kernel/partitioner.py:153-173)."""
        p = self._plan.var_plans.get(name)
        nshards = getattr(p, 'num_shards', 1) if p is not None else 1
        if nshards > 1:
            return (p.part_config,
                    ['var/%s/shard%d' % (name, i) for i in range(nshards)])
        return None, ['var/%s' % name]

    def _shard_endpoints(self, name, nshards):
        """Endpoint index per shard (extended if the strategy named
        fewer destinations than shards)."""
        idxs = self._ps_index.get(name)
        if idxs is None:
            idxs = [self._stable_idx(name, len(self._ps_addrs))]
            self._ps_index[name] = idxs
        if len(idxs) < nshards:
            idxs = [idxs[i % len(idxs)] for i in range(nshards)]
        return idxs

    def _transfer_groups(self, names):
        """Group every (variable, shard) transfer unit by the endpoint
        it lives on: ``{endpoint: [(key_suffix, name, shard_i,
        part_config)]}`` plus the per-name shard counts."""
        groups = {}
        shard_counts = {}
        for name in names:
            pc, keys = self._shard_info(name)
            idxs = self._shard_endpoints(name, len(keys))
            shard_counts[name] = len(keys)
            for i, (key, ep) in enumerate(zip(keys, idxs)):
                groups.setdefault(ep, []).append((key, name, i, pc))
        return groups, shard_counts

    def _account_ep_bytes(self, name):
        """Attribute one whole-tensor transfer's wire bytes to the
        endpoints its shards live on (per-endpoint load accounting).
        Caller must hold ``_stats_lock`` (pipeline threads and the main
        thread both account)."""
        if not self._ps_ep_bytes:
            self._ps_ep_bytes = [0] * len(self._ps_addrs)
        var = self._graph_item.var_by_name(name)
        pc, keys = self._shard_info(name)
        idxs = self._shard_endpoints(name, len(keys))
        if pc is None:
            sizes = [int(np.prod(var.shape)) if var.shape else 1]
        else:
            sizes = [int(np.prod(s)) for s in
                     pc.shard_shapes(var.shape)]
        for ep, n in zip(idxs, sizes):
            self._ps_ep_bytes[ep] += self._wire_nbytes(n)

    def _auto_checkpoint(self):
        """Chief-side recovery backstop: snapshot the post-step variable
        state every ``AUTODIST_AUTO_CHECKPOINT_EVERY`` train steps
        (async save — the device->host copy is the only on-path cost).
        Never fatal: the backstop degrading must not kill the training
        it exists to protect."""
        try:
            tree = {name: self._local_value(name)
                    for name in self._graph_item.graph.variables}
            self._auto_ckpt.save(self._step_count, tree)
            self._health['auto_checkpoints'] += 1
        except Exception as e:  # noqa: BLE001 - backstop, not the run
            logging.warning('auto-checkpoint at step %d failed: %s: %s',
                            self._step_count, type(e).__name__, e)

    @property
    def health_stats(self):
        """Elastic-recovery observability (feeds
        :func:`autodist_tpu.utils.profiling.health_report`): the peer
        failure policy, this worker's fencing generation, the current
        membership epoch, declared-dead counts, exclusions, observed
        rejoins with their recovery wall times, and the auto-checkpoint
        count. Empty for SPMD (non-loose) sessions: none of the
        recovery machinery runs there, and reporting its zero-state as
        if it did would be misleading."""
        if not self._loose:
            return {}
        # strategy re-ranks may still be running on their background
        # threads (spawned from the gate's failure_check): join them
        # all so the report never misses a decision it exists to audit
        for t in getattr(self, '_replan_threads', ()):
            if t.is_alive():
                t.join(timeout=60.0)
        out = dict(self._health)
        out.update(
            epoch=self._epoch_seen,
            generation=self._generation,
            rejoining=self._rejoining,
            joining=self._joining,
            num_workers=self._num_workers,
            world=self._world,
            active_workers=self._active_workers(),
            excluded=sorted(w.rsplit('/', 1)[-1]
                            for w in self._excluded))
        if self._monitor is not None:
            # the perf section: rolling cohort stats, active verdicts
            # (exclude candidates under policy=advise), the
            # slowdown/recovered audit and the recalibration
            # trajectory — health_report/format_health render it
            out['perf'] = self._monitor.snapshot()
        return out

    # -- telemetry plane ---------------------------------------------------
    @property
    def step_wall_series(self):
        """The uniform per-step wall series: ``run()``'s wall seconds
        for every executed train step, EVERY mode (loose or SPMD,
        pipelined or serial) — the series ``bench.py`` and the
        telemetry snapshot read. Bounded ring
        (``AUTODIST_TELEMETRY_MAX_SPANS``), oldest first."""
        return list(self._step_walls)

    def _join_tel_push(self):
        """Join the previous background telemetry push (keeps pushes
        FIFO-ordered on the lane and surfaces — logged, never raised —
        any error it hit)."""
        handle, self._tel_push_handle = self._tel_push_handle, None
        if handle is None:
            return
        try:
            handle.result()
        except Exception as e:  # noqa: BLE001 - advisory plane
            logging.warning('background telemetry batch push failed: '
                            '%s: %s', type(e).__name__, e)

    def _maybe_push_telemetry(self, client, step, final=False):
        """Batch-push this worker's drained span records to the
        ``<ns>/telemetry/`` namespace every
        ``AUTODIST_TELEMETRY_PUSH_EVERY`` train steps. Steady-state
        pushes ride a dedicated background lane (one lazily-created
        ``TransferPool`` worker with its own fenced connection): a
        telemetry batch never belongs on the step's critical path —
        at depth 1 the serial data plane would otherwise pay a full
        wire round trip per cadence. ``final=True`` (the close-time
        flush) joins the lane and pushes synchronously on the
        caller's client so nothing is in flight when the chief
        collects and purges. Never fatal: a telemetry push failing
        must not take down the training it observes."""
        if not self._tel.enabled or not self._loose:
            return
        every = ENV.AUTODIST_TELEMETRY_PUSH_EVERY.val
        if not final and (not every or step % every):
            return
        try:
            records = self._tel.drain_spans()
            # the monitor's zero-wire tap: our own drained batch is
            # ingested directly (it still goes to the wire below for
            # the cohort trace; poll skips fetching it back)
            if self._monitor is not None and records:
                self._monitor.ingest_local(records)
            if final:
                self._join_tel_push()
                _telemetry.push_records(client, self._ns,
                                        self._worker_name, records)
                return
            if not records:
                return
            if self._tel_pipe is None:
                from autodist_tpu.runtime import coord_client as cc
                coord_addr = getattr(self._coord, 'address', None)
                self._tel_pipe = cc.TransferPool(
                    [lambda: self._fenced_connect(coord_addr)])
            self._join_tel_push()
            ns, worker = self._ns, self._worker_name
            self._tel_push_handle = self._tel_pipe.submit(
                0, lambda c: _telemetry.push_records(c, ns, worker,
                                                     records))
        except Exception as e:  # noqa: BLE001 - advisory plane
            logging.warning('telemetry batch push failed at step %d: '
                            '%s: %s', step, type(e).__name__, e)

    def cohort_telemetry(self):
        """Chief-side cohort collection: every live member's pushed
        span batches off the PS telemetry namespace, tagged per
        worker and sorted on the shared wall axis. Loose mode only
        (SPMD programs have no PS plane to aggregate over); returns
        ``[]`` when telemetry is disabled or nothing was pushed."""
        if not self._loose or self._coord is None:
            return []
        members = ['p%d' % i for i in range(self._world)]
        return _telemetry.collect_records(self._coord, self._ns,
                                          members)

    def export_chrome_trace(self, path=None):
        """Assemble the cohort timeline and write Chrome
        ``trace_event`` JSON (chief-side; ``tools/trace_view.py`` is
        the offline twin). Returns the path, or None when there was
        nothing to export."""
        import json as _json
        records = self.cohort_telemetry()
        # this worker's still-undrained spans join the export (the
        # chief rarely pushes to itself)
        for rec in self._tel.drain_spans():
            rec.setdefault('worker', self._worker_name)
            records.append(rec)
        if not records:
            return None
        records.sort(key=lambda r: r.get('t0', 0.0))
        # attribute control-plane instants to THIS process's row: ring
        # events carry the SUBJECT worker (e.g. the excluded peer),
        # not the actor
        trace = _telemetry.chrome_trace(
            records,
            flight_events=[dict(e, worker_self=self._worker_name)
                           for e in self._flight.events()])
        if path is None:
            path = os.path.join(_telemetry.telemetry_dir(),
                                'trace-%s.json' % self._ns)
        os.makedirs(os.path.dirname(os.path.abspath(path)),
                    exist_ok=True)
        with open(path, 'w') as f:
            _json.dump(trace, f)
        logging.info('telemetry: wrote cohort Chrome trace (%d events) '
                     'to %s', len(trace['traceEvents']), path)
        return path

    @property
    def ps_stats(self):
        """Loose-mode wire accounting: payload bytes moved and seconds
        spent on PS pulls+pushes (the measured per-step PS overhead),
        plus the per-endpoint byte split (balanced placement evidence),
        the row-sparse plane's counters (``sparse``: sparse_pushes,
        rows_pushed, dense_bytes_avoided, zero_push_skips, row/full
        refreshes — docs/design/sparse-ps.md)
        and the async-pipeline phase breakdown — per-train-step pull /
        step / push seconds, the wire seconds actually EXPOSED on the
        critical path, and ``overlap_frac`` = the fraction of wire time
        the pipeline hid behind compute and host tail (0 at depth 1 by
        construction)."""
        with self._stats_lock:
            ph = dict(self._ps_phase)
            out = {'bytes': self._ps_bytes, 'seconds': self._ps_seconds,
                   # direction split: the quantized (i8) wire only
                   # shrinks pushes, so A/Bs must compare push bytes
                   'push_bytes': self._ps_push_bytes,
                   'pull_bytes': self._ps_pull_bytes,
                   'bytes_per_endpoint': list(self._ps_ep_bytes),
                   'mb_per_s': (self._ps_bytes / 1e6 / self._ps_seconds
                                if self._ps_seconds else 0.0),
                   'sparse': dict(self._sparse_stats)}
        steps = max(1, ph['train_steps'])
        # wire phases happen once per SYNC ROUND: at H=1 rounds ==
        # train steps (every push is a round) and the divide is the
        # legacy per-step one bit-for-bit; under a local-SGD window
        # (H>1) dividing by train steps would understate the per-round
        # pull/push/exposed averages by H x. step_s stays per train
        # step — compute happens every step regardless of the window.
        rounds = max(1, ph['sync_rounds']) if ph['sync_rounds'] \
            else steps
        wire = ph['pull_s'] + ph['push_s']
        out['pipeline'] = {
            'depth': self._pipeline_depth,
            'train_steps': ph['train_steps'],
            'sync_rounds': ph['sync_rounds'],
            'local_steps': self._local_steps,
            'discarded_prefetches': ph['discarded_prefetches'],
            'pull_s': ph['pull_s'] / rounds,
            'step_s': ph['step_s'] / steps,
            'push_s': ph['push_s'] / rounds,
            'exposed_wait_s': ph['exposed_wait_s'] / rounds,
            'overlap_frac': max(0.0, min(1.0, 1.0 -
                                ph['exposed_wait_s'] / wire))
            if wire > 0 else 0.0,
        }
        return out

    # -- multi-process placement helpers ----------------------------------
    def _put(self, value, sharding):
        """Place a host value that is logically global (same on every
        process): works for replicated and sharded NamedShardings."""
        if self._plan.num_processes == 1:
            return jax.device_put(jnp.asarray(value), sharding)
        val = np.asarray(value)
        return jax.make_array_from_callback(
            val.shape, sharding, lambda idx: val[idx])

    def _put_feed(self, value, spec):
        """Place a process-local feed: under multi-process SPMD the value
        is this worker's chunk of the global batch (reference between-graph
        feeds, remapper.py:109-123)."""
        sharding = NamedSharding(self._mesh, spec)
        if self._plan.num_processes == 1:
            return jax.device_put(jnp.asarray(value), sharding)
        return jax.make_array_from_process_local_data(
            sharding, np.asarray(value))

    def _local_stack(self, arr):
        """This process's replicas of a P(data)-stacked output.

        Dedup by data-axis offset: on a multi-axis mesh a device holds one
        addressable shard per (data × other-axes) tile, but replicas across
        non-data axes carry the same data rows."""
        if self._plan.num_processes == 1:
            return np.asarray(arr)
        by_offset = {}
        for s in arr.addressable_shards:
            by_offset.setdefault(s.index[0].start or 0, s)
        shards = [by_offset[k] for k in sorted(by_offset)]
        return np.concatenate([np.asarray(s.data) for s in shards], axis=0)

    # -- state ------------------------------------------------------------
    def _init_state(self):
        plan = self._plan
        if plan.num_processes > 1:
            # replicas must start from the chief's initial values
            # (reference shares initializers: all_reduce_synchronizer.py:
            # 175-196); broadcast before placing.
            from jax.experimental import multihost_utils
            names = sorted(self._graph_item.graph.variables)
            vals = [np.asarray(
                self._graph_item.graph.variables[n].init_value)
                for n in names]
            vals = multihost_utils.broadcast_one_to_all(vals)
            for n, v in zip(names, vals):
                self._graph_item.graph.variables[n].init_value = \
                    np.asarray(v)
        if self._loose:
            variables = self._graph_item.graph.variables

            # chief seeds the authoritative PS copies across endpoints,
            # one tensor per shard for partitioned variables — one
            # pipelined vmset batch per endpoint (one round trip each
            # instead of one per variable/shard/chunk). A REJOINING
            # incarnation must never re-seed: the PS holds the trained
            # state its replacement exists to pick up.
            if self._is_chief and not self._rejoining:
                self._store_var_parts(
                    {name: v.init_value
                     for name, v in variables.items()})
            # heartbeat baseline BEFORE the barrier: once any gate runs,
            # every peer has a timestamp (a missing one reads as dead)
            self._coord.heartbeat(self._key(self._worker_name))
            if not (self._rejoining or self._joining):
                # a live JOINer is never a barrier party: its admit
                # handshake already waited for the init-done marker, so
                # the rendezvous below completed before it could exist
                self._coord.barrier(self._key('session/init'),
                                    self._num_workers, timeout_s=120.0)
                if self._is_chief:
                    # replacements key off this marker: only skip the
                    # init rendezvous once it actually completed
                    self._coord.set(self._key('session/init-done'), '1')
            elif self._coord.get(
                    self._key('session/init-done')) is None:
                # the prior incarnation died BEFORE its cohort's init
                # rendezvous completed: the replacement must fill the
                # dead worker's barrier slot, or the original cohort
                # blocks forever on a party that no longer exists
                self._coord.barrier(self._key('session/init'),
                                    self._num_workers, timeout_s=120.0)
            if not self._is_chief or self._rejoining:
                served_map, _ = self._fetch_var_parts(list(variables))
                for name, parts in served_map.items():
                    var = variables[name]
                    pc, _ = self._shard_info(name)
                    served = parts[0] if pc is None else pc.merge(parts)
                    var.init_value = served.astype(var.init_value.dtype)
        self._var_state = {}
        for name, var in self._graph_item.graph.variables.items():
            self._var_state[name] = self._put(
                plan.pad_host(name, jnp.asarray(var.init_value)),
                plan.var_sharding(name))
        self._opt_state = {}
        self._refresh_opt_state()
        # Loose-mode optimizer slots: worker-local by default (the
        # device-local TPU-native choice), or PS-resident and shared via
        # the strategy's shared_optimizer flag — the reference's
        # semantics, where the optimizer is re-created over PS-resident
        # variables (kernel/partitioner.py:570-573) and the update op
        # runs on the PS (ps_synchronizer.py:175-176). Shared mode ships
        # raw gradients (BSTEP); the divergence is real and measured:
        # 2 workers x 5 momentum(0.9) steps on c0-style data moved b to
        # 1.477 (shared) vs 0.993 (worker-local) — 1.49x, near the
        # theoretical 1.58x for interleaved equal gradients — because
        # the PS velocity integrates all 10 pushes while each local one
        # sees only 5 (tests/integration/test_multiprocess.py::
        # test_shared_optimizer_state_on_ps).
        # compressor/aux state. These leaves are *per-replica* (e.g. each
        # device's error-feedback residual differs), so they carry an
        # explicit leading replica dimension sharded over the data axis.
        n = plan.num_replicas
        rep_sharding = NamedSharding(self._mesh, P(AXIS_DATA))
        self._aux_state = {}
        for name, vplan in plan.var_plans.items():
            aux = vplan.compressor.init_state(
                np.asarray(vplan.var.init_value))
            if aux:
                self._aux_state['compressor/%s' % name] = {
                    k: self._put(
                        jnp.broadcast_to(jnp.asarray(v),
                                         (n,) + tuple(v.shape)),
                        rep_sharding)
                    for k, v in aux.items()}

    def _place_slots(self, var_name, leafstate):
        """Shard optimizer slots like their variable (ZeRO, padded like
        the variable for uneven partitions); scalars (e.g. step counts)
        replicate. Weight-update-sharded variables store their slots as
        FLAT 1/n shards over the data axis (row-major, zero-padded to
        ``wus_padded``) — the layout the fused shard-local update
        consumes, and the ~(n-1)/n opt-slot HBM saving the sharded
        update exists for."""
        var = self._graph_item.var_by_name(var_name)
        vplan = self._plan.var_plans.get(var_name)
        sharding = self._plan.var_sharding(var_name)
        repl = self._plan.replicated_sharding()
        wus = vplan is not None and getattr(vplan, 'update_sharded',
                                            False)

        def place(leaf):
            if hasattr(leaf, 'shape') and tuple(leaf.shape) == \
                    tuple(var.shape):
                if wus:
                    flat = jnp.ravel(jnp.asarray(leaf))
                    if vplan.wus_pad:
                        flat = jnp.pad(flat, (0, vplan.wus_pad))
                    return self._put(
                        flat, NamedSharding(self._mesh, P(AXIS_DATA)))
                return self._put(
                    self._plan.pad_host(var_name, jnp.asarray(leaf)),
                    sharding)
            return self._put(jnp.asarray(leaf), repl)

        return jax.tree.map(place, leafstate)

    def _slot_spec(self, var_name, leaf):
        vplan = self._plan.var_plans.get(var_name)
        if vplan is not None and getattr(vplan, 'update_sharded',
                                         False) and \
                hasattr(leaf, 'shape') and \
                tuple(leaf.shape) == (vplan.wus_padded,):
            return P(AXIS_DATA)   # flat weight-update shard layout
        # placed slots carry the variable's physical (padded) shape
        phys = self._plan.padded_shape(var_name)
        if phys is None:
            phys = self._graph_item.var_by_name(var_name).shape
        if hasattr(leaf, 'shape') and tuple(leaf.shape) == tuple(phys):
            return self._plan.var_spec(var_name)
        return P()

    # -- run --------------------------------------------------------------
    def run(self, fetches, feed_dict=None, options=None):
        """Execute fetches (reference WrappedSession.run, runner.py:117-132).

        Observability wrapper over :meth:`_run_fetches`: every executed
        train step records one uniform wall-time sample
        (:attr:`step_wall_series` + the ``step_wall_s`` telemetry
        series) and, with telemetry enabled, a ``step`` span tagged
        with its step id and worker. A
        :class:`~autodist_tpu.runtime.coord_client.FencedWriteError`
        surfacing here means this process is a zombie — the flight
        recorder dumps before the error propagates (the evidence the
        post-mortem needs is exactly what dies with the process).
        """
        import time as _time
        from autodist_tpu.runtime.coord_client import FencedWriteError
        t0 = _time.perf_counter()
        before = self._step_count
        try:
            results = self._run_fetches(fetches, feed_dict, options)
        except FencedWriteError:
            self._flight.record('fenced_write_error',
                                worker=self._worker_name,
                                step=self._step_count)
            self._flight.dump('fenced_write_error')
            raise
        if self._step_count > before:
            wall = _time.perf_counter() - t0
            self._step_walls.append(wall)
            if self._tel.enabled:
                self._tel.observe('step_wall_s', wall)
                self._tel.gauge('step', self._step_count)
                self._tel.record_span('step', t0, wall,
                                      step=self._step_count,
                                      worker=self._worker_name)
            if self._roofline_tracker is not None:
                # exposed comms for the regime split: in loose mode the
                # wall beyond the compiled step's execution is the
                # gate/pull/push wire time; inside one SPMD program
                # collectives are part of the device step, so None
                # (the regime then splits compute vs memory only)
                comms = max(0.0, wall - self._last_exec_wall) \
                    if self._loose and self._last_exec_wall else None
                rec = self._roofline_tracker.observe_step(
                    self._step_count, wall, cost=self._last_step_cost,
                    comms_s=comms)
                if rec is not None and self._monitor is not None:
                    self._monitor.observe_roofline(self._worker_name,
                                                   rec)
            if self._monitor is not None:
                self._monitor.observe_step(self._worker_name,
                                           self._step_count, wall)
                self._maybe_poll_monitor()
        return results

    @property
    def monitor(self):
        """The chief's :class:`~autodist_tpu.telemetry.monitor.
        CohortMonitor` (None off-chief, with telemetry disabled, or
        under ``AUTODIST_STRAGGLER_POLICY=off``). Operators wire its
        :meth:`metrics` into ``AutoscaleController(metrics_source=)``
        so the built-in ``step_time_target_s`` policy runs on the
        cohort's measured step time."""
        return self._monitor

    def _maybe_poll_monitor(self):
        """Chief-side monitor cadence: poll the cohort's new span
        batches every ``AUTODIST_TELEMETRY_PUSH_EVERY`` steps (the
        batches only land on that cadence, so polling faster buys
        nothing) and refit the cost model's link constants every
        ``AUTODIST_RECALIBRATE_EVERY`` steps. Never fatal — the
        sentry must not take down the training it observes."""
        mon = self._monitor
        if mon is None:
            return
        every = max(1, ENV.AUTODIST_TELEMETRY_PUSH_EVERY.val or 8)
        if self._step_count % every:
            return
        try:
            mon.poll()
            if self._recalibrate_every and \
                    self._step_count - self._last_recalibrate_step >= \
                    self._recalibrate_every:
                rs = getattr(self._cluster, '_resource_spec', None)
                from autodist_tpu.simulator.cost_model import \
                    CostModelParams
                base = CostModelParams.from_topology(rs.topology) \
                    if rs is not None else CostModelParams()
                cross = rs.topology.multi_node if rs is not None \
                    else False
                if mon.recalibrate(base, num_replicas=max(2, self._world),
                                   cross_node=cross,
                                   step=self._step_count) is not None:
                    self._last_recalibrate_step = self._step_count
        except Exception as e:  # noqa: BLE001 - advisory plane
            logging.warning('cohort monitor poll at step %d failed: '
                            '%s: %s', self._step_count,
                            type(e).__name__, e)

    def _run_fetches(self, fetches, feed_dict=None, options=None):
        if self._closed:
            raise RuntimeError('Session is closed')
        if ENV.AUTODIST_IS_TESTING.val and \
                self._user_node_count() != self._built_node_count:
            raise RuntimeError(
                'Graph modified after distributed session creation '
                '(%d nodes, built with %d)' %
                (self._user_node_count(), self._built_node_count))
        # staged executed re-plan (AUTODIST_EXECUTE_REPLAN): apply at
        # the step boundary, before anything touches the plan
        if self._pending_replan is not None:
            self._apply_pending_replan()
        # epoch-swap handshake: discover/ack staged plans and — once
        # the commit marker is armed and our counter reaches the
        # boundary — apply the cohort's new plan before this step
        if self._loose:
            self._poll_swap_stage()
            self._apply_pending_swap()
        feed_dict = feed_dict or {}
        single = not isinstance(fetches, (list, tuple))
        fetch_list = [fetches] if single else list(fetches)
        norm = [f.read() if isinstance(f, fe.Variable) else f
                for f in fetch_list]

        feed_nodes = sorted(feed_dict.keys(), key=lambda p: p.name)
        feed_vals = []
        split_flags = []
        for ph in feed_nodes:
            v = np.asarray(feed_dict[ph])
            if v.dtype == np.float64:
                v = v.astype(np.float32)
            feed_vals.append(v)
            split_flags.append(self._plan.feed_splittable(v, ph))

        # PS-resident optimizer: also fetch the synced gradients of
        # shared vars so they can be pushed raw (BSTEP applies the step
        # service-side with shared slots)
        shared_spec, extra_fetches = ([], [])
        if self._loose and self._shared_opt_vars:
            shared_spec, extra_fetches = self._shared_push_spec(norm)
        all_fetches = norm + extra_fetches

        key = (tuple(id(f) for f in all_fetches),
               tuple((id(p), v.shape, str(v.dtype), s)
                     for p, v, s in zip(feed_nodes, feed_vals, split_flags)))
        first_compile = key not in self._cache
        if first_compile:
            self._cache[key] = self._build_step(all_fetches, feed_nodes,
                                                split_flags)
        fn = self._cache[key]

        # a run is a training step only if it executes an optimizer
        # update; fetch-only runs (variable reads, eval) must not count
        # against the staleness window or push deltas
        is_train = any(isinstance(f, fe.ApplyGradients) for f in norm)

        pulled = None
        if self._loose:
            # local-SGD window position: under H>1 only the first train
            # step of a window touches the sync plane (join, gate,
            # pull); the H-1 steps after it run purely locally against
            # the window base, and fetch-only runs serve local state
            # (a mid-window pull would clobber the local progress the
            # window delta is computed from). H=1 takes the every-step
            # path below unchanged — bit-identical to legacy loose.
            h = self._local_steps
            window_start = self._step_count % h == 0
            sync_run = h == 1 or (is_train and window_start)
            prefetch = None
            if sync_run:
                # join any in-flight background push FIRST (pipeline
                # depth >= 2): its error surfaces here instead of
                # silently, and the pull below must observe our own
                # landed pushes (read-your-writes) — the prefetch
                # record it returns was only issued after the push
                # completed.
                prefetch = self._join_pipeline()
            # bounded-staleness window (reference token queues of size s,
            # ps_synchronizer.py:387-458): before running step s (1-based)
            # every worker must have completed >= s - staleness steps.
            # Under H>1 the same gate runs once per window over sync
            # ROUNDS: before round r every worker must have published
            # >= r - staleness rounds, so no reader ever observes state
            # older than H * staleness train steps. sync=False vars are
            # unconditional no-wait (ps_strategy.py:30-35); any sync
            # var imposes its (tightest) bound.
            self._coord.heartbeat(self._key(self._worker_name))
            if is_train and sync_run and self._plan.gate_enabled:
                gate_at = self._step_count + 1 if h == 1 \
                    else self._round_count + 1
                # membership is a CALLABLE: policy=exclude can shrink
                # the quorum while we are blocked inside this gate, and
                # the wait must re-bound against the new epoch's count
                with self._tel.span('staleness_gate',
                                    step=gate_at,
                                    worker=self._worker_name):
                    self._coord.staleness_gate(
                        gate_at,
                        self._plan.gate_staleness,
                        self._active_workers,
                        prefix=self._key('step/'),
                        failure_check=self._check_peers_alive)
                # the gate guarantees every peer completed >= step -
                # staleness; a prefetch taken while some peer was still
                # below that bound may lack pushes the gate just
                # guaranteed — discard it (the refetch pays the exposed
                # wire time serial mode would have paid anyway)
                if prefetch is not None and prefetch.get(
                        'peer_floor', -1) < \
                        gate_at - self._plan.gate_staleness:
                    self._account_prefetch_discard(prefetch)
                    prefetch = None
            if sync_run:
                pulled = self._pull_ps_vars(prefetch, train=is_train)
                if h > 1:
                    # the merged state just pulled is the base the
                    # whole window's delta is computed against
                    self._window_base = pulled

        placed = []
        for v, split in zip(feed_vals, split_flags):
            placed.append(self._put_feed(v, P(AXIS_DATA) if split
                                         else P()))

        # dump-graphs and the roofline cost pull share ONE extra
        # lowering of the step (re-tracing a large step costs real
        # host seconds — never pay it twice, and only ever once per
        # compile key)
        lowered = None
        need_cost = self._roofline_tracker is not None and \
            key not in self._roofline_costs
        if (first_compile and ENV.AUTODIST_DUMP_GRAPHS.val) or \
                need_cost:
            try:
                lowered = fn.lower(self._var_state, self._opt_state,
                                   self._aux_state, placed)
            except Exception as e:  # noqa: BLE001 - never fatal:
                # both consumers are observability, not execution
                logging.debug('step lowering for dump/roofline '
                              'failed (%s: %s)', type(e).__name__, e)
        if first_compile and ENV.AUTODIST_DUMP_GRAPHS.val and \
                lowered is not None:
            # final-phase program dump (reference '3-transformed' graph)
            from autodist_tpu.utils import visualization as viz
            viz.log_compiled(lowered,
                             '4-lowered-step-%d' % len(self._cache))

        if self._roofline_tracker is not None:
            # FLOPs + bytes-accessed once per compilation
            # (cost_analysis on the lowered program — no backend
            # compile; cost_of caches per program), so the per-step
            # sampling in run() is pure arithmetic. Graceful: a
            # backend without cost_analysis leaves flops None and
            # every sampled record explains its null MFU.
            if need_cost:
                from autodist_tpu.telemetry import roofline as _roofline
                self._roofline_costs[key] = _roofline.cost_of(lowered) \
                    if lowered is not None else \
                    {'flops': None, 'bytes_accessed': None}
            self._last_step_cost = self._roofline_costs[key]

        tracing = options is not None and \
            getattr(options, 'trace_level', 0) > 0
        if tracing:
            os.makedirs(options.trace_dir, exist_ok=True)
            jax.profiler.start_trace(options.trace_dir)
        import time as _time
        t_step = _time.perf_counter()
        try:
            outs, self._var_state, self._opt_state, self._aux_state = fn(
                self._var_state, self._opt_state, self._aux_state, placed)
            if tracing:
                jax.block_until_ready(outs)
        finally:
            if tracing:
                jax.profiler.stop_trace()
                logging.info('Profiler trace written to %s',
                             options.trace_dir)
        if is_train:
            self._step_count += 1
            self._last_exec_wall = _time.perf_counter() - t_step
            if self._loose:
                with self._stats_lock:
                    self._ps_phase['step_s'] += \
                        _time.perf_counter() - t_step
                    self._ps_phase['train_steps'] += 1
                if self._local_steps == 1:
                    self._dispatch_push(shared_spec, outs, pulled)
                elif self._step_count % self._local_steps == 0:
                    # window complete: one sync round ships the whole
                    # window's delta against the base pulled at the
                    # window's first step
                    base, self._window_base = self._window_base, None
                    self._dispatch_push(shared_spec, outs, base)
                if self._auto_ckpt is not None and \
                        self._step_count % self._auto_ckpt_every == 0:
                    self._auto_checkpoint()

        split_sizes = {v.shape[0] // self._plan.local_replicas
                       for v, s in zip(feed_vals, split_flags) if s}
        results = [self._contract(f, o, split_sizes)
                   for f, o in zip(norm, outs)]
        return results[0] if single else results

    # -- loose-mode PS data plane -----------------------------------------
    def _wire_nbytes(self, n_elems, push=False):
        """Wire bytes ``n_elems`` floats cost in the given direction.

        The i8 wire is push-only (deltas/gradients quantize under the
        session's error-feedback residual); pulls and stores ride f32
        under an i8 setting (coord_client._pull_wire), so pull-side
        accounting must price the downgraded dtype, not the env
        setting."""
        from autodist_tpu.runtime import coord_client as cc
        wire = cc._wire_dtype() if push else cc._pull_wire()
        return cc.wire_nbytes(n_elems, wire)

    def _join_pipeline(self):
        """Join the in-flight background push job (pipeline depth >= 2)
        and return its prefetch record (None when nothing is in
        flight). Any error the pipeline hit — push, publish, or
        pull-ahead — re-raises HERE, on the caller's thread, so a
        failed background push can never be silently lost. The wall
        time spent blocked is the wire time the pipeline failed to
        hide; it feeds ``overlap_frac``."""
        job = self._inflight
        if job is None:
            # a read-only access (get_variable_value) may have joined
            # the job early and stashed its still-valid prefetch
            stash, self._stashed_prefetch = self._stashed_prefetch, None
            return stash
        self._inflight = None
        import time as _time
        t0 = _time.perf_counter()
        try:
            return job.result()
        finally:
            blocked = _time.perf_counter() - t0
            with self._stats_lock:
                self._ps_phase['exposed_wait_s'] += blocked
            # the 'pipeline' phase span: wire time the background
            # pipeline FAILED to hide (the monitor's phase split and
            # trace_view's per-phase columns both read it)
            self._tel.record_span(
                'pipeline_wait', t0, blocked,
                step=self._step_count + 1, worker=self._worker_name)

    def _drain_pipeline(self, keep_prefetch=False):
        """Join any in-flight pipeline work: user-facing reads/writes
        (checkpointing, variable loads) must see their own session's
        pushes. With ``keep_prefetch`` (read-only callers — a read does
        not invalidate the prefetched pull) the record is stashed for
        the next ``run()`` instead of discarded, so per-step variable
        reads don't silently degrade depth 2 to serial pulls; a load
        supersedes the prefetch and discards it (the dropped record's
        wire traffic still counts — it moved)."""
        record = self._join_pipeline()
        if record is not None and not keep_prefetch:
            self._account_prefetch_discard(record)
            record = None
        self._stashed_prefetch = record if keep_prefetch else None

    def _dispatch_push(self, shared_spec, outs, pulled):
        """Ship the just-completed step's updates.

        Depth 1: serial push + publish on the calling thread — the
        bit-exact legacy data plane. Depth >= 2: the device->host
        readback of gradients/updated state, the delta push, the step
        publish and the NEXT step's variable pull-ahead all run on the
        single-threaded pipeline worker; ``run()`` joins the result at
        the next step's entry, so the wire time hides behind this
        step's host tail and the inter-step interval.

        Ordering invariants, both depths: push -> publish (the
        staleness gate must only count a step whose update landed) and
        push -> next pull (per-variable read-your-writes; the pipeline
        issues the pull-ahead strictly after every endpoint's push
        join). run() joins the pipeline BEFORE gating, so our own
        published counter is always current at the gate, and it
        discards a prefetch whose recorded peer floor is below the next
        step's staleness bound — the pipeline adds overlap inside the
        existing staleness bound, never extra staleness.

        Under a local-SGD window (H>1) a dispatch IS a sync round: the
        published counter, the gate and the pipeline floor all count
        rounds, and the pushed delta is the whole window's parameter
        delta against ``pulled`` (the window base), scaled by 1/W when
        AUTODIST_LOCAL_SGD_AVERAGE is on so the sum-based delta wire
        lands on the mean of the W workers' windows."""
        h = self._local_steps
        scale = None
        if h > 1:
            self._round_count += 1
            step = self._round_count
            if ENV.AUTODIST_LOCAL_SGD_AVERAGE.val:
                scale = 1.0 / max(1, len(self._live_members()))
        else:
            step = self._step_count
        tstep = self._step_count
        worker = self._worker_name
        prefix = self._key('step/')
        with self._stats_lock:
            self._ps_phase['sync_rounds'] += 1

        def shared_values():
            out = {}
            for name, idx, rule, params in shared_spec:
                g = self._local_stack(outs[idx])[0]
                out[name] = (np.asarray(g, np.float32), rule, params)
            return out

        if self._pipe is None:
            import time as _time
            t0 = _time.perf_counter()
            self._snap_round_open(self._coord, worker)
            self._push_ps_deltas(pulled, shared_values(), scale=scale)
            self._coord.publish_step(worker, step, prefix=prefix)
            self._snap_round_close(self._coord, worker)
            self._flight.record('step_publish', worker=worker,
                                step=step)
            with self._stats_lock:
                self._ps_phase['exposed_wait_s'] += \
                    _time.perf_counter() - t0
            self._maybe_push_telemetry(self._coord, tstep)
            return

        # snapshot the LIVE membership (launch quorum + joins, minus
        # exclusions) — the floor must range over every worker the next
        # gate will count, not the launch-time list
        members = self._live_members()

        def job(client):
            self._snap_round_open(client, worker)
            self._push_ps_deltas(pulled, shared_values(), scale=scale)
            client.publish_step(worker, step, prefix=prefix)
            self._snap_round_close(client, worker)
            self._flight.record('step_publish', worker=worker,
                                step=step)
            self._maybe_push_telemetry(client, tstep)
            # lower-bound what the pull-ahead below will observe: a
            # peer's published counter only advances AFTER its push
            # landed (push -> publish), so every push published by now
            # is visible to the pull. run() compares this floor against
            # the next step's staleness bound and discards the prefetch
            # if it was taken too early — the pipeline must never serve
            # values staler than the gate guarantees.
            floor = step if len(members) <= 1 else min(
                client.incr(prefix + 'p%d' % i, 0) for i in members)
            to_fetch = self._pull_to_fetch()
            parts, wire_s = self._fetch_var_parts(to_fetch)
            return {'names': to_fetch, 'parts': parts,
                    'wire_s': wire_s, 'peer_floor': floor}

        self._inflight = self._pipe.submit(0, job)

    def _pull_to_fetch(self):
        """The variables a per-step pull must actually fetch (proxy
        variables with a warm cache are served locally)."""
        return [name for name in self._graph_item.graph.variables
                if not (name in self._proxy_vars and
                        name in self._proxy_cache)]

    def _fetch_var_parts(self, names):
        """Batched authoritative fetch: ONE pipelined ``vmget`` per
        endpoint covers every (variable, shard) unit it serves — all
        request frames on the wire before the first reply is drained,
        endpoints in parallel on the TransferPool workers. Returns
        ``({name: [per-shard host array]}, wall seconds)``."""
        import time as _time
        variables = self._graph_item.graph.variables
        groups, shard_counts = self._transfer_groups(names)
        results = {name: [None] * c for name, c in shard_counts.items()}
        t0 = _time.perf_counter()

        def fetch_group(units):
            def go(client):
                specs = []
                for key, name, i, pc in units:
                    shp = variables[name].shape if pc is None else \
                        pc.shard_shapes(variables[name].shape)[i]
                    specs.append((self._key(key), shp))
                arrs = client.vmget(specs)
                return [(name, i, a) for (_, name, i, _), a
                        in zip(units, arrs)]
            return go

        for got in self._pool.run([(ep, fetch_group(units))
                                   for ep, units in groups.items()]):
            for name, i, a in got:
                results[name][i] = a
        return results, _time.perf_counter() - t0

    def _store_var_parts(self, values):
        """Batched authoritative store, `_fetch_var_parts`'s write twin:
        ONE pipelined ``vmset`` per endpoint covers every (variable,
        shard) unit in ``values`` (``{name: whole host value}``; shards
        are split here)."""
        groups, _ = self._transfer_groups(list(values))

        def store_group(units):
            def go(client):
                items = []
                for key, name, i, pc in units:
                    val = np.asarray(values[name])
                    if pc is not None:
                        val = pc.split(val)[i]
                    items.append((self._key(key), val))
                client.vmset(items)
            return go

        self._pool.run([(ep, store_group(units))
                        for ep, units in groups.items()])

    def _account_prefetch_discard(self, prefetch):
        """A discarded pull-ahead still moved its whole payload on the
        wire — account that traffic (bytes, seconds, per-endpoint
        split) so ``ps_stats`` reflects what the network actually
        carried, and count the discard so the pipeline block shows how
        often the peer-floor check fell back to an exposed refetch.
        The wasted wire seconds deliberately do NOT join the per-step
        ``pull_s`` phase: overlap_frac must not improve because hidden
        wire time was thrown away."""
        n_elems = 0
        for name in prefetch['names']:
            var = self._graph_item.var_by_name(name)
            n_elems += int(np.prod(var.shape)) if var.shape else 1
        with self._stats_lock:
            for name in prefetch['names']:
                self._account_ep_bytes(name)
            self._ps_seconds += prefetch['wire_s']
            self._ps_bytes += self._wire_nbytes(n_elems)
            self._ps_pull_bytes += self._wire_nbytes(n_elems)
            self._ps_phase['discarded_prefetches'] += 1

    def _pull_ps_vars(self, prefetch=None, train=True):
        """Refresh variable state from the authoritative PS copies (the
        worker's per-step PS read); each shard of a partitioned
        variable comes from its own endpoint. With ``prefetch`` (the
        pipeline's pull-ahead record, depth >= 2) the host values were
        already fetched in the background and only device placement
        remains on the critical path. Returns the pulled host values
        for delta computation. Fetch-only runs (``train=False``) keep
        the global wire accounting but stay out of the per-train-step
        phase averages ``ps_stats['pipeline']`` divides by
        ``train_steps``."""
        import time as _time
        t_fn = _time.perf_counter()
        variables = self._graph_item.graph.variables
        to_fetch = self._pull_to_fetch()
        fetched = None
        wire_s = exposed_s = 0.0
        if prefetch is not None and prefetch['names'] == to_fetch:
            fetched = prefetch['parts']
            wire_s = prefetch['wire_s']
        if fetched is None:
            # no (usable) prefetch: the fetch is fully exposed
            fetched, wire_s = self._fetch_var_parts(to_fetch)
            exposed_s = wire_s
        pulled = {}
        n_elems = 0
        with self._stats_lock:
            for name in fetched:
                self._account_ep_bytes(name)
        for name, var in variables.items():
            if name in fetched:
                parts = fetched[name]
                pc, _ = self._shard_info(name)
                served = parts[0] if pc is None else (
                    None if any(p is None for p in parts)
                    else pc.merge(parts))
                n_elems += int(np.prod(var.shape)) if var.shape else 1
                if served is None:  # pragma: no cover - init barrier
                    served = np.asarray(var.init_value, dtype=np.float32)
                served = served.astype(var.init_value.dtype)
            else:
                # proxy read: serve from the local cache, no PS
                # round-trip on the pre-step critical path
                served = self._proxy_cache[name]
                self._proxy_hits += 1
            pulled[name] = served
            self._var_state[name] = self._put(
                self._plan.pad_host(name, jnp.asarray(served)),
                self._plan.var_sharding(name))
        with self._stats_lock:
            self._ps_seconds += wire_s
            self._ps_bytes += self._wire_nbytes(n_elems)
            self._ps_pull_bytes += self._wire_nbytes(n_elems)
            if train:
                self._ps_phase['pull_s'] += wire_s
                self._ps_phase['exposed_wait_s'] += exposed_s
        self._tel.record_span(
            'pull_vars', t_fn, _time.perf_counter() - t_fn,
            step=self._step_count + 1, worker=self._worker_name,
            prefetched=exposed_s == 0.0 and wire_s > 0.0)
        return pulled

    def _shared_push_spec(self, norm):
        """Plan the PS-side optimizer pushes for the fetched train ops:
        returns ``[(var_name, fetch_idx, rule, params)]`` plus the extra
        (synced) gradient nodes to fetch. Optimizers without scalar
        ``ps_step_params`` (schedule-driven or exotic rules) fall back
        to worker-local slots with a one-time note."""
        spec = []
        extra = []
        node_pos = {id(f): i for i, f in enumerate(norm)}
        for f in norm:
            if not isinstance(f, fe.ApplyGradients):
                continue
            params = getattr(f.optimizer, 'ps_step_params', None)
            for gnode, var in f.grads_and_vars:
                if var.name not in self._shared_opt_vars:
                    continue
                if params is None:
                    if var.name not in self._shared_warned:
                        self._shared_warned.add(var.name)
                        logging.warning(
                            'shared_optimizer requested for %s but '
                            'optimizer %s has no PS-side update rule '
                            '(sgd/momentum/adam/adagrad with scalar '
                            'hyperparameters); its slots stay '
                            'worker-local', var.name, f.optimizer.name)
                    continue
                idx = node_pos.get(id(gnode))
                if idx is None:
                    idx = len(norm) + len(extra)
                    node_pos[id(gnode)] = idx
                    extra.append(gnode)
                spec.append((var.name, idx, params['rule'],
                             params['params']))
        return spec, extra

    def _classify_push(self, deltas):
        """Per-variable push mode for this step's deltas: the set of
        all-zero deltas (skipped outright — frozen/eval-only variables
        must not ship full zero tensors every push) and, for
        sparse-flagged 2-D variables, the touched-row index vector when
        the touched fraction is at or below
        ``AUTODIST_SPARSE_PUSH_MAX_FRAC``. Lossless by construction:
        a dropped row's delta is exactly zero, so the BSADD scatter-add
        lands bit-identically to the dense BADD."""
        frac = ENV.AUTODIST_SPARSE_PUSH_MAX_FRAC.val
        zero_skip = set()
        sparse_rows = {}
        for name, delta in deltas.items():
            if frac and name in self._sparse_vars:
                # one scan: the row mask also answers "all zero"
                touched = np.flatnonzero(
                    np.any(delta != 0, axis=1)).astype(np.int32)
                if touched.size == 0:
                    zero_skip.add(name)
                elif touched.size <= frac * delta.shape[0]:
                    sparse_rows[name] = touched
                continue
            if not delta.any():
                zero_skip.add(name)
        return zero_skip, sparse_rows

    def _shard_row_starts(self, name, pc):
        """Cumulative row offsets of an axis-0-partitioned variable's
        shards (sparse vars are forced to axis 0 by the builders)."""
        var = self._graph_item.var_by_name(name)
        rows = [int(s[0]) for s in pc.shard_shapes(var.shape)]
        starts = [0]
        for r in rows:
            starts.append(starts[-1] + r)
        return starts

    def _push_ps_deltas(self, pulled, shared_push=None, scale=None):
        """Push per-variable updates. Default: ``new - pulled`` deltas —
        the binary BADD is commutative, so concurrent workers' updates
        accumulate exactly like the reference's apply-per-push
        accumulators. Sparse-flagged variables whose delta touches few
        rows ship ONLY those rows (``vmsadd``/BSADD — O(batch) wire
        instead of O(vocab x dim)); all-zero deltas are skipped
        entirely. Vars in ``shared_push`` instead ship their raw
        gradient; the service applies the optimizer step with
        PS-resident shared slots (BSTEP). Partitioned variables push
        each shard's slice to that shard's own endpoint (the reference
        splits gradients per shard, kernel/partitioner.py:686-704).
        Endpoint groups push in parallel on the TransferPool workers,
        each as ONE pipelined ``vmadd`` + one ``vmsadd`` batch (plus
        serial ``vstep`` for shared-optimizer vars — the chunk-shared
        step index makes those inherently sequential). At pipeline
        depth >= 2 this whole method runs on the background pipeline
        thread, including the device->host readback of the updated
        state.

        Under the quantized push wire (``AUTODIST_PS_WIRE_DTYPE=i8``)
        every pushed delta/gradient carries error feedback: the
        residual the LAST push's block quantization dropped is added
        back before classification (so accumulated error flushes even
        through variables whose raw delta is zero this step), and the
        new residual — ``compensated - wire_roundtrip(compensated)``,
        bit-exactly the mass the service did not receive — is kept for
        the next push. BADD/BSADD accumulate at f32 rest, so only this
        push direction quantizes; pulls stay f32.

        ``scale`` (local-SGD window averaging, docs/design/local-sgd.md)
        multiplies every delta before classification and quantization:
        under H>1 ``pulled`` is the WINDOW base and scale=1/W turns the
        sum-based wire into the mean of the W workers' window deltas.
        Scaling before classification keeps the composition exact —
        the touched-row set is the window's union (a row scaled by 1/W
        is nonzero iff the raw row is), and the i8 error feedback
        tracks the scaled wire mass that was actually dropped. None
        (the H=1 path) is bit-identical to the pre-window plane."""
        import time as _time

        from autodist_tpu.runtime import coord_client as cc
        t0 = _time.perf_counter()
        shared_push = dict(shared_push or {})
        push_wire = cc._wire_dtype()
        lossy = push_wire == 'i8'
        afters = {name: np.asarray(self._local_value(name),
                                   dtype=np.float32)
                  for name in pulled if name not in shared_push}
        deltas = {name: after - np.asarray(pulled[name],
                                           dtype=np.float32)
                  for name, after in afters.items()}
        if scale is not None and scale != 1.0:
            deltas = {name: d * np.float32(scale)
                      for name, d in deltas.items()}
        if lossy:
            for name in list(deltas):
                res = self._push_residual.get(name)
                if res is not None:
                    deltas[name] = deltas[name] + res
            for name, (g, rule, params) in list(shared_push.items()):
                res = self._push_residual.get(name)
                if res is not None:
                    shared_push[name] = (g + res, rule, params)
        zero_skip, sparse_rows = self._classify_push(deltas)
        groups, _ = self._transfer_groups(list(pulled))

        # plan every endpoint's batch on THIS thread (the pool workers
        # only move bytes), accounting the exact wire cost as we go
        ep_jobs = {}
        ep_bytes = [0] * len(self._ps_addrs)
        wire_bytes = 0
        rows_pushed = 0
        bytes_avoided = 0
        # Residual bookkeeping quantizes each pushed array once here
        # (wire_roundtrip) and once more when the client encodes the
        # actual frames — a deliberate trade: sharing one encode pass
        # would thread pre-encoded blobs through vmadd/vmsadd/vstep's
        # framing, and the extra pass is host CPU the depth-2 pipeline
        # already hides, while the roundtrip helper guarantees the
        # residual is bit-exactly what the service decodes.
        res_parts = {}   # name -> [per-shard residual part] (dense)
        new_res = {}     # name -> full-shape residual (sparse path)
        for ep, units in groups.items():
            job = ep_jobs.setdefault(
                ep, {'steps': [], 'adds': [], 'sadds': []})
            for key, name, i, pc in units:
                if name in shared_push:
                    g, rule, params = shared_push[name]
                    if pc is not None:
                        g = pc.split(g)[i]
                    job['steps'].append(
                        (self._key(key), g, rule, params))
                    nb = self._wire_nbytes(g.size, push=True)
                    if lossy:
                        parts = res_parts.setdefault(
                            name,
                            [None] * len(self._shard_info(name)[1]))
                        parts[i] = g - cc.wire_roundtrip(g, push_wire)
                elif name in zero_skip:
                    full = deltas[name] if pc is None else \
                        pc.split(deltas[name])[i]
                    bytes_avoided += self._wire_nbytes(full.size,
                                                       push=True)
                    continue
                elif name in sparse_rows:
                    delta = deltas[name]
                    idx = sparse_rows[name]
                    if pc is None:
                        sel, local, rows = idx, idx, delta[idx]
                    else:
                        starts = self._shard_row_starts(name, pc)
                        lo, hi = starts[i], starts[i + 1]
                        sel = idx[(idx >= lo) & (idx < hi)]
                        dense_nb = self._wire_nbytes(
                            (hi - lo) * delta.shape[1], push=True)
                        if sel.size == 0:
                            bytes_avoided += dense_nb
                            continue
                        local = (sel - lo).astype(np.int32)
                        rows = delta[sel]
                    job['sadds'].append((self._key(key), local, rows))
                    nb = local.size * 4 + \
                        self._wire_nbytes(rows.size, push=True)
                    dense_elems = (delta.shape[0] if pc is None
                                   else hi - lo) * delta.shape[1]
                    bytes_avoided += self._wire_nbytes(
                        dense_elems, push=True) - nb
                    rows_pushed += local.size
                    if lossy:
                        res = new_res.setdefault(
                            name, np.zeros_like(delta))
                        res[sel] = rows - cc.rows_roundtrip(rows,
                                                            push_wire)
                else:
                    delta = deltas[name]
                    if pc is not None:
                        delta = pc.split(delta)[i]
                    job['adds'].append((self._key(key), delta))
                    nb = self._wire_nbytes(delta.size, push=True)
                    if lossy:
                        parts = res_parts.setdefault(
                            name,
                            [None] * len(self._shard_info(name)[1]))
                        parts[i] = delta - cc.wire_roundtrip(
                            delta, push_wire)
                wire_bytes += nb
                ep_bytes[ep] += nb
        if lossy:
            # Reassemble and retire residuals: a zero compensated delta
            # means the accumulated error was fully flushed (or never
            # existed); merge partitioned shards back to logical shape.
            for name in zero_skip:
                self._push_residual.pop(name, None)
            for name, parts in res_parts.items():
                pc, _ = self._shard_info(name)
                new_res[name] = parts[0] if pc is None else \
                    pc.merge(parts)
            for name, res in new_res.items():
                if np.any(res):
                    self._push_residual[name] = res
                else:
                    self._push_residual.pop(name, None)

        def push_group(job):
            def go(client):
                for key, g, rule, params in job['steps']:
                    client.vstep(key, g, rule, params)
                if job['adds']:
                    client.vmadd(job['adds'])
                if job['sadds']:
                    client.vmsadd(job['sadds'])
            return go

        self._pool.run([(ep, push_group(job))
                        for ep, job in ep_jobs.items()])
        self._shared_pushes += sum(1 for n in pulled if n in shared_push)

        # post-update assign (proxy_variable.py:163-190): refresh the
        # proxy from the PS after the push, off the pre-step path. A
        # sparse push refreshes only ITS rows (vmgetrows) — rows other
        # workers touched converge via the periodic full refresh
        # (AUTODIST_SPARSE_FULL_REFRESH_EVERY); a zero push leaves the
        # cache as is on the same schedule.
        push_only_bytes = wire_bytes
        refresh_bytes, refresh_ep = self._refresh_proxies(
            zero_skip, sparse_rows)
        wire_bytes += refresh_bytes
        for ep, nb in refresh_ep.items():
            ep_bytes[ep] += nb
        push_s = _time.perf_counter() - t0
        with self._stats_lock:
            if not self._ps_ep_bytes:
                self._ps_ep_bytes = [0] * len(self._ps_addrs)
            for ep, nb in enumerate(ep_bytes):
                self._ps_ep_bytes[ep] += nb
            self._ps_seconds += push_s
            self._ps_bytes += wire_bytes
            # direction split: the proxy refresh is READ traffic even
            # though it rides the push phase, so the quantized-push
            # A/B (bench_quantized) can compare pure push bytes
            self._ps_push_bytes += push_only_bytes
            self._ps_pull_bytes += refresh_bytes
            self._ps_phase['push_s'] += push_s
            ss = self._sparse_stats
            ss['sparse_pushes'] += len(sparse_rows)
            ss['rows_pushed'] += rows_pushed
            ss['zero_push_skips'] += len(zero_skip)
            ss['dense_bytes_avoided'] += bytes_avoided
        self._tel.record_span(
            'push_deltas', t0, push_s, step=self._step_count,
            worker=self._worker_name, bytes=wire_bytes,
            sparse=len(sparse_rows), zero_skips=len(zero_skip))
        return push_s

    def _refresh_proxies(self, zero_skip, sparse_rows):
        """Post-push proxy-cache refresh. Unpartitioned sparse-pushed
        vars with a warm cache refresh only their pushed rows
        (BGETROWS); every ``AUTODIST_SPARSE_FULL_REFRESH_EVERY``-th
        refresh falls back to a full fetch so other workers' rows
        converge; everything else takes the legacy full fetch. Returns
        (wire bytes moved, {endpoint: bytes})."""
        if not self._proxy_vars:
            return 0, {}
        refresh_every = ENV.AUTODIST_SPARSE_FULL_REFRESH_EVERY.val
        full_names = []
        row_specs = {}   # name -> touched row indices
        for name in self._proxy_vars:
            pc, _ = self._shard_info(name)
            sparse_capable = (pc is None and name in self._proxy_cache
                              and name in self._sparse_vars)
            rowset = sparse_rows.get(name)
            if rowset is None and sparse_capable and name in zero_skip:
                rowset = np.empty(0, np.int32)
            if rowset is None or not sparse_capable:
                full_names.append(name)
                continue
            cnt = self._sparse_refresh_count.get(name, 0) + 1
            if refresh_every and cnt >= refresh_every:
                self._sparse_refresh_count[name] = 0
                full_names.append(name)
            else:
                self._sparse_refresh_count[name] = cnt
                if rowset.size:
                    row_specs[name] = rowset
        wire = 0
        ep_bytes = {}
        full_refreshes = 0
        if full_names:
            refreshed, _ = self._fetch_var_parts(full_names)
            for name, parts in refreshed.items():
                pc, _ = self._shard_info(name)
                served = parts[0] if pc is None else (
                    None if any(p is None for p in parts)
                    else pc.merge(parts))
                if served is not None:
                    var = self._graph_item.var_by_name(name)
                    self._proxy_cache[name] = \
                        served.astype(var.init_value.dtype)
                    wire += self._wire_nbytes(served.size)
                    # the counter tracks the SPARSE plane's full-refresh
                    # fallback; dense proxy vars full-refresh every
                    # step by design and would drown the signal
                    if name in self._sparse_vars:
                        full_refreshes += 1
                    idxs = self._shard_endpoints(name, len(parts))
                    sizes = [served.size] if pc is None else \
                        [p.size for p in parts]
                    for ep_i, sz in zip(idxs, sizes):
                        ep_bytes[ep_i] = ep_bytes.get(ep_i, 0) + \
                            self._wire_nbytes(sz)
        if row_specs:
            by_ep = {}
            for name, idx in row_specs.items():
                _, keys = self._shard_info(name)
                ep = self._shard_endpoints(name, 1)[0]
                ncols = int(
                    self._graph_item.var_by_name(name).shape[1])
                by_ep.setdefault(ep, []).append(
                    (name, self._key(keys[0]), idx, ncols))

            def fetch_rows(specs):
                def go(client):
                    arrs = client.vmgetrows(
                        [(key, idx, ncols)
                         for _, key, idx, ncols in specs])
                    return [(name, idx, a) for (name, _, idx, _), a
                            in zip(specs, arrs)]
                return go

            for got in self._pool.run(
                    [(ep, fetch_rows(specs))
                     for ep, specs in by_ep.items()]):
                for name, idx, arr in got:
                    if arr is None:   # pragma: no cover - init race
                        continue
                    cache = self._proxy_cache[name]
                    cache[idx] = arr.astype(cache.dtype)
                    nb = idx.size * 4 + self._wire_nbytes(arr.size)
                    wire += nb
                    ep = self._shard_endpoints(name, 1)[0]
                    ep_bytes[ep] = ep_bytes.get(ep, 0) + nb
        with self._stats_lock:
            self._sparse_stats['row_refreshes'] += len(row_specs)
            self._sparse_stats['rows_refreshed'] += \
                sum(i.size for i in row_specs.values())
            self._sparse_stats['full_refreshes'] += full_refreshes
        return wire, ep_bytes

    def _contract(self, fetch, stacked, split_sizes):
        """Apply the reference fetch contract to the per-replica stack."""
        if isinstance(fetch, fe.ApplyGradients):
            return None
        if isinstance(stacked, list):  # list-valued fetch (Gradients)
            return [self._local_stack(s)[0] for s in stacked]
        val = self._local_stack(stacked)
        n = self._plan.local_replicas
        local = val[0]
        # Polymorphic-dim rule (remapper.py:125-185): feeds were split and
        # the fetch still carries a per-example leading dim -> concatenate
        # across replicas.
        if split_sizes and local.ndim >= 1 and n > 1 and \
                self._looks_batched(fetch, local, split_sizes):
            return np.concatenate(list(val), axis=0)
        return local

    def _looks_batched(self, fetch, local_val, split_sizes):
        """Polymorphic-dim detection: a declared None leading dim on the
        fetch's symbolic shape; for shape-unknown computed tensors, a
        leading dim equal to the local batch split."""
        shape = getattr(fetch, 'shape', None)
        if shape is not None:
            return bool(len(shape) >= 1 and shape[0] is None)
        return local_val.shape[0] in split_sizes

    # -- step compilation --------------------------------------------------
    def _build_step(self, fetch_nodes, feed_nodes, split_flags):
        plan = self._plan
        mesh = self._mesh
        graph_item = self._graph_item

        var_specs = {name: plan.var_spec(name)
                     for name in self._var_state}
        opt_specs = {
            uid: {vname: jax.tree.map(
                lambda leaf, vn=vname: self._slot_spec(vn, leaf), state)
                for vname, state in slots.items()}
            for uid, slots in self._opt_state.items()}
        # aux leaves carry a leading per-replica dim (see _init_state)
        aux_specs = jax.tree.map(lambda _: P(AXIS_DATA), self._aux_state)
        feed_specs = [P(AXIS_DATA) if s else P() for s in split_flags]

        sharded_vars = {name for name, p in plan.var_plans.items()
                        if p.state_sharded}

        def step(var_state, opt_state, aux_state, feeds):
            shards = dict(var_state)
            full = dict(var_state)
            for name in sharded_vars:
                p = plan.var_plans[name]
                full[name] = ShardedGrad(
                    var_state[name], p.shard_axis,
                    logical_dim=p.var.shape[p.shard_axis],
                    hier_groups=plan.gather_hier_groups(p)).gather()
            # strip the per-replica leading dim for in-step aux access
            aux_local = jax.tree.map(lambda x: x[0], aux_state)
            env = fe.Env(full, dict(zip(feed_nodes, feeds)),
                         grad_sync_fn=plan.sync_gradients,
                         opt_state=opt_state, aux_state=aux_local)
            env.var_shards = shards
            env.plan = plan
            def box(v):
                if isinstance(v, ShardedGrad):
                    v = v.gather()
                return jnp.asarray(v)[None]  # stack dim for P(data)

            outs = []
            for node in fetch_nodes:
                val = fe.evaluate(node, env)
                # list-valued fetches (a Gradients node) stay a list —
                # out_specs broadcast over the subtree as a pytree prefix
                outs.append([box(v) for v in val]
                            if isinstance(val, (list, tuple)) else box(val))
            new_vars = dict(var_state)
            for name, val in env.updates.items():
                new_vars[name] = val
            new_opt = jax.tree.map(lambda x: x, opt_state)
            for uid, slots in env.opt_updates.items():
                new_opt[uid] = {**new_opt.get(uid, {}), **slots}
            new_aux = dict(aux_state)
            for k, v in env.aux_updates.items():
                new_aux[k] = jax.tree.map(lambda x: x[None], v)
            return outs, new_vars, new_opt, new_aux

        out_fetch_specs = [P(AXIS_DATA) for _ in fetch_nodes]
        mapped = _shard_map(
            step, mesh,
            (var_specs, opt_specs, aux_specs, feed_specs),
            (out_fetch_specs, var_specs, opt_specs, aux_specs))
        jitted = jax.jit(mapped, donate_argnums=(0, 1, 2))
        logging.debug('Compiled new step for %d fetches, %d feeds',
                      len(fetch_nodes), len(feed_nodes))
        return jitted

    # -- lifecycle ---------------------------------------------------------
    def close(self):
        # stop heartbeats FIRST (ADVICE r4): a beat written after the
        # run-end purge would leak a stale hb/<ns>/ key on long-lived
        # endpoints.  Workers therefore go silent before incrementing
        # 'closed', and the purger's own thread is joined before it
        # deletes the hb namespace.
        if getattr(self, '_hb_stop', None) is not None:
            self._hb_stop.set()
            thread = getattr(self, '_hb_thread', None)
            if thread is not None and thread.is_alive():
                thread.join(timeout=15.0)
        drain_err = None
        if not self._closed and self._loose and self._coord is not None:
            # our last background push must land BEFORE the done
            # marker / step sentinel (a peer released by the sentinel
            # must still see our final update). A failed final push is
            # NOT swallowed with the best-effort bookkeeping below: it
            # re-raises after peers are released and the pools closed —
            # the PS copy is missing this worker's last step.
            try:
                self._drain_pipeline()
            except Exception as e:  # noqa: BLE001 - re-raised below
                drain_err = e
                logging.error(
                    'final background PS push failed in close(): %s: %s',
                    type(e).__name__, e)
            # telemetry: flush this worker's final span batch, and on
            # the chief assemble + export the cohort trace — BOTH
            # before the purge quorum below can erase the run's
            # telemetry namespace
            if self._tel.enabled:
                try:
                    self._maybe_push_telemetry(
                        self._coord, self._step_count, final=True)
                    if self._monitor is not None:
                        # final verdict refresh over the last batches
                        # so health_stats read after close() reflects
                        # the whole run
                        self._monitor.poll()
                    if self._is_chief:
                        self.export_chrome_trace()
                except Exception as e:  # noqa: BLE001 - advisory
                    logging.warning('telemetry flush/export in close() '
                                    'failed: %s: %s',
                                    type(e).__name__, e)
            if self._is_chief:
                # the telemetry namespace must not outlive the run
                # even when the purge quorum below is never reached (a
                # peer that crashed, or a harness peer that never
                # bumps 'closed'): a reused service would replay the
                # stale batches — the per-worker batch counter hands
                # the NEXT run's collector sequence numbers that
                # decode to THIS run's spans. Collection and export
                # happened above, so nothing is lost; batch keys AND
                # the atomic counters live under <ns>/telemetry/ and
                # go together.
                try:
                    self._coord.delete_namespace(
                        self._key('telemetry/'))
                except Exception:  # noqa: BLE001 - service may be gone
                    pass
                # staged epoch-swap plans must not outlive the run
                # either, even when the purge quorum below is never
                # reached: a restarted run (same deterministic ns)
                # must never validate — let alone apply — a dead
                # cohort's staged generation
                try:
                    from autodist_tpu.runtime import swap_keys
                    swap_keys.purge_all(self._coord, self._ns)
                except Exception:  # noqa: BLE001 - service may be gone
                    pass
            self._flight.record('close', worker=self._worker_name,
                                step=self._step_count,
                                clean=drain_err is None)
            if drain_err is not None:
                # an unclean close IS a failure trigger: the PS copy is
                # missing this worker's last step and the evidence of
                # how dies with the process
                self._flight.dump('unclean_close')
            # clean shutdown is not a crash: publish a done marker so
            # peers exclude us from dead-worker checks, and advance our
            # step counter past any reachable gate bound so a peer
            # blocked on the staleness window is released
            try:
                from autodist_tpu.runtime.coord_client import \
                    CLEAN_CLOSE_STEP
                self._coord.set(
                    'done/%s' % self._key(self._worker_name), '1')
                self._coord.publish_step(self._worker_name,
                                         CLEAN_CLOSE_STEP,
                                         prefix=self._key('step/'))
                # run-end cleanup (ADVICE r3): the LAST worker out
                # purges the run's namespace from the coord service and
                # every PS endpoint — a reused long-lived endpoint must
                # not accumulate dead runs' multi-hundred-MB tensors.
                # The atomic INCR makes exactly one process the purger,
                # and only after every peer has closed. Excluded
                # (fenced) peers can never increment this counter, so
                # the quorum is the ACTIVE membership — else a run that
                # excluded a dead worker would leak its namespace.
                # Adopt membership changes this process may never have
                # observed (it finished its last gated step before the
                # excluder's epoch bump): a closer counting a stale,
                # larger quorum would strand the 'closed' counter below
                # every threshold and silently skip the purge.
                epoch = self._coord.incr(self._key('epoch'), 0)
                if epoch != self._epoch_seen:
                    self._epoch_seen = epoch
                    self._refresh_membership()
                closed = self._coord.incr(self._key('closed'), 1)
                if closed >= self._active_workers():
                    purged = sum(self._pool.run(
                        [(ep, lambda c: c.delete_namespace(
                            self._ns + '/'))
                         for ep in range(len(self._pool))]))
                    coord_addr = tuple(getattr(self._coord, 'address',
                                               ()) or ())
                    if coord_addr not in [tuple(a)
                                          for a in self._ps_addrs]:
                        purged += self._coord.delete_namespace(
                            self._ns + '/')
                    for prefix in ('hb/%s/' % self._ns,
                                   'done/%s/' % self._ns):
                        self._coord.delete_namespace(prefix)
                    logging.debug('purged %d namespace entries for run '
                                  '%s', purged, self._ns)
            except Exception:  # noqa: BLE001 - service may be gone
                pass
        self._closed = True
        for pool in (getattr(self, '_pipe', None),
                     getattr(self, '_tel_pipe', None),
                     getattr(self, '_pool', None)):
            if pool is not None:
                pool.close()
        if getattr(self, '_auto_ckpt', None) is not None:
            try:
                self._auto_ckpt.close()   # drain the in-flight save
            except Exception as e:  # noqa: BLE001 - backstop teardown
                logging.warning('auto-checkpoint drain failed in '
                                'close(): %s: %s', type(e).__name__, e)
        if drain_err is not None:
            raise drain_err

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    @property
    def step_count(self):
        return self._step_count

    # state access for savers / tests
    def _local_value(self, name):
        arr = self._var_state[name]
        if getattr(arr, 'is_fully_addressable', True):
            return np.asarray(self._plan.unpad_host(name, np.asarray(arr)))
        sharding = getattr(arr, 'sharding', None)
        if sharding is not None and sharding.is_fully_replicated:
            return np.asarray(arr.addressable_shards[0].data)
        # cross-process sharded state: gather (collective — every process
        # must make this call)
        from jax.experimental import multihost_utils
        return np.asarray(self._plan.unpad_host(
            name, np.asarray(multihost_utils.process_allgather(
                arr, tiled=True))))

    def get_variable_value(self, var):
        name = var.name if isinstance(var, fe.Variable) else var
        if self._loose:
            # read-your-writes at the API surface: our own background
            # push must land before the authoritative read (the
            # prefetch stays valid — a read pushes nothing)
            self._drain_pipeline(keep_prefetch=True)
            # authoritative copy lives on the variable's PS endpoint(s):
            # each shard of a partitioned variable on its own endpoint
            var_obj = self._graph_item.var_by_name(name)
            parts = self._fetch_var_parts([name])[0][name]
            pc, _ = self._shard_info(name)
            served = parts[0] if pc is None else pc.merge(parts)
            return served.astype(var_obj.init_value.dtype)
        return self._local_value(name)

    def load_variable_value(self, var, value):
        name = var.name if isinstance(var, fe.Variable) else var
        if self._loose:
            # the load supersedes both any in-flight push and the
            # prefetched pull (which would serve pre-load values)
            self._drain_pipeline()
        self._var_state[name] = self._put(
            self._plan.pad_host(name, jnp.asarray(value)),
            self._plan.var_sharding(name))
        if self._loose and self._is_chief:
            self._store_var_parts({name: value})
