"""Client + process manager for the native coordination service.

The service (native/coord_service.cc) provides the between-program
control plane: barriers, counters, bounded-staleness windows, heartbeats.
See the source header for the protocol. The chief starts one instance
(:func:`ensure_service`); every process connects with
:class:`CoordClient`.

Bounded staleness (reference semantics, ps_synchronizer.py:387-458 and
the c9 timing contract): each worker publishes its step counter under
``step/<worker>``; before running step ``s`` a worker calls
:meth:`staleness_gate`, which blocks until ``min(all steps) >= s -
staleness``. A fast worker can thus run at most ``staleness`` steps ahead
— the queue-capacity semantics without TF FIFO queues.

The tensor data plane (:meth:`CoordClient.vset` / ``vget`` / ``vadd`` /
``vstep``) speaks length-prefixed binary frames: a text header line
declaring the byte count, then the raw tensor bytes — f32, bf16 or
block-quantized i8 on the wire (``AUTODIST_PS_WIRE_DTYPE``), f32 at
rest on the service. This is the grpc-data-plane equivalent the
reference rode for PS traffic; base64 text framing (33% inflation,
full-line buffering) is gone.

The ``i8`` wire (EQuARX-style blockscale: ``u32 block, u32 n, f32
scales x ceil(n/block), int8 q x n`` — one f32 scale per
``AUTODIST_QUANT_BLOCK`` int8 values) is a PUSH-direction format:
deltas/gradients quantize to ~1/4 the f32 bytes, the service
accumulates at f32 rest, and the session carries a host-side
error-feedback residual per pushed delta (runtime/session.py) so loose
mode stays convergent. Pulls and authoritative stores under an ``i8``
setting ride f32 (quantizing at-rest state or reads would compound
error with no residual to absorb it) — see
docs/design/quantized-wire.md.

Row-sparse forms (:meth:`CoordClient.vsadd` / ``vgetrows`` and their
batched ``vmsadd`` / ``vmgetrows``) move only the TOUCHED rows of an
embedding-style ``[rows, cols]`` tensor: a push ships ``int32 row
indices || row data`` and the service scatter-adds it (BSADD), a fetch
requests listed rows (BGETROWS) — O(batch) wire instead of
O(vocab x dim) when a step touches few rows.

The multi-tensor variants (:meth:`CoordClient.vmget` / ``vmset`` /
``vmadd``) PIPELINE their RPCs: all request frames are written ahead of
draining the replies on the same socket, so a pull of N chunks pays one
wire round trip instead of N. The service protocol is strictly
sequential per connection (one request fully handled before the next is
read), which is exactly what makes this safe — replies come back in
request order. :class:`TransferPool` supplies the persistent
per-endpoint worker threads (one dedicated connection each) the session
drives these through.
"""
import hashlib
import hmac as hmac_mod
import os
import queue
import socket
import subprocess
import threading
import time

import numpy as np

from autodist_tpu.const import DEFAULT_COORD_PORT, ENV
from autodist_tpu.telemetry import core as _telemetry
from autodist_tpu.utils import logging

try:
    import ml_dtypes
    _BF16 = np.dtype(ml_dtypes.bfloat16)
except ImportError:  # pragma: no cover - ml_dtypes ships with jax
    _BF16 = None


class FencedWriteError(OSError):
    """A write was rejected because this connection's fencing
    generation has been superseded — this process was declared dead
    and a survivor (or its own replacement) bumped its fence counter.
    A zombie receiving this must stop writing; recovery belongs to the
    supervising coordinator, not to the fenced process."""


class ReadOnlyViolation(OSError):
    """A mutating command was attempted on a read-only connection.

    Raised LOCALLY, before the frame reaches the wire: a read-only
    client (``CoordClient(read_only=True)`` — the serving tier's data
    connection) holds the invariant that it can never perturb the
    training namespace, so the guard must not depend on server-side
    enforcement or on which keys the command happens to touch."""


#: Command verbs a read-only connection refuses locally. The mutating
#: set mirrors the server's write surface (fence_lint's MUTATING table
#: machine-checks the correspondence): SET/DEL/DELNS/INCR on the KV
#: plane, BSET/BADD/BSADD/BSTEP on the tensor plane — plus FENCE,
#: which is not a write but BINDS a writer generation: a reader taking
#: a fence would enter the cohort's zombie-detection protocol, and
#: readers must never hold writer generations.
READ_ONLY_BLOCKED = frozenset(
    {'SET', 'DEL', 'DELNS', 'INCR', 'BSET', 'BADD', 'BSADD', 'BSTEP',
     'FENCE'})


# process-wide connection-retry accounting (profiling.health_report):
# every failed connect attempt inside connect_with_retry counts here.
RETRY_STATS = {'connect_retries': 0}


def _check_fenced(resp, what):
    """Raise the typed fencing error on an `ERR fenced` reply."""
    if resp.startswith('ERR fenced'):
        raise FencedWriteError(
            '%s rejected: writer generation fenced (this process was '
            'declared dead and superseded)' % what)
    return resp


def _raise_batch(errs):
    """Raise a pipelined batch's aggregated errors, keeping the typed
    fencing error when any reply was a fence rejection (a zombie's
    whole batch dies the moment its generation is superseded)."""
    msg = '; '.join(errs)
    if any('ERR fenced' in e for e in errs):
        raise FencedWriteError(msg)
    raise OSError(msg)


def coord_token():
    """The coord-service shared secret, or '' for an open service.

    Resolution order: ``AUTODIST_COORD_TOKEN`` (direct env), then
    ``AUTODIST_COORD_TOKEN_FILE`` (the ssh coordinator ships the secret
    as a mode-0600 file because env assignments ride the remote command
    line, world-readable in ``ps``)."""
    token = ENV.AUTODIST_COORD_TOKEN.val
    if token:
        return token
    path = ENV.AUTODIST_COORD_TOKEN_FILE.val
    if path:
        try:
            with open(path) as f:
                return f.read().strip()
        except OSError:
            logging.warning('coord token file %s unreadable', path)
    return ''


def _wire_dtype(wire=None):
    """Resolve the wire dtype name ('f32'|'bf16'|'i8')."""
    wire = wire or ENV.AUTODIST_PS_WIRE_DTYPE.val
    if wire not in ('f32', 'bf16', 'i8'):
        raise ValueError('unsupported PS wire dtype %r' % wire)
    if wire == 'bf16' and _BF16 is None:  # pragma: no cover
        logging.warning('bf16 wire requested but ml_dtypes is missing; '
                        'falling back to f32')
        return 'f32'
    return wire


def _pull_wire(wire=None):
    """The wire dtype for PULLS and authoritative STORES: i8 is a
    push-direction (delta) format — quantizing reads or at-rest state
    would compound error with no error-feedback residual to absorb it —
    so an ``i8`` setting downgrades to f32 here; f32/bf16 pass
    through."""
    wire = _wire_dtype(wire)
    return 'f32' if wire == 'i8' else wire


def _quant_block():
    """Elements per f32 scale in i8 blockscale frames
    (``AUTODIST_QUANT_BLOCK``; each frame also carries its block size,
    so decode never depends on this process's setting)."""
    return ENV.AUTODIST_QUANT_BLOCK.val


def _as_f32_flat(value):
    """Host value -> flat contiguous float32 array WITHOUT copying when
    the input already conforms — the common hot-path case (session
    deltas and pulled buffers are contiguous float32 already). Only a
    wrong dtype or non-contiguous layout pays a copy."""
    arr = np.asarray(value)
    if arr.dtype != np.float32:
        arr = arr.astype(np.float32)
    if not arr.flags.c_contiguous:
        arr = np.ascontiguousarray(arr)
    return arr.reshape(-1)


def _encode(arr, wire):
    """float32 host array -> raw wire bytes.

    The f32 path returns a zero-copy memoryview over the source array
    (``tobytes`` paid a full payload copy per frame); callers must not
    mutate the source until the frame is sent. The i8 path emits the
    blockscale frame ``u32 block, u32 n, f32 scales, int8 q``
    (symmetric per-block quantization, round-half-to-even like the
    service's own encoder)."""
    arr = _as_f32_flat(arr)
    if wire == 'bf16':
        return arr.astype(_BF16).tobytes()
    if wire == 'i8':
        import struct
        block = _quant_block()
        n = arr.size
        nb = -(-n // block)
        padded = np.zeros(nb * block, np.float32)
        padded[:n] = arr
        blocks = padded.reshape(nb, block)
        # float32 throughout: the scale each q multiplies against on
        # decode (here, in C++, and in wire_roundtrip) must be the
        # same float32 value, or the error-feedback residual the
        # session carries would not be exact
        scales = (np.abs(blocks).max(axis=1) / np.float32(127.0) +
                  np.float32(1e-30)).astype(np.float32)
        q = np.clip(np.rint(blocks / scales[:, None]),
                    -127, 127).astype(np.int8)
        return (struct.pack('<II', block, n) + scales.tobytes() +
                q.reshape(-1)[:n].tobytes())
    return memoryview(arr).cast('B')


def _decode(raw, wire):
    """Raw wire bytes -> float32 host array."""
    if wire == 'bf16':
        return np.frombuffer(raw, dtype=_BF16).astype(np.float32)
    if wire == 'i8':
        import struct
        block, n = struct.unpack('<II', bytes(raw[:8]))
        nb = -(-n // block) if block else 0
        if not block or len(raw) != 8 + nb * 4 + n:
            raise ValueError('malformed i8 blockscale frame '
                             '(%d bytes, block=%d n=%d)'
                             % (len(raw), block, n))
        scales = np.frombuffer(raw, dtype='<f4', count=nb, offset=8)
        q = np.frombuffer(raw, dtype=np.int8, count=n,
                          offset=8 + nb * 4)
        padded = np.zeros(nb * block, np.float32)
        padded[:n] = q
        return (padded.reshape(nb, block) *
                scales[:, None]).reshape(-1)[:n].copy()
    return np.frombuffer(raw, dtype=np.float32)


def _wire_itemsize(wire):
    """Approximate wire bytes per element (i8 carries a ~4/block scale
    overhead on top; :func:`wire_nbytes` accounts it exactly)."""
    return {'bf16': 2, 'i8': 1}.get(wire, 4)


def _chunk_elems(wire):
    """Elements per frame chunk (AUTODIST_PS_CHUNK_BYTES of wire
    bytes); 0 disables chunking."""
    limit = ENV.AUTODIST_PS_CHUNK_BYTES.val
    if not limit:
        return 0
    return max(1, limit // _wire_itemsize(wire))


def _chunk_ranges(n_elems, wire):
    """Chunk ranges [(off, count)] covering ``n_elems``; a single
    (0, n) range means 'send unranged' (whole-tensor frame). Module
    level so :func:`wire_roundtrip` replicates the EXACT per-frame
    quantization layout a push produced."""
    chunk = _chunk_elems(wire)
    if not chunk or n_elems <= chunk:
        return [(0, n_elems)]
    return [(off, min(chunk, n_elems - off))
            for off in range(0, n_elems, chunk)]


def _row_chunk_ranges(nrows, bytes_per_row):
    """Row-chunk ranges [(off, count)] so no frame exceeds
    ``AUTODIST_PS_CHUNK_BYTES`` of wire bytes."""
    limit = ENV.AUTODIST_PS_CHUNK_BYTES.val
    if not limit or nrows * bytes_per_row <= limit:
        return [(0, nrows)]
    per = max(1, limit // bytes_per_row)
    return [(off, min(per, nrows - off))
            for off in range(0, nrows, per)]


def wire_roundtrip(arr, wire=None):
    """What the service will STORE for a dense pushed array: the exact
    ``decode(encode(chunk))`` of every frame a ``vadd``/``vstep`` of
    ``arr`` emits, reassembled to ``arr``'s shape. f32 is the identity;
    bf16 is round-to-nearest-even; i8 is the per-chunk blockscale
    round-trip. The session's error-feedback residual is
    ``compensated - wire_roundtrip(compensated)`` — exactly the mass
    the wire dropped, bit-for-bit (the same float32 ops run here and on
    the service)."""
    wire = _wire_dtype(wire)
    arr32 = np.asarray(arr, dtype=np.float32)
    if wire == 'f32':
        return arr32
    flat = _as_f32_flat(arr32)
    out = np.empty(flat.size, np.float32)
    for off, count in _chunk_ranges(flat.size, wire):
        out[off:off + count] = _decode(
            bytes(_encode(flat[off:off + count], wire)), wire)
    return out.reshape(arr32.shape)


def rows_roundtrip(rows, wire=None):
    """:func:`wire_roundtrip` for the row-sparse push (``vsadd``):
    the exact decode of every row-chunk frame's encoded blob, shaped
    ``[nrows, ncols]`` like the input."""
    wire = _wire_dtype(wire)
    rows = np.asarray(rows, dtype=np.float32)
    if wire == 'f32':
        return rows
    out = np.empty_like(rows)
    row_wire = rows.shape[1] * _wire_itemsize(wire)
    for off, count in _row_chunk_ranges(rows.shape[0], 4 + row_wire):
        out[off:off + count] = _decode(
            bytes(_encode(rows[off:off + count], wire)),
            wire).reshape(count, -1)
    return out


def wire_nbytes(n_elems, wire=None):
    """Payload bytes ``n_elems`` floats occupy on the given wire,
    including the i8 blockscale overhead (8-byte header + one f32
    scale per ``AUTODIST_QUANT_BLOCK`` elements, per chunk frame)."""
    wire = _wire_dtype(wire)
    if wire != 'i8':
        return n_elems * _wire_itemsize(wire)
    block = _quant_block()
    total = 0
    for _, count in _chunk_ranges(n_elems, wire):
        total += 8 + 4 * (-(-count // block)) + count
    return total


def ensure_service(port=DEFAULT_COORD_PORT, wait_s=10.0, bind='127.0.0.1'):
    """Start the native service on this host if nothing is listening.

    Binds loopback by default; multi-host launchers pass ``bind='0.0.0.0'``
    (or the coordinator interface) explicitly.
    """
    try:
        CoordClient(('127.0.0.1', port), timeout=0.5).ping()
        return None  # already running
    except OSError:
        pass
    from autodist_tpu.native_build import build
    binary = build('coord_service.cc')
    env = dict(os.environ)
    token = coord_token()
    if token:
        # the service reads the secret from its environment only (argv
        # would be visible in ps); resolve token-file transport here
        env['AUTODIST_COORD_TOKEN'] = token
    proc = subprocess.Popen([binary, str(port), bind],
                            stdout=subprocess.DEVNULL,
                            stderr=subprocess.DEVNULL, env=env)
    deadline = time.time() + wait_s
    while time.time() < deadline:
        try:
            CoordClient(('127.0.0.1', port), timeout=0.5).ping()
            logging.info('coord_service started on :%d (pid %d)',
                         port, proc.pid)
            return proc
        except OSError:
            time.sleep(0.05)
    # the spawned process may be alive but unresponsive (or still
    # binding): kill it before raising, or it leaks as an orphan
    # holding the port and every subsequent start attempt on this
    # port fails against the half-dead listener
    proc.terminate()
    try:
        proc.wait(timeout=5.0)
    except subprocess.TimeoutExpired:  # pragma: no cover - stuck child
        proc.kill()
        proc.wait(timeout=5.0)
    raise RuntimeError('coord_service failed to start on :%d '
                       '(spawned pid %d killed)' % (port, proc.pid))


# A step counter at/above this value means the worker has LEFT the run
# (clean close, or an exclude-policy release of a dead peer's counter),
# not that it trained 2^30 steps — see publish_step's release note.
CLEAN_CLOSE_STEP = 1 << 30


def ps_endpoints():
    """Configured PS data-plane endpoints as (host, port) tuples.

    Empty when ``AUTODIST_PS_ENDPOINTS`` is unset — the single-endpoint
    layout where variables live on the coord service itself.
    """
    raw = ENV.AUTODIST_PS_ENDPOINTS.val
    if not raw:
        return []
    eps = []
    for item in raw.split(','):
        item = item.strip()
        if not item:   # tolerate trailing commas / blank entries
            continue
        if ':' not in item:
            raise ValueError(
                'AUTODIST_PS_ENDPOINTS entries must be host:port; got %r'
                % item)
        host, port = item.rsplit(':', 1)
        eps.append((host, int(port)))
    return eps


def connect_with_retry(address=None, deadline_s=30.0, op_timeout=300.0,
                       read_only=False):
    """Connect to the coord service, retrying until it comes up (workers
    may start before the chief's ensure_service).

    Connection attempts stay snappy (5 s), but the ESTABLISHED client
    gets ``op_timeout`` per socket operation: data-plane transfers move
    multi-MB frames through per-tensor locks under contention, and a
    single 64 KB recv stalling past a short probe timeout would kill a
    healthy pull (observed as a flaky 4-worker x 105 MB test on a
    loaded one-core host). Callers that need FAST failure detection on
    an established connection (e.g. heartbeat loops) pass a small
    ``op_timeout`` instead.

    Retries back off exponentially (0.05 s doubling to a 2 s cap) with
    ±25% deterministic-free jitter so a herd of workers restarted
    together does not hammer the service in lockstep; the final
    RuntimeError chains ``from`` the last OSError so the root cause
    (ECONNREFUSED vs EHOSTUNREACH vs auth failure) survives into the
    traceback.

    ``read_only=True`` returns a reader connection (serving tier): no
    fence binding ever, and every mutating verb raises
    :class:`ReadOnlyViolation` locally."""
    import random
    deadline = time.time() + deadline_s
    last = None
    delay = 0.05
    while time.time() < deadline:
        try:
            c = CoordClient(address, timeout=5.0, op_timeout=op_timeout,
                            read_only=read_only)
            c.ping()
            return c
        except OSError as e:
            last = e
            RETRY_STATS['connect_retries'] += 1
            _telemetry.get().count('coord/connect_retries')
            time.sleep(min(delay * (1.0 + random.uniform(-0.25, 0.25)),
                           max(0.0, deadline - time.time())))
            delay = min(delay * 2.0, 2.0)
    raise RuntimeError('coord_service unreachable at %s: %s'
                       % (address, last)) from last


class CoordClient:
    """Blocking line-protocol client."""

    # Fault-injection hook (utils/faultline.py): when set (class-wide,
    # chaos tests / bench recovery only), called as
    # ``hook(client, line, payload)`` before every request frame hits
    # the wire. The hook may raise (drop/close faults), sleep (delay
    # faults) or return a replacement ``(line, payload)`` (torn-frame
    # faults). None in production — one attribute test per frame.
    fault_hook = None

    # How long a torn pull waits for an in-flight chunked write whose
    # version has stopped advancing before declaring the writer dead.
    # Must cover one full chunk frame's encode+wire time (the version
    # only moves per landed frame); tests shrink it, deployments tune
    # it via AUTODIST_PS_STALL_TIMEOUT_S (see stall_timeout_s).
    STALL_TIMEOUT_S = 10.0

    @property
    def stall_timeout_s(self):
        """The torn-read stall window: ``AUTODIST_PS_STALL_TIMEOUT_S``
        when set (validated > 0 in const.py like the sibling
        TORN_RETRIES/BACKOFF knobs), else the class default — which
        tests shrink by patching :attr:`STALL_TIMEOUT_S`."""
        if os.environ.get(ENV.AUTODIST_PS_STALL_TIMEOUT_S.name):
            return ENV.AUTODIST_PS_STALL_TIMEOUT_S.val
        return self.STALL_TIMEOUT_S

    def __init__(self, address=None, timeout=None, op_timeout=None,
                 read_only=False):
        if address is None:
            raw = ENV.AUTODIST_COORD_SERVICE_ADDR.val
            if raw:
                host, port = raw.rsplit(':', 1)
                address = (host, int(port))
            else:
                address = ('127.0.0.1', DEFAULT_COORD_PORT)
        # the RESOLVED address, so sibling connections (e.g. a session's
        # background heartbeat thread) dial exactly what worked here —
        # the env address may differ (all-local runs rewrite to loopback)
        self.address = address
        # read-only connections (serving tier) never fence-bind and
        # refuse every mutating verb locally in _send_frame — the one
        # choke point both the scalar RPCs and the pipelined batches
        # pass through, so no command path can bypass the guard
        self.read_only = bool(read_only)
        # per-RPC telemetry spans (command + payload bytes) when the
        # plane is enabled; one attribute check per RPC when it is not
        self._tel = _telemetry.get()
        self._sock = socket.create_connection(address, timeout=timeout)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._buf = b''
        self._handshake()
        # per-operation timeout for the ESTABLISHED connection (the
        # connect `timeout` stays snappy for probes/handshake); the
        # timed waits below temporarily override and RESTORE it
        self._op_timeout = op_timeout if op_timeout is not None \
            else timeout
        self._sock.settimeout(self._op_timeout)

    def _read_reply_line(self):
        while b'\n' not in self._buf:
            chunk = self._sock.recv(65536)
            if not chunk:
                raise OSError('coord_service closed connection')
            self._buf += chunk
        resp, self._buf = self._buf.split(b'\n', 1)
        return resp.decode()

    def _handshake(self):
        """Consume the service greeting; answer the nonce challenge when
        the service is token-protected (HELLO <nonce> -> AUTH
        hmac-sha256(token, nonce))."""
        greeting = self._read_reply_line()
        parts = greeting.split()
        if len(parts) != 2 or parts[0] != 'HELLO':
            # whatever is on this port, it is not a coord service
            raise OSError('unexpected greeting %r' % greeting[:64])
        if parts[1] == 'open':
            if coord_token():
                # no silent auth downgrade: a configured token means the
                # operator expects every endpoint authenticated — an
                # open listener here is a stale/spoofed service
                raise OSError(
                    'coord service at %s is UNAUTHENTICATED but an '
                    'AUTODIST_COORD_TOKEN is configured — refusing the '
                    'auth downgrade (stale or spoofed service?)'
                    % (self.address,))
            return
        token = coord_token()
        if not token:
            raise OSError(
                'coord service at %s requires authentication but no '
                'AUTODIST_COORD_TOKEN(_FILE) is configured'
                % (self.address,))
        mac = hmac_mod.new(token.encode(), parts[1].encode(),
                           hashlib.sha256).hexdigest()
        self._sock.sendall(('AUTH %s\n' % mac).encode())
        resp = self._read_reply_line()
        if resp != 'OK':
            raise OSError('coord service rejected auth: %s' % resp)

    def _send_frame(self, line, payload=None):
        """Write one request frame (header line + optional raw payload)
        WITHOUT reading its reply — the building block the pipelined
        multi-tensor calls (vmget/vmset/vmadd/vmsadd) write batches of.

        ``payload`` may be a LIST of buffers (scatter-gather framing:
        the sparse plane's ``int32 indices || row data`` payloads ship
        without a concat copy of the row bytes)."""
        if self.read_only:
            parts = line.split(None, 3)
            verb = parts[0] if parts else ''
            # INCR <key> 0 is the plane's counter READ (the server
            # fence-exempts delta 0 for the same reason); any other
            # blocked verb dies here, before it can reach the wire
            if verb in READ_ONLY_BLOCKED and not (
                    verb == 'INCR' and len(parts) > 2
                    and parts[2] == '0'):
                raise ReadOnlyViolation(
                    '%s refused: this connection is read-only (the '
                    'serving tier must never mutate the training '
                    'namespace or bind a writer generation)'
                    % line.split(None, 1)[0])
        hook = CoordClient.fault_hook
        if hook is not None:
            if isinstance(payload, (list, tuple)):
                # the hook contract is one flat buffer; hooks are
                # test-only (faultline), so the join copy is fine there
                payload = b''.join(bytes(b) for b in payload)
            replaced = hook(self, line, payload)
            if replaced is not None:
                line, payload = replaced
        header = line.encode() + b'\n'
        if isinstance(payload, (list, tuple)):
            bufs = [b for b in payload if len(b)]
            total = sum(len(b) for b in bufs)
            if total <= 65536:
                # small frame: one syscall/segment, like the scalar
                # path below — the common O(batch)-rows sparse push
                self._sock.sendall(
                    header + b''.join(bytes(b) for b in bufs))
            else:
                self._sock.sendall(header)
                for buf in bufs:
                    self._sock.sendall(buf)
            return
        if payload is not None and len(payload) > 65536:
            # large tensor frames: send header + payload separately to
            # avoid a whole-payload concat copy (TCP_NODELAY is set, and
            # the payload write follows immediately, so no Nagle stall)
            self._sock.sendall(header)
            self._sock.sendall(payload)
        elif payload is not None and len(payload):
            # payload may be a zero-copy memoryview (_encode f32 path)
            self._sock.sendall(header + bytes(payload))
        else:
            self._sock.sendall(header)

    @staticmethod
    def _payload_nbytes(payload):
        if payload is None:
            return 0
        if isinstance(payload, (list, tuple)):
            return sum(len(b) for b in payload)
        return len(payload)

    def _rpc(self, line, payload=None):
        """Send one request (header line + optional raw payload), read the
        reply header line."""
        if not self._tel.enabled:
            self._send_frame(line, payload)
            return self._read_reply_line()
        with self._tel.span('rpc', cmd=line.split(' ', 1)[0],
                            bytes=self._payload_nbytes(payload)):
            self._send_frame(line, payload)
            return self._read_reply_line()

    def _pipelined(self, frames, on_reply, window=32):
        """Write request ``frames`` (``(token, line, payload)``) ahead of
        reading replies, keeping at most ``window`` replies outstanding;
        ``on_reply(token)`` must consume exactly one reply from the
        socket. The service handles one request per connection at a time
        and replies in request order, so pipelining is safe; the window
        bounds how far the writer runs ahead so the two directions'
        socket buffers can never both fill (the classic pipelining
        deadlock)."""
        if self._tel.enabled:
            frames = list(frames)
            span = self._tel.span(
                'rpc_batch',
                cmd=frames[0][1].split(' ', 1)[0] if frames else '',
                frames=len(frames),
                bytes=sum(self._payload_nbytes(p)
                          for _, _, p in frames))
        else:
            span = _telemetry._NULL_SPAN
        with span:
            outstanding = []
            for token, line, payload in frames:
                self._send_frame(line, payload)
                outstanding.append(token)
                if len(outstanding) >= window:
                    on_reply(outstanding.pop(0))
            while outstanding:
                on_reply(outstanding.pop(0))

    def _read_exact(self, nbytes):
        """Read exactly ``nbytes`` of reply payload (after a VAL header)."""
        parts = []
        have = len(self._buf)
        if have:
            take = min(have, nbytes)
            parts.append(self._buf[:take])
            self._buf = self._buf[take:]
            nbytes -= take
        while nbytes:
            chunk = self._sock.recv(min(nbytes, 1 << 20))
            if not chunk:
                raise OSError('coord_service closed connection')
            if len(chunk) > nbytes:  # pragma: no cover - server never
                self._buf += chunk[nbytes:]  # pipelines replies
                chunk = chunk[:nbytes]
            parts.append(chunk)
            nbytes -= len(chunk)
        return b''.join(parts)

    # -- primitives --------------------------------------------------------
    def ping(self):
        resp = self._rpc('PING')
        if resp != 'PONG':
            # whatever is on this port, it is not a coord service
            raise OSError('unexpected PING reply %r' % resp[:64])

    def fence(self, key, gen):
        """Bind this connection as a generation-``gen`` writer of fence
        counter ``key``: once that counter advances past ``gen`` (this
        process was declared dead), every write on the connection is
        rejected with :class:`FencedWriteError`. Raises immediately if
        the generation is already superseded."""
        resp = _check_fenced(self._rpc('FENCE %s %d' % (key, gen)),
                             'fence(%s, %d)' % (key, gen))
        if resp != 'OK':
            raise OSError('FENCE %s failed: %s' % (key, resp))

    def set(self, key, value):
        resp = _check_fenced(self._rpc('SET %s %s' % (key, value)),
                             'set(%s)' % key)
        assert resp == 'OK'

    def get(self, key):
        resp = self._rpc('GET %s' % key)
        return None if resp == 'NONE' else resp[4:]

    def delete(self, key):
        _check_fenced(self._rpc('DEL %s' % key), 'delete(%s)' % key)

    def incr(self, key, delta=1):
        resp = _check_fenced(self._rpc('INCR %s %d' % (key, delta)),
                             'incr(%s)' % key)
        return int(resp[4:])

    def _timed_rpc(self, line, timeout_s):
        """RPC under a wait-specific socket timeout, RESTORING the
        client's op timeout after — a gate's short slice must not
        clobber the generous data-plane timeout for the next multi-MB
        pull on the same socket."""
        self._sock.settimeout(timeout_s + 5.0)
        try:
            return self._rpc(line)
        finally:
            self._sock.settimeout(self._op_timeout)

    def wait_ge(self, key, n, timeout_s=60.0):
        resp = self._timed_rpc('WAITGE %s %d %d'
                               % (key, n, int(timeout_s * 1000)),
                               timeout_s)
        if resp == 'TIMEOUT':
            raise TimeoutError('wait_ge(%s, %d)' % (key, n))
        return int(resp[4:])

    def min_wait(self, prefix, n, k, timeout_s=60.0):
        resp = self._timed_rpc('MINWAIT %s %d %d %d'
                               % (prefix, n, k, int(timeout_s * 1000)),
                               timeout_s)
        if resp == 'TIMEOUT':
            raise TimeoutError('min_wait(%s, %d)' % (prefix, n))
        return int(resp[4:])

    def barrier(self, name, parties, timeout_s=60.0):
        resp = self._timed_rpc('BARRIER %s %d %d'
                               % (name, parties, int(timeout_s * 1000)),
                               timeout_s)
        if resp == 'TIMEOUT':
            raise TimeoutError('barrier(%s, %d)' % (name, parties))

    def shutdown(self):
        try:
            self._rpc('SHUTDOWN')
        except OSError:
            pass

    # -- tensor data plane (PS accumulator equivalent) ---------------------
    @staticmethod
    def _chunk_elems(wire):
        """Elements per frame chunk (AUTODIST_PS_CHUNK_BYTES of wire
        bytes); 0 disables chunking."""
        return _chunk_elems(wire)

    def _ranges(self, n_elems, wire):
        """Chunk ranges [(off, count)] covering ``n_elems``; a single
        (0, n) range means 'send unranged' (whole-tensor frame)."""
        return _chunk_ranges(n_elems, wire)

    def _set_frames(self, key, value, wire):
        """The BSET frame sequence for one tensor (chunked like vset)."""
        # _as_f32_flat skips the copy the old
        # ascontiguousarray(asarray(...)) pair paid even on
        # already-conforming input — the common session hot path
        flat = _as_f32_flat(value)
        ranges = self._ranges(flat.size, wire)
        for off, count in ranges:
            payload = _encode(flat[off:off + count], wire)
            suffix = '' if len(ranges) == 1 else \
                ' %d %d' % (off, flat.size)
            yield (key, 'BSET %s %d %s%s'
                   % (key, len(payload), wire, suffix), payload)

    def vset(self, key, value, wire=None):
        """Store a tensor (authoritative PS copy). Stored f32; wire dtype
        per ``AUTODIST_PS_WIRE_DTYPE``; frames above the chunk limit move
        as ranged chunks (elementwise, so chunked application is exact)."""
        self.vmset([(key, value)], wire=wire)

    def vmset(self, items, wire=None):
        """Pipelined multi-tensor :meth:`vset`: every (key, value) in
        ``items`` is stored with vset's exact chunking, but all request
        frames are written ahead of draining the replies — one wire
        round trip for the whole batch instead of one per chunk.

        Stores are AUTHORITATIVE state, so an ``i8`` wire setting
        rides f32 here (:func:`_pull_wire`): quantizing at-rest values
        would corrupt them permanently, with no error-feedback residual
        to absorb it."""
        wire = _pull_wire(wire)
        frames = [f for key, value in items
                  for f in self._set_frames(key, value, wire)]
        errs = []

        def reply(key):
            resp = self._read_reply_line()
            if resp != 'OK':
                errs.append('BSET %s failed: %s' % (key, resp))

        self._pipelined(frames, reply)
        if errs:
            _raise_batch(errs)

    def vget(self, key, shape=None, dtype=np.float32, wire=None):
        """Fetch a tensor as float32 host array, or None if absent.
        With a known ``shape``, oversized tensors are pulled as ranged
        chunks. Single-key form of :meth:`vmget` (one torn-read
        implementation serves both)."""
        return self.vmget([(key, shape)], dtype=dtype, wire=wire)[0]

    def vmget(self, specs, dtype=np.float32, wire=None):
        """Pipelined multi-tensor fetch: ``specs`` is ``[(key, shape)]``;
        returns one float32 array (or None if absent) per spec. ALL
        chunk requests for every pending key are written ahead of
        draining the replies, so a pull of K keys x C chunks pays one
        wire round trip instead of K*C.

        Torn-read safe (ADVICE r4): every BGET opts into the server's
        version field ("v" flag → ``version*2 + write_in_progress``).
        An odd value means a chunked write is mid-flight; a value that
        moves between one key's chunks means a push landed between
        them. Either way that key's pull retries (only torn keys
        re-request). Old servers without the field degrade to the
        previous (unchecked) behavior.

        Retry policy: while a key's version ADVANCES between attempts
        the writer is alive and making progress (a multi-GB chunked
        push legitimately holds the flag for seconds) — keep waiting,
        up to a configurable cap (AUTODIST_PS_TORN_RETRIES /
        AUTODIST_PS_TORN_BACKOFF_S).  The version only moves when a
        whole chunk frame lands, and one frame can take
        AUTODIST_PS_CHUNK_BYTES of wire time, so "stalled" is judged
        on a wall-clock window (``stall_timeout_s``), not an attempt
        count: a version that stays odd AND unchanged that long is
        the dead-mid-push signature.

        Exhausting the cap is only an ERROR when parity is odd (a
        write is genuinely mid-chunk: returning would hand back a
        half-applied tensor). An even version that merely keeps
        MOVING between one key's chunks means whole pushes keep
        landing — element-level staleness, the same benign mix any
        reader of a concurrently-updated accumulator sees — so the
        final assembly is returned with a warning instead of killing
        a healthy worker under frequent pushes. Caveat: each chunk of
        the assembly comes from a COMPLETE push, but different chunks
        may come from consecutive pushes — fine for commutative BADD
        accumulation and for fetch-side staleness, but a reader that
        needs one specific BSET snapshot must quiesce writers (the
        staleness gate) rather than rely on this path.

        Pulls are the READ direction: an ``i8`` wire setting rides f32
        here (:func:`_pull_wire`) — only pushes quantize, under the
        session's error-feedback residual."""
        wire = _pull_wire(wire)
        specs = list(specs)
        n_elems = [int(np.prod(shp)) if shp is not None else None
                   for _, shp in specs]
        ranges = [self._ranges(n, wire) if n else [(0, None)]
                  for n in n_elems]
        results = [None] * len(specs)
        max_attempts = max(1, ENV.AUTODIST_PS_TORN_RETRIES.val)
        backoff = ENV.AUTODIST_PS_TORN_BACKOFF_S.val
        stall_s = self.stall_timeout_s
        last_ver = {}         # idx -> last version seen while torn
        last_progress = {}    # idx -> local time the version last moved
        pending = list(range(len(specs)))
        for attempt in range(max_attempts):
            final = attempt == max_attempts - 1
            frames = []
            for idx in pending:
                key = specs[idx][0]
                for off, count in ranges[idx]:
                    suffix = '' if len(ranges[idx]) == 1 and off == 0 \
                        and (count is None or count == n_elems[idx]) \
                        else ' %d %d' % (off, count)
                    frames.append((idx, 'BGET %s %s%s v'
                                   % (key, wire, suffix), None))
            parts = {idx: [] for idx in pending}
            first_ver = {}
            cur_ver = {}
            odd = set()
            torn = set()
            absent = set()
            errors = []

            def reply(idx):
                resp = self._read_reply_line()
                if resp == 'NONE':
                    absent.add(idx)
                    return
                if not resp.startswith('VAL'):
                    # keep draining the remaining replies (the stream
                    # stays framed); raise once the batch is consumed
                    errors.append('BGET %s failed: %s'
                                  % (specs[idx][0], resp))
                    return
                fields = resp.split()
                parts[idx].append(
                    _decode(self._read_exact(int(fields[1])), wire))
                ver = int(fields[2]) if len(fields) > 2 else None
                if ver is None:
                    return
                cur_ver[idx] = ver
                if ver & 1:  # write in progress
                    odd.add(idx)
                    torn.add(idx)
                elif idx not in first_ver:
                    first_ver[idx] = ver
                elif ver != first_ver[idx]:
                    torn.add(idx)

            self._pipelined(frames, reply)
            if errors:
                raise OSError('; '.join(errors))
            now = time.monotonic()
            retry = []
            for idx in pending:
                key, shape = specs[idx]
                if idx in absent:
                    results[idx] = None
                    continue
                if idx not in torn or (final and idx not in odd):
                    if idx in torn:
                        logging.warning(
                            'BGET %s: version kept advancing for %d '
                            'attempts (concurrent single-frame pushes);'
                            ' returning the last assembly — '
                            'element-level staleness only, parity was '
                            'even throughout the final pass',
                            key, max_attempts)
                    arr = parts[idx][0] if len(parts[idx]) == 1 else \
                        np.concatenate(parts[idx])
                    if shape is not None:
                        arr = arr.reshape(shape)
                    results[idx] = arr.astype(dtype, copy=False)
                    continue
                ver = cur_ver.get(idx)
                if ver != last_ver.get(idx):
                    last_ver[idx] = ver
                    last_progress[idx] = now
                elif idx in odd and \
                        now - last_progress.get(idx, now) > stall_s:
                    raise OSError(
                        'BGET %s: a chunked write is stuck mid-flight '
                        '(version parity odd and not advancing for '
                        '%.0fs) — a peer likely died mid-push'
                        % (key, stall_s))
                retry.append(idx)
            pending = retry
            if not pending:
                return results
            # linear backoff from the configured base, capped at the
            # larger of 0.2s and one base interval (a base above 0.2
            # must not be silently clamped back to the old cap)
            time.sleep(min(max(0.2, backoff), backoff * (attempt + 1)))
        raise OSError(
            'BGET %s: a chunked write was still mid-flight (version '
            'parity odd) after %d attempts — raising rather than '
            'returning a half-applied tensor'
            % (specs[pending[0]][0], max_attempts))

    def vadd(self, key, delta, wire=None):
        """Atomically add a delta elementwise (apply-per-push, the
        reference's staleness-mode ConditionalAccumulator semantics,
        ps_synchronizer.py:556-633 with num_required=1). Returns the
        tensor's total push count. Addition commutes, so chunked pushes
        from concurrent workers interleave exactly."""
        return self.vmadd([(key, delta)], wire=wire)[key]

    def vmadd(self, items, wire=None):
        """Pipelined multi-tensor :meth:`vadd`: every (key, delta) in
        ``items`` is accumulated with vadd's exact chunking, all request
        frames written ahead of draining the replies. Returns
        ``{key: push count}``."""
        wire = _wire_dtype(wire)
        frames = []
        for key, delta in items:
            flat = _as_f32_flat(delta)
            ranges = self._ranges(flat.size, wire)
            for off, count in ranges:
                payload = _encode(flat[off:off + count], wire)
                suffix = '' if len(ranges) == 1 else \
                    ' %d %d' % (off, flat.size)
                frames.append((key, 'BADD %s %d %s%s'
                               % (key, len(payload), wire, suffix),
                               payload))
        pushes = {}
        errs = []

        def reply(key):
            resp = self._read_reply_line()
            if not resp.startswith('VAL'):
                errs.append('BADD %s failed: %s' % (key, resp))
                return
            pushes[key] = int(resp[4:])

        self._pipelined(frames, reply)
        if errs:
            _raise_batch(errs)
        return pushes

    # -- row-sparse tensor plane (embedding variables) ---------------------
    @staticmethod
    def _wire_itemsize(wire):
        return _wire_itemsize(wire)

    def _row_chunks(self, nrows, bytes_per_row):
        """Row-chunk ranges [(off, count)] so no frame exceeds
        ``AUTODIST_PS_CHUNK_BYTES`` of wire bytes (indices + row data
        for pushes, row data for row fetches)."""
        return _row_chunk_ranges(nrows, bytes_per_row)

    def _sadd_frames(self, key, indices, rows, wire):
        """The BSADD frame sequence for one row-sparse push (chunked
        over ROWS like vset chunks over elements).

        f32/bf16 declare the per-row wire bytes; i8 blockscale blobs
        are not per-row divisible (the scales header spans the chunk),
        so those frames declare the TOTAL blob length instead and the
        service derives cols from decoded elements / nrows — the
        protocol note in coord_service.cc's header."""
        idx = np.asarray(indices, dtype=np.int32).reshape(-1)
        if not idx.flags.c_contiguous:
            idx = np.ascontiguousarray(idx)
        rows = np.asarray(rows, dtype=np.float32)
        if rows.ndim != 2 or rows.shape[0] != idx.size:
            raise ValueError(
                'vsadd(%s): rows must be [len(indices), cols]; got '
                'indices %d, rows %r' % (key, idx.size, rows.shape))
        row_wire = rows.shape[1] * self._wire_itemsize(wire)
        ranges = self._row_chunks(idx.size, 4 + row_wire)
        for off, count in ranges:
            suffix = '' if len(ranges) == 1 else \
                ' %d %d' % (off, idx.size)
            # scatter-gather payload: int32 indices then the row data,
            # no concat copy of the rows (the f32 path is a memoryview)
            blob = _encode(rows[off:off + count], wire)
            declared = len(blob) if wire == 'i8' else row_wire
            payload = [memoryview(idx[off:off + count]).cast('B'), blob]
            yield (key, 'BSADD %s %d %d %s%s'
                   % (key, count, declared, wire, suffix), payload)

    def vsadd(self, key, indices, rows, wire=None):
        """Row-sparse scatter-add: ``rows[r]`` is added into row
        ``indices[r]`` of the stored ``[table_rows, cols]`` tensor.
        Addition commutes, so sparse and dense pushes from concurrent
        workers interleave exactly; a delta whose untouched rows are
        exactly zero is applied LOSSLESSLY by shipping only its touched
        rows. The tensor must already exist (a row set cannot size it).
        Returns the tensor's total push count."""
        return self.vmsadd([(key, indices, rows)], wire=wire)[key]

    def vmsadd(self, items, wire=None):
        """Pipelined multi-tensor :meth:`vsadd`: ``items`` is
        ``[(key, indices, rows)]``; all request frames are written
        ahead of draining replies, one wire round trip for the batch.
        Returns ``{key: push count}``."""
        wire = _wire_dtype(wire)
        frames = [f for key, idx, rows in items
                  for f in self._sadd_frames(key, idx, rows, wire)]
        pushes = {}
        errs = []

        def reply(key):
            resp = self._read_reply_line()
            if not resp.startswith('VAL'):
                errs.append('BSADD %s failed: %s' % (key, resp))
                return
            pushes[key] = int(resp[4:])

        self._pipelined(frames, reply)
        if errs:
            _raise_batch(errs)
        return pushes

    def vgetrows(self, key, indices, ncols, wire=None):
        """Fetch just the listed rows of a stored ``[rows, ncols]``
        tensor as a float32 ``[len(indices), ncols]`` array, or None if
        the tensor is absent. Single-key form of :meth:`vmgetrows`."""
        return self.vmgetrows([(key, indices, ncols)], wire=wire)[0]

    def vmgetrows(self, specs, dtype=np.float32, wire=None):
        """Pipelined multi-tensor row fetch: ``specs`` is ``[(key,
        indices, ncols)]``; returns one ``[len(indices), ncols]`` array
        (or None if absent) per spec.

        Torn-read contract (the BGET "v" semantics, scaled down to row
        reads): every request opts into the version field; a key whose
        parity comes back odd — or whose version moves between its own
        row chunks — retries under the same AUTODIST_PS_TORN_RETRIES /
        _BACKOFF_S budget as :meth:`vmget`, with the same stall window:
        odd parity that stops advancing for ``stall_timeout_s`` is the
        died-mid-push signature and raises. A version that keeps
        MOVING but stays even means whole pushes keep landing — the
        final assembly is returned (benign element-level staleness,
        same caveat as vmget's). Reads ride f32 under an ``i8``
        setting, like :meth:`vmget`."""
        wire = _pull_wire(wire)
        specs = [(key, np.ascontiguousarray(
                     np.asarray(idx, dtype=np.int32).reshape(-1)),
                  int(ncols)) for key, idx, ncols in specs]
        row_wire = [ncols * self._wire_itemsize(wire)
                    for _, _, ncols in specs]
        results = [None] * len(specs)
        max_attempts = max(1, ENV.AUTODIST_PS_TORN_RETRIES.val)
        backoff = ENV.AUTODIST_PS_TORN_BACKOFF_S.val
        stall_s = self.stall_timeout_s
        last_ver = {}
        last_progress = {}
        pending = list(range(len(specs)))
        for attempt in range(max_attempts):
            final = attempt == max_attempts - 1
            frames = []
            for i in pending:
                key, idx, ncols = specs[i]
                for off, count in self._row_chunks(
                        idx.size, max(1, row_wire[i])):
                    frames.append(
                        (i, 'BGETROWS %s %d %d %s v'
                         % (key, count, ncols, wire),
                         memoryview(idx[off:off + count]).cast('B')))
            parts = {i: [] for i in pending}
            first_ver = {}
            cur_ver = {}
            odd = set()
            torn = set()
            absent = set()
            errors = []

            def reply(i):
                resp = self._read_reply_line()
                if resp == 'NONE':
                    absent.add(i)
                    return
                if not resp.startswith('VAL'):
                    errors.append('BGETROWS %s failed: %s'
                                  % (specs[i][0], resp))
                    return
                fields = resp.split()
                parts[i].append(
                    _decode(self._read_exact(int(fields[1])), wire))
                ver = int(fields[2]) if len(fields) > 2 else None
                if ver is None:
                    return
                cur_ver[i] = ver
                if ver & 1:
                    odd.add(i)
                    torn.add(i)
                elif i not in first_ver:
                    first_ver[i] = ver
                elif ver != first_ver[i]:
                    torn.add(i)

            self._pipelined(frames, reply)
            if errors:
                raise OSError('; '.join(errors))
            now = time.monotonic()
            retry = []
            for i in pending:
                key, idx, ncols = specs[i]
                if i in absent:
                    results[i] = None
                    continue
                if i not in torn or (final and i not in odd):
                    if i in torn:
                        logging.warning(
                            'BGETROWS %s: version kept advancing for '
                            '%d attempts (concurrent pushes); '
                            'returning the last assembly', key,
                            max_attempts)
                    arr = np.concatenate(parts[i]) if len(parts[i]) > 1 \
                        else parts[i][0]
                    results[i] = arr.reshape(idx.size, ncols).astype(
                        dtype, copy=False)
                    continue
                ver = cur_ver.get(i)
                if ver != last_ver.get(i):
                    last_ver[i] = ver
                    last_progress[i] = now
                elif i in odd and \
                        now - last_progress.get(i, now) > stall_s:
                    raise OSError(
                        'BGETROWS %s: a chunked write is stuck '
                        'mid-flight (version parity odd and not '
                        'advancing for %.0fs) — a peer likely died '
                        'mid-push' % (key, stall_s))
                retry.append(i)
            pending = retry
            if not pending:
                return results
            time.sleep(min(max(0.2, backoff), backoff * (attempt + 1)))
        raise OSError(
            'BGETROWS %s: a chunked write was still mid-flight '
            '(version parity odd) after %d attempts'
            % (specs[pending[0]][0], max_attempts))

    def vstep(self, key, grad, rule, params, wire=None):
        """Push a raw GRADIENT; the service applies the named update
        rule with PS-resident slots shared by all workers (the
        reference re-creates the user's optimizer over PS-resident
        variables, partitioner.py:570-573 / ps_synchronizer.py:175-176).

        ``rule`` is one of ``sgd`` (params [lr, momentum]), ``adam``
        ([lr, b1, b2, eps]), ``adagrad`` ([lr, eps, init_acc]). Returns
        the shared step index used (the adam bias-correction t). Chunked
        pushes share one t: the offset-0 chunk draws it, later chunks
        pass it explicitly — every rule is elementwise in (w, slots), so
        ranged application is exact."""
        wire = _wire_dtype(wire)
        flat = _as_f32_flat(grad)
        p = (list(params) + [0.0] * 4)[:4]
        ranges = self._ranges(flat.size, wire)
        step = 0
        for off, count in ranges:
            payload = _encode(flat[off:off + count], wire)
            suffix = '' if len(ranges) == 1 else \
                ' %d %d' % (off, flat.size)
            resp = _check_fenced(self._rpc(
                'BSTEP %s %d %s %s %d %.17g %.17g %.17g %.17g%s'
                % (key, len(payload), wire, rule, step,
                   p[0], p[1], p[2], p[3], suffix), payload),
                'vstep(%s)' % key)
            if not resp.startswith('VAL'):
                raise OSError('BSTEP %s failed: %s' % (key, resp))
            step = int(resp[4:])
        return step

    def vstat(self, key):
        """Tensor introspection: ``{'pushes', 'steps', 'elems',
        'slot1', 'slot2'}`` or None if absent — verifies PS-resident
        optimizer state (e.g. shared adam: steps == total pushes)."""
        resp = self._rpc('BSTAT %s' % key)
        if resp == 'NONE':
            return None
        if not resp.startswith('VAL'):
            raise OSError('BSTAT %s failed: %s' % (key, resp))
        p, s, n, s1, s2 = resp[4:].split()
        return {'pushes': int(p), 'steps': int(s), 'elems': int(n),
                'slot1': bool(int(s1)), 'slot2': bool(int(s2))}

    def delete_namespace(self, prefix):
        """Purge every key/counter/tensor/barrier under ``prefix`` —
        run-end cleanup so a long-lived endpoint daemon does not
        accumulate dead runs' tensors. Returns the entry count purged."""
        resp = _check_fenced(self._rpc('DELNS %s' % prefix),
                             'delete_namespace(%s)' % prefix)
        if not resp.startswith('VAL'):
            raise OSError('DELNS %s failed: %s' % (prefix, resp))
        return int(resp[4:])

    def wait_key(self, key, timeout_s=60.0, poll_s=0.05):
        """Poll-wait for a KV key to appear; returns its value."""
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            v = self.get(key)
            if v is not None:
                return v
            time.sleep(poll_s)
        raise TimeoutError('wait_key(%s)' % key)

    def close(self):
        self._sock.close()

    # -- composite: bounded staleness -------------------------------------
    # A step publish landing at/above CLEAN_CLOSE_STEP is a RELEASE, not
    # training progress: Session.close and the exclude-policy claim
    # winner publish it to lift any reachable gate bound on a departed
    # worker's counter (faultline's kill_worker matcher must never treat
    # it as the worker reaching its death step).
    def publish_step(self, worker, step, prefix='step/'):
        """Publish this worker's completed-step counter."""
        key = prefix + worker
        cur = self.incr(key, 0)
        if step > cur:
            self.incr(key, step - cur)

    def staleness_gate(self, step, staleness, num_workers,
                       timeout_s=600.0, prefix='step/',
                       failure_check=None, slice_s=2.0):
        """Block until every worker is within ``staleness`` steps.

        With ``failure_check`` (a callable that raises when a peer is
        known dead), the server-side wait is chunked into ``slice_s``
        slices and the check runs between slices — a crashed peer
        surfaces as its error instead of a full-window TimeoutError.
        A TRUTHY return from ``failure_check`` means a recovery is in
        flight (peer-failure policy ``restart``): the deadline re-arms
        so supervision time is not counted against the gate window —
        the caller bounds that wait itself (failed markers raise;
        ``AUTODIST_RESTART_WAIT_S`` caps a silent supervisor).

        ``num_workers`` may be a callable, re-evaluated every slice:
        elastic membership (peer-failure policy ``exclude``) shrinks
        the party count while a survivor is already blocked here, and
        the gate must re-bound against the NEW membership instead of
        waiting forever for a step key the excluder deleted.
        """
        if step <= staleness:
            return
        k = num_workers() if callable(num_workers) else num_workers
        if failure_check is None:
            self.min_wait(prefix, step - staleness, k, timeout_s)
            return
        deadline = time.time() + timeout_s
        while True:
            if failure_check():
                deadline = time.time() + timeout_s
            k = num_workers() if callable(num_workers) else num_workers
            remaining = deadline - time.time()
            if remaining <= 0:
                raise TimeoutError('staleness_gate(%s, %d)'
                                   % (prefix, step))
            try:
                self.min_wait(prefix, step - staleness, k,
                              min(slice_s, remaining))
                return
            except TimeoutError:
                continue

    # -- composite: heartbeat / failure detection --------------------------
    # Liveness is a monotonic BEAT COUNTER, not a timestamp: each consumer
    # judges "no advance for > timeout" against its OWN clock, so
    # wall-clock skew between hosts can neither kill healthy peers nor
    # mask dead ones.
    def heartbeat(self, worker):
        self.incr('hb/%s' % worker, 1)

    def beat_count(self, worker):
        """Current beat counter for ``worker`` (0 = never beat)."""
        return self.incr('hb/%s' % worker, 0)

    def dead_workers(self, workers, timeout_s, observations,
                     now=None):
        """Workers whose beat counter has not advanced for ``timeout_s``
        on THIS process's clock. ``observations`` is caller-owned state
        {worker: (last_count, local_time_first_seen)} updated in place."""
        now = time.time() if now is None else now
        dead = []
        for w in workers:
            cnt = self.beat_count(w)
            last = observations.get(w)
            if last is None or cnt != last[0]:
                observations[w] = (cnt, now)
                continue
            if now - last[1] > timeout_s:
                dead.append(w)
        return dead


class TransferJob:
    """Future-like handle for one :class:`TransferPool` job."""

    def __init__(self, fn, endpoint):
        self.fn = fn
        self.endpoint = endpoint
        self._done = threading.Event()
        self._value = None
        self._exc = None

    def set_result(self, value):
        self._value = value
        self._done.set()

    def set_error(self, exc):
        self._exc = exc
        self._done.set()

    def done(self):
        return self._done.is_set()

    def result(self, timeout=None):
        """Join the job; re-raises the job's exception if it failed."""
        if not self._done.wait(timeout):
            raise TimeoutError(
                'PS transfer on endpoint %d did not finish within %ss'
                % (self.endpoint, timeout))
        if self._exc is not None:
            raise self._exc
        return self._value


class TransferPool:
    """Persistent per-endpoint transfer workers for the loose-mode PS
    data plane.

    One daemon thread per endpoint, each owning its OWN connection
    (CoordClient sockets are not thread-safe, and a dedicated
    connection keeps the session's control-plane client free for
    gates/heartbeats while transfers run in the background). Jobs
    submitted to one endpoint run strictly in FIFO order — which is
    what makes a pull queued behind the same variable's push
    read-your-writes safe for free — while distinct endpoints run
    concurrently, like the reference's concurrent grpc channels.
    Replaces the per-call ``threading.Thread`` spawn the session used
    to pay on every pull/push.

    Workers connect lazily on their first job and reconnect on the
    next job after a connection-level failure (the failed job carries
    the error to its joiner).
    """

    def __init__(self, connects):
        """``connects``: one zero-arg client factory per endpoint."""
        self._connects = list(connects)
        self._queues = [queue.Queue() for _ in self._connects]
        self._threads = [None] * len(self._connects)
        self._closed = False

    def __len__(self):
        return len(self._connects)

    def _worker(self, ep):
        q = self._queues[ep]
        client = None
        while True:
            job = q.get()
            if job is None:
                break
            try:
                if client is None:
                    client = self._connects[ep]()
                job.set_result(job.fn(client))
            except BaseException as e:  # noqa: BLE001 - carried to joiner
                if isinstance(e, OSError) and client is not None:
                    # connection-level failure: drop the socket so the
                    # next job reconnects instead of reusing a dead or
                    # unframed stream
                    try:
                        client.close()
                    except OSError:
                        pass
                    client = None
                job.set_error(e)
        if client is not None:
            try:
                client.close()
            except OSError:
                pass

    def submit(self, ep, fn):
        """Queue ``fn(client)`` on endpoint ``ep``'s worker; returns a
        :class:`TransferJob` to join."""
        if self._closed:
            # the workers have drained their sentinels and exited; a
            # queued job would never run and its joiner would hang
            raise OSError('TransferPool is closed')
        if self._threads[ep] is None:
            t = threading.Thread(target=self._worker, args=(ep,),
                                 daemon=True,
                                 name='autodist-ps-xfer-%d' % ep)
            self._threads[ep] = t
            t.start()
        job = TransferJob(fn, ep)
        self._queues[ep].put(job)
        return job

    def run(self, jobs):
        """Submit ``[(endpoint, fn)]`` and join them all.

        Every failure is logged WITH its endpoint before anything is
        raised; a single failure re-raises as itself (type-preserving
        for callers matching OSError), several raise one aggregate
        RuntimeError naming every endpoint — no endpoint's error is
        silently dropped. Returns the per-job results in order."""
        handles = [self.submit(ep, fn) for ep, fn in jobs]
        results = []
        errs = []
        for h in handles:
            try:
                results.append(h.result())
            # BaseException too (workers capture it): SystemExit from a
            # job must not unwind this loop before every handle is
            # joined and logged — that would drop the others' errors
            except BaseException as e:  # noqa: BLE001 - aggregated below
                logging.error('PS transfer failed on endpoint %d: %s: %s',
                              h.endpoint, type(e).__name__, e)
                errs.append((h.endpoint, e))
        # a non-Exception (KeyboardInterrupt/SystemExit) outranks any
        # aggregate: re-raise it as itself once everything is joined
        for _, e in errs:
            if not isinstance(e, Exception):
                raise e
        if len(errs) == 1:
            raise errs[0][1]
        if errs:
            raise RuntimeError(
                'PS transfer failed on %d endpoints: %s'
                % (len(errs),
                   '; '.join('endpoint %d: %s: %s'
                             % (ep, type(e).__name__, e)
                             for ep, e in errs)))
        return results

    def close(self, timeout=15.0):
        """Stop every worker (drains each queue first) and close their
        connections. Subsequent :meth:`submit` raises OSError."""
        self._closed = True
        for q, t in zip(self._queues, self._threads):
            if t is not None:
                q.put(None)
        for t in self._threads:
            if t is not None:
                t.join(timeout=timeout)
