"""Client + process manager for the native coordination service.

The service (native/coord_service.cc) provides the between-program
control plane: barriers, counters, bounded-staleness windows, heartbeats.
See the source header for the protocol. The chief starts one instance
(:func:`ensure_service`); every process connects with
:class:`CoordClient`.

Bounded staleness (reference semantics, ps_synchronizer.py:387-458 and
the c9 timing contract): each worker publishes its step counter under
``step/<worker>``; before running step ``s`` a worker calls
:meth:`staleness_gate`, which blocks until ``min(all steps) >= s -
staleness``. A fast worker can thus run at most ``staleness`` steps ahead
— the queue-capacity semantics without TF FIFO queues.
"""
import base64
import socket
import subprocess
import time

import numpy as np

from autodist_tpu.const import DEFAULT_COORD_PORT, ENV
from autodist_tpu.utils import logging


def ensure_service(port=DEFAULT_COORD_PORT, wait_s=10.0, bind='127.0.0.1'):
    """Start the native service on this host if nothing is listening.

    Binds loopback by default; multi-host launchers pass ``bind='0.0.0.0'``
    (or the coordinator interface) explicitly.
    """
    try:
        CoordClient(('127.0.0.1', port), timeout=0.5).ping()
        return None  # already running
    except OSError:
        pass
    from autodist_tpu.native_build import build
    binary = build('coord_service.cc')
    proc = subprocess.Popen([binary, str(port), bind],
                            stdout=subprocess.DEVNULL,
                            stderr=subprocess.DEVNULL)
    deadline = time.time() + wait_s
    while time.time() < deadline:
        try:
            CoordClient(('127.0.0.1', port), timeout=0.5).ping()
            logging.info('coord_service started on :%d (pid %d)',
                         port, proc.pid)
            return proc
        except OSError:
            time.sleep(0.05)
    raise RuntimeError('coord_service failed to start on :%d' % port)


def connect_with_retry(address=None, deadline_s=30.0):
    """Connect to the coord service, retrying until it comes up (workers
    may start before the chief's ensure_service)."""
    deadline = time.time() + deadline_s
    last = None
    while time.time() < deadline:
        try:
            c = CoordClient(address, timeout=5.0)
            c.ping()
            return c
        except OSError as e:
            last = e
            time.sleep(0.1)
    raise RuntimeError('coord_service unreachable at %s: %s'
                       % (address, last))


class CoordClient:
    """Blocking line-protocol client."""

    def __init__(self, address=None, timeout=None):
        if address is None:
            raw = ENV.AUTODIST_COORD_SERVICE_ADDR.val
            if raw:
                host, port = raw.rsplit(':', 1)
                address = (host, int(port))
            else:
                address = ('127.0.0.1', DEFAULT_COORD_PORT)
        # the RESOLVED address, so sibling connections (e.g. a session's
        # background heartbeat thread) dial exactly what worked here —
        # the env address may differ (all-local runs rewrite to loopback)
        self.address = address
        self._sock = socket.create_connection(address, timeout=timeout)
        self._buf = b''

    def _rpc(self, line):
        self._sock.sendall(line.encode() + b'\n')
        while b'\n' not in self._buf:
            chunk = self._sock.recv(4096)
            if not chunk:
                raise OSError('coord_service closed connection')
            self._buf += chunk
        resp, self._buf = self._buf.split(b'\n', 1)
        return resp.decode()

    # -- primitives --------------------------------------------------------
    def ping(self):
        resp = self._rpc('PING')
        if resp != 'PONG':
            # whatever is on this port, it is not a coord service
            raise OSError('unexpected PING reply %r' % resp[:64])

    def set(self, key, value):
        assert self._rpc('SET %s %s' % (key, value)) == 'OK'

    def get(self, key):
        resp = self._rpc('GET %s' % key)
        return None if resp == 'NONE' else resp[4:]

    def delete(self, key):
        self._rpc('DEL %s' % key)

    def incr(self, key, delta=1):
        resp = self._rpc('INCR %s %d' % (key, delta))
        return int(resp[4:])

    def wait_ge(self, key, n, timeout_s=60.0):
        self._sock.settimeout(timeout_s + 5.0)
        resp = self._rpc('WAITGE %s %d %d' % (key, n,
                                              int(timeout_s * 1000)))
        if resp == 'TIMEOUT':
            raise TimeoutError('wait_ge(%s, %d)' % (key, n))
        return int(resp[4:])

    def min_wait(self, prefix, n, k, timeout_s=60.0):
        self._sock.settimeout(timeout_s + 5.0)
        resp = self._rpc('MINWAIT %s %d %d %d' %
                         (prefix, n, k, int(timeout_s * 1000)))
        if resp == 'TIMEOUT':
            raise TimeoutError('min_wait(%s, %d)' % (prefix, n))
        return int(resp[4:])

    def barrier(self, name, parties, timeout_s=60.0):
        self._sock.settimeout(timeout_s + 5.0)
        resp = self._rpc('BARRIER %s %d %d' %
                         (name, parties, int(timeout_s * 1000)))
        if resp == 'TIMEOUT':
            raise TimeoutError('barrier(%s, %d)' % (name, parties))

    def shutdown(self):
        try:
            self._rpc('SHUTDOWN')
        except OSError:
            pass

    # -- tensor data plane (PS accumulator equivalent) ---------------------
    def vset(self, key, value):
        """Store a float32 tensor (authoritative PS copy)."""
        arr = np.ascontiguousarray(np.asarray(value, dtype=np.float32))
        payload = base64.b64encode(arr.tobytes()).decode()
        resp = self._rpc('VSET %s %s' % (key, payload))
        if resp != 'OK':
            raise OSError('VSET %s failed: %s' % (key, resp))

    def vget(self, key, shape=None, dtype=np.float32):
        """Fetch a float32 tensor, or None if absent."""
        resp = self._rpc('VGET %s' % key)
        if resp == 'NONE':
            return None
        arr = np.frombuffer(base64.b64decode(resp[4:]), dtype=np.float32)
        if shape is not None:
            arr = arr.reshape(shape)
        return arr.astype(dtype, copy=False)

    def vadd(self, key, delta):
        """Atomically add a float32 delta elementwise (apply-per-push,
        the reference's staleness-mode ConditionalAccumulator semantics,
        ps_synchronizer.py:556-633 with num_required=1). Returns the
        tensor's total push count."""
        arr = np.ascontiguousarray(np.asarray(delta, dtype=np.float32))
        payload = base64.b64encode(arr.tobytes()).decode()
        resp = self._rpc('VADD %s %s' % (key, payload))
        if not resp.startswith('VAL'):
            raise OSError('VADD %s failed: %s' % (key, resp))
        return int(resp[4:])

    def wait_key(self, key, timeout_s=60.0, poll_s=0.05):
        """Poll-wait for a KV key to appear; returns its value."""
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            v = self.get(key)
            if v is not None:
                return v
            time.sleep(poll_s)
        raise TimeoutError('wait_key(%s)' % key)

    def close(self):
        self._sock.close()

    # -- composite: bounded staleness -------------------------------------
    def publish_step(self, worker, step, prefix='step/'):
        """Publish this worker's completed-step counter."""
        key = prefix + worker
        cur = self.incr(key, 0)
        if step > cur:
            self.incr(key, step - cur)

    def staleness_gate(self, step, staleness, num_workers,
                       timeout_s=600.0, prefix='step/',
                       failure_check=None, slice_s=2.0):
        """Block until every worker is within ``staleness`` steps.

        With ``failure_check`` (a callable that raises when a peer is
        known dead), the server-side wait is chunked into ``slice_s``
        slices and the check runs between slices — a crashed peer
        surfaces as its error instead of a full-window TimeoutError.
        """
        if step <= staleness:
            return
        if failure_check is None:
            self.min_wait(prefix, step - staleness, num_workers,
                          timeout_s)
            return
        deadline = time.time() + timeout_s
        while True:
            failure_check()
            remaining = deadline - time.time()
            if remaining <= 0:
                raise TimeoutError('staleness_gate(%s, %d)'
                                   % (prefix, step))
            try:
                self.min_wait(prefix, step - staleness, num_workers,
                              min(slice_s, remaining))
                return
            except TimeoutError:
                continue

    # -- composite: heartbeat / failure detection --------------------------
    # Liveness is a monotonic BEAT COUNTER, not a timestamp: each consumer
    # judges "no advance for > timeout" against its OWN clock, so
    # wall-clock skew between hosts can neither kill healthy peers nor
    # mask dead ones.
    def heartbeat(self, worker):
        self.incr('hb/%s' % worker, 1)

    def beat_count(self, worker):
        """Current beat counter for ``worker`` (0 = never beat)."""
        return self.incr('hb/%s' % worker, 0)

    def dead_workers(self, workers, timeout_s, observations,
                     now=None):
        """Workers whose beat counter has not advanced for ``timeout_s``
        on THIS process's clock. ``observations`` is caller-owned state
        {worker: (last_count, local_time_first_seen)} updated in place."""
        now = time.time() if now is None else now
        dead = []
        for w in workers:
            cnt = self.beat_count(w)
            last = observations.get(w)
            if last is None or cnt != last[0]:
                observations[w] = (cnt, now)
                continue
            if now - last[1] > timeout_s:
                dead.append(w)
        return dead
