"""Strategy-distribution epoch key schema — the wire vocabulary of the
stage -> ack-quorum -> boundary-arm -> swap handshake.

This module is the SINGLE place that spells coordinator key names for
the epoch-swap handshake (docs/design/epoch-swap.md).  The runtime
session (chief staging / peer ack / boundary apply), the chaos tests,
and the ``swap-conformance`` analyzer all build keys through these
helpers, and ``MODEL_SYMBOLS`` maps every shipped key template to the
abstract symbol the verified model (``analysis/epoch_swap_model.py``)
proves the ordering with — a tier-1 pin test asserts the mapping stays
total so spec and implementation cannot drift silently.

Key layout (all under the session namespace ``<ns>/``):

  swap/gen                monotone generation counter (INCR by the
                          chief at stage time; restarted peers discover
                          the live generation by reading it)
  swap/<g>/plan           staged plan payload (SET chief, GET peers,
                          DELNS on cancel and at run end)
  swap/<g>/ack/<w>        peer <w> validated the staged plan
  swap/<g>/nack/<w>       peer <w> rejected it (payload = reason);
                          any NACK cancels the stage
  swap/<g>/B              the armed commit boundary (SET chief once
                          the ack quorum is full; GET by every member
                          piggybacked on the staleness-gate poll)
  swap/<g>/ready          chief finished re-keying the authoritative
                          PS copies under the new plan; non-chief
                          members wait on it before their first
                          new-plan pull

Generation hygiene: staging generation ``g`` purges every ``swap/<g-1>/``
key (exactly one staged generation is ever visible), a cancelled stage
deletes its own ``swap/<g>/`` subtree, and the chief's run-end namespace
purge (session ``close()``) plus the init-time ``purge_all`` sweep
guarantee a restarted run never sees a stale staged plan.
"""
import base64
import json
import pickle

#: Shipped key templates -> abstract symbols of the verified model
#: (analysis/epoch_swap_model.py).  The swap-conformance analyzer pins
#: this mapping against the model source: every abstract symbol the
#: model transitions on must be claimed by exactly one shipped
#: template, so renaming either side breaks tier-1 instead of silently
#: diverging from the proof.
MODEL_SYMBOLS = {
    'swap/<g>/plan': 'swap/stage',
    'swap/<g>/ack/<w>': 'swap/acks',
    'swap/<g>/nack/<w>': 'swap/nacks',
    'swap/<g>/B': 'swap/B',
}

PREFIX = 'swap/'


def gen_key():
    """The generation counter key (relative to the session ns)."""
    return 'swap/gen'


def plan_key(gen):
    return 'swap/%d/plan' % gen


def ack_key(gen, worker):
    return 'swap/%d/ack/%d' % (gen, worker)


def nack_key(gen, worker):
    return 'swap/%d/nack/%d' % (gen, worker)


def boundary_key(gen):
    return 'swap/%d/B' % gen


def ready_key(gen):
    return 'swap/%d/ready' % gen


def gen_prefix(gen):
    """Prefix covering every key of one staged generation."""
    return 'swap/%d/' % gen


def compute_boundary(floors, staleness):
    """The commit boundary ``B = prefix_min(published) + staleness + 2``.

    ``floors`` are the published step/round counters of the LIVE
    members (excluded members' floors must already be dropped by the
    caller — quorum re-evaluation over live membership).  The model's
    safety argument: a member executing step ``s`` implies every
    member published ``>= s - staleness - 1``, so at arm time no
    member can have started step ``B``; every member's step-``B``
    start check therefore observes the armed marker.
    """
    if not floors:
        raise ValueError('compute_boundary: no live members')
    return min(floors) + staleness + 2


def encode_plan(gen, world, strategy):
    """Serialize a staged plan payload (JSON envelope, pickled
    strategy) for the ``swap/<g>/plan`` key."""
    blob = base64.b64encode(pickle.dumps(strategy)).decode('ascii')
    # compact separators: the coord KV value is the rest of one
    # protocol line, so the payload must stay newline-free
    return json.dumps({'gen': gen, 'world': world, 'strategy': blob},
                      separators=(',', ':'))


def decode_plan(payload):
    """Inverse of :func:`encode_plan`; returns ``(gen, world,
    strategy)``."""
    doc = json.loads(payload)
    strategy = pickle.loads(base64.b64decode(doc['strategy']))
    return doc['gen'], doc['world'], strategy


def stage_plan(client, ns, gen, world, strategy):
    """Chief: publish generation ``gen``'s plan, purging the previous
    generation's keys first (exactly one staged generation visible)."""
    if gen > 1:
        client.delete_namespace('%s/%s' % (ns, gen_prefix(gen - 1)))
    client.set('%s/%s' % (ns, plan_key(gen)),
               encode_plan(gen, world, strategy))
    # the counter moves LAST so a peer that observes the new
    # generation always finds the plan payload already staged
    cur = client.incr('%s/%s' % (ns, gen_key()), 0)
    if cur < gen:
        client.incr('%s/%s' % (ns, gen_key()), gen - cur)


def current_gen(client, ns):
    """The latest staged generation (0 = nothing ever staged)."""
    return client.incr('%s/%s' % (ns, gen_key()), 0)


def read_plan(client, ns, gen):
    """Fetch + decode a staged plan; None if not (or no longer)
    staged."""
    payload = client.get('%s/%s' % (ns, plan_key(gen)))
    if not payload:
        return None
    return decode_plan(payload)


def write_ack(client, ns, gen, worker):
    client.set('%s/%s' % (ns, ack_key(gen, worker)), '1')


def write_nack(client, ns, gen, worker, reason):
    # one protocol line: the reason must stay newline-free
    client.set('%s/%s' % (ns, nack_key(gen, worker)),
               str(reason).replace('\n', ' ')[:512])


def read_acks(client, ns, gen, workers):
    """Poll the ack/nack state for ``workers`` (the LIVE membership at
    poll time — re-evaluated by the caller on every epoch change).
    Returns ``(acked, nacks)`` where ``nacks`` is ``{worker:
    reason}``."""
    acked, nacks = set(), {}
    for w in workers:
        if client.get('%s/%s' % (ns, ack_key(gen, w))):
            acked.add(w)
        reason = client.get('%s/%s' % (ns, nack_key(gen, w)))
        if reason:
            nacks[w] = reason
    return acked, nacks


def arm(client, ns, gen, boundary):
    """Chief: arm the commit marker.  After this every member's gate
    poll observes the boundary and applies the staged plan at the
    start of step ``boundary``."""
    client.set('%s/%s' % (ns, boundary_key(gen)), str(int(boundary)))


def read_boundary(client, ns, gen):
    """The armed boundary for ``gen``, or 0 if not (or no longer)
    armed."""
    raw = client.get('%s/%s' % (ns, boundary_key(gen)))
    try:
        return int(raw) if raw else 0
    except ValueError:
        return 0


def cancel(client, ns, gen):
    """Delete a staged generation (NACK or ack-timeout): the plan,
    acks, nacks and any armed marker all vanish atomically enough —
    peers key every decision off the plan payload's presence."""
    client.delete_namespace('%s/%s' % (ns, gen_prefix(gen)))


def purge_all(client, ns):
    """Remove every staged plan and the generation counter (run end /
    fresh-run init): a restarted run must never observe a stale staged
    plan."""
    client.delete_namespace('%s/%s' % (ns, PREFIX))


def mark_ready(client, ns, gen):
    client.set('%s/%s' % (ns, ready_key(gen)), '1')


def wait_ready(client, ns, gen, timeout_s):
    """Non-chief members: block until the chief finished re-keying the
    authoritative PS copies under the new plan (bounded)."""
    return client.wait_key('%s/%s' % (ns, ready_key(gen)),
                           timeout_s=timeout_s)
