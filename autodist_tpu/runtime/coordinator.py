"""Coordinator: launch + monitor worker processes across hosts.

Reference parity (``autodist/coordinator.py:46-110``): the chief re-runs
the *user's own script* on every other host with the serialized strategy
id in the environment, then fail-fast-monitors the remote processes
(``os._exit(1)`` when any worker dies). The TPU-native version keeps that
contract and adds the ``jax.distributed`` identity variables
(process id / process count / coordinator address) so the SPMD runtime
forms a single multi-host program instead of per-op RPC servers.

Remote execution is plain ssh via subprocess (paramiko-free: one less
dependency, same semantics); ``AUTODIST_DEBUG_REMOTE`` prints commands
instead of running them (reference cluster.py:340-342).
"""
import os
import shlex
import subprocess
import sys
import threading

from autodist_tpu.const import (DEFAULT_COORD_PORT, DEFAULT_JAX_COORD_PORT,
                                DEFAULT_WORKING_DIR, ENV)
from autodist_tpu.utils import logging

_FORWARDED_FLAGS = (ENV.AUTODIST_MIN_LOG_LEVEL, ENV.AUTODIST_IS_TESTING,
                    ENV.AUTODIST_COORD_SERVICE_ADDR,
                    ENV.AUTODIST_HEARTBEAT_TIMEOUT,
                    ENV.AUTODIST_PS_ENDPOINTS, ENV.AUTODIST_PS_WIRE_DTYPE,
                    ENV.AUTODIST_PS_CHUNK_BYTES,
                    ENV.AUTODIST_S2D_STEM, ENV.AUTODIST_DENSENET_DUS,
                    # bucket layout + overlap flags must agree on every
                    # traced host — divergent HLO across SPMD deadlocks
                    ENV.AUTODIST_BUCKET_BYTES, ENV.AUTODIST_XLA_OVERLAP,
                    ENV.AUTODIST_PS_TORN_RETRIES,
                    ENV.AUTODIST_PS_TORN_BACKOFF_S,
                    # async PS data-plane knobs: every loose-mode worker
                    # must agree on the pipeline depth and stall window
                    ENV.AUTODIST_PS_PIPELINE_DEPTH,
                    ENV.AUTODIST_PS_STALL_TIMEOUT_S,
                    ENV.SYS_DATA_PATH, ENV.SYS_RESOURCE_PATH)
# AUTODIST_COORD_TOKEN is deliberately NOT in _FORWARDED_FLAGS: env
# assignments ride the remote ssh command line, which is world-readable
# in `ps` on the worker host. The secret ships as a mode-0600 file
# instead (_copy_token), referenced via AUTODIST_COORD_TOKEN_FILE.


class Coordinator:
    """Launch the current program on every worker host and babysit it."""

    def __init__(self, strategy, resource_spec, cluster=None):
        self._strategy = strategy
        self._resource_spec = resource_spec
        self._cluster = cluster
        self._shutting_down = False
        self.threads = []
        self.procs = []
        self._token_path = ''
        # arm the XLA overlap flags BEFORE building worker envs: any
        # AllReduce node means bucketed gradient sync, and the flags
        # must reach workers at process start (their backend init)
        from autodist_tpu.strategy.base import AllReduceSynchronizer
        has_ar = any(
            isinstance(s, AllReduceSynchronizer)
            for node in strategy.node_config
            for s in [node.synchronizer] + list(node.part_config)
            if s is not None)
        if has_ar:
            from autodist_tpu.utils.jax_env import setup_overlap_flags
            applied = setup_overlap_flags()
            if applied:
                logging.info('Armed XLA overlap flags for bucketed '
                             'gradient sync: %s', applied)

    def _worker_env(self, worker_addr, process_id):
        env = {
            ENV.AUTODIST_WORKER.name: worker_addr,
            ENV.AUTODIST_STRATEGY_ID.name: self._strategy.id,
            ENV.AUTODIST_PROCESS_ID.name: str(process_id),
            ENV.AUTODIST_NUM_PROCESSES.name:
                os.environ.get(ENV.AUTODIST_NUM_PROCESSES.name) or
                str(len(list(self._resource_spec.nodes))),
            ENV.AUTODIST_COORDINATOR_ADDR.name:
                ENV.AUTODIST_COORDINATOR_ADDR.val or
                ('%s:%d' % (self._resource_spec.chief,
                            DEFAULT_JAX_COORD_PORT)),
            ENV.AUTODIST_COORD_SERVICE_ADDR.name:
                ENV.AUTODIST_COORD_SERVICE_ADDR.val or
                ('%s:%d' % (self._resource_spec.chief,
                            DEFAULT_COORD_PORT)),
        }
        for flag in _FORWARDED_FLAGS:
            raw = os.environ.get(flag.name)
            if raw:
                env[flag.name] = raw
        # libtpu reads this once at backend init: forwarding it lets the
        # overlap flags armed on the chief (utils/jax_env.py
        # setup_overlap_flags) take effect from worker process start
        raw = os.environ.get('LIBTPU_INIT_ARGS')
        if raw:
            env['LIBTPU_INIT_ARGS'] = raw
        if self._token_path:
            env[ENV.AUTODIST_COORD_TOKEN_FILE.name] = self._token_path
        return env

    def _ssh_base(self, ssh_config, scp=False):
        cmd = ['scp' if scp else 'ssh', '-o',
               'StrictHostKeyChecking=no']
        if ssh_config and ssh_config.key_file:
            cmd += ['-i', ssh_config.key_file]
        if ssh_config and ssh_config.port != 22:
            cmd += ['-P' if scp else '-p', str(ssh_config.port)]
        return cmd

    @staticmethod
    def _target(address, ssh_config):
        return address if not (ssh_config and ssh_config.username) \
            else '%s@%s' % (ssh_config.username, address)

    def _copy_strategy(self, address, ssh_config):
        """Ship the serialized strategy file to a worker host (reference
        coordinator.py:56-64 SFTP copy).

        Copies to a temp name then renames remotely: atomic placement,
        and safe when chief and worker share a filesystem (scp'ing a
        file onto its own path truncates it before reading)."""
        src = self._strategy.path
        tmp = '%s.ship.%d' % (src, os.getpid())
        target = self._target(address, ssh_config)
        scp_cmd = self._ssh_base(ssh_config, scp=True) + \
            [src, '%s:%s' % (target, tmp)]
        mv_cmd = self._ssh_base(ssh_config) + \
            [target, 'mv -f %s %s' % (shlex.quote(tmp), shlex.quote(src))]
        if ENV.AUTODIST_DEBUG_REMOTE.val:
            logging.info('[debug-remote] %s', ' '.join(scp_cmd))
            logging.info('[debug-remote] %s', ' '.join(mv_cmd))
            return
        subprocess.run(scp_cmd, check=True)
        subprocess.run(mv_cmd, check=True)

    def _copy_token(self, address, ssh_config):
        """Ship the coord-service shared secret to a worker host as a
        mode-0600 file (env assignments ride the remote command line —
        world-readable in `ps` — so the secret goes by file, like the
        reference rode authenticated scp for everything it shipped)."""
        from autodist_tpu.runtime.coord_client import coord_token
        token = coord_token()
        if not token:
            self._token_path = ''
            return
        path = os.path.join(os.path.dirname(self._strategy.path),
                            'coord_token')
        fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
        with os.fdopen(fd, 'w') as f:
            f.write(token)
        self._token_path = path
        tmp = '%s.ship.%d' % (path, os.getpid())
        target = self._target(address, ssh_config)
        scp_cmd = self._ssh_base(ssh_config, scp=True) + \
            [path, '%s:%s' % (target, tmp)]
        mv_cmd = self._ssh_base(ssh_config) + \
            [target, 'chmod 600 %s && mv -f %s %s' %
             (shlex.quote(tmp), shlex.quote(tmp), shlex.quote(path))]
        if ENV.AUTODIST_DEBUG_REMOTE.val:
            logging.info('[debug-remote] %s', ' '.join(scp_cmd))
            logging.info('[debug-remote] %s', ' '.join(mv_cmd))
            return
        subprocess.run(scp_cmd, check=True)
        subprocess.run(mv_cmd, check=True)

    def launch_clients(self):
        """Re-run ``sys.argv`` on every non-chief replica host."""
        chief = self._resource_spec.chief
        workers = [n for n in self._resource_spec.nodes if n != chief]
        script = ' '.join(shlex.quote(a) for a in
                          [sys.executable] + sys.argv)
        for i, address in enumerate(workers, start=1):
            ssh_config = self._resource_spec.ssh_config(address)
            self._copy_strategy(address, ssh_config)
            self._copy_token(address, ssh_config)
            env = self._worker_env(address, i)
            env_str = ' '.join('%s=%s' % (k, shlex.quote(v))
                               for k, v in env.items())
            venv = ''
            if ssh_config and ssh_config.python_venv:
                venv = '. %s/bin/activate && ' % ssh_config.python_venv
            remote_cmd = 'cd %s && %s%s %s' % (
                shlex.quote(os.getcwd()), venv, env_str, script)
            cmd = self._ssh_base(ssh_config) + \
                [self._target(address, ssh_config), remote_cmd]
            if ENV.AUTODIST_DEBUG_REMOTE.val:
                logging.info('[debug-remote] %s', ' '.join(cmd))
                continue
            logging.info('Launching worker on %s', address)
            proc = subprocess.Popen(cmd)
            self.procs.append(proc)
            t = threading.Thread(target=self._monitor,
                                 args=(address, proc), daemon=True)
            t.start()
            self.threads.append(t)
        return self

    def _monitor(self, address, proc):
        """Fail fast: if any worker dies, kill the chief (reference
        coordinator.py:98-110). Suppressed during intentional shutdown
        so a clean exit's SIGTERMs don't read as worker failures."""
        code = proc.wait()
        if code != 0 and not self._shutting_down:
            logging.error('Worker %s exited with code %s; aborting chief',
                          address, code)
            os._exit(1)

    def join(self):
        for p in self.procs:
            p.wait()

    def terminate(self):
        self._shutting_down = True
        for p in self.procs:
            if p.poll() is None:
                p.terminate()


def launch_cli(argv=None):
    """``python -m autodist_tpu.launch [--spec r.yml] script.py args...``

    The pod-native launcher: starts one process per host entry of the
    resource spec (locally via subprocess, remotely via ssh) with the
    jax.distributed identity env set — the same-binary-everywhere model
    of TPU pods, while the Coordinator covers the reference's
    chief-re-runs-your-script model.
    """
    import argparse
    parser = argparse.ArgumentParser(prog='autodist_tpu.launch')
    parser.add_argument('--spec', help='resource spec YAML',
                        default=ENV.SYS_RESOURCE_PATH.val or None)
    parser.add_argument('--coordinator-port', type=int,
                        default=DEFAULT_JAX_COORD_PORT)
    parser.add_argument('script')
    parser.add_argument('args', nargs=argparse.REMAINDER)
    ns = parser.parse_args(argv)

    from autodist_tpu.resource_spec import ResourceSpec
    from autodist_tpu.runtime.cluster import is_local_address
    spec = ResourceSpec(resource_file=ns.spec) if ns.spec else None
    nodes = list(spec.nodes) if spec else ['localhost']
    chief = spec.chief if spec else 'localhost'
    nodes = [chief] + [n for n in nodes if n != chief]
    coord = '%s:%d' % (chief, ns.coordinator_port)
    coord_service = ENV.AUTODIST_COORD_SERVICE_ADDR.val or \
        '%s:%d' % (chief, DEFAULT_COORD_PORT)

    os.makedirs(DEFAULT_WORKING_DIR, exist_ok=True)
    # The launcher owns the coord service (and any local PS endpoint
    # services): they must outlive every process (a fast chief may
    # finish while slow workers still push PS deltas).
    service_procs = []
    cs_host, cs_port = coord_service.rsplit(':', 1)
    if is_local_address(cs_host):
        from autodist_tpu.runtime import coord_client
        all_local = all(is_local_address(n) for n in nodes)
        service_procs.append(coord_client.ensure_service(
            int(cs_port), bind='127.0.0.1' if all_local else '0.0.0.0'))
        if all_local:
            # bound to loopback -> children must connect via loopback,
            # even when the spec names this host by its NIC IP
            coord_service = '127.0.0.1:%s' % cs_port
        for ep_host, ep_port in coord_client.ps_endpoints():
            if is_local_address(ep_host):
                service_procs.append(coord_client.ensure_service(
                    ep_port, bind='127.0.0.1' if all_local else '0.0.0.0'))
    import uuid
    run_id = uuid.uuid4().hex[:12]
    procs = []
    for i, address in enumerate(nodes):
        env = dict(os.environ)
        env.update({
            ENV.AUTODIST_PROCESS_ID.name: str(i),
            ENV.AUTODIST_NUM_PROCESSES.name: str(len(nodes)),
            ENV.AUTODIST_COORDINATOR_ADDR.name: coord,
            ENV.AUTODIST_COORD_SERVICE_ADDR.name: coord_service,
            ENV.AUTODIST_RUN_ID.name: run_id,
        })
        if i > 0:
            env[ENV.AUTODIST_WORKER.name] = address
        cmd = [sys.executable, ns.script] + ns.args
        if is_local_address(address):
            # same-host process (multi-process-per-host and test tiers)
            procs.append(subprocess.Popen(cmd, env=env))
        else:
            ssh_config = spec.ssh_config(address) if spec else None
            env_flags = {k: env[k] for k in env
                         if k.startswith('AUTODIST_')}
            env_str = ' '.join('%s=%s' % (k, shlex.quote(v))
                               for k, v in env_flags.items())
            remote = 'cd %s && %s %s' % (
                shlex.quote(os.getcwd()), env_str,
                ' '.join(shlex.quote(a) for a in cmd))
            ssh_cmd = ['ssh', '-o', 'StrictHostKeyChecking=no']
            if ssh_config and ssh_config.key_file:
                ssh_cmd += ['-i', ssh_config.key_file]
            target = address if not (ssh_config and ssh_config.username) \
                else '%s@%s' % (ssh_config.username, address)
            ssh_cmd += [target, remote]
            if ENV.AUTODIST_DEBUG_REMOTE.val:
                logging.info('[debug-remote] %s', ' '.join(ssh_cmd))
                continue
            procs.append(subprocess.Popen(ssh_cmd, env=env))
    rc = 0
    for p in procs:
        rc = p.wait() or rc
    for sp in service_procs:
        if sp is not None:
            sp.terminate()
    return rc
